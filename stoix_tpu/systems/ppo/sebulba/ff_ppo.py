"""Sebulba PPO (reference stoix/systems/ppo/sebulba/ff_ppo.py, 1046 LoC).

Actor/learner disaggregation for non-pure-JAX environments: actor THREADS pin
jitted inference to actor devices and step stateful envs (EnvPool/C++/JAX
adapters behind the EnvFactory seam); trajectories flow through bounded queues
(OnPolicyPipeline) to a learner thread running the PPO update over a learner-
device mesh; fresh params return via the ParameterServer; evaluation runs
asynchronously on its own device.

TPU-native differences from the reference (SURVEY.md §7.1.3):
  - the learner consumes GLOBAL arrays assembled with
    jax.make_array_from_single_device_arrays (no host concat, no
    device_put_sharded), and the update itself is jit+shard_map over the
    learner mesh rather than pmap.
  - actor->learner backpressure (queue maxsize=1) and the skip-fetch-on-first-
    rollout pipelining (reference :202-214) are preserved.

Fault tolerance (stoix_tpu/resilience, docs/DESIGN.md §2.3): actor threads
are owned by an ActorSupervisor (crash -> bounded-backoff restart with a
fresh env and re-primed params; budget exhausted or heartbeat wedge -> typed
ComponentFailure poison-pill so the learner fails fast), SIGTERM/SIGINT stop
the learner loop at the next update boundary, and `system.update_guard`
guards the gradient step against non-finite losses/grads.
"""

from __future__ import annotations

import queue
import sys
import threading
import time
from typing import Any, Callable, List, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from stoix_tpu.base_types import ActorCriticOptStates, ActorCriticParams, PPOTransition
from stoix_tpu.envs.factory import make_factory
from stoix_tpu.evaluator import get_distribution_act_fn, get_ff_evaluator_fn
from stoix_tpu.observability import (
    RunStats,
    annotate,
    flightrec,
    get_health_monitor,
    get_logger,
    get_registry,
    get_status_board,
    goodput,
    span,
)
from stoix_tpu.ops import (
    losses,
    running_statistics,
    scan_kernels,
    truncated_generalized_advantage_estimation,
)
from stoix_tpu.parallel import MeshRoles, assemble_global_array
from stoix_tpu.parallel.mesh import shard_map
from stoix_tpu.resilience import (
    PreemptionHandler,
    faultinject,
    fleet,
    guards,
    integrity,
    preflight,
    supervisor_from_config,
)
from stoix_tpu.resilience.errors import EvaluatorStallError
from stoix_tpu.sebulba.core import (
    AsyncEvaluator,
    OffPolicyPipeline,
    OnPolicyPipeline,
    ParameterServer,
    ThreadLifetime,
)
from stoix_tpu.utils import compilecache
from stoix_tpu.utils import config as config_lib
from stoix_tpu.utils.logger import LogEvent, StoixLogger
from stoix_tpu.utils.timing import TimingTracker
from stoix_tpu.utils.training import make_learning_rate

# Throughput stats of the most recent run_experiment call in this process
# (steady-state window: after the first eval block, i.e. post-compile).
# Read by bench.py --sebulba; dict-compatible (RunStats) so callers can
# ignore it entirely. The underlying series live in the metrics registry
# (stoix_tpu_sebulba_*).
LAST_RUN_STATS = RunStats()


class CoreLearnerState(NamedTuple):
    params: ActorCriticParams
    opt_states: ActorCriticOptStates
    key: jax.Array
    obs_stats: Any  # observation running statistics (updates gated by config)


class ImpactSettings(NamedTuple):
    """Validated `system.impact` knobs (IMPACT stale-trajectory reuse,
    arXiv:1912.00167; docs/DESIGN.md §2.12)."""

    target_update_interval: int
    rho_clip: float
    max_staleness: int
    max_reuse: int
    buffer_size: int


def impact_settings_from_config(config: Any) -> "ImpactSettings | None":
    """None unless system.impact.enabled — the disabled path constructs the
    unchanged on-policy objects (OnPolicyPipeline + get_learn_step)."""
    raw = dict(config.system.get("impact") or {})
    if not bool(raw.get("enabled", False)):
        return None
    settings = ImpactSettings(
        target_update_interval=int(raw.get("target_update_interval", 4)),
        rho_clip=float(raw.get("rho_clip", 2.0)),
        max_staleness=int(raw.get("max_staleness", 4)),
        max_reuse=int(raw.get("max_reuse", 2)),
        buffer_size=int(raw.get("buffer_size", 4)),
    )
    if settings.target_update_interval < 1:
        raise ValueError(
            "system.impact.target_update_interval must be >= 1 "
            f"(got {settings.target_update_interval})"
        )
    if settings.rho_clip < 1.0:
        raise ValueError(
            "system.impact.rho_clip must be >= 1.0 — clipping the IS ratio "
            f"below 1 would down-weight FRESH data (got {settings.rho_clip})"
        )
    if settings.max_staleness < 1 or settings.max_reuse < 0 or settings.buffer_size < 1:
        raise ValueError(
            "system.impact: max_staleness/buffer_size must be >= 1 and "
            f"max_reuse >= 0 (got {settings})"
        )
    return settings


class ImpactBatch(NamedTuple):
    """One learner step's worth of data on the IMPACT path."""

    batch: Any  # assembled global-array trajectory batch
    behavior_version: int  # oldest param version that collected it
    fresh: bool  # False when re-stepping a buffered stale batch


class ImpactIngest:
    """Host-side fresh/stale scheduling for the IMPACT learner
    (docs/DESIGN.md §2.12).

    The learner prefers a FULL set of fresh payloads (`need` of them — any
    actor mix, shapes are identical, so one compiled learn step serves both
    paths). When fresh data is late it re-steps the newest eligible buffered
    batch instead of blocking in collect; only with an empty buffer does it
    block in wait_for_data (warmup, or reuse budget exhausted). Buffered
    entries retire on a reuse budget and are dropped once their version lag
    exceeds max_staleness."""

    def __init__(self, pipeline: OffPolicyPipeline, need: int, settings: ImpactSettings):
        import collections

        self._pipeline = pipeline
        self._need = need
        self._settings = settings
        self._pending: List[Any] = []  # (behavior_version, payload) FIFO
        # [behavior_version, batch, reuse_left]; bounded — an append past
        # capacity retires the OLDEST (stalest) entry.
        self._buffer = collections.deque(maxlen=settings.buffer_size)
        registry = get_registry()
        self._reused = registry.counter(
            "stoix_tpu_impact_reused_batches_total",
            "Learner updates that re-stepped a buffered stale batch because "
            "fresh rollouts were late",
        )
        self._dropped = registry.counter(
            "stoix_tpu_impact_dropped_batches_total",
            "Buffered batches retired for exceeding system.impact.max_staleness",
        )

    def _ingest(self, items: List[Any]) -> None:
        for _actor_id, (version, payload) in items:
            self._pending.append((version, payload))

    def _pop_reusable(self, current_version: int) -> "ImpactBatch | None":
        max_lag = self._settings.max_staleness
        while self._buffer:
            # Newest entry first: it has the smallest lag, so if IT is too
            # stale everything behind it is too.
            version, batch, reuse_left = self._buffer[-1]
            if current_version - version > max_lag:
                self._dropped.inc(len(self._buffer))
                self._buffer.clear()
                return None
            if reuse_left <= 0:
                self._buffer.pop()
                continue
            self._buffer[-1][2] = reuse_left - 1
            self._reused.inc()
            return ImpactBatch(batch, version, fresh=False)
        return None

    def next_batch(
        self, assemble: Callable[[List[Any]], Any], current_version: int,
        timeout: float = 180.0,
    ) -> ImpactBatch:
        """One update's batch: fresh when a full payload set is available (or
        arrives while the buffer is empty), else a buffered stale batch."""
        self._ingest(self._pipeline.poll(max_items=4 * self._need, timeout=0.0))
        if len(self._pending) < self._need:
            reusable = self._pop_reusable(current_version)
            if reusable is not None:
                return reusable
            while len(self._pending) < self._need:
                self._ingest(self._pipeline.wait_for_data(timeout=timeout))
        take, self._pending = self._pending[: self._need], self._pending[self._need:]
        version = min(v for v, _ in take)
        batch = assemble([p for _, p in take])
        if self._settings.max_reuse > 0:
            self._buffer.append([version, batch, self._settings.max_reuse])
        return ImpactBatch(batch, version, fresh=True)


def _build_networks(config: Any, num_actions: int, obs_value: Any, env: Any = None):
    from stoix_tpu.networks.base import FeedForwardActor, FeedForwardCritic

    net_cfg = config.network
    if env is not None:
        # Infer head kwargs from the action space (discrete num_actions or
        # continuous action_dim/minimum/maximum), like the Anakin systems.
        from stoix_tpu.systems.anakin import head_kwargs_for_env

        head_kwargs = head_kwargs_for_env(net_cfg.actor_network.action_head, env)
    else:
        head_kwargs = {"num_actions": num_actions}
    actor = FeedForwardActor(
        action_head=config_lib.instantiate(
            net_cfg.actor_network.action_head, **head_kwargs
        ),
        torso=config_lib.instantiate(net_cfg.actor_network.pre_torso),
        input_layer=config_lib.instantiate(net_cfg.actor_network.input_layer),
    )
    critic = FeedForwardCritic(
        critic_head=config_lib.instantiate(net_cfg.critic_network.critic_head),
        torso=config_lib.instantiate(net_cfg.critic_network.pre_torso),
        input_layer=config_lib.instantiate(net_cfg.critic_network.input_layer),
    )
    return actor, critic


def get_learn_step(actor_apply, critic_apply, update_fns, config, mesh: Mesh):
    """jit+shard_map PPO update over the learner mesh; batch arrives as global
    arrays sharded on the env axis."""
    actor_update, critic_update = update_fns
    gamma = float(config.system.gamma)
    normalize_obs = bool(config.system.get("normalize_observations", False))
    guard_mode = guards.resolve_mode(config)

    def _maybe_normalize(observation, obs_stats):
        if not normalize_obs:
            return observation
        return running_statistics.normalize_observation(observation, obs_stats)

    def per_shard(state: CoreLearnerState, traj: PPOTransition):
        # Actors already acted on observations normalized with these (pre-
        # update) statistics; normalize the stored RAW obs identically, then
        # fold the raw batch into the statistics (psum over the mesh axis).
        obs_stats = state.obs_stats
        raw_obs = traj.obs
        traj = traj._replace(
            obs=_maybe_normalize(raw_obs, obs_stats),
            next_obs=_maybe_normalize(traj.next_obs, obs_stats),
        )
        if normalize_obs:
            obs_stats = running_statistics.update(
                obs_stats, raw_obs.agent_view, axis_names=("data",),
                std_min_value=5e-4, std_max_value=5e4,
            )
        v_t = critic_apply(state.params.critic_params, traj.next_obs)
        d_t = gamma * (1.0 - traj.done.astype(jnp.float32))
        advantages, targets = truncated_generalized_advantage_estimation(
            traj.reward, d_t, float(config.system.gae_lambda),
            v_tm1=traj.value, v_t=v_t,
            truncation_t=traj.truncated.astype(jnp.float32),
            standardize_advantages=bool(config.system.get("standardize_advantages", True)),
            impl=str(config.system.get("multistep_impl", "scan")),
        )

        @annotate("ppo_minibatch")
        def _minibatch(carry, batch):
            params, opt_states = carry
            mb_traj, mb_adv, mb_tgt = batch

            def actor_loss_fn(p):
                dist = actor_apply(p, mb_traj.obs)
                log_prob = dist.log_prob(mb_traj.action)
                loss = losses.ppo_clip_loss(
                    log_prob, mb_traj.log_prob, mb_adv, float(config.system.clip_eps)
                )
                entropy = dist.entropy().mean()
                return loss - float(config.system.ent_coef) * entropy, (loss, entropy)

            def critic_loss_fn(p):
                value = critic_apply(p, mb_traj.obs)
                loss = losses.clipped_value_loss(
                    value, mb_traj.value, mb_tgt, float(config.system.clip_eps)
                )
                return float(config.system.vf_coef) * loss, loss

            # value_and_grad: the divergence guard needs the total losses;
            # unused under update_guard=off, so XLA DCEs them (jax.grad is
            # itself a value_and_grad that drops the value).
            (a_total, (a_loss, entropy)), a_grads = jax.value_and_grad(
                actor_loss_fn, has_aux=True
            )(params.actor_params)
            (c_total, v_loss), c_grads = jax.value_and_grad(
                critic_loss_fn, has_aux=True
            )(params.critic_params)
            a_grads, c_grads = jax.lax.pmean((a_grads, c_grads), axis_name="data")
            a_updates, a_opt = actor_update(a_grads, opt_states.actor_opt_state)
            c_updates, c_opt = critic_update(c_grads, opt_states.critic_opt_state)
            new_params = ActorCriticParams(
                optax.apply_updates(params.actor_params, a_updates),
                optax.apply_updates(params.critic_params, c_updates),
            )
            # Divergence guard (resilience/guards.py): the per-shard loss is
            # pmean'ed over "data" inside the guard so every shard makes the
            # same keep/skip decision on the replicated params.
            (params, opt_states), guard_metrics = guards.guard_update(
                guard_mode,
                new=(new_params, ActorCriticOptStates(a_opt, c_opt)),
                old=(params, opt_states),
                loss=a_total + c_total,
                grads=(a_grads, c_grads),
                opt_state=opt_states,
                axis_names=("data",),
            )
            return (params, opt_states), {
                "actor_loss": a_loss, "value_loss": v_loss, "entropy": entropy,
                **guard_metrics,
            }

        @annotate("ppo_epoch")
        def _epoch(carry, _):
            params, opt_states, key = carry
            key, shuffle_key = jax.random.split(key)
            batch_size = advantages.shape[0] * advantages.shape[1]
            perm = jax.random.permutation(shuffle_key, batch_size)
            flat = jax.tree.map(
                lambda x: x.reshape((-1,) + x.shape[2:]), (traj, advantages, targets)
            )
            shuffled = jax.tree.map(lambda x: jnp.take(x, perm, axis=0), flat)
            minibatches = jax.tree.map(
                lambda x: x.reshape(
                    (int(config.system.num_minibatches), -1) + x.shape[1:]
                ),
                shuffled,
            )
            (params, opt_states), metrics = jax.lax.scan(
                _minibatch, (params, opt_states), minibatches
            )
            return (params, opt_states, key), metrics

        (params, opt_states, key), metrics = jax.lax.scan(
            _epoch, (state.params, state.opt_states, state.key), None,
            int(config.system.epochs),
        )
        metrics = jax.lax.pmean(metrics, axis_name="data")
        return CoreLearnerState(params, opt_states, key, obs_stats), metrics

    return jax.jit(
        shard_map(
            per_shard,
            mesh=mesh,
            in_specs=(CoreLearnerState(P(), P(), P(), P()), P(None, "data")),
            out_specs=(CoreLearnerState(P(), P(), P(), P()), P()),
            # No in-shard vmap axis here, so the varying-manual-axes
            # validator runs (Anakin's pmean-over-vmap-axis limitation
            # does not apply — see systems/anakin.py).
            check_vma=True,
        )
    )


def get_impact_learn_step(
    actor_apply, critic_apply, update_fns, config, mesh: Mesh, rho_clip: float
):
    """IMPACT variant of get_learn_step (arXiv:1912.00167, docs/DESIGN.md
    §2.12): the update takes a THIRD input — the slow-moving target params
    (replicated; a host-refreshed alias of a recent online version) — and the
    actor objective becomes losses.impact_loss: the PPO clip taken against
    the target policy, importance-weighted by the clipped target/behavior
    ratio. `traj.log_prob` is the BEHAVIOR log-prob recorded by whichever
    (possibly stale) param version collected the trajectory, which is what
    makes re-stepping buffered batches sound. Everything else — GAE on the
    stored values, epoch/minibatch scan, value loss, pmean over "data",
    guards.guard_update — is the on-policy schedule unchanged."""
    actor_update, critic_update = update_fns
    gamma = float(config.system.gamma)
    normalize_obs = bool(config.system.get("normalize_observations", False))
    guard_mode = guards.resolve_mode(config)

    def _maybe_normalize(observation, obs_stats):
        if not normalize_obs:
            return observation
        return running_statistics.normalize_observation(observation, obs_stats)

    def per_shard(state: CoreLearnerState, target_params, traj: PPOTransition):
        obs_stats = state.obs_stats
        raw_obs = traj.obs
        traj = traj._replace(
            obs=_maybe_normalize(raw_obs, obs_stats),
            next_obs=_maybe_normalize(traj.next_obs, obs_stats),
        )
        if normalize_obs:
            obs_stats = running_statistics.update(
                obs_stats, raw_obs.agent_view, axis_names=("data",),
                std_min_value=5e-4, std_max_value=5e4,
            )
        v_t = critic_apply(state.params.critic_params, traj.next_obs)
        d_t = gamma * (1.0 - traj.done.astype(jnp.float32))
        advantages, targets = truncated_generalized_advantage_estimation(
            traj.reward, d_t, float(config.system.gae_lambda),
            v_tm1=traj.value, v_t=v_t,
            truncation_t=traj.truncated.astype(jnp.float32),
            standardize_advantages=bool(config.system.get("standardize_advantages", True)),
            impl=str(config.system.get("multistep_impl", "scan")),
        )

        @annotate("impact_minibatch")
        def _minibatch(carry, batch):
            params, opt_states = carry
            mb_traj, mb_adv, mb_tgt = batch

            def actor_loss_fn(p):
                dist = actor_apply(p, mb_traj.obs)
                log_prob = dist.log_prob(mb_traj.action)
                # Target policy log-probs on the same (normalized) obs; no
                # gradient flows into them (target_params is not `p`).
                target_dist = actor_apply(target_params.actor_params, mb_traj.obs)
                target_log_prob = target_dist.log_prob(mb_traj.action)
                loss = losses.impact_loss(
                    log_prob, mb_traj.log_prob, target_log_prob, mb_adv,
                    float(config.system.clip_eps), rho_clip,
                )
                entropy = dist.entropy().mean()
                return loss - float(config.system.ent_coef) * entropy, (loss, entropy)

            def critic_loss_fn(p):
                value = critic_apply(p, mb_traj.obs)
                loss = losses.clipped_value_loss(
                    value, mb_traj.value, mb_tgt, float(config.system.clip_eps)
                )
                return float(config.system.vf_coef) * loss, loss

            (a_total, (a_loss, entropy)), a_grads = jax.value_and_grad(
                actor_loss_fn, has_aux=True
            )(params.actor_params)
            (c_total, v_loss), c_grads = jax.value_and_grad(
                critic_loss_fn, has_aux=True
            )(params.critic_params)
            a_grads, c_grads = jax.lax.pmean((a_grads, c_grads), axis_name="data")
            a_updates, a_opt = actor_update(a_grads, opt_states.actor_opt_state)
            c_updates, c_opt = critic_update(c_grads, opt_states.critic_opt_state)
            new_params = ActorCriticParams(
                optax.apply_updates(params.actor_params, a_updates),
                optax.apply_updates(params.critic_params, c_updates),
            )
            # Divergence guard stays wired on the stale-reuse path — a
            # blown-up IS ratio meeting a stale minibatch is exactly the
            # non-finite-update class system.update_guard exists for.
            (params, opt_states), guard_metrics = guards.guard_update(
                guard_mode,
                new=(new_params, ActorCriticOptStates(a_opt, c_opt)),
                old=(params, opt_states),
                loss=a_total + c_total,
                grads=(a_grads, c_grads),
                opt_state=opt_states,
                axis_names=("data",),
            )
            return (params, opt_states), {
                "actor_loss": a_loss, "value_loss": v_loss, "entropy": entropy,
                **guard_metrics,
            }

        @annotate("impact_epoch")
        def _epoch(carry, _):
            params, opt_states, key = carry
            key, shuffle_key = jax.random.split(key)
            batch_size = advantages.shape[0] * advantages.shape[1]
            perm = jax.random.permutation(shuffle_key, batch_size)
            flat = jax.tree.map(
                lambda x: x.reshape((-1,) + x.shape[2:]), (traj, advantages, targets)
            )
            shuffled = jax.tree.map(lambda x: jnp.take(x, perm, axis=0), flat)
            minibatches = jax.tree.map(
                lambda x: x.reshape(
                    (int(config.system.num_minibatches), -1) + x.shape[1:]
                ),
                shuffled,
            )
            (params, opt_states), metrics = jax.lax.scan(
                _minibatch, (params, opt_states), minibatches
            )
            return (params, opt_states, key), metrics

        (params, opt_states, key), metrics = jax.lax.scan(
            _epoch, (state.params, state.opt_states, state.key), None,
            int(config.system.epochs),
        )
        metrics = jax.lax.pmean(metrics, axis_name="data")
        return CoreLearnerState(params, opt_states, key, obs_stats), metrics

    return jax.jit(
        shard_map(
            per_shard,
            mesh=mesh,
            in_specs=(CoreLearnerState(P(), P(), P(), P()), P(), P(None, "data")),
            out_specs=(CoreLearnerState(P(), P(), P(), P()), P()),
            check_vma=True,
        )
    )


def rollout_thread(
    actor_id: int,
    actor_device: jax.Device,
    env_factory,
    actor_apply,
    critic_apply,
    config: Any,
    pipeline: OnPolicyPipeline,
    param_server: ParameterServer,
    learner_devices: List[jax.Device],
    learner_mesh: Mesh,
    lifetime: ThreadLifetime,
    seed: int,
    metrics_sink: "queue.Queue",
    supervisor: Any = None,
) -> None:
    envs_per_actor = int(config.arch.actor.envs_per_actor)
    rollout_length = int(config.system.rollout_length)
    timer = TimingTracker()

    try:
        _rollout_body(
            actor_id, actor_device, env_factory, actor_apply, critic_apply,
            config, pipeline, param_server, learner_devices, learner_mesh,
            lifetime, seed, metrics_sink, envs_per_actor, rollout_length, timer,
        )
    except Exception as exc:
        import traceback

        get_registry().counter(
            "stoix_tpu_sebulba_actor_crashes_total",
            "Actor threads that died with an exception",
        ).inc(labels={"actor": str(actor_id)})
        get_logger("stoix_tpu.sebulba").error(
            "[actor-%d] CRASHED:\n%s", actor_id, traceback.format_exc()
        )
        if supervisor is not None:
            # Supervised: restart with backoff, or propagate a typed
            # ComponentFailure poison-pill (resilience/supervisor.py).
            supervisor.report_crash(actor_id, exc)
        else:
            lifetime.stop()


def _rollout_body(
    actor_id, actor_device, env_factory, actor_apply, critic_apply, config,
    pipeline, param_server, learner_devices, learner_mesh, lifetime, seed,
    metrics_sink, envs_per_actor, rollout_length, timer,
):
    envs = env_factory(envs_per_actor)
    timestep = envs.reset(seed=seed)

    normalize_obs = bool(config.system.get("normalize_observations", False))
    # IMPACT path (docs/DESIGN.md §2.12): fetch params WITH their version and
    # tag every pushed trajectory with it — the learner computes per-batch
    # staleness (its current version minus this behavior version).
    impact_on = impact_settings_from_config(config) is not None

    @jax.jit
    def act_fn(bundle, observation, key):
        params, obs_stats = bundle
        if normalize_obs:
            observation = running_statistics.normalize_observation(observation, obs_stats)
        dist = actor_apply(params.actor_params, observation)
        value = critic_apply(params.critic_params, observation)
        action = dist.sample(seed=key)
        return action, dist.log_prob(action), value

    with jax.default_device(actor_device):
        key = jax.random.PRNGKey(seed)
        versioned = param_server.get_params_versioned(actor_id)
        if versioned is None:
            return
        behavior_version, params = versioned
        rollout_idx = 0
        while not lifetime.should_stop():
            # Chaos injection points (no-ops unless STOIX_TPU_FAULT armed):
            # a deterministic crash exercises supervised restart, a
            # deterministic wedge exercises heartbeat wedge detection.
            faultinject.maybe_crash_actor(actor_id, rollout_idx)
            faultinject.maybe_stall_queue(
                actor_id, rollout_idx, should_abort=lifetime.should_stop
            )
            # Pipelining: skip the param fetch on the second rollout so actors
            # run ahead while the learner computes (reference :202-214).
            if rollout_idx > 1:
                with timer.time("get_params"):
                    fetched = param_server.get_params_versioned(actor_id)
                    if fetched is None:
                        break
                    behavior_version, params = fetched
            traj: List[PPOTransition] = []
            with span("actor_rollout", actor=actor_id, idx=rollout_idx), timer.time("rollout"):
                for _ in range(rollout_length):
                    key, act_key = jax.random.split(key)
                    with timer.time("inference"):
                        # Envs may live on a different device (e.g. CPU for
                        # C++/EnvPool backends); stage observations onto the
                        # actor device for inference.
                        obs_local = jax.device_put(timestep.observation, actor_device)
                        action, log_prob, value = act_fn(params, obs_local, act_key)
                    with timer.time("env_step"):
                        next_timestep = envs.step(action)
                    traj.append(
                        PPOTransition(
                            done=next_timestep.discount == 0.0,
                            truncated=jnp.logical_and(
                                next_timestep.last(), next_timestep.discount != 0.0
                            ),
                            action=action,
                            value=value,
                            reward=next_timestep.reward,
                            log_prob=log_prob,
                            obs=obs_local,
                            next_obs=next_timestep.extras["next_obs"],
                            info=next_timestep.extras["episode_metrics"],
                        )
                    )
                    timestep = next_timestep

            with span("actor_prepare_data", actor=actor_id), timer.time("prepare_data"):
                # Stack [T, E] then split the env axis across learner devices
                # as single-device shards for global-array assembly.
                stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *traj)
                n_learners = len(learner_devices)
                payload = jax.tree.map(
                    lambda x: [
                        jax.device_put(s, d)
                        for s, d in zip(jnp.split(x, n_learners, axis=1), learner_devices)
                    ],
                    stacked,
                )
            with timer.time("queue_put"):
                try:
                    if impact_on:
                        pipeline.push(actor_id, (behavior_version, payload), timeout=60.0)
                    else:
                        pipeline.send_rollout(actor_id, payload, timeout=60.0)
                except queue.Full:
                    if lifetime.should_stop():
                        break
                    raise
            metrics_sink.put(
                {
                    "episode_metrics": jax.tree.map(np.asarray, stacked.info),
                    "timings": {
                        **timer.all_means(prefix=f"actor{actor_id}_"),
                        **timer.all_percentiles(prefix=f"actor{actor_id}_"),
                    },
                }
            )
            rollout_idx += 1


def run_experiment(
    config: Any,
    learn_step_builder: Callable = None,
    networks_builder: Callable = None,
) -> float:
    LAST_RUN_STATS.clear()
    # Resilience (docs/DESIGN.md §2.3): arm the chaos plan before anything is
    # traced (the in-jit nan_loss fault binds at trace time) and resolve the
    # divergence-guard mode for the learner loop's host-side checks.
    faultinject.configure(config.arch.get("fault_spec"))
    guard_mode = guards.resolve_mode(config)
    # Compile economy (docs/DESIGN.md §2.7): persistent XLA cache knobs must
    # land before the first compile, and the multistep scan-kernel default
    # before the learner is traced.
    compilecache.configure(config)
    scan_kernels.configure_from_config(config)
    # Launch hardening (docs/DESIGN.md §2.4, arch.preflight): subprocess
    # backend probe + config cross-validation before any device work — the
    # actor/learner device-id split below is exactly the class of config this
    # catches (ids out of range, envs not divisible by actors).
    pf = preflight.settings_from_config(config)
    if pf.enabled:
        probe = preflight.probe_backend(
            timeout_s=pf.probe_timeout_s,
            attempts=pf.probe_attempts,
            backoff_base_s=pf.probe_backoff_base_s,
            backoff_max_s=pf.probe_backoff_max_s,
        )
        preflight.validate_config(config, device_count=probe.device_count)
    # Device assignment through the unified mesh-role abstraction
    # (parallel/roles.py, docs/DESIGN.md §2.11): the actor/learner/evaluator
    # split — historically resolved ad hoc from arch.actor.device_ids /
    # arch.learner.device_ids / arch.evaluator_device_id — now arrives as one
    # validated MeshRoles object (the same object the Anakin runner, serve,
    # and the population runner consume).
    roles = MeshRoles.from_config(config)
    actor_devices = roles.role_devices("act")
    learner_devices = roles.role_devices("learn")
    evaluator_device = roles.device("evaluate")
    learner_mesh = roles.learn_mesh()
    eval_mesh = roles.role_mesh("evaluate")

    actors_per_device = int(config.arch.actor.actor_per_device)
    num_actors = len(actor_devices) * actors_per_device
    config.arch.actor.envs_per_actor = int(config.arch.total_num_envs) // num_actors

    # Budget accounting (reference total_timestep_checker sebulba branch).
    steps_per_update = int(config.system.rollout_length) * int(config.arch.total_num_envs)
    if config.arch.get("num_updates") in (None, "~"):
        config.arch.num_updates = max(
            1, int(float(config.arch.total_timesteps)) // steps_per_update
        )
    config.arch.total_timesteps = int(config.arch.num_updates) * steps_per_update
    num_evaluation = max(1, int(config.arch.get("num_evaluation", 1)))
    config.arch.num_updates_per_eval = max(1, int(config.arch.num_updates) // num_evaluation)
    config.logger.system_name = config.system.system_name

    env_factory = make_factory(config)
    probe_envs = env_factory(1)
    num_actions = probe_envs.num_actions
    config.system.action_dim = num_actions
    dummy_obs = jax.tree.map(
        lambda x: np.asarray(x)[None], probe_envs.observation_space().generate_value()
        if hasattr(probe_envs.observation_space(), "generate_value")
        else probe_envs.reset(seed=0).observation,
    )

    build = networks_builder or (
        lambda cfg, n, obs: _build_networks(cfg, n, obs, env=probe_envs)
    )
    actor, critic = build(config, num_actions, dummy_obs)
    key = jax.random.PRNGKey(int(config.arch.seed))
    key, a_key, c_key = jax.random.split(key, 3)
    obs0 = jax.tree.map(lambda x: jnp.asarray(x), probe_envs.reset(seed=0).observation)
    actor_params = actor.init(a_key, obs0)
    critic_params = critic.init(c_key, obs0)

    actor_optim = optax.chain(
        optax.clip_by_global_norm(float(config.system.max_grad_norm)),
        optax.adam(make_learning_rate(float(config.system.actor_lr), config,
                                      int(config.system.epochs),
                                      int(config.system.num_minibatches)), eps=1e-5),
    )
    critic_optim = optax.chain(
        optax.clip_by_global_norm(float(config.system.max_grad_norm)),
        optax.adam(make_learning_rate(float(config.system.critic_lr), config,
                                      int(config.system.epochs),
                                      int(config.system.num_minibatches)), eps=1e-5),
    )
    params = ActorCriticParams(actor_params, critic_params)
    opt_states = ActorCriticOptStates(
        actor_optim.init(actor_params), critic_optim.init(critic_params)
    )
    key, learn_key = jax.random.split(key)
    obs0_single = jax.tree.map(lambda x: jnp.asarray(x)[0], obs0.agent_view)
    obs_stats = running_statistics.init_state(obs0_single)
    learner_state = jax.device_put(
        CoreLearnerState(params, opt_states, learn_key, obs_stats),
        NamedSharding(learner_mesh, P()),
    )

    # IMPACT stale-trajectory reuse (docs/DESIGN.md §2.12): None (the
    # default) constructs the UNCHANGED on-policy objects below — same
    # OnPolicyPipeline, same get_learn_step trace.
    impact = impact_settings_from_config(config)
    if impact is not None and learn_step_builder is not None:
        raise ValueError(
            "system.impact.enabled is incompatible with a custom "
            "learn_step_builder: the IMPACT update takes (state, "
            "target_params, batch), not (state, batch)"
        )
    if impact is not None:
        learn_step = get_impact_learn_step(
            actor.apply, critic.apply, (actor_optim.update, critic_optim.update),
            config, learner_mesh, rho_clip=impact.rho_clip,
        )
    else:
        builder = learn_step_builder or get_learn_step
        learn_step = builder(
            actor.apply, critic.apply, (actor_optim.update, critic_optim.update),
            config, learner_mesh,
        )

    # State-integrity sentinel (docs/DESIGN.md §2.9, arch.integrity): Sebulba
    # has no coalesced fetch to piggyback fingerprints on, so the learner
    # loop checks the replicated learner state synchronously at each eval
    # boundary (the vector is [num_learner_devices] uint32 — tiny). Off (the
    # default) = None = unchanged loop.
    sentinel = integrity.sentinel_from_config(config)
    if sentinel is not None:
        sentinel.bind(learner_mesh, learner_state)
        sentinel.install_excepthook()

    normalize_obs = bool(config.system.get("normalize_observations", False))

    def eval_apply(payload, observation):
        if normalize_obs:
            p, stats = payload
            observation = running_statistics.normalize_observation(observation, stats)
            return actor.apply(p, observation)
        return actor.apply(payload, observation)

    # Evaluation on the dedicated device via the standard sharded evaluator
    # when the scenario has a JAX env (registry/suites); stateful backends
    # with no JAX twin (EnvPool Atari ids) evaluate on a factory pool instead
    # (reference: Sebulba evaluates EnvPool envs on factory envs).
    from stoix_tpu.envs.registry import make_single
    from stoix_tpu.envs.wrappers import RecordEpisodeMetrics
    from stoix_tpu.evaluator import get_stateful_evaluator_fn

    from stoix_tpu.envs import suites
    from stoix_tpu.envs.registry import ENV_REGISTRY

    scenario = (
        config.env.scenario.name
        if hasattr(config.env.scenario, "name")
        else config.env.scenario
    )
    suite = getattr(config.env, "env_name", None)
    has_jax_twin = scenario in ENV_REGISTRY or suite in suites.SUITE_MAKERS
    if has_jax_twin:
        # Genuine construction errors must surface — only the known
        # no-JAX-twin case (EnvPool/Gymnasium task ids) falls back.
        eval_env = RecordEpisodeMetrics(
            make_single(scenario, suite=suite, **dict(config.env.get("kwargs", {}) or {}))
        )
        eval_fn = get_ff_evaluator_fn(
            eval_env, get_distribution_act_fn(config, eval_apply), config, eval_mesh
        )
    else:
        eval_fn = get_stateful_evaluator_fn(
            env_factory, get_distribution_act_fn(config, eval_apply), config
        )

    logger = StoixLogger(config)
    # Ops plane (docs/DESIGN.md §2.13): StoixLogger's configure() just reset
    # the health monitor and flight recorder — and started the ops HTTP
    # server if `logger.telemetry.http.enabled` — so register THIS run's
    # identity, goodput ledger, and heartbeat board on the fresh instances.
    http_cfg = dict(dict(config.logger.get("telemetry") or {}).get("http") or {})
    ledger = goodput.GoodputLedger().start()
    goodput.set_active(ledger)
    recorder = flightrec.get_flight_recorder()
    recorder.set_context(
        architecture="sebulba",
        system=str(config.system.system_name),
        seed=int(config.arch.seed),
    )
    status = get_status_board()
    status.update(
        {
            "run_id": f"{config.system.system_name}_seed{config.arch.seed}",
            "architecture": "sebulba",
            "system": str(config.system.system_name),
            "step": 0,
        }
    )
    lifetime = ThreadLifetime()
    # Fleet coordination (docs/DESIGN.md §2.6, arch.fleet): in a multi-host
    # Sebulba deployment the learner loop exchanges window-indexed stop votes
    # through the jax.distributed KV store (there is no coalesced device
    # fetch to piggyback on here), publishes heartbeats, and fails collects
    # fast on a declared partition. Off (default) = None = unchanged loop.
    fleet_coord = fleet.fleet_from_config(config)
    if fleet_coord is not None:
        fleet_coord.start()
    if impact is None:
        pipeline = OnPolicyPipeline(num_actors, fleet=fleet_coord)
    else:
        # Push/poll ingestion: a slow actor no longer gates every update —
        # the learner re-steps buffered stale batches instead (ImpactIngest).
        pipeline = OffPolicyPipeline(num_actors, fleet=fleet_coord)
    # One heartbeat board for the whole run: actor beats come from the
    # pipeline, param-server and evaluator beats land on the same board so
    # the stall detector sees every component's age — and /healthz reads the
    # same board through the process-wide health monitor.
    monitor = get_health_monitor()
    monitor.register_board(
        "sebulba-pipeline",
        pipeline.heartbeats,
        stale_after_s=float(http_cfg.get("stale_after_s", 60.0) or 60.0),
    )
    param_server = ParameterServer(
        actor_devices, actors_per_device, heartbeats=pipeline.heartbeats
    )
    metrics_sink: "queue.Queue" = queue.Queue()

    eval_results: List[float] = []

    def on_eval_result(metrics, params_used, t):
        logger.log(metrics, t, len(eval_results), LogEvent.EVAL)
        eval_results.append(float(jnp.mean(metrics["episode_return"])))

    async_evaluator = AsyncEvaluator(
        eval_fn, lifetime, on_eval_result, heartbeats=pipeline.heartbeats
    )
    async_evaluator.thread.start()

    param_server.distribute_params((params, obs_stats))

    # Actor threads are owned by the supervisor (arch.supervision, on by
    # default): a crashed actor is respawned from its factory — fresh thread,
    # fresh env instance, re-primed params — with bounded backoff; past the
    # restart budget (or on a heartbeat wedge) a ComponentFailure poison-pill
    # makes the learner fail fast instead of burning the collect timeout.
    supervisor = supervisor_from_config(config, lifetime, pipeline, param_server)
    actor_threads: List[threading.Thread] = []

    def _actor_factory(actor_id: int, device) -> Callable[[], threading.Thread]:
        def make() -> threading.Thread:
            return threading.Thread(
                target=rollout_thread,
                args=(
                    actor_id, device, env_factory, actor.apply, critic.apply,
                    config, pipeline, param_server, learner_devices, learner_mesh,
                    lifetime, int(config.arch.seed) + 7919 * actor_id, metrics_sink,
                    supervisor,
                ),
                name=f"actor-{actor_id}",
                daemon=True,
            )

        return make

    for d_idx, device in enumerate(actor_devices):
        for a_idx in range(actors_per_device):
            actor_id = d_idx * actors_per_device + a_idx
            factory = _actor_factory(actor_id, device)
            if supervisor is not None:
                supervisor.register(actor_id, factory)
            else:
                t = factory()
                t.start()
                actor_threads.append(t)
    if supervisor is not None:
        supervisor.start_watchdog(pipeline.heartbeats)

    # Graceful preemption: SIGTERM/SIGINT stop the learner loop at the next
    # update boundary and run the orderly shutdown path (lifetime stop, queue
    # drain, evaluator drain) instead of dying mid-handoff.
    preempt = PreemptionHandler().install()

    timer = TimingTracker()

    def _assemble_batch(payloads):
        # Per learner device: concat all payloads' shards, then build one
        # global array per leaf. The shards are [T, E/n] slices of the ENV
        # axis, so they tile array_axis=1 — assembling on the leading axis
        # would stack trajectories along TIME and let GAE bootstrap across
        # the device seam. (IMPACT note: any num_actors payloads tile to the
        # same global shape, so fresh and reused batches share one compile.)
        def to_global(*leaves):
            per_device = []
            for d in range(len(learner_devices)):
                shards = [leaf[d] for leaf in leaves]
                with jax.default_device(learner_devices[d]):
                    per_device.append(jnp.concatenate(shards, axis=1))
            return assemble_global_array(
                per_device, learner_mesh, axis="data", array_axis=1
            ) if len(per_device) > 1 else per_device[0]

        # leaves are lists of per-device arrays; traverse manually.
        flat_payloads = [jax.tree.flatten(p, is_leaf=lambda x: isinstance(x, list))
                         for p in payloads]
        treedef = flat_payloads[0][1]
        merged_leaves = [
            to_global(*(fp[0][i] for fp in flat_payloads))
            for i in range(len(flat_payloads[0][0]))
        ]
        return jax.tree.unflatten(treedef, merged_leaves)

    impact_ingest = None
    impact_stats = None
    target_params = None
    if impact is not None:
        impact_ingest = ImpactIngest(pipeline, num_actors, impact)
        # Target network = device-side alias of a recent online version,
        # refreshed on the host every target_update_interval updates.
        target_params = learner_state.params
        impact_staleness_gauge = get_registry().gauge(
            "stoix_tpu_impact_batch_staleness",
            "Param-version lag (learner version minus behavior version) of "
            "the batch consumed by the most recent IMPACT update",
        )
        impact_refreshes = get_registry().counter(
            "stoix_tpu_impact_target_refreshes_total",
            "IMPACT target-network refreshes from the online params",
        )
        impact_stats = {
            "updates": 0, "fresh_updates": 0, "reused_updates": 0,
            "staleness_sum": 0, "max_staleness_seen": 0, "target_refreshes": 0,
        }

    t_steps = 0
    skipped_base = guards.skipped_counter().value()
    steady_start_time = None  # set after the first eval block (post-compile)
    steady_start_steps = 0
    run_start_time = time.perf_counter()  # whole-run FPS denominator (incl.
    # first-rollout compile — the number a fleet scheduler actually gets)
    fleet_window_started = time.perf_counter()
    try:
        for update_idx in range(int(config.arch.num_updates)):
            fresh = True
            if impact_ingest is None:
                with timer.time("rollout_get"):
                    payloads = pipeline.collect_rollouts()
                ledger.note(
                    goodput.SEBULBA_PHASE_MAP["rollout_get"],
                    timer.latest("rollout_get"),
                )
                with span("learner_assemble", update=update_idx), timer.time("assemble"):
                    batch = _assemble_batch(payloads)
                ledger.note(
                    goodput.SEBULBA_PHASE_MAP["assemble"], timer.latest("assemble")
                )
            else:
                with span("impact_next_batch", update=update_idx), timer.time("rollout_get"):
                    got = impact_ingest.next_batch(
                        _assemble_batch, param_server.version
                    )
                ledger.note(
                    goodput.SEBULBA_PHASE_MAP["rollout_get"],
                    timer.latest("rollout_get"),
                )
                batch, fresh = got.batch, got.fresh
                # First-class staleness: the learner's current version (=
                # completed distributes, i.e. the params it just trained)
                # minus the OLDEST behavior version in the batch; grows on
                # every re-step of the same buffered batch.
                staleness = param_server.version - got.behavior_version
                impact_staleness_gauge.set(staleness)
                impact_stats["updates"] += 1
                impact_stats["fresh_updates" if fresh else "reused_updates"] += 1
                impact_stats["staleness_sum"] += staleness
                impact_stats["max_staleness_seen"] = max(
                    impact_stats["max_staleness_seen"], staleness
                )

            with span("learner_update", update=update_idx), timer.time("learn"):
                if impact_ingest is None:
                    learner_state, train_metrics = learn_step(learner_state, batch)
                else:
                    learner_state, train_metrics = learn_step(
                        learner_state, target_params, batch
                    )
                jax.block_until_ready(train_metrics)
            ledger.note(goodput.SEBULBA_PHASE_MAP["learn"], timer.latest("learn"))
            param_server.distribute_params(
                (learner_state.params, learner_state.obs_stats)
            )
            if impact_ingest is not None:
                if impact_stats["updates"] % impact.target_update_interval == 0:
                    target_params = learner_state.params
                    impact_stats["target_refreshes"] += 1
                    impact_refreshes.inc()
            if fresh:
                # Re-stepping a buffered batch consumes no NEW env frames:
                # t_steps stays an env-frame count (fps denominators, eval
                # t axis) rather than a gradient-step count.
                t_steps += steps_per_update
            # Divergence guard, host half: count skipped updates; halt mode
            # raises DivergenceError here (metrics are already materialized
            # by the block_until_ready above — no extra sync).
            guards.publish_guard_metrics(guard_mode, train_metrics, t_steps)
            if fleet_coord is None:
                if preempt.stop_requested():
                    preempt.acknowledge(t_steps)
                    break
            else:
                # Fleet mode: never stop alone. The local preemption flag
                # becomes this host's vote at the next eval-window boundary
                # (below), so every host drains at the SAME window; a peer
                # partition declared by the monitor raises the typed error
                # here instead of wedging a future collective.
                fleet_coord.check_partition()
                if preempt.stop_requested():
                    fleet_coord.request_stop(
                        fleet.FLAG_PREEMPT,
                        note=f"{preempt.signal_name} at update {update_idx}",
                    )

            if (update_idx + 1) % int(config.arch.num_updates_per_eval) == 0:
                # Drain actor metrics and log.
                ep_returns, timings = [], {}
                while not metrics_sink.empty():
                    m = metrics_sink.get_nowait()
                    em = m["episode_metrics"]
                    mask = em["is_terminal_step"].reshape(-1)
                    if mask.any():
                        ep_returns.extend(em["episode_return"].reshape(-1)[mask].tolist())
                    timings.update(m["timings"])
                if ep_returns:
                    logger.log({"episode_return": np.asarray(ep_returns)}, t_steps,
                               update_idx, LogEvent.ACT)
                logger.log(jax.tree.map(lambda x: jnp.mean(x), train_metrics),
                           t_steps, update_idx, LogEvent.TRAIN)
                logger.log(
                    {
                        **timings,
                        **timer.all_means(prefix="learner_"),
                        **timer.all_percentiles(prefix="learner_"),
                    },
                    t_steps, update_idx, LogEvent.MISC,
                )
                key, ek = jax.random.split(key)
                if normalize_obs:
                    eval_payload = (
                        learner_state.params.actor_params, learner_state.obs_stats
                    )
                else:
                    eval_payload = learner_state.params.actor_params
                eval_params = jax.device_put(
                    jax.tree.map(np.asarray, eval_payload), evaluator_device
                )
                async_evaluator.submit(eval_params, ek, t_steps)
                if steady_start_time is None:
                    # Steady-state SPS window opens once compile/warmup has
                    # been paid (end of the first eval block).
                    steady_start_time = time.perf_counter()
                    steady_start_steps = t_steps
                window_idx = (update_idx + 1) // int(config.arch.num_updates_per_eval)
                status.update({"window": window_idx, "step": t_steps})
                recorder.record(
                    "window", window=window_idx, step=t_steps,
                    updates=update_idx + 1,
                    queue_wait_s=round(timer.mean("rollout_get"), 6),
                    learn_s=round(timer.mean("learn"), 6),
                )
                corruption = None
                if sentinel is not None:
                    # Integrity check at the eval boundary (docs/DESIGN.md
                    # §2.9): synchronous fingerprint + compare of the
                    # replicated learner state. A verdict becomes this
                    # host's FLAG_CORRUPT on the window's fleet vote (so the
                    # stop reason is agreed and visible fleet-wide) and is
                    # raised below — never swallowed by the agreed break.
                    corruption = sentinel.check_state(
                        learner_state, window_idx, t_steps
                    )
                    if corruption is not None and fleet_coord is not None:
                        fleet_coord.request_stop(
                            fleet.FLAG_CORRUPT, note=str(corruption)
                        )
                if fleet_coord is not None:
                    # Window-boundary agreement: exchange stop votes for THIS
                    # window through the KV store — identical decision on
                    # every host, so all drain together — and swap straggler
                    # wall-times for the skew gauges.
                    now = time.perf_counter()
                    fleet_coord.observe_window_wall(
                        window_idx, now - fleet_window_started
                    )
                    fleet_window_started = now
                    decision = fleet_coord.agree_at_window(window_idx)
                    if decision.stop:
                        if corruption is not None:
                            raise corruption
                        if preempt.stop_requested():
                            preempt.acknowledge(t_steps)
                        else:
                            get_logger("stoix_tpu.sebulba").warning(
                                "[fleet] %s — stopping at window %d in "
                                "lockstep with the fleet",
                                decision.describe(), window_idx,
                            )
                        break
                if corruption is not None:
                    raise corruption
        # Close the window BEFORE shutdown: thread joins / evaluator drain in
        # the finally block below can take tens of seconds and must not
        # deflate the steady-state number.
        steady_end_time = time.perf_counter()
    except KeyboardInterrupt:
        # The fleet monitor interrupts the main thread when a peer dies (it
        # may be blocked in collect_rollouts' bounded get). Convert its
        # interrupt into the typed error — the excepthook then translates it
        # to EXIT_CODE_FLEET_PARTITION for the supervising launcher, exactly
        # as in the Anakin runner. A genuine operator ^C re-raises untouched.
        if fleet_coord is not None and fleet_coord.partition_event.is_set():
            raise fleet_coord.partition_error from None
        raise
    finally:
        preempt.uninstall()
        goodput.set_active(None)
        monitor.unregister("sebulba-pipeline")
        if sentinel is not None:
            # BEFORE fleet stop: the excepthook chain unwinds in reverse
            # install order. Keeps the hook across a propagating corruption
            # verdict (it must still translate to exit code 88).
            sentinel.deactivate()
        if fleet_coord is not None:
            fleet_coord.stop()
        lifetime.stop()
        param_server.shutdown()
        # Unblock actors waiting to enqueue (uninstrumented: drain gets are
        # teardown artifacts and must not pollute the queue-wait series).
        for _ in range(2):
            if pipeline.drain(timeout=0.5) == 0:
                break
        if supervisor is not None:
            supervisor.join_all(timeout=10.0)
        for t in actor_threads:
            t.join(timeout=10.0)
        # Capture BEFORE our own try: inside the except block sys.exc_info()
        # would report the stall error itself, not the failure (if any) that
        # brought us into this finally.
        failure_propagating = sys.exc_info()[0] is not None
        try:
            async_evaluator.wait_until_idle(timeout=120.0)
        except EvaluatorStallError:
            # Raising from a finally would REPLACE the failure that brought
            # us here (actor ComponentFailure, learner divergence); surface
            # the stall as the primary error only on the clean-exit path.
            if not failure_propagating:
                raise
            get_logger("stoix_tpu.sebulba").error(
                "[shutdown] evaluator still busy while handling another "
                "failure — dropping its in-flight work"
            )

    if steady_start_time is not None and t_steps > steady_start_steps:
        steady = (t_steps - steady_start_steps) / (
            steady_end_time - steady_start_time
        )
        get_registry().gauge(
            "stoix_tpu_sebulba_steps_per_sec_steady",
            "Post-compile steady-state env-steps/sec of the most recent run",
        ).set(steady)
        LAST_RUN_STATS["steps_per_sec_steady"] = steady
        LAST_RUN_STATS["steady_window_steps"] = t_steps - steady_start_steps
    if t_steps > 0:
        # Whole-run env frames per second (ROADMAP item-1 leftover): total
        # env steps over the full learner-loop wall INCLUDING first-rollout
        # compile — the steady number above excludes it by design; this one
        # is what a scheduler provisioning actor fleets observes. First-class
        # in the bench --sebulba payload as `fps` (+ rep dispersion).
        fps = t_steps / max(steady_end_time - run_start_time, 1e-9)
        get_registry().gauge(
            "stoix_tpu_sebulba_fps",
            "Whole-run env-steps/sec (incl. compile) of the most recent run",
        ).set(fps)
        LAST_RUN_STATS["fps"] = fps
        LAST_RUN_STATS["total_env_steps"] = t_steps
    # Goodput close-out (docs/DESIGN.md §2.13): queue_wait/compute were noted
    # per update; finalize() attributes the residual learner-loop wall (host
    # work concurrent with actor rollouts, teardown joins) to compute per the
    # pipelined-residual rule, so the fractions sum to 1.
    LAST_RUN_STATS["goodput"] = ledger.finalize()
    # None when disabled (the pin tests/test_impact.py asserts): the default
    # config must report the untouched on-policy path, not a zeroed dict.
    LAST_RUN_STATS["impact"] = None if impact is None else {
        "rho_clip": impact.rho_clip,
        "target_update_interval": impact.target_update_interval,
        "max_staleness": impact.max_staleness,
        "max_reuse": impact.max_reuse,
        "updates": impact_stats["updates"],
        "fresh_updates": impact_stats["fresh_updates"],
        "reused_updates": impact_stats["reused_updates"],
        "mean_staleness": (
            impact_stats["staleness_sum"] / max(1, impact_stats["updates"])
        ),
        "max_staleness_seen": impact_stats["max_staleness_seen"],
        "target_refreshes": impact_stats["target_refreshes"],
    }
    LAST_RUN_STATS["resilience"] = {
        "update_guard": guard_mode,
        "skipped_updates": guards.skipped_counter().value() - skipped_base,
        "actor_restarts": supervisor.restart_count() if supervisor is not None else 0,
        "preempted": preempt.stop_requested(),
        # Sebulba has no checkpoint path yet: a preemption stops cleanly but
        # cannot resume mid-run.
        "resume_capable": False,
        "fleet": fleet_coord is not None,
    }
    LAST_RUN_STATS["integrity"] = (
        sentinel.stats() if sentinel is not None else integrity.disabled_stats()
    )

    logger.close()
    return eval_results[-1] if eval_results else 0.0


def main() -> float:
    import sys

    config = config_lib.compose(
        config_lib.default_config_dir(),
        "default/sebulba/default_ff_ppo.yaml",
        sys.argv[1:],
    )
    return run_experiment(config)


if __name__ == "__main__":
    main()
