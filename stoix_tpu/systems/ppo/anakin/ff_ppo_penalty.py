"""Anakin PPO-penalty (reference stoix/systems/ppo/anakin/ff_ppo_penalty.py,
602 LoC): KL-penalty surrogate instead of clipping (reference loss.py:35).
The KL to the behavior policy is the ANALYTIC full-distribution divergence
(recomputed from the pre-epoch params, the reference's form); heads without
a closed form fall back to the (ratio - 1 - log ratio) k3 estimator.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp

from stoix_tpu.ops import losses
from stoix_tpu.systems.ppo.anakin.ff_ppo import learner_setup as _ppo_learner_setup
from stoix_tpu.systems.runner import run_anakin_experiment
from stoix_tpu.utils import config as config_lib


def penalty_policy_loss(dist, action, old_log_prob, gae, config, behavior_dist=None,
                        beta=None):
    log_prob = dist.log_prob(action)
    kl = None
    if behavior_dist is not None:
        # Analytic full-distribution KL(behavior - current), the reference's
        # form (reference loss.py:44): exact and LOW-variance when the
        # distributions are close — the sampled k3 estimator's variance
        # explodes exactly as the policy sharpens, which stalled refinement.
        try:
            kl = behavior_dist.kl_divergence(dist)
        except NotImplementedError:  # continuous heads: no closed form
            kl = None
    if kl is None:
        log_ratio = jnp.clip(  # finite guard, same bound as the surrogates
            log_prob - old_log_prob, -losses._LOG_RATIO_CLAMP, losses._LOG_RATIO_CLAMP
        )
        kl = jnp.exp(log_ratio) - 1.0 - log_ratio  # k3 estimator, >= 0
    if beta is None:
        beta = float(config.system.get("kl_beta", 3.0))
    loss = losses.ppo_penalty_loss(log_prob, old_log_prob, gae, beta, kl)
    return loss, dist.entropy().mean()


# Marks the loss as consuming the kl_beta learner-state scalar, which gates
# system.adaptive_kl_beta (ff_ppo.get_learner_fn rejects the flag otherwise).
penalty_policy_loss.uses_kl_beta = True


def learner_setup(env, config, mesh, key):
    return _ppo_learner_setup(env, config, mesh, key, policy_loss_fn=penalty_policy_loss)


def run_experiment(config: Any) -> float:
    return run_anakin_experiment(config, learner_setup)


def main() -> float:
    import sys

    config = config_lib.compose(
        config_lib.default_config_dir(),
        "default/anakin/default_ff_ppo_penalty.yaml",
        sys.argv[1:],
    )
    return run_experiment(config)


if __name__ == "__main__":
    main()
