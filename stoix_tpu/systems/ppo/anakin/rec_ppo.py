"""Anakin Recurrent PPO (reference stoix/systems/ppo/anakin/rec_ppo.py, 769 LoC).

Distinctives preserved: time-major RNN unroll via ScannedRNN with per-step
hidden reset on done|truncated (reference rec_ppo.py:90-94), hidden states
stored in the trajectory so minibatches can re-unroll from true initial
carries, minibatching shuffles over ENVS (keeping time contiguous, reference
rec_ppo minibatch scheme), truncation-aware GAE from per-step bootstrap values.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

from stoix_tpu import envs
from stoix_tpu.base_types import (
    ActorCriticOptStates,
    ActorCriticParams,
    ExperimentOutput,
    RNNLearnerState,
)
from stoix_tpu.ops import (
    losses,
    running_statistics,
    truncated_generalized_advantage_estimation,
)
from stoix_tpu.systems import anakin
from stoix_tpu.systems.runner import AnakinSetup
from stoix_tpu.utils import config as config_lib
from stoix_tpu.utils.training import make_learning_rate


class RNNPPOTransition(NamedTuple):
    done: jax.Array
    truncated: jax.Array
    entering_done: jax.Array  # reset flag fed to the RNN at this step
    action: jax.Array
    value: jax.Array
    reward: jax.Array
    bootstrap_value: jax.Array
    log_prob: jax.Array
    obs: Any
    hstates: Tuple[Any, Any]  # (actor, critic) carries at the START of the step
    info: Dict[str, Any]


def get_learner_fn(env, apply_fns, update_fns, config):
    actor_apply, critic_apply = apply_fns
    actor_update, critic_update = update_fns
    gamma = float(config.system.gamma)
    normalize_obs = bool(config.system.get("normalize_observations", False))

    def _maybe_normalize(observation, obs_stats):
        if not normalize_obs:
            return observation
        return running_statistics.normalize_observation(observation, obs_stats)

    def _env_step(learner_state: RNNLearnerState, _):
        (params, opt_states, key, env_state, last_timestep, done, truncated,
         hstates, obs_stats) = learner_state
        key, policy_key = jax.random.split(key)
        actor_hstate, critic_hstate = hstates

        # Single-step time-major unroll: [1, E, ...]. Hidden states reset on
        # done OR truncation (both start a fresh episode).
        reset_flag = jnp.logical_or(done, truncated)
        observation = _maybe_normalize(last_timestep.observation, obs_stats)
        obs_t = jax.tree.map(lambda x: x[None], observation)
        done_t = reset_flag[None]
        new_actor_hstate, dist = actor_apply(params.actor_params, actor_hstate, (obs_t, done_t))
        new_critic_hstate, value = critic_apply(
            params.critic_params, critic_hstate, (obs_t, done_t)
        )
        action = dist.sample(seed=policy_key)
        log_prob = dist.log_prob(action)

        env_state, timestep = env.step(env_state, action[0])
        next_done = timestep.discount == 0.0
        next_trunc = jnp.logical_and(timestep.last(), timestep.discount != 0.0)

        # Bootstrap value of the TRUE next obs using the post-step critic carry
        # (carry itself is not advanced by this evaluation).
        next_obs_t = jax.tree.map(
            lambda x: x[None], _maybe_normalize(timestep.extras["next_obs"], obs_stats)
        )
        _, bootstrap_value = critic_apply(
            params.critic_params, new_critic_hstate, (next_obs_t, jnp.zeros_like(done_t))
        )

        transition = RNNPPOTransition(
            done=next_done,
            truncated=next_trunc,
            entering_done=reset_flag,
            action=action[0],
            value=value[0],
            reward=timestep.reward,
            bootstrap_value=bootstrap_value[0],
            log_prob=log_prob[0],
            obs=last_timestep.observation,  # RAW; normalized at use
            hstates=(actor_hstate, critic_hstate),
            info=timestep.extras["episode_metrics"],
        )
        new_state = RNNLearnerState(
            params, opt_states, key, env_state, timestep, next_done, next_trunc,
            (new_actor_hstate, new_critic_hstate), obs_stats,
        )
        return new_state, transition

    def _actor_loss_fn(actor_params, traj: RNNPPOTransition, advantages):
        # Re-unroll from the stored initial carry with the SAME reset flags the
        # rollout fed the RNN (entering_done), so recomputed log-probs match
        # the behavior policy exactly.
        init_hstate = jax.tree.map(lambda x: x[0], traj.hstates[0])
        _, dist = actor_apply(actor_params, init_hstate, (traj.obs, traj.entering_done))
        log_prob = dist.log_prob(traj.action)
        loss_actor = losses.ppo_clip_loss(
            log_prob, traj.log_prob, advantages, float(config.system.clip_eps)
        )
        entropy = dist.entropy().mean()
        total = loss_actor - float(config.system.ent_coef) * entropy
        return total, (loss_actor, entropy)

    def _critic_loss_fn(critic_params, traj: RNNPPOTransition, targets):
        init_hstate = jax.tree.map(lambda x: x[0], traj.hstates[1])
        _, value = critic_apply(critic_params, init_hstate, (traj.obs, traj.entering_done))
        if config.system.get("clip_value", True):
            value_loss = losses.clipped_value_loss(
                value, traj.value, targets, float(config.system.clip_eps)
            )
        else:
            value_loss = jnp.mean((value - targets) ** 2)
        return float(config.system.vf_coef) * value_loss, value_loss

    def _update_minibatch(train_state: Tuple, batch_info: Tuple):
        params, opt_states = train_state
        traj_batch, advantages, targets = batch_info
        actor_grads, (loss_actor, entropy) = jax.grad(_actor_loss_fn, has_aux=True)(
            params.actor_params, traj_batch, advantages
        )
        critic_grads, value_loss = jax.grad(_critic_loss_fn, has_aux=True)(
            params.critic_params, traj_batch, targets
        )
        actor_grads, critic_grads = jax.lax.pmean(
            jax.lax.pmean((actor_grads, critic_grads), axis_name="batch"), axis_name="data"
        )
        a_updates, a_opt = actor_update(actor_grads, opt_states.actor_opt_state)
        c_updates, c_opt = critic_update(critic_grads, opt_states.critic_opt_state)
        params = ActorCriticParams(
            optax.apply_updates(params.actor_params, a_updates),
            optax.apply_updates(params.critic_params, c_updates),
        )
        loss_info = {
            "actor_loss": loss_actor,
            "value_loss": value_loss,
            "entropy": entropy,
        }
        return (params, ActorCriticOptStates(a_opt, c_opt)), loss_info

    def _update_epoch(update_state: Tuple, _):
        params, opt_states, traj, advantages, targets, key = update_state
        key, shuffle_key = jax.random.split(key)
        # Shuffle over ENV axis only; sequences stay time-contiguous.
        n_envs = advantages.shape[1]
        perm = jax.random.permutation(shuffle_key, n_envs)
        shuffled = jax.tree.map(lambda x: jnp.take(x, perm, axis=1), (traj, advantages, targets))
        minibatches = jax.tree.map(
            lambda x: jnp.stack(
                jnp.split(x, int(config.system.num_minibatches), axis=1)
            ),
            shuffled,
        )
        (params, opt_states), loss_info = jax.lax.scan(
            _update_minibatch, (params, opt_states), minibatches
        )
        return (params, opt_states, traj, advantages, targets, key), loss_info

    def _update_step(learner_state: RNNLearnerState, _):
        learner_state, traj = jax.lax.scan(
            _env_step, learner_state, None, int(config.system.rollout_length)
        )
        (params, opt_states, key, env_state, last_timestep, done, truncated,
         hstates, obs_stats) = learner_state
        # Trajectory obs are stored RAW; normalize with the PRE-update
        # statistics (identical to what the rollout's log_probs/values used so
        # the re-unrolls match the behavior policy exactly), then fold the raw
        # batch into the statistics.
        raw_obs = traj.obs
        traj = traj._replace(obs=_maybe_normalize(raw_obs, obs_stats))
        if normalize_obs:
            obs_stats = running_statistics.update(
                obs_stats, raw_obs.agent_view, axis_names=("batch", "data"),
                std_min_value=5e-4, std_max_value=5e4,
            )
        advantages, targets = truncated_generalized_advantage_estimation(
            traj.reward,
            gamma * (1.0 - traj.done.astype(jnp.float32)),
            float(config.system.gae_lambda),
            v_tm1=traj.value,
            v_t=traj.bootstrap_value,
            truncation_t=traj.truncated.astype(jnp.float32),
            standardize_advantages=bool(config.system.get("standardize_advantages", True)),
        )
        update_state = (params, opt_states, traj, advantages, targets, key)
        update_state, loss_info = jax.lax.scan(
            _update_epoch, update_state, None, int(config.system.epochs)
        )
        params, opt_states, _, _, _, key = update_state
        learner_state = RNNLearnerState(
            params, opt_states, key, env_state, last_timestep, done, truncated,
            hstates, obs_stats,
        )
        return learner_state, (traj.info, loss_info)

    def learner_fn(learner_state: RNNLearnerState) -> ExperimentOutput:
        key = learner_state.key[0]
        state = learner_state._replace(key=key)
        state, (episode_info, loss_info) = jax.lax.scan(
            jax.vmap(_update_step, axis_name="batch"),
            state, None, int(config.arch.num_updates_per_eval),
        )
        state = state._replace(key=state.key[None])
        loss_info = jax.lax.pmean(loss_info, axis_name="data")
        return ExperimentOutput(state, episode_info, loss_info)

    return learner_fn


def learner_setup(env: envs.Environment, config: Any, mesh: Mesh, key: jax.Array) -> AnakinSetup:
    from stoix_tpu.networks.base import (
        RecurrentActor,
        RecurrentCritic,
        ScannedRNN,
    )

    config.system.action_dim = env.num_actions
    net_cfg = config.network
    hidden_size = int(config.network.get("rnn_hidden_size", 128))
    cell_type = str(config.network.get("rnn_cell_type", "gru"))

    actor_network = RecurrentActor(
        action_head=config_lib.instantiate(
            net_cfg.actor_network.action_head,
            **anakin.head_kwargs_for_env(net_cfg.actor_network.action_head, env),
        ),
        rnn=ScannedRNN(hidden_size=hidden_size, cell_type=cell_type),
        pre_torso=config_lib.instantiate(net_cfg.actor_network.pre_torso),
        post_torso=config_lib.instantiate(net_cfg.actor_network.post_torso),
        input_layer=config_lib.instantiate(net_cfg.actor_network.input_layer),
    )
    critic_network = RecurrentCritic(
        critic_head=config_lib.instantiate(net_cfg.critic_network.critic_head),
        rnn=ScannedRNN(hidden_size=hidden_size, cell_type=cell_type),
        pre_torso=config_lib.instantiate(net_cfg.critic_network.pre_torso),
        post_torso=config_lib.instantiate(net_cfg.critic_network.post_torso),
        input_layer=config_lib.instantiate(net_cfg.critic_network.input_layer),
    )

    actor_optim = optax.chain(
        optax.clip_by_global_norm(float(config.system.max_grad_norm)),
        optax.adam(make_learning_rate(float(config.system.actor_lr), config,
                                      int(config.system.epochs),
                                      int(config.system.num_minibatches)), eps=1e-5),
    )
    critic_optim = optax.chain(
        optax.clip_by_global_norm(float(config.system.max_grad_norm)),
        optax.adam(make_learning_rate(float(config.system.critic_lr), config,
                                      int(config.system.epochs),
                                      int(config.system.num_minibatches)), eps=1e-5),
    )

    key, actor_key, critic_key, env_key = jax.random.split(key, 4)
    dummy_obs = jax.tree.map(lambda x: x[None, None], env.observation_value())  # [T=1, B=1]
    dummy_done = jnp.zeros((1, 1), bool)
    dummy_h = ScannedRNN.initialize_carry(cell_type, hidden_size, (1,))
    actor_params = actor_network.init(actor_key, dummy_h, (dummy_obs, dummy_done))
    critic_params = critic_network.init(critic_key, dummy_h, (dummy_obs, dummy_done))
    params = ActorCriticParams(actor_params, critic_params)
    opt_states = ActorCriticOptStates(
        actor_optim.init(actor_params), critic_optim.init(critic_params)
    )

    n_shards = int(mesh.shape["data"])
    update_batch = int(config.arch.get("update_batch_size", 1))
    envs_axis = int(config.arch.total_num_envs) // update_batch

    state_specs = RNNLearnerState(
        params=P(), opt_states=P(), key=P("data"),
        env_state=P(None, "data"), timestep=P(None, "data"),
        done=P(None, "data"), truncated=P(None, "data"),
        hstates=P(None, "data"), obs_stats=P(),
    )
    env_state, timestep = anakin.reset_envs_for_anakin(env, config, env_key)
    init_h = lambda: ScannedRNN.initialize_carry(cell_type, hidden_size, (update_batch, envs_axis))
    learner_state = RNNLearnerState(
        params=anakin.broadcast_to_update_batch(params, update_batch),
        opt_states=anakin.broadcast_to_update_batch(opt_states, update_batch),
        key=anakin.make_step_keys(key, mesh, config),
        env_state=env_state,
        timestep=timestep,
        done=jnp.zeros((update_batch, envs_axis), bool),
        truncated=jnp.zeros((update_batch, envs_axis), bool),
        hstates=(init_h(), init_h()),
        obs_stats=anakin.broadcast_to_update_batch(
            running_statistics.init_state(env.observation_value().agent_view),
            update_batch,
        ),
    )
    learner_state = anakin.place_learner_state(learner_state, mesh, state_specs)

    learn_per_shard = get_learner_fn(
        env, (actor_network.apply, critic_network.apply),
        (actor_optim.update, critic_optim.update), config,
    )
    learn = anakin.shardmap_learner(learn_per_shard, mesh, state_specs)

    normalize_obs = bool(config.system.get("normalize_observations", False))

    def rnn_act_fn(payload, hstate, observation, done, act_key):
        if normalize_obs:
            params, stats = payload
            observation = running_statistics.normalize_observation(observation, stats)
        else:
            params = payload
        obs_t = jax.tree.map(lambda x: x[None, None], observation)
        done_t = jnp.asarray(done).reshape(1, 1)
        hstate, dist = actor_network.apply(params, hstate, (obs_t, done_t))
        greedy = bool(config.arch.get("evaluation_greedy", False))
        action = dist.mode() if greedy else dist.sample(seed=act_key)
        return hstate, action[0, 0]

    if normalize_obs:
        eval_params_fn = lambda s: (
            anakin.unbatch_params(s.params.actor_params),
            anakin.unbatch_params(s.obs_stats),
        )
    else:
        eval_params_fn = lambda s: anakin.unbatch_params(s.params.actor_params)

    setup = AnakinSetup(
        learn=learn,
        learner_state=learner_state,
        eval_act_fn=rnn_act_fn,  # consumed by the RNN evaluator
        eval_params_fn=eval_params_fn,
    )
    return setup


def run_experiment(config: Any) -> float:
    from stoix_tpu.systems.runner import run_rnn_anakin_experiment

    return run_rnn_anakin_experiment(config, learner_setup)


def main() -> float:
    import sys

    config = config_lib.compose(
        config_lib.default_config_dir(), "default/anakin/default_rec_ppo.yaml", sys.argv[1:]
    )
    return run_experiment(config)


if __name__ == "__main__":
    main()
