"""Anakin DPO, continuous actions (reference
stoix/systems/ppo/anakin/ff_dpo_continuous.py, 603 LoC): drift-based surrogate
replacing the PPO clip (reference loss.py:50)."""

from __future__ import annotations

from typing import Any

from stoix_tpu.ops import losses
from stoix_tpu.systems.ppo.anakin.ff_ppo import learner_setup as _ppo_learner_setup
from stoix_tpu.systems.runner import run_anakin_experiment
from stoix_tpu.utils import config as config_lib


def dpo_policy_loss(dist, action, old_log_prob, gae, config, behavior_dist=None,
                    beta=None):
    del behavior_dist, beta  # DPO's drift uses the stored per-sample log-probs
    log_prob = dist.log_prob(action)
    loss = losses.dpo_loss(
        log_prob,
        old_log_prob,
        gae,
        float(config.system.get("dpo_alpha", 2.0)),
        float(config.system.get("dpo_beta", 0.6)),
    )
    return loss, dist.entropy().mean()


def learner_setup(env, config, mesh, key):
    return _ppo_learner_setup(env, config, mesh, key, policy_loss_fn=dpo_policy_loss)


def run_experiment(config: Any) -> float:
    return run_anakin_experiment(config, learner_setup)


def main() -> float:
    import sys

    config = config_lib.compose(
        config_lib.default_config_dir(),
        "default/anakin/default_ff_dpo_continuous.yaml",
        sys.argv[1:],
    )
    return run_experiment(config)


if __name__ == "__main__":
    main()
