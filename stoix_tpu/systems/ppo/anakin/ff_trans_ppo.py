"""Anakin Transformer-PPO — PPO with a causal attention context window.

The reference has no attention anywhere (SURVEY.md §5 long-context: RNN-only
sequence memory); this system is a TPU-native addition that makes the
transformer torso (networks/attention.py — Pallas flash attention on TPU) a
first-class policy: each env maintains a sliding window of its last W
observations, the actor/critic attend causally over the window and read the
final position, and the window clears at episode boundaries so attention
never crosses an auto-reset (generalized frame-stacking with attention in
place of concatenation).

Scaffolding (GAE, clip objective, epoch/minibatch scans, shard_map mesh
layout) mirrors the canonical ff_ppo template; transitions store the acting
window so training replays exactly what acting saw.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

from stoix_tpu import envs
from stoix_tpu.base_types import (
    ActorCriticOptStates,
    ActorCriticParams,
    ExperimentOutput,
)
from stoix_tpu.ops import losses, truncated_generalized_advantage_estimation
from stoix_tpu.systems import anakin
from stoix_tpu.systems.runner import AnakinSetup, run_anakin_experiment
from stoix_tpu.utils import config as config_lib
from stoix_tpu.utils.jax_utils import tree_merge_leading_dims
from stoix_tpu.utils.training import make_learning_rate


class TransPPOLearnerState(NamedTuple):
    params: Any
    opt_states: Any
    key: jax.Array
    env_state: Any
    timestep: Any
    window: jax.Array  # [E, W, F] past-observation context (zeros = padding)


class TransPPOTransition(NamedTuple):
    done: jax.Array
    truncated: jax.Array
    action: jax.Array
    value: jax.Array
    reward: jax.Array
    log_prob: jax.Array
    window: jax.Array  # [E, W, F] context the policy actually saw
    next_obs: jax.Array  # [E, F] true successor obs (bootstrap; the successor
    # CONTEXT is derived at update time — storing it would duplicate the
    # window tensor)
    info: Any


def _push(window: jax.Array, obs: jax.Array) -> jax.Array:
    """Slide the window one step: drop the oldest frame, append `obs` last."""
    return jnp.concatenate([window[:, 1:], obs[:, None]], axis=1)


def get_learner_fn(
    env: envs.Environment,
    apply_fns: Tuple[Callable, Callable],
    update_fns: Tuple[Callable, Callable],
    config: Any,
) -> Callable[[TransPPOLearnerState], ExperimentOutput]:
    actor_apply, critic_apply = apply_fns
    actor_update, critic_update = update_fns
    gamma = float(config.system.gamma)

    def _flat(view: jax.Array) -> jax.Array:
        return view.reshape((view.shape[0], -1))  # [E, F] (pixels flattened)

    def _env_step(learner_state: TransPPOLearnerState, _: Any):
        params, opt_states, key, env_state, last_timestep, window = learner_state
        key, policy_key = jax.random.split(key)

        ctx = _push(window, _flat(last_timestep.observation.agent_view))  # [E, W, F]
        actor_policy = actor_apply(params.actor_params, ctx)
        value = critic_apply(params.critic_params, ctx)
        action = actor_policy.sample(seed=policy_key)
        log_prob = actor_policy.log_prob(action)

        env_state, timestep = env.step(env_state, action)
        done = timestep.discount == 0.0
        truncated = jnp.logical_and(timestep.last(), timestep.discount != 0.0)

        # Episode boundary: clear the context so attention never spans an
        # auto-reset.
        new_window = jnp.where(timestep.last()[:, None, None], 0.0, ctx)

        transition = TransPPOTransition(
            done=done,
            truncated=truncated,
            action=action,
            value=value,
            reward=timestep.reward,
            log_prob=log_prob,
            window=ctx,
            next_obs=_flat(timestep.extras["next_obs"].agent_view),
            info=timestep.extras["episode_metrics"],
        )
        return (
            TransPPOLearnerState(
                params, opt_states, key, env_state, timestep, new_window
            ),
            transition,
        )

    def _actor_loss_fn(actor_params, window, action, old_log_prob, gae):
        actor_policy = actor_apply(actor_params, window)
        log_prob = actor_policy.log_prob(action)
        loss_actor = losses.ppo_clip_loss(
            log_prob, old_log_prob, gae, float(config.system.clip_eps)
        )
        entropy = actor_policy.entropy().mean()
        total = loss_actor - float(config.system.ent_coef) * entropy
        return total, (loss_actor, entropy)

    def _critic_loss_fn(critic_params, window, targets, old_value):
        value = critic_apply(critic_params, window)
        if config.system.get("clip_value", True):
            value_loss = losses.clipped_value_loss(
                value, old_value, targets, float(config.system.clip_eps)
            )
        else:
            value_loss = jnp.mean((value - targets) ** 2)
        return float(config.system.vf_coef) * value_loss, value_loss

    def _update_minibatch(train_state: Tuple, batch_info: Tuple):
        params, opt_states = train_state
        traj_batch, advantages, targets = batch_info

        actor_grads, (loss_actor, entropy) = jax.grad(_actor_loss_fn, has_aux=True)(
            params.actor_params,
            traj_batch.window,
            traj_batch.action,
            traj_batch.log_prob,
            advantages,
        )
        critic_grads, value_loss = jax.grad(_critic_loss_fn, has_aux=True)(
            params.critic_params, traj_batch.window, targets, traj_batch.value
        )
        actor_grads, critic_grads = jax.lax.pmean(
            jax.lax.pmean((actor_grads, critic_grads), axis_name="batch"),
            axis_name="data",
        )
        actor_updates, actor_opt_state = actor_update(
            actor_grads, opt_states.actor_opt_state
        )
        critic_updates, critic_opt_state = critic_update(
            critic_grads, opt_states.critic_opt_state
        )
        params = ActorCriticParams(
            optax.apply_updates(params.actor_params, actor_updates),
            optax.apply_updates(params.critic_params, critic_updates),
        )
        loss_info = {
            "actor_loss": loss_actor,
            "value_loss": value_loss,
            "entropy": entropy,
        }
        return (params, ActorCriticOptStates(actor_opt_state, critic_opt_state)), loss_info

    def _update_epoch(update_state: Tuple, _: Any):
        params, opt_states, traj_batch, advantages, targets, key = update_state
        key, shuffle_key = jax.random.split(key)
        batch_size = advantages.shape[0] * advantages.shape[1]
        permutation = jax.random.permutation(shuffle_key, batch_size)
        flat = tree_merge_leading_dims((traj_batch, advantages, targets), 2)
        shuffled = jax.tree.map(lambda x: jnp.take(x, permutation, axis=0), flat)
        minibatches = jax.tree.map(
            lambda x: x.reshape(
                (int(config.system.num_minibatches), -1) + x.shape[1:]
            ),
            shuffled,
        )
        (params, opt_states), loss_info = jax.lax.scan(
            _update_minibatch, (params, opt_states), minibatches
        )
        return (params, opt_states, traj_batch, advantages, targets, key), loss_info

    def _update_step(learner_state: TransPPOLearnerState, _: Any):
        learner_state, traj_batch = jax.lax.scan(
            _env_step, learner_state, None, int(config.system.rollout_length)
        )
        params, opt_states, key, env_state, last_timestep, window = learner_state

        # Successor contexts for the bootstrap, derived in one shot from the
        # stored windows (true next obs pushed onto each acting context —
        # valid across truncation; terminal values die via discount 0), then
        # one batched critic apply.
        next_windows = jnp.concatenate(
            [traj_batch.window[:, :, 1:], traj_batch.next_obs[:, :, None]], axis=2
        )
        v_t = critic_apply(params.critic_params, next_windows)
        d_t = gamma * (1.0 - traj_batch.done.astype(jnp.float32))
        advantages, targets = truncated_generalized_advantage_estimation(
            traj_batch.reward,
            d_t,
            float(config.system.gae_lambda),
            v_tm1=traj_batch.value,
            v_t=v_t,
            truncation_t=traj_batch.truncated.astype(jnp.float32),
            standardize_advantages=bool(
                config.system.get("standardize_advantages", True)
            ),
        )

        update_state = (params, opt_states, traj_batch, advantages, targets, key)
        update_state, loss_info = jax.lax.scan(
            _update_epoch, update_state, None, int(config.system.epochs)
        )
        params, opt_states, _, _, _, key = update_state
        learner_state = TransPPOLearnerState(
            params, opt_states, key, env_state, last_timestep, window
        )
        return learner_state, (traj_batch.info, loss_info)

    def learner_fn(learner_state: TransPPOLearnerState) -> ExperimentOutput:
        key = learner_state.key[0]
        state = learner_state._replace(key=key)
        state, (episode_info, loss_info) = jax.lax.scan(
            jax.vmap(_update_step, axis_name="batch"),
            state, None, int(config.arch.num_updates_per_eval),
        )
        state = state._replace(key=state.key[None])
        loss_info = jax.lax.pmean(loss_info, axis_name="data")
        return ExperimentOutput(state, episode_info, loss_info)

    return learner_fn


def learner_setup(env: envs.Environment, config: Any, mesh: Mesh, key: jax.Array) -> AnakinSetup:
    import flax.linen as nn

    from stoix_tpu.networks import heads as heads_lib
    from stoix_tpu.networks.attention import TransformerTorso

    config.system.action_dim = env.num_actions
    num_actions = env.num_actions
    window = int(config.system.get("window_length", 16))
    num_layers = int(config.system.get("num_layers", 2))
    num_heads = int(config.system.get("num_heads", 4))
    head_dim = int(config.system.get("head_dim", 32))
    ffn_dim = int(config.system.get("ffn_dim", 256))

    def make_torso():
        return TransformerTorso(
            num_layers=num_layers,
            num_heads=num_heads,
            head_dim=head_dim,
            ffn_dim=ffn_dim,
            max_timesteps=window,
        )

    class WindowActor(nn.Module):
        @nn.compact
        def __call__(self, ctx):  # [..., W, F]
            x = make_torso()(ctx.reshape((-1,) + ctx.shape[-2:]))
            x = x[:, -1].reshape(ctx.shape[:-2] + (x.shape[-1],))
            return heads_lib.CategoricalHead(num_actions=num_actions)(x)

    class WindowCritic(nn.Module):
        @nn.compact
        def __call__(self, ctx):
            x = make_torso()(ctx.reshape((-1,) + ctx.shape[-2:]))
            x = x[:, -1].reshape(ctx.shape[:-2] + (x.shape[-1],))
            return heads_lib.ScalarCriticHead()(x)

    actor_network, critic_network = WindowActor(), WindowCritic()

    actor_optim = optax.chain(
        optax.clip_by_global_norm(float(config.system.max_grad_norm)),
        optax.adam(make_learning_rate(float(config.system.actor_lr), config,
                                      int(config.system.epochs),
                                      int(config.system.num_minibatches)), eps=1e-5),
    )
    critic_optim = optax.chain(
        optax.clip_by_global_norm(float(config.system.max_grad_norm)),
        optax.adam(make_learning_rate(float(config.system.critic_lr), config,
                                      int(config.system.epochs),
                                      int(config.system.num_minibatches)), eps=1e-5),
    )

    key, actor_key, critic_key, env_key = jax.random.split(key, 4)
    feat = int(env.observation_value().agent_view.reshape(-1).shape[0])
    dummy_ctx = jnp.zeros((1, window, feat))
    actor_params = actor_network.init(actor_key, dummy_ctx)
    critic_params = critic_network.init(critic_key, dummy_ctx)
    params = ActorCriticParams(actor_params, critic_params)
    opt_states = ActorCriticOptStates(
        actor_optim.init(actor_params), critic_optim.init(critic_params)
    )

    update_batch = int(config.arch.get("update_batch_size", 1))
    state_specs = TransPPOLearnerState(
        params=P(), opt_states=P(), key=P("data"),
        env_state=P(None, "data"), timestep=P(None, "data"),
        window=P(None, "data"),
    )
    env_state, timestep = anakin.reset_envs_for_anakin(env, config, env_key)
    envs_total = timestep.reward.shape[1]
    learner_state = TransPPOLearnerState(
        params=anakin.broadcast_to_update_batch(params, update_batch),
        opt_states=anakin.broadcast_to_update_batch(opt_states, update_batch),
        key=anakin.make_step_keys(key, mesh, config),
        env_state=env_state,
        timestep=timestep,
        window=jnp.zeros((update_batch, envs_total, window, feat)),
    )
    learner_state = anakin.place_learner_state(learner_state, mesh, state_specs)

    learn_per_shard = get_learner_fn(
        env, (actor_network.apply, critic_network.apply),
        (actor_optim.update, critic_optim.update), config,
    )
    learn = anakin.shardmap_learner(learn_per_shard, mesh, state_specs)

    # Evaluator: the context window plays the RNN evaluator's hidden-state
    # role — carried across eval steps, cleared on done (rnn_act_fn
    # signature, runner wires get_rnn_evaluator_fn via evaluator_setup_fn).
    def window_act_fn(p, ctx_state, observation, done, act_key):
        flat = observation.agent_view.reshape(-1)[None]  # [1, F]
        ctx_state = jnp.where(jnp.asarray(done), 0.0, ctx_state)
        ctx_state = _push(ctx_state, flat)  # [1, W, F]
        dist = actor_network.apply(p, ctx_state)
        greedy = bool(config.arch.get("evaluation_greedy", False))
        action = dist.mode() if greedy else dist.sample(seed=act_key)
        return ctx_state, action[0]

    return AnakinSetup(
        learn=learn,
        learner_state=learner_state,
        eval_act_fn=window_act_fn,
        eval_params_fn=lambda s: anakin.unbatch_params(s.params.actor_params),
    )


def run_experiment(config: Any) -> float:
    from stoix_tpu.evaluator import get_rnn_evaluator_fn

    window = int(config.system.get("window_length", 16))

    def evaluator_setup(eval_env, act_fn, cfg, mesh):
        feat = int(eval_env.observation_value().agent_view.reshape(-1).shape[0])
        init_h = lambda: jnp.zeros((1, window, feat))
        evaluator = get_rnn_evaluator_fn(eval_env, act_fn, cfg, mesh, init_h)
        absolute = get_rnn_evaluator_fn(
            eval_env, act_fn, cfg, mesh, init_h,
            eval_multiplier=int(cfg.arch.get("absolute_metric_multiplier", 10)),
        )
        return evaluator, absolute

    return run_anakin_experiment(config, learner_setup, evaluator_setup_fn=evaluator_setup)


def main() -> float:
    import sys

    config = config_lib.compose(
        config_lib.default_config_dir(),
        "default/anakin/default_ff_trans_ppo.yaml",
        sys.argv[1:],
    )
    return run_experiment(config)


if __name__ == "__main__":
    main()
