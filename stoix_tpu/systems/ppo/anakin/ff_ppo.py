"""Anakin PPO (discrete) — THE canonical system template.

Behavioral parity: reference stoix/systems/ppo/anakin/ff_ppo.py (731 LoC) —
single-file layout with get_learner_fn / learner_setup / run_experiment /
entry point, truncation-aware GAE from per-step bootstrap values
(reference ff_ppo.py:96-179), epoch/minibatch SGD scans (:296-334), optional
observation normalization (:90-94,145-162).

TPU-native redesign (SURVEY.md §7.1):
  - ONE global `jax.sharding.Mesh` ("data" axis) replaces
    pmap(axis="device") + replicate/unreplicate. The learner step is written
    per-shard and wrapped in `jax.shard_map`; gradient sync is an explicit
    `lax.pmean` over ("batch", "data") riding ICI/DCN.
  - `arch.update_batch_size` (U) is an in-shard vmap with axis_name "batch"
    (reference's nested vmap, ff_ppo.py:361), params carrying a leading [U]
    axis that stays replicated across the mesh.
  - Bootstrap values for extras["next_obs"] are computed in ONE batched
    critic apply over the whole [T, E] rollout after the scan instead of per
    step — bigger matmuls for the MXU, identical math.
  - Learner state lives as global sharded arrays; checkpointing saves them
    directly; there is no unreplicate dance.

Layout (S = data shards, U = update batch, E = envs per (shard, batch)):
  params/opt_states:      [U, ...]        P()        (replicated)
  key:                    [S, U, 2]       P("data")
  env_state / timestep:   [U, S*E, ...]   P(None, "data")
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

from stoix_tpu import envs
from stoix_tpu.base_types import (
    ActorCriticOptStates,
    ActorCriticParams,
    ExperimentOutput,
    PPOTransition,
)
from stoix_tpu.evaluator import get_distribution_act_fn
from stoix_tpu.observability import annotate, get_logger
from stoix_tpu.ops import (
    losses,
    running_statistics,
    truncated_generalized_advantage_estimation,
)
from stoix_tpu.parallel import is_coordinator
from stoix_tpu.resilience import guards
from stoix_tpu.utils import config as config_lib
from stoix_tpu.utils.jax_utils import count_parameters, tree_merge_leading_dims
from stoix_tpu.systems.runner import AnakinSetup, run_anakin_experiment
from stoix_tpu.utils.training import make_learning_rate


class PPOLearnerState(NamedTuple):
    """OnPolicyLearnerState + observation running statistics (the reference
    injects this field dynamically, ff_ppo.py:90-94; here it is explicit).

    `kl_beta` is the KL-penalty coefficient as TRAINED STATE: constant unless
    `system.adaptive_kl_beta` is set (PPO-penalty's adaptive-KL variant,
    Schulman et al. 2017 §4), in which case it doubles/halves around
    `system.kl_target` after every update step. Unused (zero) for clip/DPO."""

    params: Any
    opt_states: Any
    key: jax.Array
    env_state: Any
    timestep: Any
    obs_stats: Any
    kl_beta: Any


def get_learner_fn(
    env: envs.Environment,
    apply_fns: Tuple[Callable, Callable],
    update_fns: Tuple[Callable, Callable],
    config: Any,
    policy_loss_fn: Callable = None,
    hparams: Any = None,
) -> Callable[[PPOLearnerState], ExperimentOutput]:
    """Build the PER-SHARD learner function (wrapped in shard_map by setup).

    policy_loss_fn(dist, action, old_log_prob, gae, config, behavior_dist=...)
        -> (loss, entropy); behavior_dist is the pre-epoch policy re-applied
        on the same observations (analytic-KL penalties anchor to it)
    overrides the PPO clip objective (penalty/DPO variants).

    `hparams` (stoix_tpu/population, docs/DESIGN.md §2.11): a mapping of
    hyperparameter name -> scalar that OVERRIDES the config float. The plain
    path passes None and every value stays a trace-time Python float —
    byte-identical jaxprs. The population runner calls get_learner_fn inside
    its vmapped member function with per-member TRACED scalars, so one
    compiled program trains P members with different lr/ent_coef/gamma/...
    When `actor_lr`/`critic_lr` are present, `update_fns` must be built
    WITHOUT a learning rate (clip + scale_by_adam); the lr multiply happens
    here as `u * (-lr)` — bitwise the same multiply optax's scale(-lr) does.
    """

    actor_apply, critic_apply = apply_fns
    actor_update, critic_update = update_fns
    adaptive_kl = bool(config.system.get("adaptive_kl_beta", False))
    if adaptive_kl and not getattr(policy_loss_fn, "uses_kl_beta", False):
        # Fail fast: adapting beta for a loss that discards it (clip, DPO)
        # would log a "working" kl_beta while changing nothing.
        raise ValueError(
            "system.adaptive_kl_beta=true requires a policy loss that consumes "
            "kl_beta (the PPO-penalty loss); the configured loss does not."
        )
    hp = dict(hparams or {})
    gamma = hp.get("gamma", float(config.system.gamma))
    reward_scale = hp.get("reward_scale", float(config.system.get("reward_scale", 1.0)))
    gae_lambda = hp.get("gae_lambda", float(config.system.gae_lambda))
    clip_eps = hp.get("clip_eps", float(config.system.clip_eps))
    ent_coef = hp.get("ent_coef", float(config.system.ent_coef))
    vf_coef = hp.get("vf_coef", float(config.system.vf_coef))
    actor_lr = hp.get("actor_lr")  # None = update_fns already bake the lr
    critic_lr = hp.get("critic_lr")
    normalize_obs = bool(config.system.get("normalize_observations", False))
    guard_mode = guards.resolve_mode(config)
    # Hot-path compute knobs (docs/DESIGN.md §2.7): which scan kernel
    # evaluates the GAE recurrence, and whether actor+critic loss/grad/pmean
    # ride ONE fused gradient pass (2 collectives instead of 4) or the
    # reference's two independent passes (the bit-identical default).
    multistep_impl = str(config.system.get("multistep_impl", "scan"))
    fused_update = bool(config.system.get("fused_update", False))

    def _maybe_normalize(observation, obs_stats):
        if not normalize_obs:
            return observation
        return running_statistics.normalize_observation(observation, obs_stats)

    def _env_step(learner_state: PPOLearnerState, _: Any):
        params, opt_states, key = (
            learner_state.params, learner_state.opt_states, learner_state.key,
        )
        env_state, last_timestep = learner_state.env_state, learner_state.timestep
        obs_stats = learner_state.obs_stats
        key, policy_key = jax.random.split(key)

        observation = _maybe_normalize(last_timestep.observation, obs_stats)
        actor_policy = actor_apply(params.actor_params, observation)
        value = critic_apply(params.critic_params, observation)
        action = actor_policy.sample(seed=policy_key)
        log_prob = actor_policy.log_prob(action)

        env_state, timestep = env.step(env_state, action)

        done = timestep.discount == 0.0
        truncated = jnp.logical_and(timestep.last(), timestep.discount != 0.0)
        transition = PPOTransition(
            done=done,
            truncated=truncated,
            action=action,
            value=value,
            reward=timestep.reward,
            log_prob=log_prob,
            obs=last_timestep.observation,  # RAW; normalized at use
            next_obs=timestep.extras["next_obs"],  # RAW; normalized at use
            info=timestep.extras["episode_metrics"],
        )
        return (
            learner_state._replace(key=key, env_state=env_state, timestep=timestep),
            transition,
        )

    def _actor_loss_fn(
        actor_params, behavior_actor_params, obs, action, old_log_prob, gae, kl_beta
    ):
        actor_policy = actor_apply(actor_params, obs)
        if policy_loss_fn is not None:
            # The behavior distribution (pre-epoch params on the SAME
            # normalized observations) backs analytic-KL penalties — the
            # reference's PPO-penalty recomputes it exactly this way
            # (reference ff_ppo_penalty.py:158).
            behavior_policy = actor_apply(behavior_actor_params, obs)
            loss_actor, entropy = policy_loss_fn(
                actor_policy, action, old_log_prob, gae, config,
                behavior_dist=behavior_policy, beta=kl_beta,
            )
        else:
            log_prob = actor_policy.log_prob(action)
            loss_actor = losses.ppo_clip_loss(log_prob, old_log_prob, gae, clip_eps)
            entropy = actor_policy.entropy().mean()
        total = loss_actor - ent_coef * entropy
        return total, (loss_actor, entropy)

    def _critic_loss_fn(critic_params, obs, targets, old_value):
        value = critic_apply(critic_params, obs)
        if config.system.get("clip_value", True):
            value_loss = losses.clipped_value_loss(value, old_value, targets, clip_eps)
        else:
            value_loss = jnp.mean((value - targets) ** 2)
        return vf_coef * value_loss, value_loss

    def _fused_loss_fn(
        joint_params, behavior_actor_params, obs, action, old_log_prob, gae,
        kl_beta, targets, old_value,
    ):
        """Joint actor+critic objective for the fused update: the two losses
        share no parameters, so d(total)/d(actor) == the actor grad and
        d(total)/d(critic) == the critic grad — the SAME gradients as the
        two-pass path, computed in one backward pass over one params tree."""
        actor_total, (loss_actor, entropy) = _actor_loss_fn(
            joint_params.actor_params, behavior_actor_params, obs, action,
            old_log_prob, gae, kl_beta,
        )
        critic_total, value_loss = _critic_loss_fn(
            joint_params.critic_params, obs, targets, old_value
        )
        return actor_total + critic_total, (loss_actor, entropy, value_loss)

    @annotate("ppo_minibatch")
    def _update_minibatch(train_state: Tuple, batch_info: Tuple):
        params, opt_states, behavior_actor_params, kl_beta = train_state
        traj_batch, advantages, targets = batch_info

        if fused_update:
            # ONE backward pass + ONE pmean pair over the joint grads tree:
            # XLA sees a single all-reduce per axis for actor+critic together
            # instead of two, and the actor/critic backward graphs fuse.
            joint_grads, (loss_actor, entropy, value_loss) = jax.grad(
                _fused_loss_fn, has_aux=True
            )(
                params,
                behavior_actor_params,
                traj_batch.obs,
                traj_batch.action,
                traj_batch.log_prob,
                advantages,
                kl_beta,
                targets,
                traj_batch.value,
            )
            joint_grads = jax.lax.pmean(joint_grads, axis_name="batch")
            joint_grads = jax.lax.pmean(joint_grads, axis_name="data")
            actor_grads = joint_grads.actor_params
            critic_grads = joint_grads.critic_params
        else:
            actor_grad_fn = jax.grad(_actor_loss_fn, has_aux=True)
            actor_grads, (loss_actor, entropy) = actor_grad_fn(
                params.actor_params,
                behavior_actor_params,
                traj_batch.obs,
                traj_batch.action,
                traj_batch.log_prob,
                advantages,
                kl_beta,
            )
            critic_grad_fn = jax.grad(_critic_loss_fn, has_aux=True)
            critic_grads, value_loss = critic_grad_fn(
                params.critic_params, traj_batch.obs, targets, traj_batch.value
            )

            # Gradient sync: mean over the in-shard update-batch vmap axis,
            # then the mesh data axis (the latter rides ICI/DCN).
            actor_grads = jax.lax.pmean(actor_grads, axis_name="batch")
            actor_grads = jax.lax.pmean(actor_grads, axis_name="data")
            critic_grads = jax.lax.pmean(critic_grads, axis_name="batch")
            critic_grads = jax.lax.pmean(critic_grads, axis_name="data")

        actor_updates, actor_opt_state = actor_update(
            actor_grads, opt_states.actor_opt_state
        )
        if actor_lr is not None:
            # Threaded lr (population path): update_fns end at scale_by_adam,
            # so the update IS the adam direction; `u * (-lr)` is bitwise the
            # multiply optax's scale(-lr) performs inside adam(lr).
            actor_updates = jax.tree.map(lambda u: u * (-actor_lr), actor_updates)
        actor_params = optax.apply_updates(params.actor_params, actor_updates)
        critic_updates, critic_opt_state = critic_update(
            critic_grads, opt_states.critic_opt_state
        )
        if critic_lr is not None:
            critic_updates = jax.tree.map(lambda u: u * (-critic_lr), critic_updates)
        critic_params = optax.apply_updates(params.critic_params, critic_updates)

        # Divergence guard (resilience/guards.py): select the pre-update
        # (params, opt_states) when loss/grad-norm is non-finite. Zero added
        # ops and no extra metrics under the default update_guard=off.
        # Grads sync over BOTH ("batch", "data") above, so the [U] replicas
        # are bit-identical and the guard verdict must be too — a per-replica
        # decision would silently desync the replicated params forever.
        (params, opt_states), guard_metrics = guards.guard_update(
            guard_mode,
            new=(
                ActorCriticParams(actor_params, critic_params),
                ActorCriticOptStates(actor_opt_state, critic_opt_state),
            ),
            old=(params, opt_states),
            loss=loss_actor + value_loss,
            grads=(actor_grads, critic_grads),
            opt_state=opt_states,
            axis_names=("batch", "data"),
            metric_axes=("batch",),
        )

        loss_info = {
            "total_loss": loss_actor + value_loss,
            "actor_loss": loss_actor,
            "value_loss": value_loss,
            "entropy": entropy,
            **guard_metrics,
        }
        return (
            params,
            opt_states,
            behavior_actor_params,
            kl_beta,
        ), loss_info

    @annotate("ppo_epoch")
    def _update_epoch(update_state: Tuple, _: Any):
        (
            params, opt_states, behavior_actor_params, kl_beta,
            traj_batch, advantages, targets, key,
        ) = update_state
        key, shuffle_key = jax.random.split(key)

        # Flatten [T, E] -> [T*E] and shuffle across both time and envs.
        batch_size = advantages.shape[0] * advantages.shape[1]
        permutation = jax.random.permutation(shuffle_key, batch_size)
        flat = tree_merge_leading_dims((traj_batch, advantages, targets), 2)
        shuffled = jax.tree.map(lambda x: jnp.take(x, permutation, axis=0), flat)
        minibatches = jax.tree.map(
            lambda x: x.reshape(
                (int(config.system.num_minibatches), -1) + x.shape[1:]
            ),
            shuffled,
        )
        (params, opt_states, behavior_actor_params, kl_beta), loss_info = jax.lax.scan(
            _update_minibatch,
            (params, opt_states, behavior_actor_params, kl_beta),
            minibatches,
        )
        return (
            params, opt_states, behavior_actor_params, kl_beta,
            traj_batch, advantages, targets, key,
        ), loss_info

    def _update_step(learner_state: PPOLearnerState, _: Any):
        learner_state, traj_batch = jax.lax.scan(
            _env_step, learner_state, None, int(config.system.rollout_length)
        )
        params, opt_states, key = (
            learner_state.params, learner_state.opt_states, learner_state.key,
        )
        env_state, last_timestep = learner_state.env_state, learner_state.timestep
        obs_stats, kl_beta = learner_state.obs_stats, learner_state.kl_beta

        # Trajectory obs are stored RAW; normalize them with the PRE-update
        # statistics (identical to what the rollout's log_probs/values used),
        # THEN fold the raw policy-consumed observations into the statistics
        # (psummed over the vmap + mesh axes so every replica stays in sync —
        # reference ff_ppo.py:145-162).
        raw_obs = traj_batch.obs
        traj_batch = traj_batch._replace(
            obs=_maybe_normalize(raw_obs, obs_stats),
            next_obs=_maybe_normalize(traj_batch.next_obs, obs_stats),
        )
        if normalize_obs:
            obs_stats = running_statistics.update(
                obs_stats,
                raw_obs.agent_view,
                axis_names=("batch", "data"),
                std_min_value=5e-4,
                std_max_value=5e4,
            )

        # ONE batched critic apply for all bootstrap values [T, E].
        v_t = critic_apply(params.critic_params, traj_batch.next_obs)

        d_t = gamma * (1.0 - traj_batch.done.astype(jnp.float32))
        advantages, targets = truncated_generalized_advantage_estimation(
            traj_batch.reward * reward_scale,
            d_t,
            gae_lambda,
            v_tm1=traj_batch.value,
            v_t=v_t,
            truncation_t=traj_batch.truncated.astype(jnp.float32),
            standardize_advantages=bool(config.system.get("standardize_advantages", True)),
            impl=multistep_impl,
        )

        # Behavior params (the rollout's) stay FIXED across all epochs: KL
        # penalties anchor to them, matching the reference's
        # behaviour_actor_params capture (reference ff_ppo_penalty.py:128).
        update_state = (
            params, opt_states, params.actor_params, kl_beta,
            traj_batch, advantages, targets, key,
        )
        update_state, loss_info = jax.lax.scan(
            _update_epoch, update_state, None, int(config.system.epochs)
        )
        params, opt_states, behavior_actor_params, kl_beta = update_state[:4]
        key = update_state[7]

        if adaptive_kl:
            # Adaptive-KL PPO (Schulman et al. 2017 §4): after the full
            # update, measure the analytic KL(behavior ‖ new policy) over the
            # rollout batch and double/halve beta around `kl_target`. The KL
            # is pmeaned over the update-batch and mesh axes FIRST so the
            # replicated beta state stays bit-identical on every replica.
            kl_target = float(config.system.get("kl_target", 0.01))
            new_dist = actor_apply(params.actor_params, traj_batch.obs)
            behavior_dist = actor_apply(behavior_actor_params, traj_batch.obs)
            try:
                measured_kl = jnp.mean(behavior_dist.kl_divergence(new_dist))
            except NotImplementedError:
                log_ratio = jnp.clip(
                    new_dist.log_prob(traj_batch.action) - traj_batch.log_prob,
                    -losses._LOG_RATIO_CLAMP, losses._LOG_RATIO_CLAMP,
                )
                measured_kl = jnp.mean(jnp.exp(log_ratio) - 1.0 - log_ratio)
            measured_kl = jax.lax.pmean(measured_kl, axis_name="batch")
            measured_kl = jax.lax.pmean(measured_kl, axis_name="data")
            kl_beta = jnp.where(measured_kl > 1.5 * kl_target, kl_beta * 2.0, kl_beta)
            kl_beta = jnp.where(measured_kl < kl_target / 1.5, kl_beta / 2.0, kl_beta)
            kl_beta = jnp.clip(kl_beta, 1e-3, 1e3)
            loss_info = {**loss_info, "measured_kl": measured_kl, "kl_beta": kl_beta}

        learner_state = PPOLearnerState(
            params, opt_states, key, env_state, last_timestep, obs_stats, kl_beta
        )
        return learner_state, (traj_batch.info, loss_info)

    def learner_fn(learner_state: PPOLearnerState) -> ExperimentOutput:
        """Per-shard learner: scans vmapped update steps for one eval period."""
        key = learner_state.key[0]  # [S=1 slice, U, 2] -> [U, 2]
        state = learner_state._replace(key=key)

        batched_update_step = jax.vmap(_update_step, axis_name="batch")
        state, (episode_info, loss_info) = jax.lax.scan(
            batched_update_step, state, None, int(config.arch.num_updates_per_eval)
        )

        state = state._replace(key=state.key[None])  # restore [1, U, 2]
        # Losses are identical across shards post-pmean of grads only in
        # expectation; reduce them globally so P() outputs are truly replicated.
        loss_info = jax.lax.pmean(loss_info, axis_name="data")
        return ExperimentOutput(
            learner_state=state,
            episode_metrics=episode_info,
            train_metrics=loss_info,
        )

    return learner_fn


def build_networks(env: envs.Environment, config: Any):
    """Actor/critic network construction from the network config — shared by
    learner_setup and the population setup (stoix_tpu/population), which
    builds ONE network pair for all P members."""
    from stoix_tpu.systems import anakin
    from stoix_tpu.networks.base import FeedForwardActor, FeedForwardCritic

    net_cfg = config.network
    actor_network = FeedForwardActor(
        action_head=config_lib.instantiate(
            net_cfg.actor_network.action_head,
            **anakin.head_kwargs_for_env(net_cfg.actor_network.action_head, env),
        ),
        torso=config_lib.instantiate(net_cfg.actor_network.pre_torso),
        input_layer=config_lib.instantiate(net_cfg.actor_network.input_layer),
    )
    critic_network = FeedForwardCritic(
        critic_head=config_lib.instantiate(net_cfg.critic_network.critic_head),
        torso=config_lib.instantiate(net_cfg.critic_network.pre_torso),
        input_layer=config_lib.instantiate(net_cfg.critic_network.input_layer),
    )
    return actor_network, critic_network


def learner_setup(
    env: envs.Environment, config: Any, mesh: Mesh, keys: jax.Array,
    policy_loss_fn: Callable = None,
) -> AnakinSetup:
    """Instantiate networks/optimizers, build the shard_mapped learner, and
    initialise the (globally sharded) learner state."""

    from stoix_tpu.systems import anakin

    if "group" in mesh.axis_names:
        # ("group", "data") mesh: G gossip-averaged learner groups
        # (parallel/gossip.py, docs/DESIGN.md §2.12).
        return grouped_learner_setup(env, config, mesh, keys, policy_loss_fn)

    num_actions = env.num_actions
    config.system.action_dim = num_actions

    actor_network, critic_network = build_networks(env, config)

    actor_lr = make_learning_rate(
        float(config.system.actor_lr), config, int(config.system.epochs),
        int(config.system.num_minibatches),
    )
    critic_lr = make_learning_rate(
        float(config.system.critic_lr), config, int(config.system.epochs),
        int(config.system.num_minibatches),
    )
    actor_optim = optax.chain(
        optax.clip_by_global_norm(float(config.system.max_grad_norm)),
        optax.adam(actor_lr, eps=1e-5),
    )
    critic_optim = optax.chain(
        optax.clip_by_global_norm(float(config.system.max_grad_norm)),
        optax.adam(critic_lr, eps=1e-5),
    )

    key, actor_key, critic_key, env_key = jax.random.split(keys, 4)
    dummy_obs = jax.tree.map(lambda x: x[None], env.observation_value())
    actor_params = actor_network.init(actor_key, dummy_obs)
    critic_params = critic_network.init(critic_key, dummy_obs)
    actor_opt_state = actor_optim.init(actor_params)
    critic_opt_state = critic_optim.init(critic_params)

    apply_fns = (actor_network.apply, critic_network.apply)
    update_fns = (actor_optim.update, critic_optim.update)
    learn_per_shard = get_learner_fn(env, apply_fns, update_fns, config, policy_loss_fn)

    # ---- Global learner-state construction (shared anakin conventions) -----
    update_batch = int(config.arch.get("update_batch_size", 1))
    state_specs = PPOLearnerState(
        params=P(),
        opt_states=P(),
        key=P("data"),
        env_state=P(None, "data"),
        timestep=P(None, "data"),
        obs_stats=P(),
        kl_beta=P(),
    )
    env_state, timestep = anakin.reset_envs_for_anakin(env, config, env_key)
    obs_stats = running_statistics.init_state(env.observation_value().agent_view)
    learner_state = PPOLearnerState(
        params=anakin.broadcast_to_update_batch(
            ActorCriticParams(actor_params, critic_params), update_batch
        ),
        opt_states=anakin.broadcast_to_update_batch(
            ActorCriticOptStates(actor_opt_state, critic_opt_state), update_batch
        ),
        key=anakin.make_step_keys(key, mesh, config),
        env_state=env_state,
        timestep=timestep,
        obs_stats=anakin.broadcast_to_update_batch(obs_stats, update_batch),
        kl_beta=anakin.broadcast_to_update_batch(
            # 3.0 matches the penalty loss's historical default so a config
            # omitting kl_beta keeps the KL penalty ACTIVE (0.0 would
            # silently disable it). Unused state for clip/DPO losses.
            jnp.asarray(float(config.system.get("kl_beta", 3.0))), update_batch
        ),
    )
    learner_state = anakin.place_learner_state(learner_state, mesh, state_specs)
    learn = anakin.shardmap_learner(learn_per_shard, mesh, state_specs)

    if is_coordinator():
        n_params = count_parameters(actor_params) + count_parameters(critic_params)
        get_logger("stoix_tpu.setup").info(
            "[setup] %s parameters | mesh %s | %s global envs",
            f"{n_params:,}", dict(mesh.shape), config.arch.total_num_envs,
        )

    normalize_obs = bool(config.system.get("normalize_observations", False))
    if normalize_obs:
        # Eval params bundle the actor params with the current statistics.
        def eval_apply(bundle, observation):
            params, stats = bundle
            observation = running_statistics.normalize_observation(observation, stats)
            return actor_network.apply(params, observation)

        eval_act_fn = get_distribution_act_fn(config, eval_apply)
        eval_params_fn = lambda s: (
            jax.tree.map(lambda x: x[0], s.params.actor_params),
            jax.tree.map(lambda x: x[0], s.obs_stats),
        )
    else:
        eval_act_fn = get_distribution_act_fn(config, actor_network.apply)
        eval_params_fn = lambda s: jax.tree.map(lambda x: x[0], s.params.actor_params)

    setup = AnakinSetup(
        learn=learn,
        learner_state=learner_state,
        eval_act_fn=eval_act_fn,
        eval_params_fn=eval_params_fn,
    )
    return setup


def grouped_learner_setup(
    env: envs.Environment, config: Any, mesh: Mesh, keys: jax.Array,
    policy_loss_fn: Callable = None,
) -> AnakinSetup:
    """G gossip-averaged learner groups on a ("group", "data") mesh
    (parallel/gossip.py, docs/DESIGN.md §2.12; arxiv 1906.04585).

    Each group is the UNCHANGED per-shard learner: inside shard_map its
    `pmean(axis_name="data")` reduces within the group's data slice only, so
    the dense gradient all-reduce never crosses a group boundary. Groups all
    start from group 0's params/opt state (gossip-SGD averages replicas of
    ONE model — unlike population members, which are independent agents) but
    roll out on fold_in-separated env/step key streams, and the runner mixes
    the per-group parameter stacks with the jitted gossip step every
    `arch.gossip.interval` windows. Env counts are PER GROUP."""

    import os

    from stoix_tpu.parallel import gossip as gossip_lib
    from stoix_tpu.parallel.mesh import shard_map
    from stoix_tpu.systems import anakin

    gossip_lib.validate_grouped_config(config, mesh)
    num_groups = int(mesh.shape["group"])

    num_actions = env.num_actions
    config.system.action_dim = num_actions

    actor_network, critic_network = build_networks(env, config)

    actor_lr = make_learning_rate(
        float(config.system.actor_lr), config, int(config.system.epochs),
        int(config.system.num_minibatches),
    )
    critic_lr = make_learning_rate(
        float(config.system.critic_lr), config, int(config.system.epochs),
        int(config.system.num_minibatches),
    )
    actor_optim = optax.chain(
        optax.clip_by_global_norm(float(config.system.max_grad_norm)),
        optax.adam(actor_lr, eps=1e-5),
    )
    critic_optim = optax.chain(
        optax.clip_by_global_norm(float(config.system.max_grad_norm)),
        optax.adam(critic_lr, eps=1e-5),
    )
    apply_fns = (actor_network.apply, critic_network.apply)
    update_fns = (actor_optim.update, critic_optim.update)

    update_batch = int(config.arch.get("update_batch_size", 1))
    dummy_obs = jax.tree.map(lambda x: x[None], env.observation_value())
    obs_stats0 = running_statistics.init_state(env.observation_value().agent_view)
    kl_beta0 = jnp.asarray(float(config.system.get("kl_beta", 3.0)))

    # Group 0's key path is EXACTLY learner_setup's (the single-group
    # bit-identity pin rides on it); groups g>0 fold_in(g) for their env and
    # step streams but share group 0's network init.
    shared_params = None
    shared_opt = None
    member_states = []
    for g in range(num_groups):
        member_key = keys if g == 0 else jax.random.fold_in(keys, g)
        key_g, actor_key, critic_key, env_key = jax.random.split(member_key, 4)
        if g == 0:
            actor_params = actor_network.init(actor_key, dummy_obs)
            critic_params = critic_network.init(critic_key, dummy_obs)
            shared_params = ActorCriticParams(actor_params, critic_params)
            shared_opt = ActorCriticOptStates(
                actor_optim.init(actor_params), critic_optim.init(critic_params)
            )
        env_state, timestep = anakin.reset_envs_for_anakin(env, config, env_key)
        member_states.append(
            PPOLearnerState(
                params=anakin.broadcast_to_update_batch(shared_params, update_batch),
                opt_states=anakin.broadcast_to_update_batch(shared_opt, update_batch),
                key=anakin.make_step_keys(key_g, mesh, config),
                env_state=env_state,
                timestep=timestep,
                obs_stats=anakin.broadcast_to_update_batch(obs_stats0, update_batch),
                kl_beta=anakin.broadcast_to_update_batch(kl_beta0, update_batch),
            )
        )
    grouped_state = jax.tree.map(lambda *xs: jnp.stack(xs), *member_states)

    grouped_specs = PPOLearnerState(
        params=P("group"),
        opt_states=P("group"),
        key=P("group", "data"),
        env_state=P("group", None, "data"),
        timestep=P("group", None, "data"),
        obs_stats=P("group"),
        kl_beta=P("group"),
    )
    grouped_state = anakin.place_learner_state(grouped_state, mesh, grouped_specs)

    learn_member = get_learner_fn(env, apply_fns, update_fns, config, policy_loss_fn)

    def per_shard_learn(state: PPOLearnerState) -> ExperimentOutput:
        # The stacked [G] axis is sharded 1:1 over the mesh's group axis, so
        # the local slice is always ONE group: squeeze -> the unchanged
        # ff_ppo learner -> unsqueeze. Reshapes only, which is why a single
        # group trains BIT-identically to plain ff_ppo.
        local = jax.tree.map(lambda x: x[0], state)
        out = learn_member(local)
        return jax.tree.map(lambda x: x[None], out)

    learn_sm = shard_map(
        per_shard_learn,
        mesh=mesh,
        in_specs=(grouped_specs,),
        out_specs=ExperimentOutput(
            learner_state=grouped_specs,
            episode_metrics=P("group", None, None, None, "data"),
            train_metrics=P("group"),
        ),
        # Same Anakin opt-out as systems/anakin.py shardmap_learner: the
        # in-shard update-batch vmap's pmean trips check_vma's
        # varying-manual-axes assert.
        check_vma=False,
    )
    donate = {} if os.environ.get("STOIX_TPU_NO_DONATE") else {"donate_argnums": (0,)}
    learn = jax.jit(learn_sm, **donate)

    gossip_plan = gossip_lib.build_gossip_plan(config, mesh, state_specs=grouped_specs)

    if is_coordinator():
        n_params = count_parameters(shared_params.actor_params) + count_parameters(
            shared_params.critic_params
        )
        get_logger("stoix_tpu.setup").info(
            "[setup] %s parameters | mesh %s | %s envs/group | %d groups (%s, "
            "interval %s)",
            f"{n_params:,}", dict(mesh.shape), config.arch.total_num_envs,
            num_groups,
            gossip_plan.topology if gossip_plan else "lockstep",
            gossip_plan.interval if gossip_plan else "-",
        )

    # Evaluation serves group 0's replica-0 slice — the same values the
    # lockstep path's `x[0]` serves (post-gossip, group 0 already carries its
    # mixed parameters: the snapshot is taken AFTER the gossip dispatch).
    normalize_obs = bool(config.system.get("normalize_observations", False))
    if normalize_obs:

        def eval_apply(bundle, observation):
            params, stats = bundle
            observation = running_statistics.normalize_observation(observation, stats)
            return actor_network.apply(params, observation)

        eval_act_fn = get_distribution_act_fn(config, eval_apply)
        eval_params_fn = lambda s: (
            jax.tree.map(lambda x: x[0, 0], s.params.actor_params),
            jax.tree.map(lambda x: x[0, 0], s.obs_stats),
        )
    else:
        eval_act_fn = get_distribution_act_fn(config, actor_network.apply)
        eval_params_fn = lambda s: jax.tree.map(lambda x: x[0, 0], s.params.actor_params)

    return AnakinSetup(
        learn=learn,
        learner_state=grouped_state,
        eval_act_fn=eval_act_fn,
        eval_params_fn=eval_params_fn,
        gossip=gossip_plan,
    )


def run_experiment(config: Any) -> float:
    """Train Anakin PPO; returns the final evaluation episode-return mean."""
    return run_anakin_experiment(config, learner_setup)


def main() -> float:
    import sys

    config = config_lib.compose(
        config_lib.default_config_dir(),
        "default/anakin/default_ff_ppo.yaml",
        sys.argv[1:],
    )
    return run_experiment(config)


if __name__ == "__main__":
    main()
