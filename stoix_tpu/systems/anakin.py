"""Anakin learner-construction helpers shared by system files.

Encapsulates the mesh/layout conventions every Anakin system uses
(see ff_ppo.py module docstring for the layout):

  params/opt/buffer:   [U, ...]       P()          (replicated)
  key:                 [S, U, 2]      P("data")
  env_state/timestep:  [U, S*E, ...]  P(None, "data")
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from stoix_tpu import envs
from stoix_tpu.base_types import ExperimentOutput
from stoix_tpu.parallel.mesh import shard_map


def head_kwargs_for_env(head_cfg: Any, env: envs.Environment) -> dict:
    """Infer action-head constructor kwargs from the env's action space, so one
    network config mechanism serves discrete/continuous/multi-discrete heads.
    """
    from stoix_tpu.envs import spaces as env_spaces
    from stoix_tpu.utils.config import _import_target

    import numpy as np

    target = _import_target(head_cfg["_target_"])
    fields = getattr(target, "__dataclass_fields__", {})
    kwargs: dict = {}
    space = env.action_space()

    def bound(v: Any) -> Any:
        # Preserve per-dimension bounds (heads broadcast arrays/lists fine).
        arr = np.asarray(v)
        return float(arr) if arr.ndim == 0 or np.all(arr == arr.flat[0]) else arr.tolist()

    if "num_actions" in fields:
        kwargs["num_actions"] = env.num_actions
    if "action_dim" in fields:
        kwargs["action_dim"] = env.num_actions
    if "num_values" in fields and isinstance(space, env_spaces.MultiDiscrete):
        kwargs["num_values"] = space.num_values
    if "minimum" in fields and hasattr(space, "low"):
        kwargs["minimum"] = bound(space.low)
    if "maximum" in fields and hasattr(space, "high"):
        kwargs["maximum"] = bound(space.high)
    # Explicit values in the network YAML win over inferred ones.
    return {k: v for k, v in kwargs.items() if k not in head_cfg}


def broadcast_to_update_batch(tree: Any, update_batch: int) -> Any:
    return jax.tree.map(lambda x: jnp.broadcast_to(x, (update_batch,) + x.shape), tree)


def reset_envs_for_anakin(
    env: envs.Environment, config: Any, env_key: jax.Array
) -> Tuple[Any, Any]:
    """Reset all global envs and shape leaves to [U, S*E, ...]."""
    update_batch = int(config.arch.get("update_batch_size", 1))
    envs_axis = int(config.arch.total_num_envs) // update_batch
    env_keys = jax.random.split(env_key, update_batch * envs_axis)
    env_state, timestep = env.reset(env_keys)
    reshape = lambda x: x.reshape((update_batch, envs_axis) + x.shape[1:])
    return jax.tree.map(reshape, env_state), jax.tree.map(reshape, timestep)


def make_step_keys(key: jax.Array, mesh: Mesh, config: Any) -> jax.Array:
    n_shards = int(mesh.shape["data"])
    update_batch = int(config.arch.get("update_batch_size", 1))
    return jax.random.split(key, n_shards * update_batch).reshape(n_shards, update_batch, -1)


def place_learner_state(learner_state: Any, mesh: Mesh, state_specs: Any) -> Any:
    """Device-put the state pytree with per-subtree PartitionSpecs."""
    shardings = jax.tree.map(
        lambda spec: NamedSharding(mesh, spec), state_specs,
        is_leaf=lambda s: isinstance(s, P),
    )
    return jax.device_put(learner_state, shardings)


def shardmap_learner(
    learn_per_shard: Callable[[Any], ExperimentOutput],
    mesh: Mesh,
    state_specs: Any,
    episode_metrics_spec: P = P(None, None, None, "data"),
) -> Callable[[Any], ExperimentOutput]:
    """Wrap a per-shard learner in shard_map + jit with the standard specs.

    The learner state is donated (donate_argnums): the host loop's
    `state = learn(state).learner_state` never reads the old state again, and
    donation lets XLA reuse its HBM for the output instead of holding both
    copies live across the update. Validated on a healthy v5e runtime
    (round 2); an earlier WEDGED tunneled runtime deadlocked with donation on,
    so STOIX_TPU_NO_DONATE=1 is the kill-switch for broken runtimes.

    Snapshot-vs-donation invariant (the pipelined runner depends on it):
    anything read AFTER the next `learn(state)` dispatch — eval params, best
    params, the checkpoint state — must be an on-device COPY taken from the
    device stream BEFORE that dispatch (systems/runner.py _tree_copy). The
    copy is enqueued ahead of the donating program, so the runtime orders the
    read before the buffers are reused; reading the donated tree itself after
    the dispatch is a use-after-free. tests/test_runner_pipeline.py guards
    this with donation on and off.
    """
    import os

    donate = {} if os.environ.get("STOIX_TPU_NO_DONATE") else {"donate_argnums": (0,)}
    return jax.jit(
        shard_map(
            learn_per_shard,
            mesh=mesh,
            in_specs=(state_specs,),
            out_specs=ExperimentOutput(
                learner_state=state_specs,
                episode_metrics=episode_metrics_spec,
                train_metrics=P(),
            ),
            # Anakin-specific opt-out (VERDICT r3 #9, investigated r4): with
            # check_vma=True the learner compiles until the first
            # `jax.lax.pmean(..., axis_name="batch")` — the in-shard
            # update-batch VMAP axis — which fails an internal assert in
            # JAX's varying-manual-axes machinery (collectives over vmap axes
            # nested in shard_map are outside what the validator models).
            # The Sebulba learners have no in-shard vmap axis and run with
            # check_vma=True (systems/ppo/sebulba/ff_ppo.py); carry-leaf
            # varying-ness was fixed where real (wrappers._ensure_truncation).
            check_vma=False,
        ),
        **donate,
    )


def unbatch_params(params: Any) -> Any:
    """Strip the [U] update-batch axis (all replicas identical post-pmean)."""
    return jax.tree.map(lambda x: x[0], params)
