"""Sebulba IMPALA with a shared torso (reference
stoix/systems/impala/sebulba/ff_impala_shared_torso.py, 1018 LoC): ONE network
with a PolicyValueHead serves both the policy and the value function
(reference uses a single net + PolicyValueHead). Implemented as two views over
the same module: the actor view returns the distribution, the critic view the
value; both views share parameters and the combined V-trace loss updates them
once through the actor optimizer (the critic optimizer sees an empty tree).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

from stoix_tpu.base_types import ActorCriticOptStates, ActorCriticParams, PPOTransition
from stoix_tpu.ops import running_statistics
from stoix_tpu.parallel.mesh import shard_map
from stoix_tpu.systems.ppo.sebulba.ff_ppo import CoreLearnerState, run_experiment as _run
from stoix_tpu.utils import config as config_lib


class _SharedView(nn.Module):
    """Callable view over a shared actor-critic module selecting one output."""

    net: nn.Module
    index: int

    @nn.compact
    def __call__(self, observation):
        return self.net(observation)[self.index]


def build_shared_networks(config: Any, num_actions: int, dummy_obs: Any):
    from stoix_tpu.networks.base import FeedForwardActorCritic
    from stoix_tpu.networks.heads import CategoricalHead, PolicyValueHead, ScalarCriticHead

    net_cfg = config.network
    shared = FeedForwardActorCritic(
        shared_head=PolicyValueHead(
            action_head=CategoricalHead(num_actions=num_actions),
            critic_head=ScalarCriticHead(),
        ),
        torso=config_lib.instantiate(net_cfg.actor_network.pre_torso),
        input_layer=config_lib.instantiate(net_cfg.actor_network.input_layer),
    )
    actor_view = _SharedView(net=shared, index=0)
    critic_view = _SharedView(net=shared, index=1)
    return actor_view, critic_view


def get_shared_impala_learn_step(actor_apply, critic_apply, update_fns, config, mesh: Mesh):
    """V-trace update through the shared parameters only (actor slot)."""
    from stoix_tpu.systems.impala.sebulba.ff_impala import (
        build_impala_loss,
        maybe_normalize_rewards,
        split_env_minibatches,
    )

    actor_update, _ = update_fns
    normalize_obs = bool(config.system.get("normalize_observations", False))
    num_minibatches = int(config.system.get("num_minibatches", 1))
    impala_loss = build_impala_loss(actor_apply, critic_apply, config)

    def per_shard(state: CoreLearnerState, traj: PPOTransition):
        # Match the actor path: observations the behavior policy consumed were
        # normalized with these (pre-update) statistics; fold the raw batch in
        # afterwards so the stats keep advancing.
        obs_stats = state.obs_stats
        if normalize_obs:
            raw_obs = traj.obs
            traj = traj._replace(
                obs=running_statistics.normalize_observation(traj.obs, obs_stats),
                next_obs=running_statistics.normalize_observation(traj.next_obs, obs_stats),
            )
            obs_stats = running_statistics.update(
                obs_stats, raw_obs.agent_view, axis_names=("data",),
                std_min_value=5e-4, std_max_value=5e4,
            )

        traj = maybe_normalize_rewards(traj, config)

        def loss_fn(shared_params, mb: PPOTransition):
            return impala_loss(shared_params, shared_params, mb)

        def _minibatch(carry, mb: PPOTransition):
            shared, a_opt = carry
            grads, metrics = jax.grad(loss_fn, has_aux=True)(shared, mb)
            grads, metrics = jax.lax.pmean((grads, metrics), axis_name="data")
            updates, a_opt = actor_update(grads, a_opt)
            return (optax.apply_updates(shared, updates), a_opt), metrics

        (shared, a_opt), metrics = jax.lax.scan(
            _minibatch,
            (state.params.actor_params, state.opt_states.actor_opt_state),
            split_env_minibatches(traj, num_minibatches),
        )
        metrics = jax.tree.map(jnp.mean, metrics)
        # Keep both param slots in sync (the rollout's critic view reads the
        # critic slot).
        params = ActorCriticParams(shared, shared)
        new_opts = ActorCriticOptStates(a_opt, state.opt_states.critic_opt_state)
        return CoreLearnerState(params, new_opts, state.key, obs_stats), metrics

    return jax.jit(
        shard_map(
            per_shard,
            mesh=mesh,
            in_specs=(CoreLearnerState(P(), P(), P(), P()), P(None, "data")),
            out_specs=(CoreLearnerState(P(), P(), P(), P()), P()),
            # No in-shard vmap axis here, so the varying-manual-axes
            # validator runs (Anakin's pmean-over-vmap-axis limitation
            # does not apply — see systems/anakin.py).
            check_vma=True,
        )
    )


def run_experiment(config: Any) -> float:
    return _run(
        config,
        learn_step_builder=get_shared_impala_learn_step,
        networks_builder=build_shared_networks,
    )


def main() -> float:
    import sys

    config = config_lib.compose(
        config_lib.default_config_dir(),
        "default/sebulba/default_ff_impala_shared_torso.yaml",
        sys.argv[1:],
    )
    return run_experiment(config)


if __name__ == "__main__":
    main()
