"""Sebulba IMPALA with a shared torso (reference
stoix/systems/impala/sebulba/ff_impala_shared_torso.py, 1018 LoC): ONE network
with a PolicyValueHead serves both the policy and the value function
(reference uses a single net + PolicyValueHead). Implemented as two views over
the same module: the actor view returns the distribution, the critic view the
value; both views share parameters and the combined V-trace loss updates them
once through the actor optimizer (the critic optimizer sees an empty tree).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

from stoix_tpu.base_types import ActorCriticOptStates, ActorCriticParams, PPOTransition
from stoix_tpu.ops import running_statistics
from stoix_tpu.ops.multistep import vtrace_td_error_and_advantage
from stoix_tpu.systems.ppo.sebulba.ff_ppo import CoreLearnerState, run_experiment as _run
from stoix_tpu.utils import config as config_lib


class _SharedView(nn.Module):
    """Callable view over a shared actor-critic module selecting one output."""

    net: nn.Module
    index: int

    @nn.compact
    def __call__(self, observation):
        return self.net(observation)[self.index]


def build_shared_networks(config: Any, num_actions: int, dummy_obs: Any):
    from stoix_tpu.networks.base import FeedForwardActorCritic
    from stoix_tpu.networks.heads import CategoricalHead, PolicyValueHead, ScalarCriticHead

    net_cfg = config.network
    shared = FeedForwardActorCritic(
        shared_head=PolicyValueHead(
            action_head=CategoricalHead(num_actions=num_actions),
            critic_head=ScalarCriticHead(),
        ),
        torso=config_lib.instantiate(net_cfg.actor_network.pre_torso),
        input_layer=config_lib.instantiate(net_cfg.actor_network.input_layer),
    )
    actor_view = _SharedView(net=shared, index=0)
    critic_view = _SharedView(net=shared, index=1)
    return actor_view, critic_view


def get_shared_impala_learn_step(actor_apply, critic_apply, update_fns, config, mesh: Mesh):
    """V-trace update through the shared parameters only (actor slot)."""
    actor_update, _ = update_fns
    gamma = float(config.system.gamma)

    normalize_obs = bool(config.system.get("normalize_observations", False))

    def per_shard(state: CoreLearnerState, traj: PPOTransition):
        # Match the actor path: observations the behavior policy consumed were
        # normalized with these (pre-update) statistics; fold the raw batch in
        # afterwards so the stats keep advancing.
        obs_stats = state.obs_stats
        if normalize_obs:
            raw_obs = traj.obs
            traj = traj._replace(
                obs=running_statistics.normalize_observation(traj.obs, obs_stats),
                next_obs=running_statistics.normalize_observation(traj.next_obs, obs_stats),
            )
            obs_stats = running_statistics.update(
                obs_stats, raw_obs.agent_view, axis_names=("data",),
                std_min_value=5e-4, std_max_value=5e4,
            )

        def loss_fn(shared_params):
            dist = actor_apply(shared_params, traj.obs)
            online_log_prob = dist.log_prob(traj.action)
            values = critic_apply(shared_params, traj.obs)
            bootstrap = critic_apply(shared_params, traj.next_obs)

            rhos = jnp.exp(jax.lax.stop_gradient(online_log_prob) - traj.log_prob)
            d_t = gamma * (1.0 - traj.done.astype(jnp.float32))
            lam = float(config.system.get("vtrace_lambda", 1.0))
            errors, pg_adv, _ = jax.vmap(
                lambda v, b, r, d, rho: vtrace_td_error_and_advantage(v, b, r, d, rho, lam),
                in_axes=1, out_axes=1,
            )(
                jax.lax.stop_gradient(values),
                jax.lax.stop_gradient(bootstrap),
                traj.reward, d_t, rhos,
            )
            pg_loss = -jnp.mean(pg_adv * online_log_prob)
            value_targets = jax.lax.stop_gradient(errors + values)
            value_loss = 0.5 * jnp.mean((values - value_targets) ** 2)
            entropy = dist.entropy().mean()
            total = (
                pg_loss
                + float(config.system.get("vf_coef", 0.5)) * value_loss
                - float(config.system.get("ent_coef", 0.01)) * entropy
            )
            return total, {
                "actor_loss": pg_loss, "value_loss": value_loss, "entropy": entropy,
            }

        grads, metrics = jax.grad(loss_fn, has_aux=True)(state.params.actor_params)
        grads = jax.lax.pmean(grads, axis_name="data")
        updates, a_opt = actor_update(grads, state.opt_states.actor_opt_state)
        shared = optax.apply_updates(state.params.actor_params, updates)
        # Keep both param slots in sync (the rollout's critic view reads the
        # critic slot).
        params = ActorCriticParams(shared, shared)
        metrics = jax.lax.pmean(metrics, axis_name="data")
        new_opts = ActorCriticOptStates(a_opt, state.opt_states.critic_opt_state)
        return CoreLearnerState(params, new_opts, state.key, obs_stats), metrics

    return jax.jit(
        jax.shard_map(
            per_shard,
            mesh=mesh,
            in_specs=(CoreLearnerState(P(), P(), P(), P()), P(None, "data")),
            out_specs=(CoreLearnerState(P(), P(), P(), P()), P()),
            check_vma=False,
        )
    )


def run_experiment(config: Any) -> float:
    return _run(
        config,
        learn_step_builder=get_shared_impala_learn_step,
        networks_builder=build_shared_networks,
    )


def main() -> float:
    import sys

    config = config_lib.compose(
        config_lib.default_config_dir(),
        "default/sebulba/default_ff_impala_shared_torso.yaml",
        sys.argv[1:],
    )
    return run_experiment(config)


if __name__ == "__main__":
    main()
