"""Sebulba IMPALA (reference stoix/systems/impala/sebulba/ff_impala.py, 1054 LoC).

Off-policy actor-critic with V-trace corrections (Espeholt et al. 2018): the
actor threads' stored log-probs are the behavior policy; the learner computes
V-trace value targets and policy-gradient advantages
(stoix_tpu.ops.multistep.vtrace_td_error_and_advantage, replacing the
reference's rlax vmap at :426-439) in one pass per rollout. Shares the Sebulba
scaffolding (threads/pipeline/param-server/async-eval) with sebulba ff_ppo.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

from stoix_tpu.base_types import ActorCriticOptStates, ActorCriticParams, PPOTransition
from stoix_tpu.ops import running_statistics
from stoix_tpu.ops.multistep import vtrace_td_error_and_advantage
from stoix_tpu.systems.ppo.sebulba.ff_ppo import CoreLearnerState, run_experiment as _run
from stoix_tpu.utils import config as config_lib


def get_impala_learn_step(actor_apply, critic_apply, update_fns, config, mesh: Mesh):
    actor_update, critic_update = update_fns
    gamma = float(config.system.gamma)

    normalize_obs = bool(config.system.get("normalize_observations", False))

    def per_shard(state: CoreLearnerState, traj: PPOTransition):
        # Match the actor path: observations the behavior policy consumed were
        # normalized with these (pre-update) statistics; fold the raw batch in
        # afterwards so the stats keep advancing.
        obs_stats = state.obs_stats
        if normalize_obs:
            raw_obs = traj.obs
            traj = traj._replace(
                obs=running_statistics.normalize_observation(traj.obs, obs_stats),
                next_obs=running_statistics.normalize_observation(traj.next_obs, obs_stats),
            )
            obs_stats = running_statistics.update(
                obs_stats, raw_obs.agent_view, axis_names=("data",),
                std_min_value=5e-4, std_max_value=5e4,
            )

        def loss_fn(params: ActorCriticParams):
            dist = actor_apply(params.actor_params, traj.obs)
            online_log_prob = dist.log_prob(traj.action)  # [T, E]
            values = critic_apply(params.critic_params, traj.obs)  # [T, E]
            bootstrap = critic_apply(params.critic_params, traj.next_obs)  # [T, E]

            rhos = jnp.exp(jax.lax.stop_gradient(online_log_prob) - traj.log_prob)
            d_t = gamma * (1.0 - traj.done.astype(jnp.float32))
            lam = float(config.system.get("vtrace_lambda", 1.0))
            errors, pg_adv, _ = jax.vmap(
                lambda v, b, r, d, rho: vtrace_td_error_and_advantage(v, b, r, d, rho, lam),
                in_axes=1,
                out_axes=1,
            )(
                jax.lax.stop_gradient(values),
                jax.lax.stop_gradient(bootstrap),
                traj.reward,
                d_t,
                rhos,
            )
            pg_loss = -jnp.mean(pg_adv * online_log_prob)
            value_targets = jax.lax.stop_gradient(errors + values)
            value_loss = 0.5 * jnp.mean((values - value_targets) ** 2)
            entropy = dist.entropy().mean()
            total = (
                pg_loss
                + float(config.system.get("vf_coef", 0.5)) * value_loss
                - float(config.system.get("ent_coef", 0.01)) * entropy
            )
            return total, {
                "actor_loss": pg_loss,
                "value_loss": value_loss,
                "entropy": entropy,
                "mean_rho": jnp.mean(rhos),
            }

        grads, metrics = jax.grad(loss_fn, has_aux=True)(state.params)
        grads = jax.lax.pmean(grads, axis_name="data")
        a_updates, a_opt = actor_update(
            grads.actor_params, state.opt_states.actor_opt_state
        )
        c_updates, c_opt = critic_update(
            grads.critic_params, state.opt_states.critic_opt_state
        )
        params = ActorCriticParams(
            optax.apply_updates(state.params.actor_params, a_updates),
            optax.apply_updates(state.params.critic_params, c_updates),
        )
        metrics = jax.lax.pmean(metrics, axis_name="data")
        return CoreLearnerState(params, ActorCriticOptStates(a_opt, c_opt), state.key, obs_stats), metrics

    return jax.jit(
        jax.shard_map(
            per_shard,
            mesh=mesh,
            in_specs=(CoreLearnerState(P(), P(), P(), P()), P(None, "data")),
            out_specs=(CoreLearnerState(P(), P(), P(), P()), P()),
            check_vma=False,
        )
    )


def run_experiment(config: Any) -> float:
    return _run(config, learn_step_builder=get_impala_learn_step)


def main() -> float:
    import sys

    config = config_lib.compose(
        config_lib.default_config_dir(),
        "default/sebulba/default_ff_impala.yaml",
        sys.argv[1:],
    )
    return run_experiment(config)


if __name__ == "__main__":
    main()
