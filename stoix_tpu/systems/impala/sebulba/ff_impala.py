"""Sebulba IMPALA (reference stoix/systems/impala/sebulba/ff_impala.py, 1054 LoC).

Off-policy actor-critic with V-trace corrections (Espeholt et al. 2018): the
actor threads' stored log-probs are the behavior policy; the learner computes
V-trace value targets and policy-gradient advantages
(stoix_tpu.ops.multistep.vtrace_td_error_and_advantage, replacing the
reference's rlax vmap at :426-439) in one pass per rollout. Shares the Sebulba
scaffolding (threads/pipeline/param-server/async-eval) with sebulba ff_ppo.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

from stoix_tpu.base_types import ActorCriticOptStates, ActorCriticParams, PPOTransition
from stoix_tpu.observability import annotate
from stoix_tpu.ops import running_statistics, vtrace_td_error_and_advantage
from stoix_tpu.parallel.mesh import shard_map
from stoix_tpu.resilience import guards
from stoix_tpu.systems.ppo.sebulba.ff_ppo import CoreLearnerState, run_experiment as _run
from stoix_tpu.utils import config as config_lib


def build_impala_loss(actor_apply, critic_apply, config):
    """V-trace actor-critic loss over one [T, E] minibatch — shared by the
    separate-network and shared-torso variants. `actor_params`/`critic_params`
    may alias (shared torso)."""
    gamma = float(config.system.gamma)
    lam = float(config.system.get("vtrace_lambda", 1.0))
    clip_rho = float(config.system.get("clip_rho_threshold", 1.0))
    clip_pg_rho = float(config.system.get("clip_pg_rho_threshold", 1.0))

    def loss_fn(actor_params, critic_params, mb: PPOTransition):
        dist = actor_apply(actor_params, mb.obs)
        online_log_prob = dist.log_prob(mb.action)  # [T, E/m]
        values = critic_apply(critic_params, mb.obs)  # [T, E/m]
        bootstrap = critic_apply(critic_params, mb.next_obs)  # [T, E/m]

        rhos = jnp.exp(jax.lax.stop_gradient(online_log_prob) - mb.log_prob)
        d_t = gamma * (1.0 - mb.done.astype(jnp.float32))
        errors, pg_adv, _ = jax.vmap(
            lambda v, b, r, d, rho: vtrace_td_error_and_advantage(
                v, b, r, d, rho, lam, clip_rho, clip_pg_rho
            ),
            in_axes=1,
            out_axes=1,
        )(
            jax.lax.stop_gradient(values),
            jax.lax.stop_gradient(bootstrap),
            mb.reward,
            d_t,
            rhos,
        )
        pg_loss = -jnp.mean(pg_adv * online_log_prob)
        value_targets = jax.lax.stop_gradient(errors + values)
        value_loss = 0.5 * jnp.mean((values - value_targets) ** 2)
        entropy = dist.entropy().mean()
        total = (
            pg_loss
            + float(config.system.get("vf_coef", 0.5)) * value_loss
            - float(config.system.get("ent_coef", 0.01)) * entropy
        )
        return total, {
            "actor_loss": pg_loss,
            "value_loss": value_loss,
            "entropy": entropy,
            "mean_rho": jnp.mean(rhos),
        }

    return loss_fn


def split_env_minibatches(traj: PPOTransition, num_minibatches: int) -> PPOTransition:
    """[T, E] -> [m, T, E/m], time contiguous so each V-trace sees whole
    trajectories (reference ff_impala.py:525-556)."""
    return jax.tree.map(
        lambda x: jnp.swapaxes(
            x.reshape((x.shape[0], num_minibatches, -1) + x.shape[2:]), 0, 1
        ),
        traj,
    )


def maybe_normalize_rewards(traj: PPOTransition, config) -> PPOTransition:
    """Batch reward normalization option (reference ff_impala.py:385-389).

    Statistics are reduced over the "data" mesh axis so the scaling matches
    the reference's whole-batch normalization regardless of learner device
    count (per-shard stats would make gradients depend on the sharding)."""
    if not bool(config.system.get("normalize_rewards", False)):
        return traj
    r_mean = jax.lax.pmean(jnp.mean(traj.reward), "data")
    r_sq = jax.lax.pmean(jnp.mean(traj.reward**2), "data")
    r_std = jnp.sqrt(jnp.maximum(r_sq - r_mean**2, 0.0))
    scale = float(config.system.get("reward_scale", 1.0))
    eps = float(config.system.get("reward_eps", 1e-8))
    return traj._replace(reward=scale * (traj.reward - r_mean) / (r_std + eps))


def get_impala_learn_step(actor_apply, critic_apply, update_fns, config, mesh: Mesh):
    actor_update, critic_update = update_fns

    normalize_obs = bool(config.system.get("normalize_observations", False))
    num_minibatches = int(config.system.get("num_minibatches", 1))
    guard_mode = guards.resolve_mode(config)
    impala_loss = build_impala_loss(actor_apply, critic_apply, config)

    def per_shard(state: CoreLearnerState, traj: PPOTransition):
        # Match the actor path: observations the behavior policy consumed were
        # normalized with these (pre-update) statistics; fold the raw batch in
        # afterwards so the stats keep advancing.
        obs_stats = state.obs_stats
        if normalize_obs:
            raw_obs = traj.obs
            traj = traj._replace(
                obs=running_statistics.normalize_observation(traj.obs, obs_stats),
                next_obs=running_statistics.normalize_observation(traj.next_obs, obs_stats),
            )
            obs_stats = running_statistics.update(
                obs_stats, raw_obs.agent_view, axis_names=("data",),
                std_min_value=5e-4, std_max_value=5e4,
            )

        traj = maybe_normalize_rewards(traj, config)

        def loss_fn(params: ActorCriticParams, mb: PPOTransition):
            return impala_loss(params.actor_params, params.critic_params, mb)

        @annotate("impala_minibatch")
        def _minibatch(carry, mb: PPOTransition):
            params, opt_states = carry
            # value_and_grad: the guard needs the total loss (DCE'd when the
            # guard is off — jax.grad is a value_and_grad that drops it).
            (total_loss, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, mb
            )
            grads, metrics = jax.lax.pmean((grads, metrics), axis_name="data")
            a_updates, a_opt = actor_update(grads.actor_params, opt_states.actor_opt_state)
            c_updates, c_opt = critic_update(grads.critic_params, opt_states.critic_opt_state)
            new_params = ActorCriticParams(
                optax.apply_updates(params.actor_params, a_updates),
                optax.apply_updates(params.critic_params, c_updates),
            )
            # Divergence guard (resilience/guards.py): shard-consistent
            # skip/halt of non-finite updates on the replicated params.
            (params, opt_states), guard_metrics = guards.guard_update(
                guard_mode,
                new=(new_params, ActorCriticOptStates(a_opt, c_opt)),
                old=(params, opt_states),
                loss=total_loss,
                grads=grads,
                opt_state=opt_states,
                axis_names=("data",),
            )
            return (params, opt_states), {**metrics, **guard_metrics}

        (params, opt_states), metrics = jax.lax.scan(
            _minibatch,
            (state.params, state.opt_states),
            split_env_minibatches(traj, num_minibatches),
        )
        # skipped_updates is a COUNT (summed on the host into the registry
        # counter); everything else reports as a per-minibatch mean.
        metrics = {
            k: (jnp.sum(v) if k == "skipped_updates" else jnp.mean(v))
            for k, v in metrics.items()
        }
        return CoreLearnerState(params, opt_states, state.key, obs_stats), metrics

    return jax.jit(
        shard_map(
            per_shard,
            mesh=mesh,
            in_specs=(CoreLearnerState(P(), P(), P(), P()), P(None, "data")),
            out_specs=(CoreLearnerState(P(), P(), P(), P()), P()),
            # No in-shard vmap axis here, so the varying-manual-axes
            # validator runs (Anakin's pmean-over-vmap-axis limitation
            # does not apply — see systems/anakin.py).
            check_vma=True,
        )
    )


def run_experiment(config: Any) -> float:
    return _run(config, learn_step_builder=get_impala_learn_step)


def main() -> float:
    import sys

    config = config_lib.compose(
        config_lib.default_config_dir(),
        "default/sebulba/default_ff_impala.yaml",
        sys.argv[1:],
    )
    return run_experiment(config)


if __name__ == "__main__":
    main()
