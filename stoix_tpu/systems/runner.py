"""Shared Anakin host loop.

The reference repeats `run_experiment` in every system file (deliberate
duplication, reference README.md:50-52); here the host loop — the part that is
genuinely identical across systems — is shared, while each system file keeps
its full learner (`get_learner_fn`) and setup (`learner_setup`) for
hackability. The loop matches reference ff_ppo.py:554-705: learn / log /
evaluate / checkpoint / absolute metric.
"""

from __future__ import annotations

import time
from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

from stoix_tpu import envs
from stoix_tpu.evaluator import evaluator_setup, get_rnn_evaluator_fn
from stoix_tpu.parallel import create_mesh, fetch_global, is_coordinator, maybe_initialize_distributed
from stoix_tpu.utils.checkpointing import checkpointer_from_config
from stoix_tpu.utils.logger import LogEvent, StoixLogger
from stoix_tpu.utils.timestep_checker import check_total_timesteps


class AnakinSetup(NamedTuple):
    """What a system's learner_setup returns to the shared runner."""

    learn: Callable[[Any], Any]  # jitted shard_mapped learner
    learner_state: Any
    eval_act_fn: Callable[..., Any]  # act_fn for the evaluator
    eval_params_fn: Callable[[Any], Any]  # learner_state -> params for eval


SetupFn = Callable[[envs.Environment, Any, Any, jax.Array], AnakinSetup]


def run_anakin_experiment(
    config: Any,
    setup_fn: SetupFn,
    warmup_fn: Optional[Callable] = None,
    evaluator_setup_fn: Callable = None,
) -> float:
    """Generic Anakin experiment: returns final eval episode-return mean."""
    maybe_initialize_distributed(config)
    mesh = create_mesh(dict(config.arch.get("mesh") or {"data": -1}))
    config = check_total_timesteps(config, int(mesh.shape["data"]))
    config.logger.system_name = config.system.system_name

    env, eval_env = envs.make(config)

    key = jax.random.PRNGKey(int(config.arch.seed))
    key, setup_key = jax.random.split(key)
    setup = setup_fn(env, config, mesh, setup_key)
    learner_state = setup.learner_state

    if warmup_fn is not None:
        learner_state = warmup_fn(learner_state)
        jax.block_until_ready(jax.tree.leaves(learner_state)[0])

    # Resume: restore a saved learner state into the freshly built (correctly
    # sharded) template (reference ff_ppo.py:504-512 via Checkpointer.restore).
    ckpt_cfg = config.logger.checkpointing
    start_step = 0
    if ckpt_cfg.get("load_model", False):
        from stoix_tpu.utils.checkpointing import Checkpointer

        load_args = ckpt_cfg.get("load_args") or {}
        loader = Checkpointer(
            model_name=config.system.system_name,
            rel_dir=load_args.get("load_path") or "checkpoints",
            checkpoint_uid=load_args.get("checkpoint_uid"),
        )
        loader.check_version()
        learner_state, start_step = loader.restore(
            learner_state, load_args.get("timestep")
        )
        if is_coordinator():
            print(f"[checkpoint] restored state from step {start_step}")

    make_evaluators = evaluator_setup_fn or evaluator_setup
    evaluator, absolute_evaluator = make_evaluators(eval_env, setup.eval_act_fn, config, mesh)
    logger = StoixLogger(config)
    checkpointer = checkpointer_from_config(config, config.system.system_name)

    steps_per_eval = (
        int(config.system.rollout_length)
        * int(config.arch.total_num_envs)
        * int(config.arch.num_updates_per_eval)
    )

    best_params = jax.tree.map(jnp.copy, setup.eval_params_fn(learner_state))
    best_return = -jnp.inf
    final_return = 0.0

    for eval_idx in range(int(config.arch.num_evaluation)):
        start = time.time()
        output = setup.learn(learner_state)
        jax.block_until_ready(output.learner_state)
        learner_state = output.learner_state
        elapsed = time.time() - start
        t = start_step + (eval_idx + 1) * steps_per_eval

        # Collective fetch: sharded global metrics are not host-addressable
        # under multi-process runs; every process participates.
        episode_metrics = envs.get_final_step_metrics(
            fetch_global(dict(output.episode_metrics), mesh)
        )
        train_metrics = fetch_global(dict(output.train_metrics), mesh)
        sps = steps_per_eval / elapsed
        if is_coordinator():
            logger.log({**episode_metrics, "steps_per_second": sps}, t, eval_idx, LogEvent.ACT)
            logger.log(
                jax.tree.map(lambda x: x.mean(), train_metrics), t, eval_idx, LogEvent.TRAIN
            )

        trained_params = setup.eval_params_fn(learner_state)
        key, ek = jax.random.split(key)
        eval_metrics = fetch_global(evaluator(trained_params, ek), mesh)
        if is_coordinator():
            logger.log(eval_metrics, t, eval_idx, LogEvent.EVAL)

        mean_return = float(eval_metrics["episode_return"].mean())
        final_return = mean_return
        if mean_return >= float(best_return):
            best_return = mean_return
            best_params = jax.tree.map(jnp.copy, trained_params)

        # Orbax saves sharded globals collectively: ALL processes call save.
        if checkpointer is not None:
            checkpointer.save(t, learner_state, mean_return)
            # The state is donated to the next learn() call — an async save
            # still serializing those buffers would read deleted memory.
            checkpointer.wait()

    if bool(config.arch.get("absolute_metric", True)):
        key, ek = jax.random.split(key)
        abs_metrics = fetch_global(absolute_evaluator(best_params, ek), mesh)
        if is_coordinator():
            logger.log(
                abs_metrics,
                start_step + int(config.arch.total_timesteps),
                int(config.arch.num_evaluation),
                LogEvent.ABSOLUTE,
            )
        final_return = float(abs_metrics["episode_return"].mean())

    if checkpointer is not None:
        # Wait for in-flight async saves; otherwise interpreter shutdown races
        # orbax's executor ("cannot schedule new futures after shutdown").
        checkpointer.close()
    logger.close()
    return final_return


def run_rnn_anakin_experiment(config: Any, setup_fn: SetupFn) -> float:
    """Anakin host loop for recurrent systems: identical to
    run_anakin_experiment but evaluates with the hidden-state-carrying RNN
    evaluator (setup_fn's eval_act_fn must have the rnn_act_fn signature)."""
    from stoix_tpu.networks.base import ScannedRNN

    hidden_size = int(config.network.get("rnn_hidden_size", 128))
    cell_type = str(config.network.get("rnn_cell_type", "gru"))

    def rnn_evaluator_setup(eval_env, act_fn, cfg, mesh):
        init_h = lambda: ScannedRNN.initialize_carry(cell_type, hidden_size, (1,))
        evaluator = get_rnn_evaluator_fn(eval_env, act_fn, cfg, mesh, init_h)
        absolute = get_rnn_evaluator_fn(
            eval_env, act_fn, cfg, mesh, init_h,
            eval_multiplier=int(cfg.arch.get("absolute_metric_multiplier", 10)),
        )
        return evaluator, absolute

    return run_anakin_experiment(config, setup_fn, evaluator_setup_fn=rnn_evaluator_setup)
