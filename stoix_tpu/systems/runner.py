"""Shared Anakin host loop — a PIPELINED dispatcher.

The reference repeats `run_experiment` in every system file (deliberate
duplication, reference README.md:50-52); here the host loop — the part that is
genuinely identical across systems — is shared, while each system file keeps
its full learner (`get_learner_fn`) and setup (`learner_setup`) for
hackability.

The Podracer/Anakin promise is that the accelerator never idles, yet the
original synchronous loop serialized every eval window:

    learn -> block_until_ready -> 2x collective fetch -> eval launch
          -> checkpointer.save + wait  (state donated to the next learn)

Every host-side phase in that chain was dead accelerator time. This loop is a
one-window-deep software pipeline instead. Per eval window it DISPATCHES

    learn_k -> snapshot_k (on-device params/state copy) -> eval_k
            -> fetch_k (ONE coalesced collective over episode+train+eval
               metrics)

and only THEN processes window k-1 on the host (materialize metrics, log,
update best params, hand the checkpoint snapshot to orbax). JAX async dispatch
overlaps all of that host work with the device executing window k. The
invariants that make it legal:

  * Donation stays legal: `snapshot_k` is a fresh on-device copy taken from
    the stream BEFORE `learn_{k+1}` is dispatched, so eval, best-params
    tracking, and orbax serialization read buffers no later program donates.
    The forced `checkpointer.wait()` on the hot path is gone — async saves
    serialize the snapshot, not the donated state (utils/checkpointing.py).
  * Bit-identical training: the sequence of `learn` calls, their inputs, and
    the per-window eval key splits are exactly those of the synchronous loop
    (`arch.pipelined_loop=false` keeps that loop as a debug fallback;
    tests/test_runner_pipeline.py pins trajectory equality).
  * The learner is AOT-compiled (utils/jax_utils.aot_warmup) before the timed
    loop, so the first window's logged steps_per_second no longer includes
    XLA compile time; `LAST_RUN_STATS["steady_state_sps"]` additionally
    reports the post-first-window rate.

`arch.fused_eval` folds a fusion-capable (FF) evaluator INTO the jitted learn
program — classic Anakin, one XLA launch per window; RNN/stateful evaluators
fall back to the snapshot-overlap path automatically.

Observability (stoix_tpu/observability, docs/DESIGN.md §2.2): per-phase
host-side wall time (learn_s/eval_s/fetch_s/ckpt_s + compile_s) accumulates
into the process-wide metrics registry
(`stoix_tpu_runner_phase_seconds_total{phase=...}`) and is mirrored into the
dict-compatible `LAST_RUN_STATS["phase_breakdown"]` view at run end (bench.py
forwards it). With `logger.telemetry.enabled=true` every dispatcher phase
also records a host span (learn_dispatch / snapshot_dispatch / eval_dispatch
/ fetch_dispatch / fetch_materialize / log / ckpt_save), exported as
Perfetto-loadable JSON next to the `jax.profiler` device trace that
STOIX_TPU_PROFILE_DIR=<dir> wraps around one steady-state eval window. In the
pipelined loop the phases are HOST attribution: device time spent in
learn/eval surfaces as fetch_s (the materialize wait), while learn_s/eval_s
shrink to dispatch cost.

Resilience (stoix_tpu/resilience, docs/DESIGN.md §2.3): SIGTERM/SIGINT
request a graceful stop at the next window boundary — the loop drains the
one-window-deep dispatcher, force-saves an emergency checkpoint of the live
state, and returns cleanly so the run resumes instead of losing the window.
`system.update_guard` wires the in-jit divergence guard's host half through
process_window (skip counting / halt raising), and STOIX_TPU_FAULT /
arch.fault_spec arms the deterministic chaos layer.

Launch hardening (docs/DESIGN.md §2.4, `arch.preflight`): with
`arch.preflight.enabled=true` the run starts with a subprocess-isolated
backend probe (bounded timeout + backoff retries — a wedged PJRT runtime
raises BackendUnavailableError instead of hanging this process) and config
cross-validation BEFORE any device work; the AOT compile and the first
window's execution run under deadline watchdogs that dump all thread stacks
+ the registry snapshot and raise CompileStallError on stall; and the
compiled learner's memory_analysis() is checked against device HBM
(ResourcePreflightError beats a 20-minutes-later runtime OOM). Off (the
default) adds zero work and zero host syncs — bit-identical. On, the only
semantic change is ONE block_until_ready on the first window's metrics (the
watchdogged first-execution check); trajectory values are unchanged.

Restore is topology-elastic (utils/checkpointing.py): a checkpoint saved on
an 8-device mesh resumes on 1 device (and vice versa) with bit-identical
params — the state materializes to host and re-places via the fresh
template's shardings.

State integrity (stoix_tpu/resilience/integrity.py, docs/DESIGN.md §2.9,
`arch.integrity`): with the sentinel on, every window's dispatch also
enqueues a tiny shard_mapped fingerprint program over the replicated state
groups; the resulting [num_devices] uint32 vectors ride the SAME coalesced
metric fetch (zero extra collectives) and are compared on the host when the
window materializes — a cross-replica disagreement (HBM bit-flip, wrong-math
core) raises StateCorruptionError BEFORE that window's checkpoint snapshot
is handed to orbax, so a corrupt state is never saved. The optional
determinism probe replays a recorded learn step every N windows and compares
output fingerprints bitwise. Off (the default) adds zero dispatches and zero
host work — bit-identical (tests/test_integrity.py pins on AND off).
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

from stoix_tpu import envs
from stoix_tpu.evaluator import evaluator_setup, get_rnn_evaluator_fn
from stoix_tpu.observability import (
    HeartbeatBoard,
    RunStats,
    device_annotation,
    flightrec,
    get_health_monitor,
    get_logger,
    get_ops_server,
    get_registry,
    get_status_board,
    goodput,
    span,
)
from stoix_tpu.observability import aggregate as fleet_metrics
from stoix_tpu.parallel import (
    MeshRoles,
    fetch_global,
    fetch_global_async,
    is_coordinator,
    materialize,
    maybe_initialize_distributed,
)
from stoix_tpu.resilience import (
    PreemptionHandler,
    Watchdog,
    elastic,
    faultinject,
    fleet,
    guards,
    integrity,
    preflight,
)
from stoix_tpu.ops import scan_kernels
from stoix_tpu.utils import compilecache
from stoix_tpu.utils.checkpointing import checkpointer_from_config
from stoix_tpu.utils.jax_utils import aot_warmup
from stoix_tpu.utils.logger import LogEvent, StoixLogger
from stoix_tpu.utils.timestep_checker import check_total_timesteps

# Stats of the most recent run_anakin_experiment call (this process):
# phase_breakdown {compile_s, learn_s, eval_s, fetch_s, ckpt_s},
# steady_state_sps, pipelined, fused_eval. bench.py reads this. The values
# are published to the process-wide metrics registry during the run
# (stoix_tpu_runner_* series — the source of truth) and refreshed into this
# dict-compatible view at run end.
LAST_RUN_STATS = RunStats()

_PHASE_NAMES = ("compile_s", "learn_s", "gossip_s", "eval_s", "fetch_s", "ckpt_s")


class _PhaseClock:
    """Per-run view over the cumulative registry phase counter: records into
    `stoix_tpu_runner_phase_seconds_total{phase=...}` and reports this run's
    deltas (the registry is process-wide; LAST_RUN_STATS is per-run)."""

    def __init__(self) -> None:
        self._counter = get_registry().counter(
            "stoix_tpu_runner_phase_seconds_total",
            "Cumulative Anakin host-loop wall time per phase",
        )
        self._base = {
            name: self._counter.value({"phase": name}) for name in _PHASE_NAMES
        }
        self._touched: set = set()

    def add(self, name: str, seconds: float) -> None:
        self._touched.add(name)
        self._counter.inc(seconds, {"phase": name})

    def breakdown(self) -> dict:
        # gossip_s appears only in runs that actually dispatched a gossip step;
        # lockstep runs keep the original five-key schema bench.py and the
        # observability contract tests pin.
        return {
            name: self._counter.value({"phase": name}) - self._base[name]
            for name in _PHASE_NAMES
            if name != "gossip_s" or name in self._touched
        }


class AnakinSetup(NamedTuple):
    """What a system's learner_setup returns to the shared runner."""

    learn: Callable[[Any], Any]  # jitted shard_mapped learner
    learner_state: Any
    eval_act_fn: Callable[..., Any]  # act_fn for the evaluator
    eval_params_fn: Callable[[Any], Any]  # learner_state -> params for eval
    # Optional GossipPlan (parallel/gossip.py, docs/DESIGN.md §2.12): when its
    # step is set, the runner dispatches it every plan.interval windows right
    # after the learn dispatch. None (the default) = lockstep — the field
    # defaults keep older setups (and _replace-based wrappers) source-compatible.
    gossip: Any = None
    # Optional elastic-restore seam (docs/DESIGN.md §2.14): a transform over
    # the emergency store's digest-verified host arrays, applied BEFORE
    # tree-path placement. The population setup installs its shrink/grow
    # member re-placement here; None = restore the store as saved.
    restore_transform: Any = None


SetupFn = Callable[[envs.Environment, Any, Any, jax.Array], AnakinSetup]


class _Window(NamedTuple):
    """Everything dispatched for one eval window, processed one iteration
    later (pipelined) or immediately (synchronous fallback)."""

    eval_idx: int
    t: int  # global env-step count at window end
    snapshot: Any  # on-device copy of eval params (donation-safe)
    ckpt_state: Any  # on-device copy of the full learner state, or None
    metrics: Any  # ONE coalesced device tree: episode/train/eval metrics


def _maybe_watchdog(pf: Any, stage: str, deadline_s: float):
    """A deadline Watchdog when preflight is enabled; a free nullcontext
    otherwise (the off path must add zero threads and zero work)."""
    if not pf.enabled:
        return contextlib.nullcontext()
    return Watchdog(stage, deadline_s, hard_exit_grace_s=pf.hard_exit_grace_s)


# ONE jit instance so per-window snapshot copies hit the compile cache
# (jax.jit memoizes per input tree structure/avals).
_TREE_COPY = jax.jit(lambda t: jax.tree.map(jnp.copy, t))


def _tree_copy(tree: Any) -> Any:
    """On-device snapshot: a jitted whole-tree copy (shardings preserved).
    The copy is enqueued in the device stream BEFORE the next learn dispatch,
    so donating the source buffers afterwards is legal."""
    return _TREE_COPY(tree)


def run_anakin_experiment(
    config: Any,
    setup_fn: SetupFn,
    warmup_fn: Optional[Callable] = None,
    evaluator_setup_fn: Callable = None,
) -> float:
    """Generic Anakin experiment: returns final eval episode-return mean."""
    # Resilience (docs/DESIGN.md §2.3): arm the chaos plan (no-op unless
    # STOIX_TPU_FAULT / arch.fault_spec is set) BEFORE the learner is built —
    # the in-jit nan_loss guard reads it at trace time — and resolve the
    # divergence-guard mode for the host-side checks below.
    faultinject.configure(config.arch.get("fault_spec"))
    guard_mode = guards.resolve_mode(config)
    # Goodput ledger (docs/DESIGN.md §2.13): opened before any setup work so
    # restore/compile/stall seconds are all inside the attributed wall. Pure
    # host arithmetic — always on, bit-identity untouched. set_active lets
    # out-of-loop sites (faultinject stalls, watchdog) charge their seconds.
    ledger = goodput.GoodputLedger().start()
    goodput.set_active(ledger)
    # Compile economy (docs/DESIGN.md §2.7): the persistent-cache knobs must
    # land before the FIRST compile this process does (network init included),
    # and the multistep scan-kernel default before the learner is traced —
    # both are trace/compile-time statics, so the off defaults add zero work.
    compilecache.configure(config)
    scan_kernels.configure_from_config(config)
    # Launch hardening (docs/DESIGN.md §2.4): probe the backend in a
    # SUBPROCESS and cross-validate the config BEFORE this process commits to
    # device work — a wedged PJRT runtime or a bad shape aborts here with a
    # typed error, not twenty minutes in. Off by default (zero added work).
    pf = preflight.settings_from_config(config)
    if pf.enabled:
        with span("preflight"):
            probe = preflight.probe_backend(
                timeout_s=pf.probe_timeout_s,
                attempts=pf.probe_attempts,
                backoff_base_s=pf.probe_backoff_base_s,
                backoff_max_s=pf.probe_backoff_max_s,
            )
            preflight.validate_config(config, device_count=probe.device_count)
            get_logger("stoix_tpu.resilience").info(
                "[preflight] backend healthy (%s x%d, attempt %d) and config "
                "cross-checks pass", probe.platform, probe.device_count,
                probe.attempts,
            )
    maybe_initialize_distributed(config)
    # Device assignment goes through the unified mesh-role abstraction
    # (parallel/roles.py, docs/DESIGN.md §2.11): Anakin's learn role owns the
    # whole `arch.mesh` (colocated act/learn/evaluate), so this is the same
    # mesh create_mesh built directly before MeshRoles existed — and the
    # population runner's ("pop", "data") mesh arrives through the same path.
    roles = MeshRoles.from_config(config)
    mesh = roles.learn_mesh()
    # Fleet coordination (docs/DESIGN.md §2.6, arch.fleet): cross-host agreed
    # stop decisions (flags piggybacked on the coalesced metric fetch),
    # heartbeat-based partition detection, straggler skew telemetry, and the
    # local-shard emergency checkpoint. Off (the default) = None = zero extra
    # work, bit-identical host loop.
    fleet_coord = fleet.fleet_from_config(config)
    if fleet_coord is not None:
        fleet_coord.start()
    # State-integrity sentinel (docs/DESIGN.md §2.9, arch.integrity): bound
    # below once the learner state exists. None (the default) = zero extra
    # dispatches, zero host work, bit-identical host loop.
    sentinel = integrity.sentinel_from_config(config)
    config = check_total_timesteps(config, int(mesh.shape["data"]))
    config.logger.system_name = config.system.system_name

    env, eval_env = envs.make(config)

    key = jax.random.PRNGKey(int(config.arch.seed))
    key, setup_key = jax.random.split(key)
    setup = setup_fn(env, config, mesh, setup_key)
    learner_state = setup.learner_state

    if warmup_fn is not None:
        learner_state = warmup_fn(learner_state)
        jax.block_until_ready(jax.tree.leaves(learner_state)[0])

    # Resume: restore a saved learner state into the freshly built (correctly
    # sharded) template (reference ff_ppo.py:504-512 via Checkpointer.restore).
    ckpt_cfg = config.logger.checkpointing
    start_step = 0
    restore_skipped = 0
    restore_report: list = []
    t_restore = time.perf_counter()
    if ckpt_cfg.get("load_model", False):
        load_args = ckpt_cfg.get("load_args") or {}
        load_path = load_args.get("load_path")
        if load_path and fleet.is_emergency_store(load_path):
            # A fleet local-shard emergency store (a partition survivor's
            # rescue save, docs/DESIGN.md §2.6): restore through the same
            # tree-path placement as the topology-elastic path — params
            # round-trip bit-identical onto the (possibly shrunk) new mesh.
            learner_state, start_step = fleet.restore_emergency(
                learner_state, load_path,
                raw_transform=getattr(setup, "restore_transform", None),
            )
        else:
            from stoix_tpu.utils.checkpointing import Checkpointer

            loader = Checkpointer(
                model_name=config.system.system_name,
                rel_dir=load_path or "checkpoints",
                checkpoint_uid=load_args.get("checkpoint_uid"),
            )
            loader.check_version()
            learner_state, start_step = loader.restore(
                learner_state, load_args.get("timestep")
            )
            # How many newer-but-unusable checkpoints the fallback walk
            # rejected (with typed reasons — structure / non_finite /
            # digest), surfaced in LAST_RUN_STATS.resilience below.
            restore_skipped = len(loader.last_restore_report)
            restore_report = list(loader.last_restore_report)
        # Restore wall time is recovery, not compute: a relaunch spending
        # minutes re-reading checkpoints must show up in the badput ledger.
        ledger.note("recovery", time.perf_counter() - t_restore)
        if is_coordinator():
            get_logger("stoix_tpu.checkpoint").info(
                "[checkpoint] restored state from step %d%s", start_step,
                f" ({restore_skipped} newer checkpoint(s) rejected)"
                if restore_skipped else "",
            )

    make_evaluators = evaluator_setup_fn or evaluator_setup
    evaluator, absolute_evaluator = make_evaluators(eval_env, setup.eval_act_fn, config, mesh)
    logger = StoixLogger(config)
    checkpointer = checkpointer_from_config(config, config.system.system_name)

    # Ops plane (docs/DESIGN.md §2.13), wired AFTER StoixLogger: its
    # observability.configure() call is the per-run reset (fresh
    # HealthMonitor + flight-recorder ring) and starts the /metrics·/healthz
    # ·/statusz·/varz server when logger.telemetry.http.enabled. Everything
    # below is host-memory bookkeeping — always on, bit-identity untouched.
    telemetry_cfg = dict(config.logger.get("telemetry") or {})
    http_cfg = dict(telemetry_cfg.get("http") or {})
    recorder = flightrec.get_flight_recorder()
    recorder.set_context(
        architecture="anakin",
        system=str(config.system.system_name),
        seed=int(config.arch.seed),
    )
    status = get_status_board()
    status.update(
        {
            "run_id": f"{config.system.system_name}_seed{int(config.arch.seed)}",
            "architecture": "anakin",
            "system": str(config.system.system_name),
            "step": start_step,
            "restore_skipped": restore_skipped,
            "last_restore_report": restore_report,
            "quarantine_file": dict(config.arch.get("integrity") or {}).get(
                "quarantine_file", "checkpoints/quarantine.json"
            ),
        }
    )
    # /healthz source: the host loop beats once per window; an injected
    # host_stall (or a genuinely wedged loop) lets the age cross
    # stale_after_s and the endpoint flips to 503. Registered fresh each run
    # — configure() above already dropped any previous incarnation's board.
    monitor = get_health_monitor()
    loop_beats = HeartbeatBoard()
    monitor.register_board(
        "anakin-host-loop",
        loop_beats,
        stale_after_s=float(http_cfg.get("stale_after_s", 60.0) or 60.0),
    )
    ops_server = get_ops_server()
    aggregator = None
    if ops_server is not None and fleet_coord is not None:
        # Host-level metric federation over the fleet KV store: publish this
        # host's snapshots off the hot path; /metrics/fleet folds every
        # host's newest blob with per-host labels (aggregate.py).
        aggregator = fleet_metrics.aggregator_from_fleet(
            fleet_coord,
            interval_s=float(http_cfg.get("aggregate_interval_s", 10.0) or 10.0),
        )
        if aggregator is not None:
            aggregator.start()
            ops_server.set_aggregator(aggregator)

    if sentinel is not None:
        # Bind AFTER restore: the fingerprint program is built once for this
        # mesh + state structure (never per window — STX012). The resume info
        # points a rc-88 relaunch at THIS run's orbax store, whose newest
        # digest-verified step is the recovery target.
        sentinel.bind(mesh, learner_state)
        if checkpointer is not None:
            sentinel.set_resume_info(checkpointer.directory)
        sentinel.install_excepthook()

    steps_per_eval = (
        int(config.system.rollout_length)
        * int(config.arch.total_num_envs)
        * int(config.arch.num_updates_per_eval)
    )
    num_evaluation = int(config.arch.num_evaluation)

    pipelined = bool(config.arch.get("pipelined_loop", True))
    fused = bool(config.arch.get("fused_eval", False)) and getattr(
        evaluator, "supports_fusion", False
    )
    # arch.ckpt_snapshot=false: memory fallback for states too big to copy
    # (off-policy replay buffers near HBM capacity). No on-device snapshot is
    # taken; the loop runs synchronously and saves the LIVE state + wait()
    # before the next donating dispatch — the pre-pipeline semantics.
    snapshot_ckpt = bool(config.arch.get("ckpt_snapshot", True))
    if checkpointer is not None and not snapshot_ckpt:
        pipelined = False

    learn = setup.learn
    # Gossip groups (parallel/gossip.py, docs/DESIGN.md §2.12): the mixing
    # step the grouped setup returned, dispatched through this same pipelined
    # stream every `interval` windows so it overlaps the next window's host
    # work like any other device program. step=None covers both lockstep
    # setups and the single-group identity short-circuit that keeps group:1
    # bitwise-lockstep.
    gossip_plan = getattr(setup, "gossip", None)
    gossip_step = gossip_plan.step if gossip_plan is not None else None
    gossip_interval = gossip_plan.interval if gossip_plan is not None else 0
    gossip_rounds = 0
    gossip_counter = (
        get_registry().counter(
            "stoix_tpu_gossip_rounds_total",
            "Cross-group parameter mixing rounds dispatched",
        )
        if gossip_step is not None
        else None
    )
    phases = _PhaseClock()
    compile_counter = get_registry().counter(
        "stoix_tpu_runner_compile_seconds_total",
        "Cumulative XLA compile time paid by AOT warmup",
    )

    if fused:
        # One XLA program per window: learn + eval-params selection + the FF
        # evaluator, donated like the bare learner. The system's jit wrapper
        # is unwrapped so donation lives ONLY on this outer jit.
        learn_inner = getattr(learn, "__wrapped__", learn)
        donate = {} if os.environ.get("STOIX_TPU_NO_DONATE") else {"donate_argnums": (0,)}

        def _fused_step(state: Any, eval_key: jax.Array):
            output = learn_inner(state)
            eval_metrics = evaluator(setup.eval_params_fn(output.learner_state), eval_key)
            return output, eval_metrics

        fused_step = jax.jit(_fused_step, **donate)

    # AOT warmup: pay the learner's XLA compile before the timed loop so the
    # first window's steps_per_second is throughput, not compile time. With
    # preflight on, the compile runs under a deadline watchdog (a wedged
    # backend raises CompileStallError with a full stack dump instead of
    # hanging) and the compiled program's memory_analysis() is gated against
    # device HBM before anything executes. With `arch.compile_cache.export_dir`
    # set, the non-fused learner additionally round-trips the jax.export AOT
    # store (docs/DESIGN.md §2.7): a matching serialized artifact skips
    # trace+lower here, and a miss serializes this compile for peer hosts.
    cc_settings = compilecache.settings_from_config(config)
    export_dir = cc_settings["export_dir"] if cc_settings["enabled"] else None
    cache_before = compilecache.cache_stats()
    aot_info = {"source": "compile", "export_path": None}
    t0 = time.perf_counter()
    with span("aot_warmup", fused=fused):
        with _maybe_watchdog(pf, "first_compile", pf.compile_deadline_s):
            faultinject.maybe_slow_compile()
            if fused:
                # Aval-identical stand-in for the per-window eval keys below.
                # (The fused program embeds the evaluator, so it is not served
                # by the learn-function export store.)
                example_key = jax.random.split(jax.random.PRNGKey(0))[1]
                fused_step = aot_warmup(fused_step, learner_state, example_key)
            else:
                learn, aot_info = compilecache.warmup_with_export(
                    learn, (learner_state,), export_dir,
                    name=config.system.system_name,
                )
            if gossip_step is not None:
                # The mixing program's compile is paid here too, so the first
                # gossip window's wall time is dispatch cost like every other.
                gossip_step = aot_warmup(
                    gossip_step, learner_state, jnp.asarray(0, jnp.int32)
                )
    compile_s = time.perf_counter() - t0
    phases.add("compile_s", compile_s)
    compile_counter.inc(compile_s)
    # Per-entry compile observability (docs/DESIGN.md §2.7): which program
    # paid how much compile, and whether the persistent cache absorbed it.
    cache_after = compilecache.cache_stats()
    compile_stats = {
        "compile_s": round(compile_s, 6),
        "cache_hits": cache_after["hits"] - cache_before["hits"],
        "cache_misses": cache_after["misses"] - cache_before["misses"],
        "aot_source": aot_info["source"],
    }
    get_registry().gauge(
        "stoix_tpu_compile_entry_seconds",
        "AOT warmup wall seconds of the most recent compile, per entry point",
    ).set(compile_s, {"entry": "fused_step" if fused else "learn"})
    if pf.enabled:
        preflight.check_device_memory(
            fused_step if fused else learn, headroom=pf.hbm_headroom
        )

    best_params = _tree_copy(setup.eval_params_fn(learner_state))
    best_return = -jnp.inf
    final_return = 0.0

    profile_dir = os.environ.get("STOIX_TPU_PROFILE_DIR")
    # Profile a steady-state window (the second) when there is one; the first
    # window still carries one-off costs (evaluator/fetch compiles).
    profile_window = (1 if num_evaluation > 1 else 0) if profile_dir else -1

    window_walls: list = []
    window_done_at = time.perf_counter()
    # Step of the most recent window we DECIDED to checkpoint (the save is
    # issued one window later): orbax's own latest_step lags by that window,
    # so should_save consults this to avoid a spurious full-state copy.
    last_save_t: Optional[int] = None

    def dispatch_window(eval_idx: int) -> _Window:
        """Enqueue one full eval window on the device stream; never blocks on
        device results (post-compile, each call is dispatch cost only)."""
        nonlocal learner_state, key, last_save_t, gossip_rounds
        key, eval_key = jax.random.split(key)
        ts = time.perf_counter()
        # device_annotation: names this dispatch in the jax.profiler device
        # trace (STOIX_TPU_PROFILE_DIR) so host spans and TraceMe rows share
        # the taxonomy; a TraceMe is nanoseconds when no profiler is active.
        with span("learn_dispatch", window=eval_idx, fused=fused), \
                device_annotation("learn_dispatch"):
            if fused:
                output, eval_metrics = fused_step(learner_state, eval_key)
            else:
                output = learn(learner_state)
        phases.add("learn_s", time.perf_counter() - ts)
        learner_state = output.learner_state
        if gossip_step is not None and (eval_idx + 1) % gossip_interval == 0:
            # Mix BEFORE the snapshot below: eval, best-params tracking, and
            # checkpoints all observe the POST-gossip parameters. The round
            # index seeds random_peer's edge draw deterministically, and the
            # step donates the learn output it consumes (nothing else reads
            # the pre-gossip state).
            ts = time.perf_counter()
            with span("gossip_dispatch", window=eval_idx), \
                    device_annotation("gossip_dispatch"):
                learner_state = gossip_step(
                    learner_state, jnp.asarray(eval_idx, jnp.int32)
                )
            phases.add("gossip_s", time.perf_counter() - ts)
            gossip_rounds += 1
            gossip_counter.inc()
        t = start_step + (eval_idx + 1) * steps_per_eval

        # On-device snapshots, enqueued BEFORE the next learn dispatch ever
        # happens: donation of learner_state stays legal while eval/best/ckpt
        # consumers read the copies at their leisure. The full-state copy is
        # only taken for windows orbax's save policy will actually accept.
        with span("snapshot_dispatch", window=eval_idx):
            snapshot = _tree_copy(setup.eval_params_fn(learner_state))
            take_ckpt = (
                checkpointer is not None
                and snapshot_ckpt
                and checkpointer.should_save(t, last_issued=last_save_t)
            )
            if take_ckpt:
                last_save_t = t
            ckpt_state = _tree_copy(learner_state) if take_ckpt else None
            if fleet_coord is not None:
                # Rescue candidate for the partition path: an on-device copy
                # enqueued right after this window's learn, so once the
                # window's metrics materialize the copy is provably complete
                # and readable without any (possibly dead) peer.
                fleet_coord.stage_candidate(
                    t, ckpt_state if take_ckpt else _tree_copy(learner_state)
                )

        if not fused:
            ts = time.perf_counter()
            with span("eval_dispatch", window=eval_idx):
                eval_metrics = evaluator(snapshot, eval_key)
            phases.add("eval_s", time.perf_counter() - ts)

        # ONE coalesced collective fetch for the whole window (episode, train,
        # and eval metrics ride a single pytree -> a single host-sync point).
        ts = time.perf_counter()
        with span("fetch_dispatch", window=eval_idx):
            tree = {
                "episode": dict(output.episode_metrics),
                "train": dict(output.train_metrics),
                "eval": dict(eval_metrics),
            }
            if fleet_coord is not None:
                # Agreed-stop + skew transport: a tiny per-device payload
                # (stop-flag byte + last window wall-time) rides the SAME
                # coalesced fetch collective — every host decodes every
                # host's values when this window materializes, at zero extra
                # collectives, and the cross-host collective SEQUENCE stays
                # exactly the fetch stream (docs/DESIGN.md §2.6).
                tree["fleet"] = fleet_coord.telemetry_for_fetch(mesh)
            if sentinel is not None:
                # Replica fingerprints (docs/DESIGN.md §2.9): each device
                # folds ITS copy of the replicated state groups to a uint32
                # — the reduction is device-local, and the [num_devices]
                # vectors ride this same fetch, so the integrity check adds
                # zero collectives to the window.
                tree["integrity"] = sentinel.fingerprints(output.learner_state)
            metrics = fetch_global_async(tree, mesh)
        phases.add("fetch_s", time.perf_counter() - ts)
        return _Window(eval_idx, t, snapshot, ckpt_state, metrics)

    def process_window(window: _Window) -> None:
        """Host half: materialize the window's metrics, log, track best
        params, and hand the checkpoint snapshot to orbax (async, no wait)."""
        nonlocal best_params, best_return, final_return, window_done_at, last_save_t
        nonlocal agreed_stop
        ts = time.perf_counter()
        with span("fetch_materialize", window=window.eval_idx):
            fetched = materialize(window.metrics)
        phases.add("fetch_s", time.perf_counter() - ts)

        now = time.perf_counter()
        wall = now - window_done_at
        window_done_at = now
        window_walls.append(wall)

        if sentinel is not None:
            # Integrity verdict FIRST — before this window's checkpoint
            # snapshot is handed to orbax AND before confirm_candidate
            # promotes this window's state to the fleet rescue snapshot: a
            # corrupt state must never be persisted by EITHER path (a
            # concurrent partition would otherwise rescue-save exactly the
            # corruption being proven; window N-1's verified state stays the
            # candidate). The fingerprint vector is replicated data, so every
            # host computes the SAME verdict at the SAME window — the
            # corruption flag on the fleet byte is observability, not the
            # agreement mechanism.
            integrity_payload = fetched.pop("integrity")
            corruption = sentinel.verify(integrity_payload, window.eval_idx, window.t)
            if corruption is not None:
                # Last ring entry before the rc-88 path unwinds: the dumped
                # flight record ends with the verdict itself.
                recorder.record(
                    "integrity_verdict",
                    window=window.eval_idx,
                    step=window.t,
                    detail=str(corruption),
                )
                if fleet_coord is not None:
                    fleet_coord.request_stop(fleet.FLAG_CORRUPT, note=str(corruption))
                raise corruption
            if window.eval_idx == 0:
                # Window 0's fingerprint IS fingerprint(learn(probe_input))
                # — the determinism probe's reference, recorded for free.
                sentinel.record_probe_reference(integrity_payload)

        if fleet_coord is not None:
            # This window's metrics are on the host, so (stream ordering) its
            # learn completed — and the sentinel (above) vouched for its
            # state: promote the rescue candidate, decode the fleet-wide
            # flags + straggler wall-times, and record this window's wall for
            # the next dispatch's payload.
            fleet_coord.confirm_candidate(window.t)
            payload = fetched.pop("fleet")
            decision = fleet_coord.decide_from_fetch(payload, mesh)
            if decision.stop and agreed_stop is None:
                agreed_stop = decision
            fleet_coord.skew_from_fetch(payload, mesh, window.eval_idx)
            fleet_coord.note_window_wall(wall)

        episode_metrics = envs.get_final_step_metrics(fetched["episode"])
        train_metrics = fetched["train"]
        eval_metrics = fetched["eval"]
        # Divergence guard, host half: fold this window's skipped-update flags
        # into the registry counter; update_guard=halt raises DivergenceError
        # here, naming the step and the offending metric.
        guards.publish_guard_metrics(guard_mode, train_metrics, window.t)
        sps = steps_per_eval / wall
        get_registry().gauge(
            "stoix_tpu_runner_steps_per_second",
            "Env-steps/sec over the most recent eval window",
        ).set(sps)
        # Ops plane: /statusz freshness + one flight-recorder ring entry per
        # completed window (the last N of these are what an rc-86/87/88 dump
        # hands the post-mortem).
        status.update(
            {"window": window.eval_idx, "step": window.t,
             "steps_per_second": round(sps, 3)}
        )
        recorder.record(
            "window",
            window=window.eval_idx,
            step=window.t,
            wall_s=round(wall, 6),
            steps_per_second=round(sps, 3),
            phases={k: round(v, 6) for k, v in phases.breakdown().items()},
            fleet=fleet_coord is not None,
            fleet_stop=agreed_stop.describe() if agreed_stop is not None else None,
            integrity=sentinel is not None,
        )
        if is_coordinator():
            with span("log", window=window.eval_idx):
                logger.log(
                    {**episode_metrics, "steps_per_second": sps},
                    window.t, window.eval_idx, LogEvent.ACT,
                )
                logger.log(
                    jax.tree.map(lambda x: x.mean(), train_metrics),
                    window.t, window.eval_idx, LogEvent.TRAIN,
                )
                logger.log(eval_metrics, window.t, window.eval_idx, LogEvent.EVAL)

        mean_return = float(eval_metrics["episode_return"].mean())
        final_return = mean_return
        if mean_return >= float(best_return):
            best_return = mean_return
            best_params = window.snapshot  # already a donation-safe copy

        if checkpointer is not None:
            # Orbax saves sharded globals collectively: ALL processes call
            # save. The snapshot is not donated to anything, so the async save
            # needs no wait() here — serialization overlaps the next window.
            ts = time.perf_counter()
            with span("ckpt_save", window=window.eval_idx):
                if window.ckpt_state is not None:
                    checkpointer.save(window.t, window.ckpt_state, mean_return)
                elif not snapshot_ckpt and checkpointer.should_save(window.t):
                    # ckpt_snapshot=false forced the loop synchronous: the live
                    # state is not yet donated here, so save it directly and
                    # wait before the next dispatch can donate it (old
                    # semantics). Record the step so the preemption path does
                    # not force-rewrite an identical emergency checkpoint.
                    checkpointer.save(window.t, learner_state, mean_return)
                    checkpointer.wait()
                    last_save_t = window.t
            phases.add("ckpt_s", time.perf_counter() - ts)

        if window.eval_idx == profile_window:
            try:
                jax.profiler.stop_trace()
            except Exception:  # noqa: BLE001 — profiling must never kill a run
                pass

    # Graceful preemption: SIGTERM/SIGINT set a flag; the loop observes it at
    # the next window boundary, drains the one-window-deep dispatcher, writes
    # an emergency checkpoint, and returns normally (exit code 0) so the run
    # resumes from the saved state instead of losing the window.
    preempt = PreemptionHandler().install()
    preempted = False
    agreed_stop: Optional[fleet.FleetDecision] = None
    skipped_base = guards.skipped_counter().value()
    dispatched_t = start_step
    pending: Optional[_Window] = None
    if sentinel is not None and sentinel.probe_enabled:
        # Determinism-probe input: a donation-safe copy of the state going
        # into window 0 (every replay runs learn on a fresh copy of it).
        sentinel.capture_probe_input(_tree_copy(learner_state))
    try:
        for eval_idx in range(num_evaluation):
            # One beat per window top: an injected host_stall (next line) or
            # a wedged dispatch stops the beats and /healthz goes 503 once
            # the age crosses the stale threshold.
            loop_beats.beat("window")
            faultinject.maybe_host_stall(eval_idx)
            # Chaos: `bitflip:N` rebuilds the replicated state with ONE
            # mantissa bit flipped in one device's copy going INTO window N
            # — the silent-corruption class only the sentinel can see.
            learner_state = faultinject.maybe_bitflip(learner_state, eval_idx)
            if sentinel is not None and sentinel.should_probe(eval_idx):
                probe_err = sentinel.run_probe(setup.learn, _tree_copy)
                if probe_err is not None:
                    if fleet_coord is not None:
                        fleet_coord.request_stop(
                            fleet.FLAG_CORRUPT, note=str(probe_err)
                        )
                    raise probe_err
            if eval_idx == profile_window:
                try:
                    jax.profiler.start_trace(profile_dir)
                except Exception:  # noqa: BLE001
                    profile_window = -1
            if eval_idx == 0 and pf.enabled:
                # First-window execution watchdog (docs/DESIGN.md §2.4): force
                # this window's metrics to the host under a deadline, so a
                # backend that compiled fine but wedges on EXECUTION raises
                # CompileStallError instead of hanging the run's first fetch.
                # The extra sync exists only with preflight on; the dispatched
                # program sequence (and hence the trajectory) is unchanged.
                with _maybe_watchdog(pf, "first_window", pf.first_window_deadline_s):
                    window = dispatch_window(eval_idx)
                    jax.block_until_ready(window.metrics)
            else:
                window = dispatch_window(eval_idx)
            dispatched_t = window.t
            faultinject.maybe_sigterm(eval_idx)
            faultinject.maybe_host_loss(eval_idx)
            if pipelined:
                # Process LAST window's host work while the device runs this one.
                if pending is not None:
                    process_window(pending)
                pending = window
            else:
                process_window(window)
            # Chaos: `shrink:N`/`grow:N` vacate for a different topology
            # (docs/DESIGN.md §2.14). AFTER process_window so the newest
            # CONFIRMED rescue candidate exists — the resize exit's emergency
            # snapshot is what the relaunch restores digest-identically.
            resize_action = faultinject.maybe_resize(eval_idx)
            if resize_action is not None:
                elastic.resize_exit(
                    resize_action,
                    config=config,
                    window_idx=eval_idx,
                    step=dispatched_t,
                    fleet_coord=fleet_coord,
                )
            if fleet_coord is None:
                if preempt.stop_requested():
                    preempted = True
                    break
            else:
                # Fleet mode: a host-local stop request is never acted on
                # alone — it becomes this host's flag on the NEXT window's
                # fetch, and every host breaks together once the combined
                # decision (identical everywhere, it is a pure function of
                # the same replicated flag vector) comes back. A partition
                # verdict from the monitor thread surfaces here as the typed
                # error instead of a hung collective.
                fleet_coord.check_partition()
                if preempt.stop_requested():
                    fleet_coord.request_stop(
                        fleet.FLAG_PREEMPT,
                        note=f"{preempt.signal_name} at window {eval_idx}",
                    )
                if agreed_stop is not None:
                    preempted = True
                    break
        # Drain the dispatcher: the final (or preemption-interrupted) window's
        # host half — metrics, logging, and its pending checkpoint snapshot.
        if pending is not None:
            process_window(pending)
            pending = None

        if fleet_coord is not None and not preempted:
            # Final-boundary agreement: a SIGTERM that landed during the last
            # window(s) has no later fetch to carry its flag, so without this
            # vote it would be silently dropped (no acknowledge, no forced
            # emergency save, and a march into the absolute-metric eval under
            # a scheduler kill deadline). One bounded KV vote — not a device
            # collective — at a point every host reaches; every host computes
            # the same verdict, so the skip-absolute decision stays
            # collective-safe.
            if preempt.stop_requested():
                fleet_coord.request_stop(
                    fleet.FLAG_PREEMPT,
                    note=f"{preempt.signal_name} during the final window",
                )
            final_decision = fleet_coord.agree_at_window(num_evaluation)
            if final_decision.stop:
                if agreed_stop is None:
                    agreed_stop = final_decision
                preempted = True

        if preempted:
            if preempt.stop_requested():
                preempt.acknowledge(dispatched_t)
            elif agreed_stop is not None:
                # This host is stopping on a PEER's flag: same drain, same
                # emergency checkpoint, same window — the coordinated half
                # of graceful preemption (docs/DESIGN.md §2.6).
                get_logger("stoix_tpu.resilience").warning(
                    "[fleet] %s — draining and checkpointing at step %d in "
                    "lockstep with the fleet", agreed_stop.describe(), dispatched_t,
                )
            if checkpointer is not None:
                if last_save_t != dispatched_t:
                    # The regular cadence did not cover the last completed
                    # window: force an emergency save of the live state (no
                    # later program donates it — nothing was dispatched after
                    # it) and block until it is on disk.
                    with span("emergency_ckpt", step=dispatched_t):
                        checkpointer.save(
                            dispatched_t, learner_state, final_return, force=True
                        )
                        checkpointer.wait()
                get_logger("stoix_tpu.resilience").warning(
                    "[preemption] emergency state secured at step %d — exiting "
                    "cleanly; resume with logger.checkpointing.load_model=true",
                    dispatched_t,
                )
            else:
                get_logger("stoix_tpu.resilience").warning(
                    "[preemption] checkpointing disabled "
                    "(logger.checkpointing.save_model=false): stopping "
                    "cleanly at step %d WITHOUT saving state", dispatched_t,
                )
        elif bool(config.arch.get("absolute_metric", True)):
            key, ek = jax.random.split(key)
            abs_metrics = fetch_global(absolute_evaluator(best_params, ek), mesh)
            if is_coordinator():
                logger.log(
                    abs_metrics,
                    start_step + int(config.arch.total_timesteps),
                    num_evaluation,
                    LogEvent.ABSOLUTE,
                )
            final_return = float(abs_metrics["episode_return"].mean())
    except KeyboardInterrupt:
        # The fleet monitor interrupts the main thread when a peer dies (the
        # main thread may even have been wedged inside the dead collective).
        # Convert its interrupt into the typed error; a genuine operator ^C
        # (no partition declared) re-raises untouched.
        if fleet_coord is not None and fleet_coord.partition_event.is_set():
            fleet_coord.emergency_save()  # idempotent; monitor usually saved
            raise fleet_coord.partition_error from None
        raise
    finally:
        preempt.uninstall()
        goodput.set_active(None)
        monitor.unregister("anakin-host-loop")
        if aggregator is not None:
            aggregator.close()
            if ops_server is not None:
                ops_server.set_aggregator(None)
        if sentinel is not None:
            # BEFORE fleet stop, so the excepthook chain unwinds in reverse
            # install order. Restores the hook UNLESS a corruption verdict
            # is propagating — that error must still translate to exit code
            # 88 for the supervising launcher after this finally completes.
            sentinel.deactivate()
        if fleet_coord is not None:
            fleet_coord.stop()
        if checkpointer is not None:
            # Drain in-flight async saves; otherwise interpreter shutdown races
            # orbax's executor ("cannot schedule new futures after shutdown").
            checkpointer.close()
        logger.close()

    steady = (
        steps_per_eval * (len(window_walls) - 1) / sum(window_walls[1:])
        if len(window_walls) > 1
        else (steps_per_eval / window_walls[0] if window_walls else 0.0)
    )
    get_registry().gauge(
        "stoix_tpu_runner_steady_state_sps",
        "Post-first-window env-steps/sec of the most recent Anakin run",
    ).set(steady)
    # Close the goodput books: attribute this run's phase-clock deltas, then
    # assign the residual wall (host idle while the device computes, in the
    # pipelined loop) to compute. Fractions sum to 1 by construction
    # (tests/test_opsplane.py pins it on a real pipelined run).
    ledger.note_phases(phases.breakdown())
    goodput_report = ledger.finalize()
    LAST_RUN_STATS.clear()
    LAST_RUN_STATS.update(
        {
            "phase_breakdown": {k: round(v, 6) for k, v in phases.breakdown().items()},
            "goodput": goodput_report,
            "steady_state_sps": steady,
            "pipelined": pipelined,
            "fused_eval": fused,
            "compile": compile_stats,
            "resilience": {
                "update_guard": guard_mode,
                "skipped_updates": guards.skipped_counter().value() - skipped_base,
                "preempted": preempted,
                "resume_capable": checkpointer is not None,
                "preflight": pf.enabled,
                "fleet": fleet_coord is not None,
                "fleet_agreed_stop": (
                    agreed_stop.describe() if agreed_stop is not None else None
                ),
                "restore_skipped": restore_skipped,
            },
            "integrity": (
                sentinel.stats() if sentinel is not None
                else integrity.disabled_stats()
            ),
            "gossip": (
                {
                    "num_groups": gossip_plan.num_groups,
                    "interval": gossip_plan.interval,
                    "topology": gossip_plan.topology,
                    "mixing_weight": gossip_plan.mixing_weight,
                    "average_opt_states": gossip_plan.average_opt_states,
                    "rounds": gossip_rounds,
                }
                if gossip_plan is not None
                else None
            ),
        }
    )
    return final_return


def run_rnn_anakin_experiment(config: Any, setup_fn: SetupFn) -> float:
    """Anakin host loop for recurrent systems: identical to
    run_anakin_experiment but evaluates with the hidden-state-carrying RNN
    evaluator (setup_fn's eval_act_fn must have the rnn_act_fn signature)."""
    from stoix_tpu.networks.base import ScannedRNN

    hidden_size = int(config.network.get("rnn_hidden_size", 128))
    cell_type = str(config.network.get("rnn_cell_type", "gru"))

    def rnn_evaluator_setup(eval_env, act_fn, cfg, mesh):
        init_h = lambda: ScannedRNN.initialize_carry(cell_type, hidden_size, (1,))
        evaluator = get_rnn_evaluator_fn(eval_env, act_fn, cfg, mesh, init_h)
        absolute = get_rnn_evaluator_fn(
            eval_env, act_fn, cfg, mesh, init_h,
            eval_multiplier=int(cfg.arch.get("absolute_metric_multiplier", 10)),
        )
        return evaluator, absolute

    return run_anakin_experiment(config, setup_fn, evaluator_setup_fn=rnn_evaluator_setup)
