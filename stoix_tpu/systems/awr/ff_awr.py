"""Anakin AWR (reference stoix/systems/awr/ff_awr.py, 672 LoC).

Advantage-Weighted Regression (Peng et al. 2019): store rollouts in a
trajectory replay buffer (reference ff_awr.py:431), sample sequences, fit the
critic to TD(lambda) returns, and regress the policy onto actions weighted by
exp(advantage / beta) (clipped). Serves discrete and continuous heads
(ff_awr_continuous shares this learner).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh

from stoix_tpu import envs
from stoix_tpu.base_types import (
    ActorCriticOptStates,
    ActorCriticParams,
    ExperimentOutput,
    OffPolicyLearnerState,
)
from stoix_tpu.buffers import make_trajectory_buffer
from stoix_tpu.evaluator import get_distribution_act_fn
from stoix_tpu.ops import lambda_returns
from stoix_tpu.systems import anakin, off_policy_core as core
from stoix_tpu.systems.runner import AnakinSetup, run_anakin_experiment
from stoix_tpu.utils import config as config_lib
from stoix_tpu.utils.training import make_learning_rate


def get_learner_fn(env, apply_fns, update_fns, buffer, config):
    actor_apply, critic_apply = apply_fns
    actor_update, critic_update = update_fns
    gamma = float(config.system.gamma)
    lam = float(config.system.get("gae_lambda", 0.95))
    beta = float(config.system.get("awr_beta", 0.05))
    w_max = float(config.system.get("weight_clip", 20.0))

    def _env_step(learner_state: OffPolicyLearnerState, _):
        params, opt_states, buffer_state, key, env_state, last_timestep = learner_state
        key, act_key = jax.random.split(key)
        dist = actor_apply(params.actor_params, last_timestep.observation)
        action = dist.sample(seed=act_key)
        env_state, timestep = env.step(env_state, action)
        data = {
            "obs": last_timestep.observation,
            "action": action,
            "reward": timestep.reward,
            "discount": timestep.discount,
            "info": timestep.extras["episode_metrics"],
        }
        return (
            OffPolicyLearnerState(params, opt_states, buffer_state, key, env_state, timestep),
            data,
        )

    def _update_epoch(carry, _):
        params, opt_states, buffer_state, key = carry
        key, sample_key = jax.random.split(key)
        seq = buffer.sample(buffer_state, sample_key).experience  # [B, L, ...]

        values = critic_apply(params.critic_params, seq["obs"])  # [B, L]
        returns = lambda_returns(
            seq["reward"][:, :-1],
            gamma * seq["discount"][:, :-1],
            values[:, 1:],
            lam,
            batch_major=True,
        )
        adv = returns - values[:, :-1]

        def actor_loss_fn(actor_params):
            dist = actor_apply(actor_params, jax.tree.map(lambda x: x[:, :-1], seq["obs"]))
            log_prob = dist.log_prob(seq["action"][:, :-1])
            weights = jnp.minimum(jnp.exp(jax.lax.stop_gradient(adv) / beta), w_max)
            loss = -jnp.mean(weights * log_prob)
            return loss, {"actor_loss": loss, "mean_weight": jnp.mean(weights)}

        def critic_loss_fn(critic_params):
            v = critic_apply(critic_params, jax.tree.map(lambda x: x[:, :-1], seq["obs"]))
            loss = 0.5 * jnp.mean((v - jax.lax.stop_gradient(returns)) ** 2)
            return loss, {"value_loss": loss}

        actor_grads, actor_metrics = jax.grad(actor_loss_fn, has_aux=True)(params.actor_params)
        critic_grads, critic_metrics = jax.grad(critic_loss_fn, has_aux=True)(params.critic_params)
        actor_grads, critic_grads = jax.lax.pmean(
            jax.lax.pmean((actor_grads, critic_grads), axis_name="batch"), axis_name="data"
        )
        a_updates, a_opt = actor_update(actor_grads, opt_states.actor_opt_state)
        c_updates, c_opt = critic_update(critic_grads, opt_states.critic_opt_state)
        params = ActorCriticParams(
            optax.apply_updates(params.actor_params, a_updates),
            optax.apply_updates(params.critic_params, c_updates),
        )
        opt_states = ActorCriticOptStates(a_opt, c_opt)
        return (params, opt_states, buffer_state, key), {**actor_metrics, **critic_metrics}

    def _update_step(learner_state: OffPolicyLearnerState, _):
        learner_state, traj = jax.lax.scan(
            _env_step, learner_state, None, int(config.system.rollout_length)
        )
        params, opt_states, buffer_state, key, env_state, timestep = learner_state
        # Trajectory buffer rows are envs: [T, E, ...] -> [E, T, ...]; episode
        # metrics are host-side only and never sampled, so keep them out of
        # replay memory.
        store = {k: v for k, v in traj.items() if k != "info"}
        batch = jax.tree.map(lambda x: jnp.swapaxes(x, 0, 1), store)
        buffer_state = buffer.add(buffer_state, batch)

        (params, opt_states, buffer_state, key), loss_info = jax.lax.scan(
            _update_epoch, (params, opt_states, buffer_state, key), None,
            int(config.system.epochs),
        )
        learner_state = OffPolicyLearnerState(
            params, opt_states, buffer_state, key, env_state, timestep
        )
        return learner_state, (traj["info"], loss_info)

    def learner_fn(learner_state: OffPolicyLearnerState) -> ExperimentOutput:
        key = learner_state.key[0]
        state = learner_state._replace(key=key)
        state, (episode_info, loss_info) = jax.lax.scan(
            jax.vmap(_update_step, axis_name="batch"),
            state, None, int(config.arch.num_updates_per_eval),
        )
        state = state._replace(key=state.key[None])
        loss_info = jax.lax.pmean(loss_info, axis_name="data")
        return ExperimentOutput(state, episode_info, loss_info)

    return learner_fn


def learner_setup(env: envs.Environment, config: Any, mesh: Mesh, key: jax.Array):
    from stoix_tpu.networks.base import FeedForwardActor, FeedForwardCritic

    config.system.action_dim = env.num_actions
    net_cfg = config.network
    actor_network = FeedForwardActor(
        action_head=config_lib.instantiate(
            net_cfg.actor_network.action_head,
            **anakin.head_kwargs_for_env(net_cfg.actor_network.action_head, env),
        ),
        torso=config_lib.instantiate(net_cfg.actor_network.pre_torso),
        input_layer=config_lib.instantiate(net_cfg.actor_network.input_layer),
    )
    critic_network = FeedForwardCritic(
        critic_head=config_lib.instantiate(net_cfg.critic_network.critic_head),
        torso=config_lib.instantiate(net_cfg.critic_network.pre_torso),
        input_layer=config_lib.instantiate(net_cfg.critic_network.input_layer),
    )
    actor_optim = optax.chain(
        optax.clip_by_global_norm(float(config.system.max_grad_norm)),
        optax.adam(make_learning_rate(float(config.system.actor_lr), config,
                                      int(config.system.epochs)), eps=1e-5),
    )
    critic_optim = optax.chain(
        optax.clip_by_global_norm(float(config.system.max_grad_norm)),
        optax.adam(make_learning_rate(float(config.system.critic_lr), config,
                                      int(config.system.epochs)), eps=1e-5),
    )

    key, actor_key, critic_key, env_key = jax.random.split(key, 4)
    dummy_obs = jax.tree.map(lambda x: x[None], env.observation_value())
    actor_params = actor_network.init(actor_key, dummy_obs)
    critic_params = critic_network.init(critic_key, dummy_obs)
    params = ActorCriticParams(actor_params, critic_params)
    opt_states = ActorCriticOptStates(
        actor_optim.init(actor_params), critic_optim.init(critic_params)
    )

    discrete = not hasattr(env.action_space(), "low")
    local_envs, sample_batch, max_length = core.trajectory_buffer_sizing(
        config, mesh, 2 * int(config.system.rollout_length)
    )
    buffer = make_trajectory_buffer(
        add_batch_size=local_envs,
        sample_batch_size=sample_batch,
        sample_sequence_length=int(config.system.get("sample_sequence_length", 8)),
        period=int(config.system.get("sample_period", 1)),
        max_length_time_axis=max_length,
    )
    dummy_item = {
        "obs": env.observation_value(),
        "action": jnp.asarray(env.action_value(), jnp.int32 if discrete else jnp.float32),
        "reward": jnp.zeros((), jnp.float32),
        "discount": jnp.zeros((), jnp.float32),
    }
    buffer_state = buffer.init(dummy_item)

    learn_per_shard = get_learner_fn(
        env, (actor_network.apply, critic_network.apply),
        (actor_optim.update, critic_optim.update), buffer, config,
    )
    learner_state, state_specs = core.assemble_off_policy_state(
        config, mesh, env, params, opt_states, buffer_state, key, env_key
    )

    learn = core.wrap_learn(learn_per_shard, mesh, state_specs)

    return AnakinSetup(
        learn=learn,
        learner_state=learner_state,
        eval_act_fn=get_distribution_act_fn(config, actor_network.apply),
        eval_params_fn=lambda s: anakin.unbatch_params(s.params.actor_params),
    )


def run_experiment(config: Any) -> float:
    return run_anakin_experiment(config, learner_setup)


def main() -> float:
    import sys

    config = config_lib.compose(
        config_lib.default_config_dir(), "default/anakin/default_ff_awr.yaml", sys.argv[1:]
    )
    return run_experiment(config)


if __name__ == "__main__":
    main()
