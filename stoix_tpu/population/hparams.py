"""Hyperparameter lifting for population training (docs/DESIGN.md §2.11).

The Podracer/Anakin scaling move the sweep never exploited: "a population of
agents with different hyperparameters" trained as one accelerator program
(arxiv 2104.06272). This module is the config half — it turns

    arch:
      population:
        size: 8
        hparams:
          system.ent_coef: [0.0, 0.001, 0.003, 0.01, 0.01, 0.03, 0.1, 0.3]
          system.actor_lr: 3.0e-4          # scalar = broadcast to all members

into `{short_name: np.ndarray[P]}` arrays that the population runner stacks
into the learner state and threads through the vmapped member learner
(`ff_ppo.get_learner_fn(..., hparams=...)`). Only LIFTABLE leaves — scalars
the learner consumes per update, not structural shape knobs — may vary per
member; `epochs`/`num_minibatches`/`rollout_length` change program shapes and
can never live on a vmapped axis.

`arch.seed` is special: it does not thread into the learner at all — it
reseeds each member's PRNG stream at setup (member p trains from
PRNGKey(seed_p)). Without it, member 0 keeps the run's own setup key
bit-identically (the population-of-1 pin) and members p>0 fold_in(p).
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import numpy as np

# Dotted config path -> the hparam name ff_ppo.get_learner_fn resolves.
LIFTABLE_HPARAMS: Dict[str, str] = {
    "system.actor_lr": "actor_lr",
    "system.critic_lr": "critic_lr",
    "system.gamma": "gamma",
    "system.gae_lambda": "gae_lambda",
    "system.clip_eps": "clip_eps",
    "system.ent_coef": "ent_coef",
    "system.vf_coef": "vf_coef",
    "system.reward_scale": "reward_scale",
    "arch.seed": "seed",
}

# Exploit/explore may multiply these; seeds are identities, never perturbed.
PERTURBABLE = frozenset(set(LIFTABLE_HPARAMS.values()) - {"seed"})


class PopulationConfigError(ValueError):
    """An arch.population block that cannot be lifted onto the pop axis."""


def population_size(config: Any) -> int:
    pop_cfg = (config.get("arch") or {}).get("population") or {}
    size = int(pop_cfg.get("size", 1) or 1)
    if size <= 0:
        raise PopulationConfigError(f"arch.population.size must be positive, got {size}")
    return size


def lift_hparams(config: Any) -> Tuple[int, Dict[str, np.ndarray]]:
    """Resolve arch.population into (P, {name: [P] array}).

    Every entry of arch.population.hparams must be a liftable dotted path
    mapping to either a scalar (broadcast) or a length-P list. Values are
    float32 (seed: int32) — the dtype the per-member scalars hold on device.
    """
    size = population_size(config)
    pop_cfg = (config.get("arch") or {}).get("population") or {}
    raw = dict(pop_cfg.get("hparams") or {})
    arrays: Dict[str, np.ndarray] = {}
    for dotted, values in raw.items():
        if dotted not in LIFTABLE_HPARAMS:
            raise PopulationConfigError(
                f"arch.population.hparams key '{dotted}' is not liftable onto "
                f"the pop axis — liftable leaves: "
                f"{', '.join(sorted(LIFTABLE_HPARAMS))}. Structural knobs "
                "(epochs, num_minibatches, rollout_length, network sizes) "
                "change program shapes and cannot vary per member."
            )
        name = LIFTABLE_HPARAMS[dotted]
        if isinstance(values, (int, float)):
            values = [values] * size
        values = list(values)
        if len(values) != size:
            raise PopulationConfigError(
                f"arch.population.hparams['{dotted}'] has {len(values)} "
                f"values for a population of {size} — give one scalar or "
                "exactly P values"
            )
        dtype = np.int32 if name == "seed" else np.float32
        arrays[name] = np.asarray(values, dtype=dtype)
    return size, arrays


def learner_hparams(arrays: Dict[str, Any]) -> Dict[str, Any]:
    """The subset threaded into get_learner_fn (seed acts at setup only)."""
    return {k: v for k, v in arrays.items() if k != "seed"}
