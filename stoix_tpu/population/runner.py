"""Population runner: P agents as ONE jitted program on a ("pop", "data")
mesh, dispatched by the unchanged pipelined Anakin host loop
(docs/DESIGN.md §2.11).

Layout (P = population, S = data shards per member, U = update batch):

  members.params/opt_states:    [P, U, ...]       P("pop")
  members.key:                  [P, S, U, 2]      P("pop", "data")
  members.env_state/timestep:   [P, U, S*E, ...]  P("pop", None, "data")
  hparams[name] / fitness:      [P]               P("pop")
  updates_done/pbt_key/exploit: scalars           P()   (replicated)

The per-member learner is ff_ppo's OWN `get_learner_fn`, called inside the
vmapped member function with that member's traced hparam scalars — so one
compiled program trains P members with different lr/ent_coef/gamma/... Each
member keeps its own optax state and PRNG stream. When the local pop slice
is a single member (pop axis fully sharded, or P=1), the vmap is elided
entirely — squeeze -> plain per-shard learner -> unsqueeze — which is what
makes the population-of-1 trajectory BIT-identical to the plain Anakin
ff_ppo run (pinned, tests/test_population.py).

Fitness (the psum-consistent mean completed-episode return of the window)
updates inside the program; PBT exploit/explore (population/pbt.py) composes
into the SAME jitted program behind `arch.population.pbt.enabled`, so
selection costs zero host round-trips. Per-member episode metrics and
fitness ride the runner's existing coalesced metric fetch; eval snapshots
serve the currently-fittest member through the standard evaluator.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from stoix_tpu import envs
from stoix_tpu.base_types import ActorCriticOptStates, ActorCriticParams, ExperimentOutput
from stoix_tpu.evaluator import get_distribution_act_fn
from stoix_tpu.observability import RunStats, get_logger
from stoix_tpu.ops import running_statistics
from stoix_tpu.parallel import is_coordinator, materialize
from stoix_tpu.parallel.mesh import shard_map
from stoix_tpu.population import hparams as hparams_lib
from stoix_tpu.population import pbt as pbt_lib
from stoix_tpu.systems import anakin
from stoix_tpu.systems.ppo.anakin import ff_ppo
from stoix_tpu.systems.runner import AnakinSetup, run_anakin_experiment, _tree_copy
from stoix_tpu.utils import config as config_lib
from stoix_tpu.utils.training import make_learning_rate

import optax


class PopulationState(NamedTuple):
    """The whole population as one pytree: stacked member learner states plus
    the lifted hparams, fitness, and PBT bookkeeping."""

    members: Any  # ff_ppo.PPOLearnerState with a leading [P] axis
    hparams: Dict[str, jax.Array]  # name -> [P]
    fitness: jax.Array  # [P] f32; -inf until a member completes an episode
    updates_done: jax.Array  # scalar int32 window counter (replicated)
    pbt_key: jax.Array  # [2] uint32 (replicated)
    exploit_total: jax.Array  # scalar int32 cumulative exploited members


# Stats of the most recent run_population_experiment in this process:
# population_size, member_fitness [P], hparams {name: [P]}, pbt_exploits,
# pbt_enabled. bench.py --population and sweep.py --backend population read
# this after the run; values are host numpy (materialized once, at run end).
LAST_POPULATION_STATS = RunStats()


def _validate_population_config(config: Any, mesh: Any) -> None:
    if "pop" not in mesh.axis_names:
        raise hparams_lib.PopulationConfigError(
            f"population training needs a 'pop' mesh axis; arch.mesh declares "
            f"{dict(mesh.shape)} — compose with arch=population (or add pop "
            "to arch.mesh)"
        )
    if bool(((config.get("arch") or {}).get("integrity") or {}).get("enabled", False)):
        raise hparams_lib.PopulationConfigError(
            "arch.integrity.enabled=true is not supported under population "
            "training yet: the sentinel's replica fingerprints assume "
            "replicated state, but population members are SHARDED over the "
            "pop axis — use arch.population.member_fingerprints plus "
            "population.pbt.quarantine_members (docs/DESIGN.md §2.11)"
        )
    if bool(config.arch.get("fused_eval", False)):
        raise hparams_lib.PopulationConfigError(
            "arch.fused_eval is not supported under population training (the "
            "evaluator serves the argmax-fitness member, selected per window)"
        )


def population_setup(
    env: envs.Environment, config: Any, mesh: Any, keys: jax.Array
) -> AnakinSetup:
    """Build the population learner state + ONE jitted learn program.

    Matches the AnakinSetup contract, so systems/runner.py dispatches it
    exactly like any single-agent learner."""
    import os

    _validate_population_config(config, mesh)
    pop_size, hp_arrays = hparams_lib.lift_hparams(config)
    pop_shards = int(mesh.shape["pop"])
    if pop_size % pop_shards != 0:
        raise hparams_lib.PopulationConfigError(
            f"arch.population.size ({pop_size}) must divide over the pop mesh "
            f"axis ({pop_shards} shard(s))"
        )
    p_local = pop_size // pop_shards
    learner_hp = hparams_lib.learner_hparams(hp_arrays)
    lr_threaded = "actor_lr" in learner_hp or "critic_lr" in learner_hp
    if lr_threaded and bool(config.system.get("decay_learning_rates", False)):
        raise hparams_lib.PopulationConfigError(
            "system.decay_learning_rates cannot combine with a lifted "
            "actor_lr/critic_lr: per-member learning rates are flat scalars"
        )

    config.system.action_dim = env.num_actions
    actor_network, critic_network = ff_ppo.build_networks(env, config)

    # Optimizers: when lr is lifted onto the pop axis the chain ends at
    # scale_by_adam and get_learner_fn applies `u * (-lr)` per member —
    # bitwise the multiply optax's scale(-lr) performs. Otherwise the chain
    # is exactly learner_setup's (config lr, schedules included).
    max_grad_norm = float(config.system.max_grad_norm)
    if "actor_lr" in learner_hp:
        actor_optim = optax.chain(
            optax.clip_by_global_norm(max_grad_norm),
            optax.scale_by_adam(eps=1e-5),
        )
    else:
        actor_lr = make_learning_rate(
            float(config.system.actor_lr), config, int(config.system.epochs),
            int(config.system.num_minibatches),
        )
        actor_optim = optax.chain(
            optax.clip_by_global_norm(max_grad_norm), optax.adam(actor_lr, eps=1e-5)
        )
    if "critic_lr" in learner_hp:
        critic_optim = optax.chain(
            optax.clip_by_global_norm(max_grad_norm),
            optax.scale_by_adam(eps=1e-5),
        )
    else:
        critic_lr = make_learning_rate(
            float(config.system.critic_lr), config, int(config.system.epochs),
            int(config.system.num_minibatches),
        )
        critic_optim = optax.chain(
            optax.clip_by_global_norm(max_grad_norm), optax.adam(critic_lr, eps=1e-5)
        )
    apply_fns = (actor_network.apply, critic_network.apply)
    update_fns = (actor_optim.update, critic_optim.update)

    # --- per-member state construction (host loop over P; P is small) -------
    # Member 0's key path is EXACTLY learner_setup's (the population-of-1
    # bit-identity pin); members p>0 fold_in(p) — or, when arch.seed is
    # lifted, each member restarts the full key path from PRNGKey(seed_p).
    seeds = hp_arrays.get("seed")
    update_batch = int(config.arch.get("update_batch_size", 1))
    dummy_obs = jax.tree.map(lambda x: x[None], env.observation_value())
    obs_stats0 = running_statistics.init_state(env.observation_value().agent_view)
    kl_beta0 = jnp.asarray(float(config.system.get("kl_beta", 3.0)))
    member_states = []
    for p in range(pop_size):
        if seeds is not None:
            _, member_key = jax.random.split(jax.random.PRNGKey(int(seeds[p])))
        elif p == 0:
            member_key = keys
        else:
            member_key = jax.random.fold_in(keys, p)
        key_p, actor_key, critic_key, env_key = jax.random.split(member_key, 4)
        actor_params = actor_network.init(actor_key, dummy_obs)
        critic_params = critic_network.init(critic_key, dummy_obs)
        env_state, timestep = anakin.reset_envs_for_anakin(env, config, env_key)
        member_states.append(
            ff_ppo.PPOLearnerState(
                params=anakin.broadcast_to_update_batch(
                    ActorCriticParams(actor_params, critic_params), update_batch
                ),
                opt_states=anakin.broadcast_to_update_batch(
                    ActorCriticOptStates(
                        actor_optim.init(actor_params), critic_optim.init(critic_params)
                    ),
                    update_batch,
                ),
                key=anakin.make_step_keys(key_p, mesh, config),
                env_state=env_state,
                timestep=timestep,
                obs_stats=anakin.broadcast_to_update_batch(obs_stats0, update_batch),
                kl_beta=anakin.broadcast_to_update_batch(kl_beta0, update_batch),
            )
        )
    members = jax.tree.map(lambda *xs: jnp.stack(xs), *member_states)

    pop_state = PopulationState(
        members=members,
        hparams={k: jnp.asarray(v) for k, v in learner_hp.items()},
        fitness=jnp.full((pop_size,), -jnp.inf, dtype=jnp.float32),
        updates_done=jnp.asarray(0, dtype=jnp.int32),
        pbt_key=jax.random.fold_in(keys, 0x5B7),
        exploit_total=jnp.asarray(0, dtype=jnp.int32),
    )

    member_specs = ff_ppo.PPOLearnerState(
        params=P("pop"),
        opt_states=P("pop"),
        key=P("pop", "data"),
        env_state=P("pop", None, "data"),
        timestep=P("pop", None, "data"),
        obs_stats=P("pop"),
        kl_beta=P("pop"),
    )
    pop_specs = PopulationState(
        members=member_specs,
        hparams=P("pop"),
        fitness=P("pop"),
        updates_done=P(),
        pbt_key=P(),
        exploit_total=P(),
    )
    pop_state = anakin.place_learner_state(pop_state, mesh, pop_specs)

    fingerprint_members = bool(
        ((config.get("arch") or {}).get("population") or {}).get(
            "member_fingerprints", False
        )
    )
    settings = pbt_lib.settings_from_config(config)
    pbt_step = pbt_lib.make_pbt_step(settings, pop_size) if settings.enabled else None

    def per_shard_learn(state: PopulationState) -> ExperimentOutput:
        def member_learn(member_state: Any, member_hp: Dict[str, Any]):
            fn = ff_ppo.get_learner_fn(
                env, apply_fns, update_fns, config, hparams=member_hp
            )
            return fn(member_state)

        if p_local == 1:
            # Squeeze -> plain per-shard learner -> unsqueeze: reshapes only,
            # so a population of one trains BIT-identically to plain ff_ppo
            # (and a fully-sharded pop axis pays zero vmap overhead).
            m1 = jax.tree.map(lambda x: x[0], state.members)
            h1 = {k: v[0] for k, v in state.hparams.items()}
            out = member_learn(m1, h1)
            out = jax.tree.map(lambda x: x[None], out)
        else:
            out = jax.vmap(member_learn)(state.members, state.hparams)

        # Fitness: mean completed-episode return of this window, psummed over
        # the data axis so every data shard agrees; members with no completed
        # episode keep their previous fitness.
        info = out.episode_metrics
        ret = info["episode_return"]
        mask = info["is_terminal_step"].astype(jnp.float32)
        reduce_axes = tuple(range(1, ret.ndim))
        total = jax.lax.psum(jnp.sum(ret * mask, axis=reduce_axes), axis_name="data")
        count = jax.lax.psum(jnp.sum(mask, axis=reduce_axes), axis_name="data")
        fitness = jnp.where(
            count > 0, total / jnp.maximum(count, 1.0), state.fitness
        )
        new_state = state._replace(
            members=out.learner_state,
            fitness=fitness,
            updates_done=state.updates_done + 1,
        )
        train_metrics = dict(out.train_metrics)
        train_metrics["member_fitness"] = fitness
        if fingerprint_members:
            train_metrics["member_fingerprint"] = pbt_lib.member_fingerprints(
                out.learner_state.params
            )
        return ExperimentOutput(
            learner_state=new_state,
            episode_metrics=out.episode_metrics,
            train_metrics=train_metrics,
        )

    learn_sm = shard_map(
        per_shard_learn,
        mesh=mesh,
        in_specs=(pop_specs,),
        out_specs=ExperimentOutput(
            learner_state=pop_specs,
            episode_metrics=P("pop", None, None, None, "data"),
            train_metrics=P("pop"),
        ),
        # Same Anakin opt-out as systems/anakin.py shardmap_learner: the
        # in-member update-batch vmap's pmean trips check_vma's
        # varying-manual-axes assert.
        check_vma=False,
    )

    def _full_step(state: PopulationState) -> ExperimentOutput:
        out = learn_sm(state)
        if pbt_step is not None:
            # Exploit/explore composes INTO the same program: gather/where
            # over the (possibly sharded) pop axis, partitioned by GSPMD —
            # zero host round-trips per selection round.
            out = out._replace(learner_state=pbt_step(out.learner_state))
        return out

    donate = {} if os.environ.get("STOIX_TPU_NO_DONATE") else {"donate_argnums": (0,)}
    learn = jax.jit(_full_step, **donate)

    # --- evaluation: serve the currently-fittest member ---------------------
    normalize_obs = bool(config.system.get("normalize_observations", False))

    def _best_member(state: PopulationState) -> jax.Array:
        fit = jnp.where(jnp.isfinite(state.fitness), state.fitness, -jnp.inf)
        return jnp.argmax(fit)

    if normalize_obs:

        def eval_apply(bundle, observation):
            params, stats = bundle
            observation = running_statistics.normalize_observation(observation, stats)
            return actor_network.apply(params, observation)

        eval_act_fn = get_distribution_act_fn(config, eval_apply)

        def eval_params_fn(state: PopulationState):
            best = _best_member(state)
            return (
                jax.tree.map(lambda x: x[best, 0], state.members.params.actor_params),
                jax.tree.map(lambda x: x[best, 0], state.members.obs_stats),
            )

    else:
        eval_act_fn = get_distribution_act_fn(config, actor_network.apply)

        def eval_params_fn(state: PopulationState):
            best = _best_member(state)
            return jax.tree.map(
                lambda x: x[best, 0], state.members.params.actor_params
            )

    if is_coordinator():
        get_logger("stoix_tpu.population").info(
            "[population] %d member(s) | mesh %s | lifted hparams: %s | pbt %s",
            pop_size, dict(mesh.shape), sorted(learner_hp) or "none",
            "on" if settings.enabled else "off",
        )

    from stoix_tpu.population import elastic as elastic_lib

    return AnakinSetup(
        learn=learn,
        learner_state=pop_state,
        eval_act_fn=eval_act_fn,
        eval_params_fn=eval_params_fn,
        # Elastic restore (docs/DESIGN.md §2.14): an emergency store saved by
        # a DIFFERENT population size is re-placed onto this one before tree
        # placement — identity when the sizes already agree.
        restore_transform=elastic_lib.raw_resize_transform(config),
    )


def run_population_experiment(config: Any) -> float:
    """Train a population through the pipelined Anakin dispatcher; returns
    the final eval episode-return mean (of the fittest member) and fills
    LAST_POPULATION_STATS with per-member results."""
    holder: Dict[str, Any] = {}
    pop_size = hparams_lib.population_size(config)

    def recording_setup(env, cfg, mesh, key):
        setup = population_setup(env, cfg, mesh, key)
        inner = setup.learn

        def _capture(out):
            # Donation-safe per-window capture: a jitted on-device COPY of
            # the tiny per-member summary, enqueued BEFORE the next learn
            # dispatch can donate the state (the snapshot-vs-donation
            # invariant, systems/anakin.py). Materialized ONCE, at run end.
            holder["summary"] = _tree_copy(
                {
                    "fitness": out.learner_state.fitness,
                    "hparams": out.learner_state.hparams,
                    "exploit_total": out.learner_state.exploit_total,
                    "updates_done": out.learner_state.updates_done,
                }
            )

        def learn(state):
            out = inner(state)
            _capture(out)
            return out

        def lower(state):
            # Forward AOT lowering to the real jit (the runner's aot_warmup
            # would otherwise silently degrade on this wrapper and push the
            # whole compile into window 0), wrapping the compiled executable
            # so per-window capture survives warmup.
            lowered = inner.lower(state)

            class _RecordingLowered:
                @staticmethod
                def compile():
                    compiled = lowered.compile()

                    def run(s):
                        out = compiled(s)
                        _capture(out)
                        return out

                    return run

            return _RecordingLowered()

        learn.lower = lower
        return setup._replace(learn=learn)

    final_return = run_anakin_experiment(config, recording_setup)

    LAST_POPULATION_STATS.clear()
    LAST_POPULATION_STATS["population_size"] = pop_size
    LAST_POPULATION_STATS["pbt_enabled"] = pbt_lib.settings_from_config(config).enabled
    if holder:
        summary = materialize(holder["summary"])
        LAST_POPULATION_STATS.update(
            {
                "member_fitness": [float(v) for v in np.asarray(summary["fitness"])],
                "hparams": {
                    k: [float(v) for v in np.asarray(a)]
                    for k, a in summary["hparams"].items()
                },
                "pbt_exploits": int(summary["exploit_total"]),
                "windows": int(summary["updates_done"]),
            }
        )
    return final_return


def main() -> float:
    import sys

    config = config_lib.compose(
        config_lib.default_config_dir(),
        "default/population/default_ff_ppo.yaml",
        sys.argv[1:],
    )
    return run_population_experiment(config)


if __name__ == "__main__":
    main()
