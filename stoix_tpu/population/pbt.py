"""On-device PBT exploit/explore (docs/DESIGN.md §2.11).

Truncation selection (Jaderberg et al. 2017, arxiv 1711.09846) expressed as
pure gather/where over the population axis, composed INTO the population's
one jitted learn program — selection costs zero host round-trips, and under
a sharded pop axis GSPMD lowers the cross-member gathers to the collectives
the mesh needs.

Every window the learn program updates each member's fitness (the
psum-consistent mean completed-episode return of that window); every
`interval` windows the bottom `quantile` of members copy the top quantile's
params + optimizer state + observation statistics + hparams EXACTLY, then
perturb the copied hparams multiplicatively (x(1±perturb_scale), coin per
member x hparam) and resample the copied members' PRNG streams so clones
explore instead of replaying their source.

Integrity composition (docs/DESIGN.md §2.9): `member_fingerprints` folds
each member's params to a uint32 through the SAME position-salted murmur mix
the PR 12 sentinel uses, and `quarantine_members` re-seeds a corrupt member
from the fittest healthy survivor — the population's answer to silent
corruption is a targeted exploit, not a dead run.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from stoix_tpu.population.hparams import PERTURBABLE
from stoix_tpu.resilience.integrity import fingerprint_leaves


class PBTSettings(NamedTuple):
    enabled: bool
    interval: int  # windows between exploit/explore rounds
    quantile: float  # fraction exploited (bottom q copies top q)
    perturb_scale: float  # copied hparams multiply by (1 +- scale)


def settings_from_config(config: Any) -> PBTSettings:
    pop_cfg = (config.get("arch") or {}).get("population") or {}
    pbt_cfg = pop_cfg.get("pbt") or {}
    return PBTSettings(
        enabled=bool(pbt_cfg.get("enabled", False)),
        interval=max(1, int(pbt_cfg.get("interval", 1) or 1)),
        quantile=float(pbt_cfg.get("quantile", 0.25)),
        perturb_scale=float(pbt_cfg.get("perturb_scale", 0.2)),
    )


def truncation_selection(
    fitness: jax.Array, pop_size: int, quantile: float
) -> Tuple[jax.Array, jax.Array]:
    """(src, is_bottom): member i copies from member src[i]; is_bottom marks
    the exploited (bottom-quantile) members. Non-finite fitness (no completed
    episode yet, diverged member) ranks below every finite score, so a NaN
    member is always an exploit TARGET, never a source. Pure gather math —
    safe inside jit/shard_map."""
    n = int(pop_size * quantile)
    n = max(1, n) if pop_size > 1 else 0
    identity = jnp.arange(pop_size, dtype=jnp.int32)
    if n == 0:
        return identity, jnp.zeros((pop_size,), dtype=bool)
    fit = jnp.where(jnp.isfinite(fitness), fitness, -jnp.inf)
    order = jnp.argsort(fit)  # ascending: worst first
    bottom = order[:n]
    top = order[pop_size - n:]
    src = identity.at[bottom].set(top.astype(jnp.int32))
    is_bottom = jnp.zeros((pop_size,), dtype=bool).at[bottom].set(True)
    return src, is_bottom


def _copy_rows(tree: Any, src: jax.Array, do: jax.Array, pop_size: int) -> Any:
    """where(do, x[src], x) over every [P]-leading leaf of `tree`."""

    def sel(x: jax.Array) -> jax.Array:
        moved = jnp.take(x, src, axis=0)
        mask = do.reshape((pop_size,) + (1,) * (x.ndim - 1))
        return jnp.where(mask, moved, x)

    return jax.tree.map(sel, tree)


def _resampled_keys(template: jax.Array, key: jax.Array) -> jax.Array:
    """Fresh raw uint32 PRNG keys shaped like a member-key leaf [P, S, U, 2]:
    a cloned member must explore, not replay its source's stream."""
    flat = int(jnp.size(template) // 2)
    fresh = jax.random.split(key, flat)
    return fresh.reshape(template.shape).astype(template.dtype)


def perturb_hparams(
    hparams: Dict[str, jax.Array],
    src: jax.Array,
    do: jax.Array,
    key: jax.Array,
    scale: float,
) -> Dict[str, jax.Array]:
    """Copy each exploited member's hparams from its source, then multiply
    the perturbable ones by (1 +- scale) — one Bernoulli coin per
    (member, hparam), keyed deterministically by sorted hparam order so the
    explore step is replayable (and pinnable) from the pbt key."""
    pop_size = int(do.shape[0])
    out: Dict[str, jax.Array] = {}
    for i, name in enumerate(sorted(hparams)):
        v = hparams[name]
        copied = jnp.take(v, src, axis=0)
        if name in PERTURBABLE:
            coins = jax.random.bernoulli(
                jax.random.fold_in(key, i), 0.5, (pop_size,)
            )
            factors = jnp.where(coins, 1.0 + scale, 1.0 - scale).astype(v.dtype)
            copied = copied * factors
        out[name] = jnp.where(do, copied, v)
    return out


def make_pbt_step(settings: PBTSettings, pop_size: int):
    """Build the pure exploit/explore transform over a PopulationState.

    Runs EVERY window inside the learn program (uniform collectives — no
    cond whose branches diverge across shards); `fire` gates the writes with
    where(), so off-cadence windows are an identity at selection cost only.
    """

    def pbt_step(state: Any) -> Any:
        src, is_bottom = truncation_selection(
            state.fitness, pop_size, settings.quantile
        )
        fire = (state.updates_done > 0) & (
            state.updates_done % settings.interval == 0
        )
        do = is_bottom & fire

        key, hp_key, reseed_key = jax.random.split(state.pbt_key, 3)
        members = state.members
        members = members._replace(
            params=_copy_rows(members.params, src, do, pop_size),
            opt_states=_copy_rows(members.opt_states, src, do, pop_size),
            obs_stats=_copy_rows(members.obs_stats, src, do, pop_size),
            kl_beta=_copy_rows(members.kl_beta, src, do, pop_size),
            key=jnp.where(
                do.reshape((pop_size,) + (1,) * (members.key.ndim - 1)),
                _resampled_keys(members.key, reseed_key),
                members.key,
            ),
        )
        # Exploited members inherit their source's fitness: ranking them by
        # their own stale (pre-copy) score would re-exploit them every round
        # until their first episode completes under the new params.
        fitness = jnp.where(do, jnp.take(state.fitness, src), state.fitness)
        return state._replace(
            members=members,
            hparams=perturb_hparams(
                state.hparams, src, do, hp_key, settings.perturb_scale
            ),
            fitness=fitness,
            pbt_key=key,
            exploit_total=state.exploit_total + jnp.sum(do).astype(jnp.int32),
        )

    return pbt_step


# ---------------------------------------------------------------------------
# Integrity composition (docs/DESIGN.md §2.9)


def member_fingerprints(params: Any) -> jax.Array:
    """[P] uint32 — one fingerprint per member's params, via the sentinel's
    position-salted murmur fold (resilience/integrity.py). Rides the
    coalesced metric fetch as observability when
    arch.population.member_fingerprints is on; a member whose fingerprint
    diverges from its own history without an update is the silent-corruption
    signal quarantine_members answers."""

    def one(member_params: Any) -> jax.Array:
        return fingerprint_leaves(jax.tree.leaves(member_params))

    return jax.vmap(one)(params)


def quarantine_members(state: Any, corrupt: jax.Array, pop_size: int) -> Any:
    """Re-seed corrupt members from the fittest HEALTHY survivor instead of
    killing the run: params/opt/obs_stats/kl_beta/hparams copy from the
    survivor exactly, the corrupt members' PRNG streams resample, and their
    fitness inherits the survivor's. Pure gather/where — jit-safe."""
    fit = jnp.where(jnp.isfinite(state.fitness), state.fitness, -jnp.inf)
    healthy_fit = jnp.where(corrupt, -jnp.inf, fit)
    survivor = jnp.argmax(healthy_fit).astype(jnp.int32)
    src = jnp.where(corrupt, survivor, jnp.arange(pop_size, dtype=jnp.int32))

    key, reseed_key = jax.random.split(state.pbt_key)
    members = state.members
    members = members._replace(
        params=_copy_rows(members.params, src, corrupt, pop_size),
        opt_states=_copy_rows(members.opt_states, src, corrupt, pop_size),
        obs_stats=_copy_rows(members.obs_stats, src, corrupt, pop_size),
        kl_beta=_copy_rows(members.kl_beta, src, corrupt, pop_size),
        key=jnp.where(
            corrupt.reshape((pop_size,) + (1,) * (members.key.ndim - 1)),
            _resampled_keys(members.key, reseed_key),
            members.key,
        ),
    )
    hparams = {
        name: jnp.where(corrupt, jnp.take(v, src, axis=0), v)
        for name, v in state.hparams.items()
    }
    return state._replace(
        members=members,
        hparams=hparams,
        fitness=jnp.where(corrupt, jnp.take(state.fitness, src), state.fitness),
        pbt_key=key,
    )
