"""Population shrink/grow across a different P (docs/DESIGN.md §2.14).

The state half of the elastic resize protocol (resilience/elastic.py): when
the supervisor relaunches a population run at a different topology, the PBT
state saved by the OLD incarnation must be re-placed onto the NEW population
size. The rules are PBT's own (population/pbt.py, arxiv 1711.09846), applied
across incarnations instead of across windows:

  * **Shrink** keeps the fittest `new_size` members by the fitness the store
    RECORDED (truncation selection over the same scores
    `LAST_POPULATION_STATS` reported; non-finite ranks below every finite
    score). Surviving members' params / optimizer state / obs statistics /
    hparams / fitness move bit-identically — the shrink is a gather, never a
    recompute — pinned via leaf digests in tests/test_elastic.py.
  * **Grow** keeps every existing member bit-identical and fills the new
    slots with clones of the fittest members (cyclically), perturbing each
    clone's perturbable hparams by x(1 +- perturb_scale) with the PR 15
    explore coins and resampling the clone's PRNG stream `fold_in`-fresh
    from the stored pbt key — a clone explores, it never replays its source.
  * **Refusals**: a resize below one member, or past the configured
    `arch.population.max_size`, raises the typed ElasticResizeError — an
    impossible population must refuse before the relaunch loop burns its
    budget on it.

Wired into the restore path as `AnakinSetup.restore_transform`: the
population setup installs `raw_resize_transform(config)`, and
`fleet.restore_emergency` applies it to the digest-verified host arrays
BEFORE tree-path placement — the resize happens while the values are plain
host numpy, so it composes with any mesh the new incarnation builds.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from stoix_tpu.observability import get_logger
from stoix_tpu.population import hparams as hparams_lib
from stoix_tpu.population import pbt as pbt_lib
from stoix_tpu.resilience.elastic import ElasticResizeError

# Raw-store keys (slash-joined tree paths of PopulationState) that carry a
# leading [P] population axis. Scalars (updates_done, exploit_total) and the
# replicated pbt_key are NOT population leaves.
_POP_PREFIXES = ("members/", "hparams/")
_POP_EXACT = frozenset({"fitness"})
_FITNESS_KEY = "fitness"
_MEMBER_KEY_LEAF = "members/key"
_PBT_KEY_LEAF = "pbt_key"


def is_population_leaf(key: str) -> bool:
    return key in _POP_EXACT or any(key.startswith(p) for p in _POP_PREFIXES)


def max_population_size(config: Any) -> Optional[int]:
    """`arch.population.max_size` — the configured grow ceiling (None/~ =
    uncapped)."""
    pop_cfg = (config.get("arch") or {}).get("population") or {}
    raw = pop_cfg.get("max_size")
    if raw in (None, ""):
        return None
    value = int(raw)
    if value < 1:
        raise hparams_lib.PopulationConfigError(
            f"arch.population.max_size must be positive, got {value}"
        )
    return value


def validate_resize(
    old_size: int, new_size: int, max_size: Optional[int] = None
) -> None:
    """The refusal rules: never below one member, never past the configured
    max. Raises the typed error so the supervisor logs a refusal instead of
    relaunch-looping an impossible population."""
    if new_size < 1:
        raise ElasticResizeError(
            f"cannot shrink the population below one member "
            f"(requested {new_size}, currently {old_size})"
        )
    if max_size is not None and new_size > max_size:
        raise ElasticResizeError(
            f"cannot grow the population to {new_size} members: "
            f"arch.population.max_size caps it at {max_size}"
        )


def select_survivors(fitness: Any, new_size: int) -> np.ndarray:
    """Indices of the fittest `new_size` members by recorded fitness, in
    their ORIGINAL member order (a shrink re-indexes, it never reshuffles).
    Non-finite fitness (no completed episode, diverged member) ranks below
    every finite score — exactly truncation_selection's rule."""
    fitness = np.asarray(fitness, dtype=np.float64).reshape(-1)
    old_size = int(fitness.shape[0])
    validate_resize(old_size, new_size)
    if new_size > old_size:
        raise ElasticResizeError(
            f"select_survivors is a shrink: requested {new_size} of "
            f"{old_size} members"
        )
    fit = np.where(np.isfinite(fitness), fitness, -np.inf)
    order = np.argsort(fit, kind="stable")  # ascending: worst first
    return np.sort(order[old_size - new_size:])


def clone_sources(fitness: Any, old_size: int, new_size: int) -> np.ndarray:
    """Per-slot source index for a grow: existing slots are identities (the
    bit-identity half), new slots clone the fittest members cyclically —
    fittest first, by the same recorded-fitness ranking a shrink uses."""
    fitness = np.asarray(fitness, dtype=np.float64).reshape(-1)
    fit = np.where(np.isfinite(fitness), fitness, -np.inf)
    ranked = np.argsort(fit, kind="stable")[::-1]  # fittest first
    src = np.arange(new_size, dtype=np.int64)
    for i, slot in enumerate(range(old_size, new_size)):
        src[slot] = ranked[i % old_size]
    return src


def _fold_in(key: Any, data: int) -> Any:
    import jax

    return jax.random.fold_in(jax.numpy.asarray(key), data)


def _fresh_member_keys(template_row: np.ndarray, key: Any, slot: int) -> np.ndarray:
    """A fold_in-fresh raw-uint32 key block shaped like ONE member's key leaf
    [S, U, 2] — the cross-incarnation analogue of pbt._resampled_keys."""
    import jax

    fresh = jax.random.split(_fold_in(key, slot), int(template_row.size // 2))
    return np.asarray(fresh).reshape(template_row.shape).astype(template_row.dtype)


def resize_arrays(
    raw: Dict[str, np.ndarray],
    new_size: int,
    *,
    perturb_scale: float = 0.2,
    max_size: Optional[int] = None,
) -> Dict[str, np.ndarray]:
    """Resize every population leaf of a raw emergency-store dict to
    `new_size` members. Identity (the SAME dict) when the store is not a
    population store or already the right size — the transform is safe to
    install unconditionally."""
    fitness = raw.get(_FITNESS_KEY)
    if fitness is None:
        return raw
    old_size = int(np.asarray(fitness).shape[0])
    if old_size == new_size:
        return raw
    validate_resize(old_size, new_size, max_size)
    log = get_logger("stoix_tpu.population")
    out = dict(raw)
    if new_size < old_size:
        keep = select_survivors(fitness, new_size)
        for key, value in raw.items():
            if is_population_leaf(key):
                out[key] = np.ascontiguousarray(np.asarray(value)[keep])
        log.warning(
            "[elastic] population shrink %d -> %d: keeping members %s "
            "(fittest by recorded fitness)",
            old_size, new_size, keep.tolist(),
        )
        return out

    src = clone_sources(fitness, old_size, new_size)
    clone_slots = list(range(old_size, new_size))
    pbt_key = raw.get(_PBT_KEY_LEAF)
    if pbt_key is None:
        # A store without the PBT key still grows deterministically: derive
        # the explore stream from the recorded step-invariant fitness size.
        import jax

        pbt_key = np.asarray(jax.random.PRNGKey(old_size))
    explore_key = _fold_in(pbt_key, 0x9E37)
    for key, value in raw.items():
        if not is_population_leaf(key):
            continue
        value = np.asarray(value)
        copied = np.ascontiguousarray(value[src])
        if key == _MEMBER_KEY_LEAF:
            # A clone explores — resample its PRNG stream instead of
            # replaying the source member's.
            for slot in clone_slots:
                copied[slot] = _fresh_member_keys(copied[slot], explore_key, slot)
        elif key.startswith("hparams/"):
            name = key.split("/", 1)[1]
            if name in hparams_lib.PERTURBABLE:
                # The PR 15 explore move, keyed deterministically by sorted
                # hparam order (pbt.perturb_hparams's convention) so a grow
                # is replayable from the stored pbt key.
                import jax

                index = sorted(
                    k.split("/", 1)[1] for k in raw if k.startswith("hparams/")
                ).index(name)
                coins = np.asarray(
                    jax.random.bernoulli(
                        _fold_in(explore_key, index), 0.5, (new_size,)
                    )
                )
                factors = np.where(
                    coins, 1.0 + perturb_scale, 1.0 - perturb_scale
                ).astype(copied.dtype)
                for slot in clone_slots:
                    copied[slot] = copied[slot] * factors[slot]
        out[key] = copied
    # Advance the stored pbt key: the explore randomness above is consumed.
    if _PBT_KEY_LEAF in out:
        out[_PBT_KEY_LEAF] = np.asarray(explore_key).astype(
            np.asarray(raw[_PBT_KEY_LEAF]).dtype
        )
    log.warning(
        "[elastic] population grow %d -> %d: clone sources %s "
        "(fittest first, hparams perturbed x(1±%.3g), fresh PRNG streams)",
        old_size, new_size, [int(src[s]) for s in clone_slots], perturb_scale,
    )
    return out


def resize_population_state(
    state: Any, new_size: int, *, perturb_scale: float = 0.2,
    max_size: Optional[int] = None,
) -> Any:
    """The in-process form of the resize: a PopulationState pytree in, a
    PopulationState with `new_size` members out, through exactly the raw
    transform the restore path applies (one code path, one set of pins)."""
    import jax
    import jax.numpy as jnp

    from stoix_tpu.utils.checkpointing import _path_key

    flat, treedef = jax.tree_util.tree_flatten_with_path(state)
    raw = {"/".join(_path_key(p)): np.asarray(leaf) for p, leaf in flat}
    resized = resize_arrays(
        raw, new_size, perturb_scale=perturb_scale, max_size=max_size
    )
    return jax.tree_util.tree_unflatten(
        treedef, [jnp.asarray(resized["/".join(_path_key(p))]) for p, _ in flat]
    )


def raw_resize_transform(config: Any) -> Callable[[Dict[str, np.ndarray]], Dict[str, np.ndarray]]:
    """The restore-time transform the population setup installs as
    `AnakinSetup.restore_transform`: re-places a restored store's members
    onto THIS config's population size (identity when they already agree)."""
    target = hparams_lib.population_size(config)
    scale = pbt_lib.settings_from_config(config).perturb_scale
    cap = max_population_size(config)

    def transform(raw: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        return resize_arrays(raw, target, perturb_scale=scale, max_size=cap)

    return transform


def plan_population_size(
    config: Any, target_devices: int, from_devices: int
) -> int:
    """The population size a relaunch at `target_devices` should run:
    scaled with the device ratio (the soak's pop-per-device shape), floored
    at one member and CLAMPED at `arch.population.max_size` — the override
    computation clamps so a grow fault past the cap degrades to the cap
    instead of crashing the resize exit; the transforms themselves refuse."""
    size = hparams_lib.population_size(config)
    if from_devices < 1 or target_devices < 1:
        raise ElasticResizeError(
            f"cannot plan a population resize {from_devices} -> "
            f"{target_devices} device(s)"
        )
    new_size = max(1, (size * target_devices) // from_devices)
    cap = max_population_size(config)
    if cap is not None and new_size > cap:
        get_logger("stoix_tpu.population").warning(
            "[elastic] grow to %d members clamped at arch.population."
            "max_size=%d", new_size, cap,
        )
        new_size = cap
    return new_size


def _resized_hparam_values(
    values: List[Any], fitness: Optional[List[float]], new_size: int
) -> List[Any]:
    """A per-member hparam list re-shaped for the new population: shrink
    slices to the recorded-fitness survivors, grow extends by cloning the
    fittest cyclically. Template values only — a successful restore
    overwrites them with the (perturbed) stored leaf."""
    old_size = len(values)
    fit = (
        np.asarray(fitness, dtype=np.float64)
        if fitness is not None and len(fitness) == old_size
        else np.zeros((old_size,), dtype=np.float64)
    )
    if new_size <= old_size:
        return [values[i] for i in select_survivors(fit, new_size)]
    src = clone_sources(fit, old_size, new_size)
    return [values[int(i)] for i in src]


def population_resize_overrides(
    config: Any,
    *,
    target_devices: int,
    from_devices: Optional[int] = None,
    stats: Optional[Dict[str, Any]] = None,
) -> List[str]:
    """Config overrides re-deriving `arch.population` for a relaunch at
    `target_devices` (docs/DESIGN.md §2.14): the scaled `size`, plus
    re-shaped values for any per-member hparams LIST (a length-P list
    composed against a different P is a PopulationConfigError before the
    restore ever runs). `stats` defaults to LAST_POPULATION_STATS so the
    list re-shaping follows the same recorded fitness the restore's
    truncation will."""
    if from_devices is None:
        import jax

        from_devices = jax.device_count()
    new_size = plan_population_size(config, target_devices, from_devices)
    overrides = [f"arch.population.size={new_size}"]
    if stats is None:
        from stoix_tpu.population.runner import LAST_POPULATION_STATS

        stats = dict(LAST_POPULATION_STATS)
    fitness = stats.get("member_fitness")
    pop_cfg = (config.get("arch") or {}).get("population") or {}
    for dotted, values in dict(pop_cfg.get("hparams") or {}).items():
        if isinstance(values, (int, float)):
            continue  # scalars broadcast to any size
        resized = _resized_hparam_values(list(values), fitness, new_size)
        rendered = ",".join(repr(float(v)) for v in resized)
        overrides.append(f"arch.population.hparams.{dotted}=[{rendered}]")
    return overrides
