"""stoix_tpu/population — mesh-parallel population training (docs/DESIGN.md
§2.11): P agents with different hyperparameters trained as ONE jitted program
on a ("pop", "data") mesh, with on-device PBT exploit/explore.

    hparams.py — lifts designated scalar config leaves (lr, ent_coef, gamma,
                 clip_eps, seed, ...) into [P]-leading arrays threaded through
                 a vmapped learner;
    pbt.py     — truncation selection as pure gather/where over the pop axis
                 (zero host round-trips), hparam perturbation, per-member
                 fingerprints + survivor-reseed quarantine;
    runner.py  — the population setup + experiment entry point, reusing the
                 pipelined Anakin dispatcher (systems/runner.py) unchanged.

`sweep.py --backend population` maps a grid/TPE batch onto one population
run through this package.
"""

from stoix_tpu.population.hparams import (
    LIFTABLE_HPARAMS,
    PopulationConfigError,
    lift_hparams,
    population_size,
)
from stoix_tpu.population.pbt import (
    PBTSettings,
    member_fingerprints,
    quarantine_members,
    settings_from_config,
    truncation_selection,
)
from stoix_tpu.population.runner import (
    LAST_POPULATION_STATS,
    PopulationState,
    population_setup,
    run_population_experiment,
)

__all__ = [
    "LIFTABLE_HPARAMS",
    "PopulationConfigError",
    "lift_hparams",
    "population_size",
    "PBTSettings",
    "member_fingerprints",
    "quarantine_members",
    "settings_from_config",
    "truncation_selection",
    "LAST_POPULATION_STATS",
    "PopulationState",
    "population_setup",
    "run_population_experiment",
]
