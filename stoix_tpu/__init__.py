"""stoix_tpu — a TPU-native distributed single-agent RL framework.

A ground-up rebuild of the capabilities of EdanToledo/Stoix, designed for
jax.jit + shard_map over a global TPU mesh instead of single-host pmap.
"""

__version__ = "0.1.0"
