"""Batch launcher: fan out (system x env x seed) runs to SLURM or local shells.

The reference uses a submitit-based SLURM launcher
(reference stoix/slurm_launcher.py:40-83, configs/launcher/slurm.yaml) taking
the cartesian product of algorithm files, environments, and seeds. submitit is
not a dependency here; this launcher emits/submits plain `sbatch` scripts (or
runs locally with `--local`), and for multi-host TPU pods it injects the
`jax.distributed` coordination env vars consumed by
stoix_tpu.parallel.maybe_initialize_distributed.

Usage:
    python -m stoix_tpu.launcher \
        --systems stoix_tpu.systems.ppo.anakin.ff_ppo stoix_tpu.systems.sac.ff_sac \
        --envs cartpole pendulum --seeds 0 1 2 \
        [--local | --submit | --preflight-only [--changed-only]] \
        [--nodes 1] [--time 04:00:00] [--partition tpu] [overrides...]

    python -m stoix_tpu.launcher serve \
        arch.serve.checkpoint.path=checkpoints/<uid>/<model> \
        [--config default/serve.yaml] [--duration S] [--loadgen] [overrides...]

`serve` (docs/DESIGN.md §2.8) starts the in-process policy server
(stoix_tpu/serve): composes the serve root config, restores the checkpoint's
actor through the topology-elastic path, warms every batch bucket under the
compile watchdog, and serves until SIGINT/SIGTERM (or `--duration S`).
`--loadgen` instead drives the server with the configured open-loop load
generator and prints ONE JSON latency report line (the bench payload body),
then exits — the CI smoke mode.

`--preflight-only` (docs/DESIGN.md §2.4) runs the launch-hardening preflight —
the static-analysis gate, then ONE subprocess-isolated backend probe for the
host, then config cross-validation for every (system x env x seed) job
against the probed topology — prints a one-page report, and exits 0 (all
pass) or 1. Wire it into CI or a SLURM prolog so a wedged chip or a bad
config fails the batch in seconds instead of after scheduling.
`--changed-only` restricts the lint stage to git-changed files so the prolog
stays fast as the rule count grows.

`--supervise N` (docs/DESIGN.md §2.6 + §2.9) makes `--local` runs elastic: a
job that exits with the fleet-partition code (87, resilience/fleet.py — a
peer host died and the survivors secured a local-shard emergency checkpoint)
is relaunched up to N times at the surviving topology with resume overrides
appended (`logger.checkpointing.load_model=true` + the emergency-store
load_path); topology-elastic restore brings the params back bit-identical on
the shrunk mesh. A job that exits with the state-corruption code (88,
resilience/integrity.py — the integrity sentinel proved a silent replica
mismatch or a failed determinism probe) is relaunched with the resume
overrides the quarantine file records, restoring the newest DIGEST-VERIFIED
checkpoint; the offending host stays named in `--quarantine-file` for the
scheduler to drain. Any other exit code is final — 87 and 88 are the only
codes that mean "the run is healthy, the hardware was not".
"""

from __future__ import annotations

import argparse
import itertools
import os
import re
import subprocess
import sys
from typing import Any, List, Optional

from stoix_tpu.observability import get_logger

SBATCH_TEMPLATE = """#!/bin/bash
#SBATCH --job-name={job_name}
#SBATCH --output={log_dir}/{job_name}_%j.out
#SBATCH --nodes={nodes}
#SBATCH --ntasks-per-node=1
#SBATCH --time={time}
#SBATCH --signal=TERM@{preempt_grace}
{partition_line}{extra_lines}
# Multi-host JAX coordination: process 0's host is the coordinator. The
# per-task process id must be read INSIDE the srun'd command (the batch shell's
# SLURM_PROCID is always 0).
export JAX_COORDINATOR_ADDRESS="$(scontrol show hostnames "$SLURM_JOB_NODELIST" | head -n1):12345"
export JAX_NUM_PROCESSES="$SLURM_NNODES"

srun bash -c 'JAX_PROCESS_ID="$SLURM_PROCID" python -m {module} {overrides}'
"""


def _default_yaml_for(module: str) -> Optional[str]:
    """The root config a system module composes in its main() — every system
    entry point carries exactly one `default/{anakin,sebulba}/*.yaml` literal.
    None when the module cannot be located or breaks the convention."""
    import importlib.util

    try:
        spec = importlib.util.find_spec(module)
    except (ImportError, ModuleNotFoundError, ValueError):
        return None
    if spec is None or not spec.origin:
        return None
    try:
        with open(spec.origin) as f:
            source = f.read()
    except OSError:
        return None
    match = re.search(r"default/(?:anakin|sebulba)/[\w.\-]+\.yaml", source)
    return match.group(0) if match else None


def run_preflight_only(jobs: List[dict], changed_only: bool = False) -> int:
    """Static-analysis gate + ONE backend probe for the host + per-job config
    cross-validation against the probed topology; prints the one-page report.
    Returns the process exit code (0 = every stage passed)."""
    from stoix_tpu.resilience import preflight
    from stoix_tpu.utils import config as config_lib

    # Static-analysis gate FIRST (docs/DESIGN.md §2.5): pure-AST, no jax
    # import, milliseconds — a SLURM prolog catches an axis-name typo
    # (STX007), a misshard (STX010), or a typo'd config read (STX009) before
    # the backend probe spends its timeout budget, let alone before burning a
    # TPU allocation.
    from stoix_tpu import analysis

    lint_paths = None
    lint_scope = "files clean"
    with_tree_rules = True
    if changed_only:
        changed = analysis.changed_paths()
        if changed:
            # Tree-scoped rules need the full file set (see --changed-only in
            # the analysis CLI). git-unavailable AND a clean checkout (the
            # CI/prolog case — the bad change is already committed) both
            # fall back to the full scan: a vacuous 0-file pass is no gate.
            lint_paths = changed
            lint_scope = "changed files clean"
            with_tree_rules = False
    findings, n_files = analysis.run_paths(
        lint_paths, with_tree_rules=with_tree_rules
    )
    lint_errors, _lint_warnings = analysis.split_severity(findings)
    if lint_errors:
        # Short-circuit: the gate already failed the batch, so do not spend
        # the probe's multi-attempt backoff budget (a wedged backend would
        # hold the prolog for minutes before reporting a typo lint catches
        # in milliseconds).
        report = preflight.PreflightReport()
        rules = ", ".join(sorted({f.rule for f in lint_errors}))
        report.add(
            "static-analysis", "fail",
            f"{len(lint_errors)} finding(s) [{rules}]; first: "
            f"{lint_errors[0].render()}",
        )
        report.add("backend_probe", "skip", "static-analysis failed — probe not attempted")
        report.add("config_validation", "skip", "static-analysis failed")
        print(report.render())  # noqa: STX002 — --preflight-only's stdout contract
        return 1

    configs = []
    report_extra = []
    for job in jobs:
        yaml_file = _default_yaml_for(job["module"])
        if yaml_file is None:
            report_extra.append(
                (f"config[{job['name']}]", "skip",
                 f"could not derive a default yaml for {job['module']}")
            )
            continue
        try:
            config = config_lib.compose(
                config_lib.default_config_dir(), yaml_file, job["overrides"]
            )
        except Exception as exc:  # noqa: BLE001 — a bad override IS a finding
            report_extra.append(
                (f"config[{job['name']}]", "fail",
                 f"compose failed: {type(exc).__name__}: {exc}")
            )
            continue
        configs.append((job["name"], config))

    report = preflight.run_preflight(configs if configs else None)
    for row in report_extra:
        report.add(*row)
    report.add(
        "static-analysis", "pass",
        f"{n_files} {lint_scope} ({len(analysis.get_rules())} rules)",
    )
    # Concurrency-model visibility (docs/DESIGN.md §2.5): the STX014-017
    # family is only as good as the threadmodel under it — a refactor that
    # renames the spawn idioms out from under the AST patterns would turn
    # the whole rule family into a permanent green no-op. Counting what the
    # model actually saw makes a silently-empty model a preflight FAILURE
    # on a full scan (a changed-only scan may legitimately see no threads).
    from stoix_tpu.analysis import threadmodel

    tstats = threadmodel.repo_summary(lint_paths or ["stoix_tpu"])
    t_detail = (
        f"{tstats['spawns']} thread spawn(s), {tstats['locks']} lock(s), "
        f"{tstats['obligations']} completion obligation(s) modeled"
    )
    if tstats["spawns"] == 0 and lint_paths is None:
        report.add(
            "concurrency-model", "fail",
            f"EMPTY model on a full scan ({t_detail}) — the STX014-017 "
            f"family is blind; the spawn-site patterns no longer match the "
            f"code",
        )
    else:
        report.add("concurrency-model", "pass", t_detail)
    # Ops-contract visibility (docs/DESIGN.md §2.5): same deal for the
    # STX019-022 family — it sees only what the opsmodel sees, and a
    # refactor that renamed `get_registry()`/the KV verbs/`os._exit` idioms
    # out from under the AST patterns would green the gate forever. An
    # empty model on a full scan is a preflight FAILURE.
    from stoix_tpu.analysis import opsmodel

    ostats = opsmodel.repo_summary(lint_paths or ["stoix_tpu"])
    o_detail = (
        f"{ostats['series']} metric series, {ostats['kv_writes']} KV "
        f"write(s)/{ostats['kv_reads']} read(s), {ostats['exit_sites']} "
        f"hard-exit site(s), {ostats['fault_sites']} fault-spec site(s) "
        f"modeled"
    )
    if (
        ostats["series"] == 0
        and ostats["exit_sites"] == 0
        and ostats["kv_writes"] == 0
        and lint_paths is None
    ):
        report.add(
            "ops-contracts", "fail",
            f"EMPTY model on a full scan ({o_detail}) — the STX019-022 "
            f"family is blind; the metric/KV/exit idioms no longer match "
            f"the code",
        )
    else:
        report.add("ops-contracts", "pass", o_detail)
    # The report IS this mode's output contract (CI / SLURM prolog logs
    # capture stdout), like bench.py's JSON lines.
    print(report.render())  # noqa: STX002 — --preflight-only's stdout contract
    return 0 if report.ok else 1


def _elastic_child_env(
    env: Optional[dict],
    platform: Optional[str] = None,
    device_count: Optional[int] = None,
) -> dict:
    """Child environment for an elastic relaunch: the armed fault is consumed
    (a `shrink:1` that re-fired every incarnation would relaunch-loop the
    budget away), and on the cpu backend the virtual device count is forced
    to the target so the relaunch actually RUNS the smaller/larger topology
    (the fault-injected soak's mechanism; real TPU backends ignore it)."""
    child = dict(env if env is not None else os.environ)
    child.pop("STOIX_TPU_FAULT", None)
    if platform == "cpu" and device_count:
        flags = [
            flag
            for flag in child.get("XLA_FLAGS", "").split()
            if not flag.startswith("--xla_force_host_platform_device_count")
        ]
        flags.append(f"--xla_force_host_platform_device_count={int(device_count)}")
        child["XLA_FLAGS"] = " ".join(flags)
    return child


def run_supervised(
    cmd: List[str],
    env: Optional[dict],
    max_relaunches: int,
    resume_overrides: List[str],
    quarantine_file: Optional[str] = None,
    elastic: bool = False,
    fleet_resume_path: Optional[str] = None,
    job_overrides: Optional[List[str]] = None,
) -> int:
    """Supervision loop for one job (docs/DESIGN.md §2.6 + §2.9 + §2.14).
    Two exit codes mean "the run is healthy, relaunch-and-resume":

      * 87 (fleet partition, resilience/fleet.py) — a peer died and the
        survivors secured a local-shard emergency checkpoint; relaunch with
        `resume_overrides` so topology-elastic restore resumes at whatever
        topology survived. With `elastic`, the backend is RE-PROBED first
        and the mesh re-derived for the devices actually present
        (resilience/elastic.survivor_overrides) instead of replaying the
        dead topology.
      * 88 (state corruption, resilience/integrity.py) — the integrity
        sentinel proved silent corruption (replica fingerprint mismatch or a
        failed determinism probe) and recorded the offending host(s) in the
        quarantine file; relaunch with the quarantine record's resume
        overrides so the run restores the newest DIGEST-VERIFIED checkpoint.
        The quarantine file is the scheduler/operator's drain list — this
        loop cannot evict a host from its own allocation, but it names the
        offender with proof and keeps the job moving.
      * 89 (elastic resize, resilience/elastic.py) — ONLY with `elastic`: the
        run deliberately vacated for a different topology, leaving a
        `resize_request.json` next to the emergency store naming the target
        device count and the relaunch overrides (re-derived mesh + population
        re-placement). The request is consumed one-shot and the relaunch
        restores through the emergency path at the requested topology.
        Without `elastic`, 89 is final — fixed-topology supervision is
        bit-identical to what it was before this flag existed.

    Every OTHER exit code (clean 0, watchdog 86, crash 1) is final. Returns
    the final exit code."""
    from stoix_tpu.resilience import elastic as elastic_lib
    from stoix_tpu.resilience.exit_codes import (
        EXIT_CODE_ELASTIC_RESIZE,
        EXIT_CODE_FAILURE,
        EXIT_CODE_OK,
        EXIT_CODE_STALL,
        EXIT_CODE_USAGE,
        REGISTRY,
    )
    from stoix_tpu.resilience.fleet import EXIT_CODE_FLEET_PARTITION
    from stoix_tpu.resilience.integrity import (
        EXIT_CODE_STATE_CORRUPTION,
        corruption_resume_overrides,
        read_quarantine,
    )

    log = get_logger("stoix_tpu.launcher")
    handled = {EXIT_CODE_FLEET_PARTITION, EXIT_CODE_STATE_CORRUPTION}
    if elastic:
        handled.add(EXIT_CODE_ELASTIC_RESIZE)
    # Every registered code is dispatched here by NAME — relaunched (above)
    # or explicitly final (below) — so registering a new recovery code
    # without teaching this loop about it fails STX021's coverage check
    # instead of surfacing as an unexplained final exit. The runtime half
    # of the same contract: an rc in neither set can only be an
    # UNREGISTERED code (signal deaths, scheduler kills), logged as such.
    final_codes = {
        EXIT_CODE_OK: "clean finish",
        EXIT_CODE_FAILURE: "unrecoverable failure — a relaunch would replay it",
        EXIT_CODE_USAGE: "usage error — operator input, not run health",
        EXIT_CODE_STALL: "watchdog shot a wedged run — triage before retrying",
        EXIT_CODE_ELASTIC_RESIZE: "elastic resize without --elastic — final",
    }
    uncovered = set(REGISTRY) - set(final_codes) - handled
    assert not uncovered, f"unhandled registered exit codes: {sorted(uncovered)}"
    relaunches = 0
    extra: List[str] = []
    child_env = env
    while True:
        # Each relaunch is a FRESH subprocess, and within any process the
        # run start calls observability.configure(), which resets the
        # process-wide HealthMonitor and flight recorder — so a relaunched
        # incarnation never inherits stale heartbeat state that would read
        # as an instant stall (docs/DESIGN.md §2.13; pinned by
        # tests/test_opsplane.py).
        rc = subprocess.run(cmd + extra, env=child_env).returncode
        if rc not in handled:
            disposition = final_codes.get(
                rc,
                "unregistered code (signal death or scheduler kill?)"
                if rc not in REGISTRY
                else REGISTRY[rc].meaning,
            )
            if relaunches:
                log.info(
                    "[launcher] job finished (rc %d: %s) after %d supervised "
                    "relaunch(es)", rc, disposition, relaunches,
                )
            return rc
        reason = {
            EXIT_CODE_FLEET_PARTITION: "fleet partition",
            EXIT_CODE_STATE_CORRUPTION: "state corruption",
            EXIT_CODE_ELASTIC_RESIZE: "elastic resize",
        }[rc]
        if relaunches >= max_relaunches:
            log.error(
                "[launcher] %s exit (rc %d) with the relaunch budget (%d) "
                "exhausted — giving up", reason, rc, max_relaunches,
            )
            return rc
        relaunches += 1
        if rc == EXIT_CODE_ELASTIC_RESIZE:
            request = elastic_lib.consume_resize_request(
                fleet_resume_path or ""
            )
            if request is None:
                log.error(
                    "[launcher] elastic resize exit (rc %d) but no "
                    "%s under %s — giving up (the dying incarnation failed "
                    "before the hand-off was written)",
                    rc, elastic_lib.RESIZE_REQUEST_NAME, fleet_resume_path,
                )
                return rc
            target = int(request.get("target_devices") or 0)
            # The armed fault was consumed by this exit; `arch.fault_spec=~`
            # outranks any job-override spec so the relaunch trains instead
            # of re-firing the same resize every incarnation.
            extra = [
                *resume_overrides,
                *[str(o) for o in request.get("overrides") or []],
                "arch.fault_spec=~",
            ]
            child_env = _elastic_child_env(
                env, platform=request.get("platform"), device_count=target
            )
            log.warning(
                "[launcher] elastic %s: relaunching at %d device(s) "
                "(from %s, window %s)",
                request.get("action"), target,
                request.get("from_devices"), request.get("window"),
            )
        elif rc == EXIT_CODE_FLEET_PARTITION:
            extra = list(resume_overrides)
            if elastic:
                # Re-probe what actually survived the partition and re-derive
                # the mesh for it — never replay the dead topology.
                from stoix_tpu.resilience import preflight

                try:
                    probe = preflight.probe_backend()
                    extra = extra + elastic_lib.survivor_overrides(
                        probe.device_count, list(job_overrides or [])
                    )
                    child_env = _elastic_child_env(env)
                    log.warning(
                        "[launcher] elastic partition recovery: %d %s "
                        "device(s) survived; relaunching with re-derived mesh",
                        probe.device_count, probe.platform,
                    )
                except Exception as exc:  # noqa: STX003 — a failed re-probe degrades to the fixed-topology relaunch rather than killing a recoverable job
                    log.error(
                        "[launcher] elastic re-probe failed (%s); relaunching "
                        "at the configured topology", exc,
                    )
        else:
            quarantined = read_quarantine(quarantine_file or "").get("quarantined") or []
            if quarantined:
                latest = quarantined[-1]
                log.error(
                    "[launcher] QUARANTINE: process(es) %s (device(s) %s) "
                    "flagged for %s at step %s — recorded in %s; drain them "
                    "before the budget runs out",
                    latest.get("processes"), latest.get("devices"),
                    latest.get("kind"), latest.get("step"), quarantine_file,
                )
            extra = corruption_resume_overrides(quarantine_file or "")
            if not extra:
                log.warning(
                    "[launcher] corruption exit with no recorded resume "
                    "overrides (checkpointing was off?) — relaunching FRESH"
                )
        log.warning(
            "[launcher] %s (rc %d): relaunching (%d/%d)%s",
            reason, rc, relaunches, max_relaunches,
            f" with {' '.join(extra)}" if extra else "",
        )


def loop_main(argv: List[str]) -> int:
    """`launcher.py loop` (docs/DESIGN.md §2.15): run the closed
    train→serve→experience loop from a composed loop config and print ONE
    JSON report line. Returns the process exit code."""
    import json

    from stoix_tpu.utils import config as config_lib

    parser = argparse.ArgumentParser(
        prog="stoix_tpu.launcher loop",
        description="closed train→serve→experience loop (stoix_tpu/loop)",
    )
    parser.add_argument(
        "--config",
        default="default/loop.yaml",
        help="loop root yaml under stoix_tpu/configs (default: default/loop.yaml)",
    )
    parser.add_argument(
        "--frozen",
        action="store_true",
        help="control arm: identical traffic and ingest, learner never "
        "updates and nothing is published (the bench --loop baseline)",
    )
    parser.add_argument(
        "--duration",
        type=float,
        default=None,
        metavar="S",
        help="override arch.loop.traffic.duration_s",
    )
    parser.add_argument("overrides", nargs="*", help="key=value overrides")
    args = parser.parse_args(argv)

    overrides = list(args.overrides)
    if args.duration is not None:
        overrides.append(f"arch.loop.traffic.duration_s={args.duration}")
    config = config_lib.compose(
        config_lib.default_config_dir(), args.config, overrides
    )
    from stoix_tpu.loop import run_loop
    from stoix_tpu.resilience import faultinject

    # Arm the chaos plan exactly like the serve/train entry points (env var
    # wins over arch.fault_spec): the §2.15 drill arms
    # `replica_kill:N,replica_slow:S,feedback_stall:S` here.
    faultinject.configure((config.get("arch") or {}).get("fault_spec"))
    from stoix_tpu.observability import get_status_board, server_from_config

    ops_server = server_from_config(dict(config.arch.serve.get("http") or {}))
    get_status_board().update(
        {"run_id": "loop", "architecture": "loop", "system": "closed-loop"}
    )
    try:
        report = run_loop(config, frozen=args.frozen)
        # The JSON line IS this mode's output contract, like serve --loadgen.
        print(json.dumps(report), flush=True)  # noqa: STX002 — loop stdout contract
    finally:
        if ops_server is not None:
            ops_server.close()
    return 1 if report.get("silent_drops") else 0


def serve_main(argv: List[str]) -> int:
    """`launcher.py serve` (docs/DESIGN.md §2.8): run the policy server from
    a composed serve config. Returns the process exit code."""
    import json
    import signal
    import time

    from stoix_tpu.utils import config as config_lib

    parser = argparse.ArgumentParser(
        prog="stoix_tpu.launcher serve",
        description="serve a trained policy (stoix_tpu/serve)",
    )
    parser.add_argument(
        "--config",
        default="default/serve.yaml",
        help="serve root yaml under stoix_tpu/configs (default: default/serve.yaml)",
    )
    parser.add_argument(
        "--duration",
        type=float,
        default=None,
        metavar="S",
        help="serve for S seconds then exit cleanly (default: until SIGINT/SIGTERM)",
    )
    parser.add_argument(
        "--loadgen",
        action="store_true",
        help="drive the server with the arch.serve.loadgen open-loop load "
        "generator, print ONE JSON latency report line, and exit (CI smoke)",
    )
    parser.add_argument("overrides", nargs="*", help="key=value overrides")
    args = parser.parse_args(argv)

    config = config_lib.compose(
        config_lib.default_config_dir(), args.config, args.overrides
    )
    from stoix_tpu.resilience import faultinject
    from stoix_tpu.serve import PolicyServer, run_loadgen

    # Arm the chaos plan exactly like the training entry points do (env var
    # wins over arch.fault_spec): `STOIX_TPU_FAULT=swap_poison` must reach
    # the hot-swap canary (docs/DESIGN.md §2.9) when serving standalone.
    faultinject.configure((config.get("arch") or {}).get("fault_spec"))
    log = get_logger("stoix_tpu.launcher")
    serve_cfg = config.arch.serve
    # Ops plane (docs/DESIGN.md §2.13): start the endpoints BEFORE warmup so
    # /healthz and /statusz answer during the first compile. The serve config
    # has no `logger` block, so the switch lives at `arch.serve.http`.
    from stoix_tpu.observability import get_status_board, server_from_config

    ops_server = server_from_config(dict(serve_cfg.get("http") or {}))
    get_status_board().update(
        {"run_id": "serve", "architecture": "serve", "system": "policy-server"}
    )
    server = PolicyServer.from_config(config)
    stop_requested = {"flag": False}

    def _request_stop(_signum: int, _frame: Any) -> None:
        stop_requested["flag"] = True

    previous_handlers = {}
    for signum in (signal.SIGINT, signal.SIGTERM):
        try:
            previous_handlers[signum] = signal.signal(signum, _request_stop)
        except (ValueError, OSError):  # non-main thread / unsupported platform
            pass
    try:
        with server:
            if args.loadgen:
                loadgen_cfg = serve_cfg.loadgen
                report = run_loadgen(
                    server,
                    offered_qps=float(loadgen_cfg.offered_qps),
                    duration_s=float(loadgen_cfg.duration_s),
                )
                # The JSON line IS this mode's output contract (CI smoke),
                # like bench.py's payload lines.
                print(json.dumps(report), flush=True)  # noqa: STX002 — serve --loadgen stdout contract
            else:
                log.info(
                    "[serve] serving (step %d%s) — Ctrl-C to stop",
                    server.watcher.current_step if server.watcher else -1,
                    f", for {args.duration:.0f}s" if args.duration else "",
                )
                deadline = (
                    time.perf_counter() + args.duration if args.duration else None
                )
                while not stop_requested["flag"]:
                    if deadline is not None and time.perf_counter() >= deadline:
                        break
                    time.sleep(0.2)
                log.info(
                    "[serve] stopping: %s", server.telemetry.slo_snapshot()
                )
            telemetry_dir = serve_cfg.get("telemetry_dir")
            if telemetry_dir:
                path = server.telemetry.export(str(telemetry_dir))
                log.info("[serve] SLO metrics exported to %s", path)
    finally:
        if ops_server is not None:
            ops_server.close()
        for signum, handler in previous_handlers.items():
            signal.signal(signum, handler)
    return 0


def build_jobs(args: argparse.Namespace) -> List[dict]:
    jobs = []
    for module, env, seed in itertools.product(args.systems, args.envs, args.seeds):
        name = f"{module.rsplit('.', 1)[-1]}_{env}_s{seed}"
        overrides = [f"env={env}", f"arch.seed={seed}", *args.overrides]
        jobs.append({"name": name, "module": module, "overrides": overrides})
    return jobs


def main(argv: List[str] | None = None) -> None:
    argv = list(sys.argv[1:]) if argv is None else list(argv)
    if argv and argv[0] == "serve":
        # Subcommand dispatch: `launcher.py serve [...]` is the serving entry
        # point (docs/DESIGN.md §2.8); the batch-launch surface is unchanged.
        sys.exit(serve_main(argv[1:]))
    if argv and argv[0] == "loop":
        # `launcher.py loop [...]`: the closed train→serve→experience loop
        # (docs/DESIGN.md §2.15).
        sys.exit(loop_main(argv[1:]))
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--systems", nargs="+", required=True, help="module paths")
    parser.add_argument("--envs", nargs="+", required=True, help="env group names")
    parser.add_argument("--seeds", nargs="+", type=int, default=[0])
    parser.add_argument("--local", action="store_true", help="run sequentially here")
    parser.add_argument("--submit", action="store_true", help="sbatch immediately")
    parser.add_argument(
        "--preflight-only",
        action="store_true",
        help="run the launch-hardening preflight (subprocess backend probe + "
        "per-job config cross-validation) and exit 0/1 with a one-page "
        "report — no jobs are run or submitted (CI / SLURM prolog hook)",
    )
    parser.add_argument(
        "--changed-only",
        action="store_true",
        help="with --preflight-only: lint only the .py files git reports "
        "changed vs HEAD (the analysis CLI's --changed-only selection), so "
        "the prolog stays fast as the rule count grows; full scan when git "
        "is unavailable",
    )
    parser.add_argument(
        "--supervise",
        type=int,
        default=0,
        metavar="N",
        help="with --local: relaunch a job up to N times when it exits with "
        "the fleet-partition code (87 — a peer host died and a local-shard "
        "emergency checkpoint was secured; stoix_tpu/resilience/fleet.py) "
        "or the state-corruption code (88 — the integrity sentinel proved "
        "silent corruption and quarantined the offender; "
        "stoix_tpu/resilience/integrity.py), appending the matching resume "
        "overrides so the relaunch restores the right store. 0 (default) "
        "disables supervision.",
    )
    parser.add_argument(
        "--elastic",
        action="store_true",
        help="with --supervise: topology-elastic relaunch policy "
        "(stoix_tpu/resilience/elastic.py, docs/DESIGN.md §2.14). An "
        "elastic-resize exit (rc 89) consumes the run's resize_request.json "
        "and relaunches at the REQUESTED device count with re-derived mesh "
        "axes + population re-placement overrides; a fleet-partition exit "
        "(rc 87) re-probes the backend and relaunches at whatever topology "
        "actually survived instead of replaying the dead one. Off (default): "
        "rc 89 is final and supervision is bit-identical to fixed-topology "
        "behavior.",
    )
    parser.add_argument(
        "--fleet-resume-path",
        default=os.path.join("checkpoints", "fleet_emergency"),
        help="emergency-store path the supervised relaunch resumes from "
        "(must match arch.fleet.emergency_dir)",
    )
    parser.add_argument(
        "--quarantine-file",
        default=os.path.join("checkpoints", "quarantine.json"),
        help="quarantine record the integrity sentinel writes on a "
        "state-corruption exit (rc 88, stoix_tpu/resilience/integrity.py; "
        "must match arch.integrity.quarantine_file). --supervise reads the "
        "offender + resume overrides from it and relaunches restoring the "
        "newest digest-verified checkpoint",
    )
    parser.add_argument(
        "--compile-cache",
        default=None,
        metavar="DIR",
        help="share ONE persistent XLA compilation cache directory across "
        "every launched job (appends arch.compile_cache.enabled/dir "
        "overrides; utils/compilecache.py, docs/DESIGN.md §2.7): the first "
        "job/host pays each compile, the rest hit the cache — and a "
        "--supervise relaunch recompiles nothing",
    )
    parser.add_argument(
        "--aot-export",
        default=None,
        metavar="DIR",
        help="with --compile-cache semantics for the top-level learn "
        "function: jax.export artifacts are serialized into DIR by the "
        "first job and loaded (skipping trace+lower) by every later one "
        "(appends arch.compile_cache.export_dir; requires --compile-cache)",
    )
    parser.add_argument("--nodes", type=int, default=1)
    parser.add_argument("--time", default="04:00:00")
    parser.add_argument("--partition", default=None)
    parser.add_argument(
        "--preempt-grace",
        type=int,
        default=90,
        help="seconds of SIGTERM warning before SLURM kills the job "
        "(#SBATCH --signal=TERM@N — no B: prefix, so the signal reaches the "
        "srun'd training processes themselves, not just the batch shell). "
        "The in-process preemption handler "
        "(stoix_tpu/resilience/preemption.py) uses this window to drain the "
        "dispatcher and write an emergency checkpoint, so a preempted run "
        "resumes instead of losing up to a checkpoint interval of work.",
    )
    parser.add_argument("--sbatch-extra", nargs="*", default=[], help="raw #SBATCH lines")
    parser.add_argument("--script-dir", default="launcher_scripts")
    parser.add_argument("--log-dir", default="launcher_logs")
    parser.add_argument("overrides", nargs="*", help="shared key=value overrides")
    args = parser.parse_args(argv)
    if args.changed_only and not args.preflight_only:
        # Silently ignoring the flag would let a user believe their --submit
        # was gated on a changed-file lint that never ran.
        parser.error("--changed-only requires --preflight-only")
    if args.elastic and args.supervise <= 0:
        # An elastic policy with nothing supervising it would silently never
        # relaunch — exactly the surprise this pairing check prevents.
        parser.error("--elastic requires --supervise N (N > 0)")
    if args.aot_export and not args.compile_cache:
        # The export store exists to be shared alongside the cache dir; an
        # export-only launch silently paying full per-job XLA compiles is
        # exactly the surprise this flag pairing prevents.
        parser.error("--aot-export requires --compile-cache")
    if args.compile_cache:
        # Ride the ordinary override mechanism so the same knobs reach SLURM
        # scripts, --local runs, and --supervise relaunches identically.
        args.overrides = [
            "arch.compile_cache.enabled=true",
            f"arch.compile_cache.dir={args.compile_cache}",
            *(
                [f"arch.compile_cache.export_dir={args.aot_export}"]
                if args.aot_export
                else []
            ),
            *args.overrides,
        ]

    jobs = build_jobs(args)
    log = get_logger("stoix_tpu.launcher")
    log.info(
        "[launcher] %d jobs: %d systems x %d envs x %d seeds",
        len(jobs), len(args.systems), len(args.envs), len(args.seeds),
    )

    if args.preflight_only:
        sys.exit(run_preflight_only(jobs, changed_only=args.changed_only))

    if args.local:
        # Make the repo importable from any working directory.
        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ)
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
        resume_overrides = [
            "logger.checkpointing.load_model=true",
            f"logger.checkpointing.load_args.load_path={args.fleet_resume_path}",
        ]
        for job in jobs:
            log.info("[launcher] running %s", job["name"])
            cmd = [sys.executable, "-m", job["module"], *job["overrides"]]
            if args.supervise > 0:
                rc = run_supervised(
                    cmd, env, args.supervise, resume_overrides,
                    quarantine_file=args.quarantine_file,
                    elastic=args.elastic,
                    fleet_resume_path=args.fleet_resume_path,
                    job_overrides=list(job["overrides"]),
                )
                if rc != 0:
                    sys.exit(rc)
            else:
                subprocess.run(cmd, check=True, env=env)
        return

    os.makedirs(args.script_dir, exist_ok=True)
    os.makedirs(args.log_dir, exist_ok=True)
    partition_line = f"#SBATCH --partition={args.partition}\n" if args.partition else ""
    extra_lines = "".join(f"#SBATCH {line}\n" for line in args.sbatch_extra)
    for job in jobs:
        script = SBATCH_TEMPLATE.format(
            job_name=job["name"],
            log_dir=args.log_dir,
            nodes=args.nodes,
            time=args.time,
            preempt_grace=args.preempt_grace,
            partition_line=partition_line,
            extra_lines=extra_lines,
            module=job["module"],
            overrides=" ".join(job["overrides"]),
        )
        path = os.path.join(args.script_dir, f"{job['name']}.sbatch")
        with open(path, "w") as f:
            f.write(script)
        if args.submit:
            subprocess.run(["sbatch", path], check=True)
            log.info("[launcher] submitted %s", path)
        else:
            log.info("[launcher] wrote %s (pass --submit to sbatch)", path)


if __name__ == "__main__":
    main()
