"""Learning-curve plotting from the JSON logger sink.

The reference's plotting pipeline pulls W&B artifacts and feeds marl-eval /
RLiable notebooks (reference plotting/); here the JsonSink's file(s) are the
source of truth. Each metrics.json holds
{env}/{task}/{system}/seed_N/step_K -> {episode_return: [...], ...}; this
module aggregates seeds (mean +- stddev band) and writes one PNG per task.

Usage: python -m stoix_tpu.plotting results/**/metrics.json -o curves/
"""

from __future__ import annotations

import argparse
import glob
import json
import os
from collections import defaultdict
from typing import Dict, List

from stoix_tpu.observability import get_logger


def load_runs(paths: List[str]) -> Dict[str, Dict[str, Dict[int, List[float]]]]:
    """-> {task: {system: {step: [returns across seeds/episodes]}}}"""
    curves: Dict[str, Dict[str, Dict[int, List[float]]]] = defaultdict(
        lambda: defaultdict(lambda: defaultdict(list))
    )
    for path in paths:
        with open(path) as f:
            data = json.load(f)
        for env_name, tasks in data.items():
            for task, systems in tasks.items():
                for system, seeds in systems.items():
                    for _seed, steps in seeds.items():
                        for step_key, entry in steps.items():
                            if not step_key.startswith("step_"):
                                continue
                            t = int(entry.get("step_count", step_key.split("_")[1]))
                            for key, values in entry.items():
                                # Exact series only: the sink also stores
                                # /std|min|max which must not be averaged in.
                                if key in ("episode_return", "episode_return/mean"):
                                    curves[task][system][t].extend(values)
    return curves


def plot(curves, out_dir: str) -> List[str]:
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    import numpy as np

    os.makedirs(out_dir, exist_ok=True)
    written = []
    for task, systems in curves.items():
        fig, ax = plt.subplots(figsize=(7, 4.5))
        for system, by_step in sorted(systems.items()):
            steps = sorted(by_step)
            means = np.array([np.mean(by_step[t]) for t in steps])
            stds = np.array([np.std(by_step[t]) for t in steps])
            ax.plot(steps, means, label=system)
            ax.fill_between(steps, means - stds, means + stds, alpha=0.2)
        ax.set_xlabel("environment steps")
        ax.set_ylabel("episode return")
        ax.set_title(task)
        ax.legend()
        fig.tight_layout()
        path = os.path.join(out_dir, f"{task}.png")
        fig.savefig(path, dpi=120)
        plt.close(fig)
        written.append(path)
        get_logger("stoix_tpu.plotting").info("[plotting] wrote %s", path)
    return written


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("paths", nargs="+", help="metrics.json files (globs ok)")
    parser.add_argument("-o", "--out-dir", default="curves")
    args = parser.parse_args(argv)
    files = [f for pattern in args.paths for f in sorted(glob.glob(pattern, recursive=True))]
    if not files:
        raise SystemExit("no metrics.json files matched")
    plot(load_runs(files), args.out_dir)


if __name__ == "__main__":
    main()
