"""Sebulba host-side plumbing (reference stoix/utils/sebulba_utils.py, 394 LoC).

Threads + bounded queues connect actor devices to learner devices:
  - ThreadLifetime: cooperative stop signal (:20-45)
  - OnPolicyPipeline: one queue.Queue(maxsize=1) per actor; the learner must
    collect from ALL actors each update — backpressure by construction (:48-96)
  - ParameterServer: pushes fresh params to per-actor queues, device_put onto
    each actor's device; `None` is the shutdown sentinel (:99-259)
  - AsyncEvaluator: background evaluation requests with best-params tracking
    (:262-367)

TPU-native difference (SURVEY.md §7.1.3): trajectory hand-off builds GLOBAL
arrays with jax.make_array_from_single_device_arrays via
parallel.assemble_global_array, so the learner's jit consumes a correctly
sharded batch with no host concat.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, List, Optional

import jax


class ThreadLifetime:
    def __init__(self) -> None:
        self._stop = threading.Event()

    def should_stop(self) -> bool:
        return self._stop.is_set()

    def stop(self) -> None:
        self._stop.set()


class OnPolicyPipeline:
    """Bounded rollout queues, one per actor thread."""

    def __init__(self, num_actors: int, max_size: int = 1):
        self._queues: List[queue.Queue] = [queue.Queue(maxsize=max_size) for _ in range(num_actors)]

    def send_rollout(self, actor_id: int, payload: Any, timeout: Optional[float] = None) -> None:
        self._queues[actor_id].put(payload, timeout=timeout)

    def collect_rollouts(self, timeout: float = 180.0) -> List[Any]:
        """Blocks until every actor has contributed one rollout; an actor that
        died surfaces here as Empty (reference sebulba_utils.py:85)."""
        return [q.get(timeout=timeout) for q in self._queues]


class ParameterServer:
    """Latest-params distribution to actor devices."""

    def __init__(self, actor_devices: List[jax.Device], actors_per_device: int):
        self._devices = [d for d in actor_devices for _ in range(actors_per_device)]
        self._queues: List[queue.Queue] = [queue.Queue(maxsize=1) for _ in self._devices]

    @property
    def num_actors(self) -> int:
        return len(self._queues)

    def distribute_params(self, params: Any) -> None:
        for device, q in zip(self._devices, self._queues):
            local = jax.device_put(params, device)
            # Keep only the freshest params: drop a stale entry if present.
            try:
                q.get_nowait()
            except queue.Empty:
                pass
            q.put(local)

    def get_params(self, actor_id: int, timeout: Optional[float] = None) -> Any:
        """Returns fresh params, or None (shutdown sentinel)."""
        return self._queues[actor_id].get(timeout=timeout)

    def shutdown(self) -> None:
        for q in self._queues:
            try:
                q.get_nowait()
            except queue.Empty:
                pass
            q.put(None)


class AsyncEvaluator:
    """Runs evaluations off the critical path on a dedicated device."""

    def __init__(
        self,
        evaluate: Callable[[Any, jax.Array], dict],
        lifetime: ThreadLifetime,
        on_result: Callable[[dict, Any, int], None],
    ):
        self._evaluate = evaluate
        self._lifetime = lifetime
        self._on_result = on_result
        self._requests: queue.Queue = queue.Queue()
        self._idle = threading.Event()
        self._idle.set()
        self.thread = threading.Thread(target=self._run, name="async-evaluator", daemon=True)

    def submit(self, params: Any, key: jax.Array, t: int) -> None:
        self._idle.clear()
        self._requests.put((params, key, t))

    def _run(self) -> None:
        while not self._lifetime.should_stop():
            try:
                params, key, t = self._requests.get(timeout=1.0)
            except queue.Empty:
                self._idle.set()
                continue
            metrics = self._evaluate(params, key)
            self._on_result(metrics, params, t)
            if self._requests.empty():
                self._idle.set()

    def wait_until_idle(self, timeout: float = 600.0) -> None:
        self._idle.wait(timeout=timeout)
