"""Sebulba host-side plumbing (reference stoix/utils/sebulba_utils.py, 394 LoC).

Threads + bounded queues connect actor devices to learner devices:
  - ThreadLifetime: cooperative stop signal (:20-45)
  - OnPolicyPipeline: one queue.Queue(maxsize=1) per actor; the learner must
    collect from ALL actors each update — backpressure by construction (:48-96)
  - ParameterServer: pushes fresh params to per-actor queues, device_put onto
    each actor's device; `None` is the shutdown sentinel (:99-259)
  - AsyncEvaluator: background evaluation requests with best-params tracking
    (:262-367)

TPU-native difference (SURVEY.md §7.1.3): trajectory hand-off builds GLOBAL
arrays with jax.make_array_from_single_device_arrays via
parallel.assemble_global_array, so the learner's jit consumes a correctly
sharded batch with no host concat.

Telemetry (docs/DESIGN.md §2.2): every queue hand-off records depth and
put/get wait series (`stoix_tpu_sebulba_queue_*`), every component beats a
HeartbeatBoard, and a collect timeout surfaces as ActorStarvationError naming
the starved side (actor dead vs pipeline wedged vs params stale) instead of
an anonymous `queue.Empty`. All instruments are host-memory only — no device
syncs — and span recording is a no-op unless telemetry is enabled.

Fault tolerance (docs/DESIGN.md §2.3): both queue layers carry typed
`ComponentFailure` poison-pills — the supervisor injects one when an actor is
unrecoverable (crash budget exhausted, or wedged), and the peer RAISES it on
its next get instead of burning a full collect timeout against a dead
producer. `ParameterServer.reprime` re-feeds the latest params to a
supervisor-restarted actor so the restart can never deadlock against a
learner already blocked in collect. `AsyncEvaluator.wait_until_idle` raises
EvaluatorStallError on timeout instead of silently letting shutdown proceed
with dangling evaluation work.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Dict, List, NamedTuple, Optional

import jax

from stoix_tpu.observability import (
    ActorStarvationError,
    HeartbeatBoard,
    StallDetector,
    get_registry,
    span,
)
from stoix_tpu.resilience.errors import ComponentFailure, EvaluatorStallError


def _replace_nowait(q: "queue.Queue", item: Any) -> None:
    """Best-effort freshest-wins replacement on a maxsize-1 queue: drop a
    stale entry if present, then put without blocking (a concurrent producer
    winning the slot is fine — its item is at least as fresh)."""
    try:
        q.get_nowait()
    except queue.Empty:
        pass
    try:
        q.put_nowait(item)
    except queue.Full:
        pass


def _queue_instruments():
    registry = get_registry()
    return (
        registry.gauge(
            "stoix_tpu_sebulba_queue_depth",
            "Items currently buffered per Sebulba queue",
        ),
        registry.histogram(
            "stoix_tpu_sebulba_queue_put_wait_seconds",
            "Producer-side blocking time per queue put",
        ),
        registry.histogram(
            "stoix_tpu_sebulba_queue_get_wait_seconds",
            "Consumer-side blocking time per queue get",
        ),
    )


class ThreadLifetime:
    def __init__(self) -> None:
        self._stop = threading.Event()

    def should_stop(self) -> bool:
        return self._stop.is_set()

    def stop(self) -> None:
        self._stop.set()


class OnPolicyPipeline:
    """Bounded rollout queues, one per actor thread.

    `fleet` (optional, a resilience.fleet.FleetCoordinator) makes the
    learner-side collect fleet-aware: a cross-host partition declared by the
    fleet monitor fails the collect IMMEDIATELY with the typed
    FleetPartitionError instead of burning the collect timeout against
    actors that are healthy while the POD is dead (docs/DESIGN.md §2.6)."""

    def __init__(self, num_actors: int, max_size: int = 1, fleet: Optional[Any] = None):
        self._queues: List[queue.Queue] = [queue.Queue(maxsize=max_size) for _ in range(num_actors)]
        self.heartbeats = HeartbeatBoard()
        self._depth, self._put_wait, self._get_wait = _queue_instruments()
        self._failures: Dict[int, ComponentFailure] = {}
        self._failure_lock = threading.Lock()
        self._fleet = fleet

    def fail(self, actor_id: int, failure: ComponentFailure) -> None:
        """Poison-pill injection (supervisor path): record the failure and
        wake a learner blocked on this actor's queue. A payload already
        buffered may be dropped to make room — on the failure path the batch
        is lost anyway."""
        with self._failure_lock:
            self._failures[actor_id] = failure
        # Best-effort wake; collect_rollouts consults _failures before
        # blocking, so a lost put is not a lost failure.
        _replace_nowait(self._queues[actor_id], failure)

    def send_rollout(self, actor_id: int, payload: Any, timeout: Optional[float] = None) -> None:
        labels = {"queue": "rollout", "actor": str(actor_id)}
        start = time.perf_counter()
        try:
            with span("pipeline_put", actor=actor_id):
                self._queues[actor_id].put(payload, timeout=timeout)
        finally:
            # finally: a queue.Full timeout is the worst-case backpressure
            # sample — the one this histogram exists to capture.
            self._put_wait.observe(time.perf_counter() - start, labels)
            self._depth.set(self._queues[actor_id].qsize(), labels)
        self.heartbeats.beat(f"actor-{actor_id}")

    def collect_rollouts(self, timeout: float = 180.0) -> List[Any]:
        """Blocks until every actor has contributed one rollout. A timeout
        names the starved actor and its last-heartbeat age (reference
        sebulba_utils.py:85 surfaced a bare queue.Empty here)."""
        detector = StallDetector(self.heartbeats, stale_after_s=max(1.0, timeout / 4))
        payloads = []
        for actor_id, q in enumerate(self._queues):
            if self._fleet is not None:
                self._fleet.check_partition()
            with self._failure_lock:
                failure = self._failures.get(actor_id)
            if failure is not None:
                raise failure
            labels = {"queue": "rollout", "actor": str(actor_id)}
            start = time.perf_counter()
            try:
                with span("pipeline_get", actor=actor_id):
                    payload = q.get(timeout=timeout)
                    if isinstance(payload, ComponentFailure):
                        raise payload
                    payloads.append(payload)
            except queue.Empty:
                raise ActorStarvationError(
                    actor_id,
                    timeout,
                    detector.diagnose(waiting_on=f"actor-{actor_id}"),
                    self.heartbeats.age(f"actor-{actor_id}"),
                ) from None
            self._get_wait.observe(time.perf_counter() - start, labels)
            self._depth.set(q.qsize(), labels)
        self.heartbeats.beat("learner")
        return payloads

    def drain(self, timeout: float = 0.5) -> int:
        """Shutdown-path drain: unblock producers stuck in put() WITHOUT
        recording wait/depth series or heartbeats — drain gets are teardown
        artifacts, not backpressure signal. Returns items drained; stops at
        the first empty queue (matching the old best-effort loop)."""
        drained = 0
        for q in self._queues:
            try:
                q.get(timeout=timeout)
                drained += 1
            except queue.Empty:
                break
        return drained


class OffPolicyPipeline:
    """Off-policy ingestion (docs/DESIGN.md §2.10): actor devices PUSH
    transition shards whenever a rollout chunk is ready; the learner POLLS
    whatever has arrived and samples its replay service independently — no
    lockstep collect, so one slow/restarting actor never stalls the learner
    (the on-policy pipeline's must-hear-from-every-actor rule is exactly
    what an off-policy learner does not need).

    A single bounded queue carries (actor_id, payload) pairs from every
    actor; a full queue back-pressures producers (put blocks), an empty one
    never blocks the learner past its chosen timeout. Failure semantics
    mirror OnPolicyPipeline: the supervisor injects a typed ComponentFailure
    poison-pill for an unrecoverable actor; the learner raises it on its
    next poll instead of sampling forever against a quietly dead fleet."""

    def __init__(self, num_actors: int, depth_per_actor: int = 2, fleet: Optional[Any] = None):
        self.num_actors = num_actors
        self._queue: queue.Queue = queue.Queue(maxsize=max(1, num_actors * depth_per_actor))
        self.heartbeats = HeartbeatBoard()
        self._depth, self._put_wait, self._get_wait = _queue_instruments()
        self._failures: Dict[int, ComponentFailure] = {}
        self._failure_lock = threading.Lock()
        self._fleet = fleet

    def _check_failures(self) -> None:
        if self._fleet is not None:
            self._fleet.check_partition()
        with self._failure_lock:
            for failure in self._failures.values():
                raise failure

    def fail(self, actor_id: int, failure: ComponentFailure) -> None:
        """Poison-pill injection (supervisor path): record the failure and
        wake a learner blocked in wait_for_data. The shared queue may be
        full of healthy payloads — drop one to make room for the pill (the
        learner consults _failures before blocking, so a lost put is never
        a lost failure)."""
        with self._failure_lock:
            self._failures[actor_id] = failure
        try:
            self._queue.put_nowait(failure)
        except queue.Full:
            try:
                self._queue.get_nowait()
                self._queue.put_nowait(failure)
            except (queue.Empty, queue.Full):
                pass

    def push(self, actor_id: int, payload: Any, timeout: Optional[float] = None) -> None:
        labels = {"queue": "transitions", "actor": str(actor_id)}
        start = time.perf_counter()
        try:
            with span("offpolicy_push", actor=actor_id):
                self._queue.put((actor_id, payload), timeout=timeout)
        finally:
            # finally: a queue.Full timeout is the worst-case backpressure
            # sample — the one this histogram exists to capture.
            self._put_wait.observe(time.perf_counter() - start, labels)
            self._depth.set(self._queue.qsize(), labels)
        self.heartbeats.beat(f"actor-{actor_id}")

    def poll(self, max_items: int = 64, timeout: float = 0.0) -> List[Any]:
        """Drain up to `max_items` pending (actor_id, payload) pairs. Only
        the FIRST get may block (up to `timeout`); the rest are non-blocking
        — the learner ingests what exists and goes back to sampling. Raises
        the typed ComponentFailure if any actor is unrecoverably gone."""
        self._check_failures()
        labels = {"queue": "transitions", "actor": "learner"}
        items: List[Any] = []
        start = time.perf_counter()
        with span("offpolicy_poll"):
            while len(items) < max_items:
                try:
                    got = self._queue.get(timeout=timeout if not items else 0.0)
                except queue.Empty:
                    break
                if isinstance(got, ComponentFailure):
                    raise got
                items.append(got)
        if items:
            self._get_wait.observe(time.perf_counter() - start, labels)
            self._depth.set(self._queue.qsize(), labels)
            self.heartbeats.beat("learner")
        return items

    def wait_for_data(self, timeout: float = 180.0) -> List[Any]:
        """Block until at least one payload arrives (warmup / starved-replay
        path). A timeout names the stalest actor and its last-heartbeat age
        instead of surfacing a bare queue.Empty."""
        detector = StallDetector(self.heartbeats, stale_after_s=max(1.0, timeout / 4))
        items = self.poll(timeout=timeout)
        if not items:
            # Name the most-starved producer: a never-beat actor outranks
            # any stale one; otherwise the oldest heartbeat wins.
            stalest, stalest_age = 0, -1.0
            for actor_id in range(self.num_actors):
                actor_age = self.heartbeats.age(f"actor-{actor_id}")
                if actor_age is None:
                    stalest, stalest_age = actor_id, None
                    break
                if stalest_age is not None and actor_age > stalest_age:
                    stalest, stalest_age = actor_id, actor_age
            raise ActorStarvationError(
                stalest,
                timeout,
                detector.diagnose(waiting_on=f"actor-{stalest}"),
                stalest_age,
            )
        return items

    def drain(self, timeout: float = 0.5) -> int:
        """Shutdown-path drain: unblock producers stuck in put() WITHOUT
        recording wait/depth series or heartbeats (teardown artifacts, not
        backpressure signal)."""
        drained = 0
        while True:
            try:
                self._queue.get(timeout=timeout)
                drained += 1
            except queue.Empty:
                return drained


class VersionedParams(NamedTuple):
    """Queue entry the ParameterServer feeds actors: the placed params plus
    the monotone version (distribute_params call count) they came from. The
    IMPACT stale-reuse path (docs/DESIGN.md §2.12) tags every pushed
    trajectory with the behavior version so the learner can compute per-batch
    staleness; the version travels WITH the params through the queue (not as
    a separate attribute read) so an actor can never pair params vN with
    version vN+1."""

    version: int
    params: Any


class ParameterServer:
    """Latest-params distribution to actor devices.

    Transfer economy: params are device_put ONCE PER DEVICE per version, not
    once per actor — actors sharing a device receive the same placed copy
    through their own queues (re-transferring identical bytes for every
    co-located actor scaled the push cost with actors_per_device for no
    reason). `reprime` reuses the version's placed copy the same way.

    Versioning: every distribute_params bumps a monotone version counter;
    queue entries are VersionedParams. `get_params` strips the version
    (back-compat contract for the on-policy path); `get_params_versioned`
    returns (version, params) for actors that must report which policy
    collected a trajectory (IMPACT, arXiv:1912.00167)."""

    def __init__(
        self,
        actor_devices: List[jax.Device],
        actors_per_device: int,
        heartbeats: Optional[HeartbeatBoard] = None,
    ):
        self._devices = [d for d in actor_devices for _ in range(actors_per_device)]
        self._queues: List[queue.Queue] = [queue.Queue(maxsize=1) for _ in self._devices]
        self._version = 0  # bumped once per distribute_params (learner thread)
        self._latest: Any = None  # last distributed params, for reprime()
        # (params, {device: placed copy}) of the most recently COMPLETED
        # push, identity-tagged so reprime can tell whether the placed
        # copies belong to self._latest or to an older version a concurrent
        # distribute is in the middle of replacing.
        self._placed_entry: Optional[tuple] = None
        self.heartbeats = heartbeats if heartbeats is not None else HeartbeatBoard()
        self._depth, self._put_wait, self._get_wait = _queue_instruments()
        self._pushes = get_registry().counter(
            "stoix_tpu_sebulba_param_pushes_total",
            "Parameter versions pushed to each actor queue",
        )
        self._transfer = get_registry().histogram(
            "stoix_tpu_sebulba_param_transfer_seconds",
            "Host-side device_put time per param placement (once per DEVICE "
            "per version, not per actor; NOT queue blocking)",
        )

    @property
    def num_actors(self) -> int:
        return len(self._queues)

    def _place(self, params: Any, device: jax.Device, placed: Dict[Any, Any]) -> Any:
        """device_put once per device; later actors on the device reuse it."""
        local = placed.get(device)
        if local is None:
            start = time.perf_counter()
            local = jax.device_put(params, device)
            self._transfer.observe(
                time.perf_counter() - start, {"queue": "params", "device": str(device)}
            )
            placed[device] = local
        return local

    @property
    def version(self) -> int:
        """Monotone count of completed/started distribute_params calls — the
        learner's CURRENT policy version (0 before the first push)."""
        return self._version

    def distribute_params(self, params: Any) -> None:
        self._version += 1
        version = self._version
        self._latest = params
        placed: Dict[Any, Any] = {}
        with span("param_push", actors=len(self._queues)):
            for actor_id, (device, q) in enumerate(zip(self._devices, self._queues)):
                labels = {"queue": "params", "actor": str(actor_id)}
                # Transfer cost and queue blocking are separate series: a
                # slow push must be attributable to the right cause (large
                # params vs an actor not draining its queue).
                local = self._place(params, device, placed)
                start = time.perf_counter()
                # Keep only the freshest params: drop a stale entry if present.
                try:
                    q.get_nowait()
                except queue.Empty:
                    pass
                q.put(VersionedParams(version, local))
                self._put_wait.observe(time.perf_counter() - start, labels)
                self._depth.set(q.qsize(), labels)
                self._pushes.inc(labels={"actor": str(actor_id)})
        self._placed_entry = (params, placed, version)
        self.heartbeats.beat("param-server")

    def reprime(self, actor_id: int) -> bool:
        """Re-feed the LATEST distributed params to one actor queue (the
        supervisor calls this before starting a replacement actor). Never
        blocks: a concurrent learner push wins the maxsize-1 slot, which is
        at least as fresh. Reuses the latest COMPLETED version's placed copy
        for the actor's device when one exists — no redundant transfer; a
        version still mid-push places fresh (its dict may hold older copies)."""
        latest = self._latest
        if latest is None:
            return False
        entry = self._placed_entry
        if entry is not None and entry[0] is latest:
            placed, version = entry[1], entry[2]
        else:
            # Mid-push race: its dict may hold older copies; place fresh and
            # tag with the in-flight version (the one being distributed).
            placed, version = {}, self._version
        local = self._place(latest, self._devices[actor_id], placed)
        _replace_nowait(self._queues[actor_id], VersionedParams(version, local))
        return True

    def fail(self, failure: ComponentFailure, actor_id: int) -> None:
        """Poison one actor's param queue: an actor blocked in get_params
        raises `failure` instead of waiting on params that will never come.
        The supervisor uses this for the failed actor itself — a wedge
        blocked in get_params dies with a typed error instead of lingering
        forever. (Orderly teardown of HEALTHY actors stays shutdown()'s
        None-sentinel job.)"""
        _replace_nowait(self._queues[actor_id], failure)

    def get_params(self, actor_id: int, timeout: Optional[float] = None) -> Any:
        """Returns fresh params, or None (shutdown sentinel); raises a
        ComponentFailure poison-pill if the learner failed unrecoverably."""
        got = self.get_params_versioned(actor_id, timeout=timeout)
        return None if got is None else got.params

    def get_params_versioned(
        self, actor_id: int, timeout: Optional[float] = None
    ) -> Optional[VersionedParams]:
        """Like get_params, but keeps the version the entry was distributed
        under: (version, params), or None (shutdown sentinel). IMPACT actors
        use this to tag trajectories with their behavior-policy version."""
        labels = {"queue": "params", "actor": str(actor_id)}
        start = time.perf_counter()
        with span("param_get", actor=actor_id):
            entry = self._queues[actor_id].get(timeout=timeout)
        self._get_wait.observe(time.perf_counter() - start, labels)
        self._depth.set(self._queues[actor_id].qsize(), labels)
        if isinstance(entry, ComponentFailure):
            raise entry
        if entry is None:
            return None
        return entry

    def shutdown(self) -> None:
        for q in self._queues:
            try:
                q.get_nowait()
            except queue.Empty:
                pass
            q.put(None)


class AsyncEvaluator:
    """Runs evaluations off the critical path on a dedicated device."""

    def __init__(
        self,
        evaluate: Callable[[Any, jax.Array], dict],
        lifetime: ThreadLifetime,
        on_result: Callable[[dict, Any, int], None],
        heartbeats: Optional[HeartbeatBoard] = None,
    ):
        self._evaluate = evaluate
        self._lifetime = lifetime
        self._on_result = on_result
        self._requests: queue.Queue = queue.Queue()
        self._idle = threading.Event()
        self._idle.set()
        # Guards the (queue-state, _idle) pair: submit makes the queue
        # non-empty and clears _idle atomically, _maybe_set_idle only sets
        # _idle while the queue is observably empty — without it a submit
        # racing the evaluator's own empty-check could leave _idle set with a
        # request queued, and wait_until_idle would return with dangling work.
        self._idle_lock = threading.Lock()
        self.heartbeats = heartbeats if heartbeats is not None else HeartbeatBoard()
        self._depth = get_registry().gauge(
            "stoix_tpu_sebulba_queue_depth",
            "Items currently buffered per Sebulba queue",
        )
        self.thread = threading.Thread(target=self._run, name="async-evaluator", daemon=True)

    def submit(self, params: Any, key: jax.Array, t: int) -> None:
        with self._idle_lock:
            self._idle.clear()
            self._requests.put((params, key, t))
        self._depth.set(self._requests.qsize(), {"queue": "eval_requests"})

    def _maybe_set_idle(self) -> None:
        with self._idle_lock:
            if self._requests.empty():
                self._idle.set()

    def _run(self) -> None:
        # Drain-on-stop: a lifetime stop with requests still queued finishes
        # them first — shutdown must not DROP submitted evaluation work (the
        # final eval of a run is submitted right before the learner loop
        # ends, and wait_until_idle now treats dangling work as an error).
        while not (self._lifetime.should_stop() and self._requests.empty()):
            try:
                params, key, t = self._requests.get(timeout=1.0)
            except queue.Empty:
                self._maybe_set_idle()
                continue
            self._depth.set(self._requests.qsize(), {"queue": "eval_requests"})
            try:
                with span("async_eval", t=t):
                    metrics = self._evaluate(params, key)
                    self._on_result(metrics, params, t)
                self.heartbeats.beat("evaluator")
            except Exception:  # noqa: BLE001 — a lost eval window must not
                # kill the thread silently nor wedge shutdown on a cleared
                # _idle flag (mirrors rollout_thread's crash telemetry).
                import traceback

                get_registry().counter(
                    "stoix_tpu_sebulba_evaluator_errors_total",
                    "Async evaluation requests that raised",
                ).inc()
                from stoix_tpu.observability import get_logger

                get_logger("stoix_tpu.sebulba").error(
                    "[async-evaluator] eval at t=%d FAILED:\n%s",
                    t, traceback.format_exc(),
                )
            self._maybe_set_idle()
        self._maybe_set_idle()

    def wait_until_idle(self, timeout: float = 600.0) -> None:
        """Block until all submitted evaluations completed. A timeout RAISES
        (EvaluatorStallError with the evaluator's last-heartbeat age) instead
        of silently returning — shutdown must not proceed while evaluation
        work is still dangling (it would be dropped unreported)."""
        if not self._idle.wait(timeout=timeout):
            raise EvaluatorStallError(
                timeout, self.heartbeats.age("evaluator"), self._requests.qsize()
            )
