"""Static models of the two sides of STX009's config↔code cross-check.

YAML side: every file under `stoix_tpu/configs/` is mounted the way
`stoix_tpu.utils.config.compose` would mount it — group files
(`env/cartpole.yaml`, `system/ppo/ff_ppo.yaml`, ...) land under their group
key; root files under `configs/default/` merge at the top level. The model is
the UNION over all files: a key "exists" if any composition could define it.

Code side: attribute-chain reads rooted at a name `config`/`cfg` (plus
one-level aliases like `net_cfg = config.network`), split into:

  - strict reads  — `config.a.b.c` (AttributeError if missing),
  - tolerant reads — `config.a.get("b", d)` / `getattr(config.a, "b", d)`
    (consume a key for liveness but tolerate absence), and
  - writes        — `config.a.b = ...` (systems inject computed fields; a
    written path and everything under it is defined from then on).

Everything here is pure stdlib (ast + yaml); no jax import.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set, Tuple

import yaml

Path = Tuple[str, ...]
Resolver = Callable[[str, Path], Optional[Path]]


@dataclass
class ConfigKeySet:
    """The union YAML key space under stoix_tpu/configs/."""

    nodes: Set[Path] = field(default_factory=set)  # every interior + leaf path
    # leaf path -> every (rel file, line) defining it, so a dead key is
    # reported against ALL the yamls that must drop it, in one run.
    leaves: Dict[Path, List[Tuple[str, int]]] = field(default_factory=dict)
    # Paths whose subtree is consumed dynamically by config.instantiate()
    # (any dict carrying a `_target_` key): its sibling keys become
    # constructor kwargs, which no attribute-chain read will ever name.
    target_prefixes: Set[Path] = field(default_factory=set)

    def defines(self, path: Path) -> bool:
        return path in self.nodes

    def under_target(self, path: Path) -> bool:
        return any(path[: len(p)] == p for p in self.target_prefixes)


def _yaml_key_line(lines: List[str], key: str, after: int) -> int:
    """Best-effort line of `key:` at or after line index `after` (1-based)."""
    pattern = re.compile(rf"^\s*{re.escape(key)}\s*:")
    for i in range(max(after - 1, 0), len(lines)):
        if pattern.match(lines[i]):
            return i + 1
    for i, line in enumerate(lines):
        if pattern.match(line):
            return i + 1
    return 1


def load_config_keys(repo: str) -> ConfigKeySet:
    keys = ConfigKeySet()
    config_dir = os.path.join(repo, "stoix_tpu", "configs")
    for root, dirs, files in os.walk(config_dir):
        dirs.sort()
        for name in sorted(files):
            if not name.endswith((".yaml", ".yml")):
                continue
            full = os.path.join(root, name)
            rel = os.path.relpath(full, repo)
            parts = os.path.relpath(full, config_dir).split(os.sep)
            mount: Path = () if parts[0] == "default" else (parts[0],)
            try:
                with open(full) as f:
                    text = f.read()
                data = yaml.safe_load(text) or {}
            except (OSError, yaml.YAMLError):
                continue
            if not isinstance(data, dict):
                continue
            lines = text.splitlines()
            for i in range(len(mount)):
                keys.nodes.add(mount[: i + 1])
            _walk_yaml(keys, data, mount, rel, lines, hint=1)
    return keys


def _walk_yaml(
    keys: ConfigKeySet,
    node: dict,
    prefix: Path,
    rel: str,
    lines: List[str],
    hint: int,
) -> None:
    if "_target_" in node:
        keys.target_prefixes.add(prefix)
    for key, value in node.items():
        if key == "defaults" and not prefix:
            continue  # the compose() directive list, not config data
        path = prefix + (str(key),)
        keys.nodes.add(path)
        line = _yaml_key_line(lines, str(key), hint)
        if isinstance(value, dict):
            _walk_yaml(keys, value, path, rel, lines, hint=line)
        else:
            keys.leaves.setdefault(path, []).append((rel, line))


# ---------------------------------------------------------------------------
# Code side


_ROOT_NAMES = {"config", "cfg"}
_DICT_METHODS = {
    "get",
    "items",
    "keys",
    "values",
    "pop",
    "setdefault",
    "update",
    "to_dict",
    "copy",
    "from_dict",
}


@dataclass
class ConfigAccesses:
    strict: List[Tuple[Path, int]] = field(default_factory=list)  # (path, lineno)
    tolerant: List[Tuple[Path, int]] = field(default_factory=list)
    writes: Set[Path] = field(default_factory=set)


def _chain_of(node: ast.AST) -> Optional[Tuple[str, Path]]:
    """(root name, attr path) for an attribute chain like config.a.b."""
    attrs: List[str] = []
    while isinstance(node, ast.Attribute):
        attrs.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        return node.id, tuple(reversed(attrs))
    return None


def _path_of_value(
    node: ast.AST, resolve: "Resolver", depth: int = 0
) -> Optional[Path]:
    """Resolve a config-subtree EXPRESSION to its dotted path, covering the
    repo's dict-style composition idioms beyond plain attribute chains:

        (config.get("arch") or {}).get("preflight")   -> arch.preflight
        config.arch.get("supervision") or {}          -> arch.supervision
    """
    if depth > 8:
        return None
    if isinstance(node, (ast.Attribute, ast.Name)):
        chain = _chain_of(node)
        return resolve(chain[0], chain[1]) if chain else None
    if isinstance(node, ast.BoolOp) and isinstance(node.op, ast.Or) and node.values:
        return _path_of_value(node.values[0], resolve, depth + 1)
    if (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "get"
        and node.args
        and isinstance(node.args[0], ast.Constant)
        and isinstance(node.args[0].value, str)
    ):
        base = _path_of_value(node.func.value, resolve, depth + 1)
        if base is not None:
            return base + (node.args[0].value,)
    return None


def _make_resolver(aliases: Dict[str, Path]):
    def resolve(root: str, attrs: Path) -> Optional[Path]:
        # An alias REBINDING wins over the root-name convention: a local
        # `cfg = config.arch.get("preflight") or {}` is the subtree, not the
        # root config.
        if root in aliases:
            return aliases[root] + attrs
        if root in _ROOT_NAMES:
            return attrs
        if root == "self" and attrs and attrs[0] in _ROOT_NAMES:
            return attrs[1:] or None  # self.config.a.b -> a.b
        return None

    return resolve


def _collect_aliases(tree: ast.AST) -> Dict[str, Path]:
    """Subtree aliases: `net_cfg = config.network`, and the dict-style
    `pf_cfg = (config.get("arch") or {}).get("preflight") or {}` composition
    idiom (file-wide; a name rebound to two different subtrees or to an
    unresolvable value is dropped). The alias ASSIGNMENT itself still counts
    as a read of the aliased path (it is one — and a typo'd
    `x = config.system.gama` must stay reportable); reads THROUGH the alias
    extend it.

    Two passes so an alias defined in terms of another alias resolves."""
    aliases: Dict[str, Path] = {}
    for _ in range(2):
        resolve = _make_resolver(aliases)
        candidates: Dict[str, Set[Path]] = {}
        poisoned: Set[str] = set()
        for node in ast.walk(tree):
            targets: List[ast.AST] = []
            value: Optional[ast.AST] = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            for target in targets:
                if not isinstance(target, ast.Name):
                    continue
                path = _path_of_value(value, resolve) if value is not None else None
                if path:
                    candidates.setdefault(target.id, set()).add(path)
                else:
                    poisoned.add(target.id)
        aliases = {
            name: next(iter(paths))
            for name, paths in candidates.items()
            if name not in poisoned and len(paths) == 1
        }
    return aliases


def collect_config_accesses(tree: ast.AST) -> ConfigAccesses:
    aliases = _collect_aliases(tree)
    accesses = ConfigAccesses()
    resolve = _make_resolver(aliases)

    consumed: Set[ast.AST] = set()  # attribute nodes already part of a longer chain
    # Attribute nodes used as a call's function: their last component is a
    # METHOD on the leaf value (`config.logger.path.rstrip(...)`), not a key.
    call_funcs: Set[ast.AST] = {
        node.func
        for node in ast.walk(tree)
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
    }

    for node in ast.walk(tree):
        # getattr(config.a, "b"[, default]) — tolerant read.
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id in ("getattr", "hasattr")
            and len(node.args) >= 2
            and isinstance(node.args[1], ast.Constant)
            and isinstance(node.args[1].value, str)
        ):
            chain = _chain_of(node.args[0])
            if chain:
                path = resolve(chain[0], chain[1] + (node.args[1].value,))
                if path:
                    accesses.tolerant.append((path, node.lineno))
                    _mark_consumed(node.args[0], consumed)
                    consumed.add(node.func)
        # config.a.get("b"[, default]) — tolerant read of a.b; also resolves
        # the chained dict-style idiom ((config.get("arch") or {}).get(...)).
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "get"
        ):
            path = _path_of_value(node, resolve)
            if path is not None:
                accesses.tolerant.append((path, node.lineno))
                _mark_consumed(node.func, consumed)
            else:
                base = _path_of_value(node.func.value, resolve)
                if base:  # .get(<non-literal key>) keeps the node itself live
                    accesses.tolerant.append((base, node.lineno))
                    _mark_consumed(node.func, consumed)

    for node in ast.walk(tree):
        if not isinstance(node, ast.Attribute) or node in consumed:
            continue
        chain = _chain_of(node)
        if not chain:
            continue
        root, attrs = chain
        if node in call_funcs and attrs:
            attrs = attrs[:-1]  # drop the method component of a call
        # Trim trailing dict-method / dunder components referenced unbound:
        # config.system.get (the .get handled above), cfg.items, ...
        while attrs and (attrs[-1] in _DICT_METHODS or attrs[-1].startswith("_")):
            attrs = attrs[:-1]
        if not attrs:
            continue
        path = resolve(root, attrs)
        if path is None:
            continue
        _mark_consumed(node, consumed)
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            accesses.writes.add(path)
        else:
            accesses.strict.append((path, node.lineno))
    return accesses


def _mark_consumed(node: ast.AST, consumed: Set[ast.AST]) -> None:
    """Mark an attribute chain's sub-chains so the maximal-chain pass does
    not re-report `config.a` inside `config.a.b`."""
    while isinstance(node, ast.Attribute):
        consumed.add(node)
        node = node.value
