"""stoix_tpu.analysis — the first-party JAX-aware static-analysis gate.

Promoted from the flat scripts/lint.py (PRs 1-4) into a rule-plugin
subsystem: `core` holds the framework (Finding/Rule/registry/noqa/runner),
`rules/` holds one module per rule (STX001-STX013 plus the F401/hygiene core
checks), `jitreach` resolves which functions flow into jit/shard_map/scan/
pmap, `configmodel` models the Hydra-style YAML tree for STX009, and
`meshmodel` models mesh construction + every sharding expression for the
sharding-layer rules STX010-STX011 (docs/DESIGN.md §2.5).

Everything is stdlib `ast` + `yaml` — no jax import — so the gate runs in a
SLURM prolog or CI box in milliseconds and `launcher.py --preflight-only`
embeds it before any backend probe.

CLI: `python -m stoix_tpu.analysis [paths...] [--select/--ignore IDS]
[--format text|json|github] [--changed-only] [--list-rules]`;
`scripts/lint.py` is a byte-identical shim over the text format.
"""

from stoix_tpu.analysis.core import (  # noqa: F401 — public API
    DEFAULT_PATHS,
    ERROR,
    WARNING,
    FileContext,
    Finding,
    Rule,
    TreeContext,
    changed_paths,
    get_rule,
    get_rules,
    noqa_suppresses,
    run_paths,
    split_severity,
)
