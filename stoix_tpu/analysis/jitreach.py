"""Jit-boundary resolution: which functions in a module flow into a traced
program (`jax.jit` / `jax.pmap` / `jax.vmap` / `lax.scan` / `shard_map` /
`lax.while_loop` / `lax.cond` ...)?

The stoix_tpu idiom makes this tractable with a per-module analysis:

    learn_per_shard = get_learner_fn(env, apply_fns, update_fns, config)
    learn = anakin.shardmap_learner(learn_per_shard, mesh, specs)   # traced
    batched = jax.vmap(_update_step, axis_name="batch")             # wrapper
    state, _ = jax.lax.scan(batched, state, None, n)                # traced

Resolution steps (all AST, no imports executed):

  1. Collect every `FunctionDef` (nested included) by simple name.
  2. Build a wrapper-alias map: `x = jax.vmap(f, ...)` / `x = partial(f, ..)`
     / `x = jit(f)` makes `x` an alias for `f`; `y = factory(...)` where
     `factory` is a local function makes `y` an alias for every function
     `factory` returns (the `get_learner_fn -> learner_fn` pattern).
  3. Mark entry points: every function-valued argument of a traced call
     (TRACED_CALLEES below), plus functions decorated with `@jax.jit` /
     `@partial(jax.jit, ...)`.
  4. Close over references: inside a reachable function's own scope (nested
     `def` bodies excluded until *they* are reachable), any `Name` that
     resolves to a known function or alias marks that function reachable.

Known blind spots (documented in docs/DESIGN.md §2.5): cross-module flow
(a function jitted by its *importer* is invisible to the exporting module's
analysis — the scan/vmap-heavy stoix_tpu idiom keeps most trace surface
module-local), method resolution (`self.f`), functions smuggled through
containers, and conditional rebinding.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, Iterator, List, Optional, Set

# Callees whose function-valued arguments get traced. Bare-name forms are
# accepted for the jax transforms (commonly imported directly); the lax
# control-flow primitives must be attribute calls (`lax.cond`) so a local
# helper named `cond` cannot confuse the analysis.
_TRACED_ANY = {"jit", "pmap", "vmap", "scan", "shard_map", "shardmap_learner", "remat"}
_TRACED_ATTR_ONLY = {"while_loop", "fori_loop", "cond", "switch", "associative_scan", "checkpoint"}
_WRAPPERS = {"jit", "pmap", "vmap", "partial", "remat", "checkpoint", "annotate"}

FunctionNode = ast.AST  # FunctionDef | AsyncFunctionDef | Lambda


def callee_name(func: ast.AST) -> str:
    """Terminal identifier of a callee: 'scan' for `jax.lax.scan` and `scan`.
    Shared AST helper (also used by the STX007/STX008 rule modules)."""
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


_callee_name = callee_name  # internal alias


def assigned_names(target: ast.AST) -> List[str]:
    """Flat identifier list a binding target assigns: `a, (b, *c) = ...` ->
    [a, b, c]. Shared AST helper (STX005/STX008 rebind tracking)."""
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        names: List[str] = []
        for elt in target.elts:
            names.extend(assigned_names(elt))
        return names
    if isinstance(target, ast.Starred):
        return assigned_names(target.value)
    return []


def literal_int_set(node: ast.AST) -> Optional[Set[int]]:
    """{ints} of an int literal or all-int tuple/list literal, else None.
    Shared AST helper (STX008 donate_argnums, STX012 static_argnums)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        out: Set[int] = set()
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.add(elt.value)
            else:
                return None
        return out
    return None


def literal_str_set(node: ast.AST) -> Optional[Set[str]]:
    """{strs} of a str literal or all-str tuple/list literal, else None.
    Shared AST helper (STX008 donate_argnames, STX012 static_argnames)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        out: Set[str] = set()
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                out.add(elt.value)
            else:
                return None
        return out
    return None


def annotate_parents(tree: ast.AST) -> Dict[int, ast.AST]:
    """id(child) -> parent links for the whole tree. Shared AST helper
    (ModuleMeshModel scope walks, STX012 enclosing-loop walks) — build once
    per file via ctx.memo("parents", ...), it is an O(all-nodes) walk."""
    parents: Dict[int, ast.AST] = {}
    for parent in ast.walk(tree):
        for child in ast.iter_child_nodes(parent):
            parents[id(child)] = parent
    return parents


def positional_params(fn: ast.AST) -> List[str]:
    """Positional parameter names of a def, posonly included. Shared AST
    helper (STX008/STX012 name<->position cross-mapping)."""
    args = fn.args
    return [p.arg for p in list(getattr(args, "posonlyargs", [])) + list(args.args)]


def all_param_names(args: ast.arguments) -> Set[str]:
    """EVERY parameter name of a def/lambda — posonly, positional, kwonly,
    *vararg, **kwarg. Shared AST helper (STX010/011/013 parameter-shadowing:
    a parameter is a fresh caller value, never another scope's binding)."""
    return {
        p.arg
        for p in (
            list(getattr(args, "posonlyargs", []))
            + list(args.args)
            + list(args.kwonlyargs)
            + ([args.vararg] if args.vararg else [])
            + ([args.kwarg] if args.kwarg else [])
        )
    }


def walk_scope(node: ast.AST) -> Iterator[ast.AST]:
    """ast.walk limited to `node`'s own scope: nested function/lambda/class
    nodes are yielded but their bodies are not entered (their decorators and
    default-argument expressions — which evaluate in this scope — are)."""
    stack: List[ast.AST] = [node]
    while stack:
        current = stack.pop()
        if current is not node:
            yield current
            if isinstance(
                current, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)
            ):
                for deco in getattr(current, "decorator_list", []):
                    stack.append(deco)
                args = getattr(current, "args", None)
                if args is not None:
                    stack.extend(args.defaults)
                    stack.extend(d for d in args.kw_defaults if d is not None)
                continue
        stack.extend(ast.iter_child_nodes(current))


class _ModuleIndex:
    """Name->function map + wrapper-alias map for one module."""

    def __init__(self, tree: ast.AST) -> None:
        self.functions: Dict[str, List[ast.AST]] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions.setdefault(node.name, []).append(node)
        self.aliases: Dict[str, Set[str]] = {}
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            target = node.targets[0]
            if not isinstance(target, ast.Name):
                continue
            wrapped = self._function_names_in(node.value)
            if wrapped:
                self.aliases.setdefault(target.id, set()).update(wrapped)

    def _function_names_in(self, expr: ast.AST, depth: int = 0) -> Set[str]:
        """Function names an expression evaluates to / wraps (bounded depth)."""
        if depth > 6:
            return set()
        if isinstance(expr, ast.Name):
            if expr.id in self.functions:
                return {expr.id}
            return set(self.aliases.get(expr.id, set()))
        if isinstance(expr, ast.Call):
            callee = _callee_name(expr.func)
            if callee in _WRAPPERS:
                out: Set[str] = set()
                for arg in list(expr.args) + [kw.value for kw in expr.keywords]:
                    out |= self._function_names_in(arg, depth + 1)
                return out
            if callee in self.functions:
                return self._returned_function_names(callee)
        return set()

    def _returned_function_names(self, factory_name: str) -> Set[str]:
        """Functions a local factory returns by name (`return learner_fn`)."""
        out: Set[str] = set()
        for factory in self.functions[factory_name]:
            for node in ast.walk(factory):
                if isinstance(node, ast.Return) and isinstance(node.value, ast.Name):
                    if node.value.id in self.functions:
                        out.add(node.value.id)
        return out

    def resolve(self, name: str) -> Set[ast.AST]:
        nodes: Set[ast.AST] = set()
        for fn in self.functions.get(name, []):
            nodes.add(fn)
        for wrapped in self.aliases.get(name, set()):
            for fn in self.functions.get(wrapped, []):
                nodes.add(fn)
        return nodes


def _entry_function_nodes(tree: ast.AST, index: _ModuleIndex) -> Set[ast.AST]:
    entries: Set[ast.AST] = set()

    def mark(expr: ast.AST) -> None:
        if isinstance(expr, ast.Lambda):
            entries.add(expr)
            return
        for name in index._function_names_in(expr):
            entries.update(index.functions.get(name, []))

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            callee = _callee_name(node.func)
            traced = callee in _TRACED_ANY or (
                callee in _TRACED_ATTR_ONLY and isinstance(node.func, ast.Attribute)
            )
            if traced:
                for arg in list(node.args) + [kw.value for kw in node.keywords]:
                    mark(arg)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for deco in node.decorator_list:
                callee = _callee_name(deco.func if isinstance(deco, ast.Call) else deco)
                if callee == "jit":
                    entries.add(node)
                elif isinstance(deco, ast.Call) and callee == "partial":
                    if any(_callee_name(a) == "jit" for a in deco.args):
                        entries.add(node)
    return entries


def reachable_jit_functions(tree: ast.AST) -> Set[ast.AST]:
    """AST nodes of every function that (per the module-local resolution
    above) flows into a traced program."""
    index = _ModuleIndex(tree)
    reachable = set(_entry_function_nodes(tree, index))
    frontier = list(reachable)
    while frontier:
        fn = frontier.pop()
        for node in walk_scope(fn):
            targets: Iterable[ast.AST] = ()
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                targets = index.resolve(node.id)
            elif isinstance(node, ast.Lambda):
                targets = (node,)
            for target in targets:
                if target not in reachable:
                    reachable.add(target)
                    frontier.append(target)
    return reachable
