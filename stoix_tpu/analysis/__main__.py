"""CLI for the static-analysis gate.

    python -m stoix_tpu.analysis [paths...]
        [--select STX005,STX007] [--ignore HYG]
        [--format text|json|github] [--changed-only]
        [--list-rules] [--skip-external]

Text mode reproduces scripts/lint.py's historical output byte-for-byte
(warnings, errors, `[lint] N files, E errors, W warnings` summary); the shim
delegates here. JSON mode prints one object per finding
(rule/path/line/message/severity) as a single JSON array for CI consumption
(tests/test_analysis_clean.py). GitHub mode prints one workflow-command
annotation line per finding (`::error file=...,line=...,title=STX010::msg`)
so findings surface inline on the PR diff. Exit code: 0 clean, 1 findings at
error severity, 2 usage error.

`--changed-only` scans only the .py files `git` reports changed vs HEAD
(staged, unstaged, untracked) within the default scan surface — the
selection that keeps the gate fast as the rule count grows. Tree-scoped
rules (STX009) are skipped in this mode (a partial file set would make the
never-read analysis see phantom dead keys) — explicitly --select'ing one
together with --changed-only is a usage error (exit 2), never a silent
no-op — as is the mypy delegation
(whole-program inference has no meaningful per-file mode). When git is
unavailable OR the work tree is clean (the CI case: the change under test is
already committed, so a 0-file pass would be a fake gate) the full scan runs
instead — degrading to MORE coverage, never silently less.

stdout is this tool's machine-readable contract (like sweep.py's JSON
lines), hence the STX002 allowlist entry for this file.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import List, Optional

from stoix_tpu.analysis import core

# Mirrors stoix_tpu.resilience.exit_codes.EXIT_CODE_USAGE (argparse's own
# convention). Deliberately NOT imported: importing the registry executes
# the resilience package __init__, which drags jax/numpy into this
# dependency-free gate (core.py's SLURM-prolog contract — stdlib only).
# tests/test_threadmodel.py pins the mirror equal to the registry's value.
EXIT_CODE_USAGE = 2


def run_external(tool: str, args: List[str]) -> List[core.Finding]:
    """Delegate to ruff/mypy when importable (their config lives in
    pyproject.toml, so installing them upgrades the gate with zero changes)."""
    try:
        __import__(tool)
    except ImportError:
        return []
    proc = subprocess.run(
        [sys.executable, "-m", tool, *args], capture_output=True, text=True, cwd=core.REPO
    )
    if proc.returncode != 0:
        lines = [line for line in proc.stdout.splitlines() if line.strip()]
        lines += [line for line in proc.stderr.splitlines() if line.strip()]
        # A crash with no output must still fail the gate — a type check that
        # never ran is not a passing type check.
        lines = lines or [f"exited {proc.returncode} with no output"]
        return [Finding_external(tool, line) for line in lines]
    return []


def Finding_external(tool: str, line: str) -> core.Finding:
    return core.Finding(rule=tool, path=f"[{tool}]", line=0, message=line)


def _github_escape(text: str) -> str:
    """Workflow-command data escaping per the GitHub Actions spec."""
    return text.replace("%", "%25").replace("\r", "%0D").replace("\n", "%0A")


def _github_escape_property(text: str) -> str:
    """Property VALUES (file=, title=) additionally escape ',' and ':', which
    would otherwise terminate the property list / command prefix."""
    return _github_escape(text).replace(":", "%3A").replace(",", "%2C")


def render_github(finding: core.Finding) -> str:
    """One `::error`/`::warning` annotation line per finding. Paths are
    normalized repo-relative with forward slashes (annotations anchor to the
    PR diff); line-less findings (whole-file, external tools) omit `line=`."""
    level = "error" if finding.severity == core.ERROR else "warning"
    path = finding.path
    if os.path.isabs(path):
        path = os.path.relpath(path, core.REPO)
    path = path.replace(os.sep, "/")
    fields = []
    if not path.startswith("["):  # external pseudo-paths carry no file
        fields.append(f"file={_github_escape_property(path)}")
        if finding.line:
            fields.append(f"line={finding.line}")
    fields.append(f"title={_github_escape_property(finding.rule)}")
    return f"::{level} {','.join(fields)}::{_github_escape(finding.message)}"


def print_statistics(
    findings: List[core.Finding],
    rules: List[core.Rule],
    paths: Optional[List[str]],
) -> None:
    """The `--statistics` block (stderr — stdout is the findings contract):
    per-rule finding AND suppression counts plus derived-model sizes, so a
    CI log shows at a glance whether a quiet gate is quiet because the code
    is clean, because every finding is noqa'd away, or because a refactor
    silently emptied the model a rule family depends on."""
    from stoix_tpu.analysis import meshmodel, opsmodel, threadmodel

    by_rule: dict = {}
    for f in findings:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    suppressions: dict = {}
    bare = 0
    for path in core.iter_py_files(paths or core.DEFAULT_PATHS, core.REPO):
        try:
            with open(path) as fh:
                source = fh.read()
        except OSError:
            continue
        for line in source.splitlines():
            m = core._NOQA_RE.search(line)
            if not m:
                continue
            codes = core._CODE_RE.findall(m.group(1).split("—")[0])
            if not codes:
                bare += 1
            for code in codes:
                suppressions[code] = suppressions.get(code, 0) + 1
    err = sys.stderr
    print("[stats] per-rule findings / suppressions:", file=err)
    for rule in rules:
        n_found = sum(by_rule.get(fid, 0) for fid in rule.finding_ids)
        n_supp = sum(suppressions.get(fid, 0) for fid in rule.finding_ids)
        print(
            f"[stats]   {rule.id:<8} findings={n_found} suppressions={n_supp}",
            file=err,
        )
    if bare:
        print(f"[stats]   (bare noqa lines: {bare})", file=err)
    axes = sorted(meshmodel.mesh_axis_universe(core.REPO))
    print(
        f"[stats] meshmodel: {len(axes)} declared axis(es) [{', '.join(axes)}]",
        file=err,
    )
    t = threadmodel.repo_summary(paths)
    print(
        f"[stats] threadmodel: {t['spawns']} spawn(s), {t['roots']} thread "
        f"root(s), {t['locks']} lock(s), {t['shared']} shared binding(s), "
        f"{t['obligations']} completion obligation(s) across {t['files']} "
        f"file(s)",
        file=err,
    )
    o = opsmodel.repo_summary(paths)
    print(
        f"[stats] opsmodel: {o['series']} metric series "
        f"({o['metric_sites']} creation / {o['observe_sites']} observe "
        f"site(s)), {o['kv_writes']} KV write(s) / {o['kv_reads']} read(s), "
        f"{o['exit_sites']} hard-exit site(s), {o['fault_sites']} fault-spec "
        f"site(s) across {o['files']} file(s)",
        file=err,
    )


def _parse_ids(raw: Optional[List[str]]) -> Optional[List[str]]:
    if not raw:
        return None
    out: List[str] = []
    for chunk in raw:
        out.extend(s for s in chunk.replace(",", " ").split() if s)
    return out


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m stoix_tpu.analysis", description=__doc__
    )
    parser.add_argument("paths", nargs="*", help="files/dirs relative to the repo root")
    parser.add_argument(
        "--select",
        action="append",
        default=None,
        metavar="IDS",
        help="run ONLY these rule ids (comma separated; repeatable)",
    )
    parser.add_argument(
        "--ignore",
        action="append",
        default=None,
        metavar="IDS",
        help="skip these rule ids (comma separated; repeatable)",
    )
    parser.add_argument("--format", choices=("text", "json", "github"), default="text")
    parser.add_argument(
        "--changed-only",
        action="store_true",
        help="scan only .py files git reports changed vs HEAD (tree-scoped "
        "rules are skipped; full scan when git is unavailable)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog and exit"
    )
    parser.add_argument(
        "--statistics",
        action="store_true",
        help="after the run, print per-rule finding/suppression counts and "
        "derived-model sizes (mesh axes, thread roots) to stderr for CI log "
        "triage — stdout stays the machine-readable findings contract",
    )
    parser.add_argument(
        "--skip-external",
        action="store_true",
        help="do not delegate to ruff/mypy even when importable",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in core.get_rules():
            scope = "tree" if rule.check_tree else "file"
            allow = ", ".join(sorted(rule.allowlist)) or "-"
            print(f"{rule.id:<8} {scope:<5} {rule.title:<36} allowlist: {allow}")
        return 0

    select = _parse_ids(args.select)
    ignore = _parse_ids(args.ignore)

    paths: Optional[List[str]] = args.paths or None
    with_tree_rules = True
    if args.changed_only:
        if args.paths:
            print("error: --changed-only and explicit paths are mutually "
                  "exclusive", file=sys.stderr)
            return EXIT_CODE_USAGE
        changed = core.changed_paths()
        if not changed:
            # git unavailable OR a clean checkout (the CI/prolog case, where
            # the bad change is already committed): a vacuous 0-file pass
            # would be a fake gate — run the full scan instead.
            why = "git unavailable" if changed is None else "clean work tree"
            print(f"[lint] --changed-only: {why}, running the full scan",
                  file=sys.stderr)
        else:
            if select:
                # An explicitly selected tree-scoped rule cannot run on a
                # partial file set; silently dropping it would make the run a
                # permanent green no-op — refuse, like the paths conflict.
                tree_ids = {r.id for r in core.get_rules() if r.check_tree}
                dropped = sorted(tree_ids.intersection(select))
                if dropped:
                    print(
                        "error: --changed-only skips tree-scoped rules, but "
                        f"{', '.join(dropped)} was explicitly selected — run "
                        "without --changed-only",
                        file=sys.stderr,
                    )
                    return EXIT_CODE_USAGE
            paths = changed
            with_tree_rules = False

    try:
        findings, n_files = core.run_paths(
            paths, select, ignore, with_tree_rules=with_tree_rules
        )
    except KeyError as exc:
        print(f"error: {exc.args[0]}", file=sys.stderr)
        return EXIT_CODE_USAGE

    if select is None:
        # The external delegations are part of the full gate only; a
        # per-rule run (--select) is always the native rules alone. mypy has
        # no meaningful per-file mode (whole-program inference), so a
        # genuinely narrowed run skips it rather than letting it silently
        # dominate the "fast" path by type-checking the entire package.
        narrowed = not with_tree_rules
        if not args.skip_external:
            findings = list(findings)
            findings.extend(run_external("ruff", ["check", *(paths or core.DEFAULT_PATHS)]))
            if not narrowed:
                findings.extend(run_external("mypy", ["stoix_tpu"]))

    errors, warnings = core.split_severity(findings)

    if args.statistics:
        rules = core._select_rules(select, ignore)
        print_statistics(findings, rules, paths)

    if args.format == "json":
        print(json.dumps([f.to_json() for f in findings], indent=None))
        return 1 if errors else 0

    if args.format == "github":
        for f in warnings + errors:
            print(render_github(f))
        print(f"[lint] {n_files} files, {len(errors)} errors, {len(warnings)} warnings")
        return 1 if errors else 0

    for w in warnings:
        print(f"warning: {w.render()}")
    for e in errors:
        if e.rule in ("ruff", "mypy"):
            print(f"error: {e.path} {e.message}")
        else:
            print(f"error: {e.render()}")
    print(f"[lint] {n_files} files, {len(errors)} errors, {len(warnings)} warnings")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
