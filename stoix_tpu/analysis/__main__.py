"""CLI for the static-analysis gate.

    python -m stoix_tpu.analysis [paths...]
        [--select STX005,STX007] [--ignore HYG]
        [--format text|json] [--list-rules] [--skip-external]

Text mode reproduces scripts/lint.py's historical output byte-for-byte
(warnings, errors, `[lint] N files, E errors, W warnings` summary); the shim
delegates here. JSON mode prints one object per finding
(rule/path/line/message/severity) as a single JSON array for CI consumption
(tests/test_analysis_clean.py). Exit code: 0 clean, 1 findings at error
severity, 2 usage error.

stdout is this tool's machine-readable contract (like sweep.py's JSON
lines), hence the STX002 allowlist entry for this file.
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
from typing import List, Optional

from stoix_tpu.analysis import core


def run_external(tool: str, args: List[str]) -> List[core.Finding]:
    """Delegate to ruff/mypy when importable (their config lives in
    pyproject.toml, so installing them upgrades the gate with zero changes)."""
    try:
        __import__(tool)
    except ImportError:
        return []
    proc = subprocess.run(
        [sys.executable, "-m", tool, *args], capture_output=True, text=True, cwd=core.REPO
    )
    if proc.returncode != 0:
        lines = [line for line in proc.stdout.splitlines() if line.strip()]
        lines += [line for line in proc.stderr.splitlines() if line.strip()]
        # A crash with no output must still fail the gate — a type check that
        # never ran is not a passing type check.
        lines = lines or [f"exited {proc.returncode} with no output"]
        return [Finding_external(tool, line) for line in lines]
    return []


def Finding_external(tool: str, line: str) -> core.Finding:
    return core.Finding(rule=tool, path=f"[{tool}]", line=0, message=line)


def _parse_ids(raw: Optional[List[str]]) -> Optional[List[str]]:
    if not raw:
        return None
    out: List[str] = []
    for chunk in raw:
        out.extend(s for s in chunk.replace(",", " ").split() if s)
    return out


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m stoix_tpu.analysis", description=__doc__
    )
    parser.add_argument("paths", nargs="*", help="files/dirs relative to the repo root")
    parser.add_argument(
        "--select",
        action="append",
        default=None,
        metavar="IDS",
        help="run ONLY these rule ids (comma separated; repeatable)",
    )
    parser.add_argument(
        "--ignore",
        action="append",
        default=None,
        metavar="IDS",
        help="skip these rule ids (comma separated; repeatable)",
    )
    parser.add_argument("--format", choices=("text", "json"), default="text")
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog and exit"
    )
    parser.add_argument(
        "--skip-external",
        action="store_true",
        help="do not delegate to ruff/mypy even when importable",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in core.get_rules():
            scope = "tree" if rule.check_tree else "file"
            allow = ", ".join(sorted(rule.allowlist)) or "-"
            print(f"{rule.id:<8} {scope:<5} {rule.title:<36} allowlist: {allow}")
        return 0

    select = _parse_ids(args.select)
    ignore = _parse_ids(args.ignore)
    try:
        findings, n_files = core.run_paths(args.paths or None, select, ignore)
    except KeyError as exc:
        print(f"error: {exc.args[0]}", file=sys.stderr)
        return 2

    if select is None:
        # The external delegations are part of the full gate only; a
        # per-rule run (--select) is always the native rules alone.
        if not args.skip_external:
            findings = list(findings)
            findings.extend(run_external("ruff", ["check", *(args.paths or core.DEFAULT_PATHS)]))
            findings.extend(run_external("mypy", ["stoix_tpu"]))

    errors, warnings = core.split_severity(findings)

    if args.format == "json":
        print(json.dumps([f.to_json() for f in findings], indent=None))
        return 1 if errors else 0

    for w in warnings:
        print(f"warning: {w.render()}")
    for e in errors:
        if e.rule in ("ruff", "mypy"):
            print(f"error: {e.path} {e.message}")
        else:
            print(f"error: {e.render()}")
    print(f"[lint] {n_files} files, {len(errors)} errors, {len(warnings)} warnings")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
