"""Ops-contract static model (ISSUE 20): metrics, fleet-KV keyspace,
hard-exit paths, and fault-spec vocabulary — the distributed-runtime
contract surface the STX019-STX023 rule family checks.

The repo's cross-process coordination fabric is held together by *names*:
~82 hand-named `stoix_tpu_*` metric series, fleet-KV key patterns
(`hb/<pid>`, `vote/<window>/<pid>`, `ometrics/<pid>`, `flags`) written in
one module and read in another, `EXIT_CODE_*` symbols that must each dump a
flight record before `os._exit`, and the fault-spec vocabulary
(`faultinject._KNOWN`) that tests/bench/soak arm by string. None of those
names are checked by the type system; all of them have drifted by hand at
least once. This module builds a per-module, `FileContext`-memoized model
of the four surfaces (the same architecture as `meshmodel`/`threadmodel`:
pure AST, no jax import, shared across rules via `ctx.memo`):

  * **Metric sites** — every `<registry>.counter/gauge/histogram(name, ...)`
    creation with its name normalized to a *pattern* (f-string holes and
    `%`-conversions become `{}`; module-level string constants are resolved,
    so `registry.counter(_EVENTS_METRIC, ...)` stays lintable), plus every
    `inc/set/dec/observe` call whose receiver *binds* to a creation site
    (same binding-key discipline as threadmodel: `self._m` is matched
    class-wide, a module name module-wide, a local within its function),
    carrying the label-key set used at that call.
  * **Fleet-KV keyspace** — every `put/try_get/get_blocking/barrier` whose
    key normalizes to a pattern, split into writer (`put`) and reader
    (`try_get`/`get_blocking`) sides. Generic transport wrappers whose key
    is a bare parameter normalize to ``None`` and are recorded but not
    contract-checked.
  * **Hard-exit sites** — `os._exit(...)`/`sys.exit(...)` calls carrying an
    `EXIT_CODE_*` symbol or int literal, with the enclosing function and a
    statically-preceding-call index so a rule can ask "is a flight-record
    dump reachable before this exit, in this function or its callees?".
  * **Fault-spec sites** — every spec string armed via
    `faultinject.configure(...)`, `STOIX_TPU_FAULT` env plumbing
    (setenv / dict literal / subscript assignment), or an
    `arch.fault_spec=<spec>` override literal, parsed into spec names.

Documented blind spots (docs/DESIGN.md §2.5): metric receivers are matched
by *method name* (`.counter(`/`.gauge(`/`.histogram(`) — a non-registry
object exposing those method names with a string first argument would be
modeled as a metric; KV `put` receivers are matched by a name hint
(store/backend/kv/fleet) so `queue.put(item)` is not misread as a KV write,
which means a KV store bound to an unrelated name is *missed*, not
misattributed; observe-site binding resolution is one assignment deep
(a metric handle passed across functions as an argument is not followed);
exit-code reachability follows module-local and self-method callees to a
fixed depth, not across modules; f-string holes match greedily, so two
patterns differing only inside holes unify.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from stoix_tpu.analysis.threadmodel import dotted

_METRIC_CTOR_ATTRS = ("counter", "gauge", "histogram")
_OBSERVE_ATTRS = ("inc", "dec", "set", "observe")
# Methods whose *names* are distinctive enough to attribute to the fleet-KV
# protocol on any receiver; `put` additionally needs a receiver name hint
# (queue.Queue.put(item) carries payloads, not keys).
_KV_READ_ATTRS = ("try_get", "get_blocking")
_KV_WRITE_ATTRS = ("put",)
_KV_RECEIVER_HINTS = ("store", "backend", "kv", "fleet")

_PCT_CONVERSION = re.compile(r"%[-#0-9 +.]*[a-zA-Z]")
_SPEC_NAME = re.compile(r"^[a-z_][a-z0-9_]*$")
_SPEC_ITEM = re.compile(r"^([a-z_{][a-z0-9_{}]*)(?::.+)?$")
_FAULT_ENV_VAR = "STOIX_TPU_FAULT"
_FAULT_OVERRIDE = re.compile(r"fault_spec=([^\s'\"]*)")


# ---------------------------------------------------------------------------
# Sites


@dataclass
class MetricSite:
    """One `registry.counter/gauge/histogram(name, ...)` creation."""

    pattern: Optional[str]  # normalized name; None = not normalizable
    kind: str  # "counter" | "gauge" | "histogram"
    lineno: int


@dataclass
class ObserveSite:
    """One `inc/set/dec/observe` call resolved to a metric series."""

    pattern: Optional[str]
    kind: str
    method: str
    label_keys: Optional[Tuple[str, ...]]  # sorted; None = dynamic/unknown
    lineno: int


@dataclass
class KVSite:
    """One fleet-KV protocol call."""

    op: str  # "put" | "try_get" | "get_blocking" | "barrier"
    side: str  # "write" | "read" | "barrier"
    pattern: Optional[str]  # normalized key; None = generic wrapper
    lineno: int


@dataclass
class ExitSite:
    """One os._exit / sys.exit call site."""

    via: str  # "os._exit" | "sys.exit"
    code_name: Optional[str]  # EXIT_CODE_* symbol at the call, if any
    code_value: Optional[int]  # int literal at the call, if any
    lineno: int
    fn_id: Optional[int]  # id() of the enclosing function node


@dataclass
class FaultSpecSite:
    """One armed fault-spec string (configure / env / override literal)."""

    names: Tuple[str, ...]  # parsed spec names ("" entries dropped)
    raw: str
    lineno: int
    complete: bool  # False when part of the spec was dynamic


# ---------------------------------------------------------------------------
# Pattern helpers (shared with the rules and their tests)


def normalize_name(
    node: ast.AST, constants: Optional[Dict[str, str]] = None
) -> Optional[str]:
    """Normalize a name expression to a pattern: literal parts verbatim,
    dynamic holes as `{}`, module-level string constants resolved. Returns
    None when no literal skeleton survives (a bare unresolved name, a call,
    arbitrary arithmetic)."""
    constants = constants or {}
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        return constants.get(node.id)
    if isinstance(node, ast.JoinedStr):
        parts: List[str] = []
        for value in node.values:
            if isinstance(value, ast.Constant) and isinstance(value.value, str):
                parts.append(value.value)
            elif isinstance(value, ast.FormattedValue):
                inner = normalize_name(value.value, constants)
                parts.append(inner if inner is not None else "{}")
            else:
                return None
        return "".join(parts)
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mod):
        left = normalize_name(node.left, constants)
        if left is None:
            return None
        return _PCT_CONVERSION.sub("{}", left)
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        left = normalize_name(node.left, constants)
        right = normalize_name(node.right, constants)
        if left is None or right is None:
            return None
        return left + right
    return None


def _pattern_regex(pattern: str) -> "re.Pattern[str]":
    parts = [re.escape(piece) for piece in pattern.split("{}")]
    return re.compile("(?s:" + ".+".join(parts) + ")\\Z")


def patterns_match(a: str, b: str) -> bool:
    """Whether two normalized key patterns can name the same KV entry:
    `hb/{}` matches `hb/{}` and the literal `hb/3`; `flags` only matches
    `flags`. Holes match greedily in either direction (documented blind
    spot: patterns differing only inside holes unify)."""
    if a == b:
        return True
    return bool(
        _pattern_regex(a).match(b.replace("{}", "\x00"))
        or _pattern_regex(b).match(a.replace("{}", "\x00"))
    )


def parse_fault_spec(raw: str) -> Tuple[Tuple[str, ...], bool]:
    """Parse a fault-spec string into (names, complete). The null form `~`
    and the empty string carry no names; a `{}` hole from normalization
    marks the site incomplete (dynamic name part) without inventing names."""
    raw = raw.strip()
    if raw in ("", "~"):
        return (), True
    names: List[str] = []
    complete = True
    for item in raw.split(","):
        item = item.strip()
        if not item:
            continue
        match = _SPEC_ITEM.match(item)
        if match is None:
            complete = False
            continue
        name = match.group(1)
        if _SPEC_NAME.match(name):
            names.append(name)
        else:
            complete = False  # a `{}` hole or malformed name part
    return tuple(names), complete


def module_string_constants(tree: ast.AST) -> Dict[str, str]:
    """Module-level `NAME = "literal"` bindings (incl. annotated), the
    resolution table for constant-named metrics/keys/specs."""
    constants: Dict[str, str] = {}
    for node in getattr(tree, "body", []):
        target: Optional[ast.AST] = None
        value: Optional[ast.AST] = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target, value = node.targets[0], node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            target, value = node.target, node.value
        if (
            isinstance(target, ast.Name)
            and isinstance(value, ast.Constant)
            and isinstance(value.value, str)
        ):
            constants[target.id] = value.value
    return constants


def module_int_constants(tree: ast.AST) -> Dict[str, int]:
    """Module-level `NAME = <int>` bindings (the EXIT_CODE_* fallback for
    fixtures that define their own codes)."""
    constants: Dict[str, int] = {}
    for node in getattr(tree, "body", []):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target, value = node.targets[0], node.value
            if (
                isinstance(target, ast.Name)
                and isinstance(value, ast.Constant)
                and isinstance(value.value, int)
                and not isinstance(value.value, bool)
            ):
                constants[target.id] = value.value
    return constants


def known_fault_specs(tree: ast.AST) -> Tuple[str, ...]:
    """The `_KNOWN = (...)` vocabulary tuple, if this module defines one."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
            if isinstance(target, ast.Name) and target.id == "_KNOWN":
                value = node.value
                if isinstance(value, (ast.Tuple, ast.List)):
                    return tuple(
                        elt.value
                        for elt in value.elts
                        if isinstance(elt, ast.Constant)
                        and isinstance(elt.value, str)
                    )
    return ()


# ---------------------------------------------------------------------------
# The per-module model


class ModuleOpsModel:
    """All four ops-contract surfaces of one parsed module."""

    def __init__(self, tree: ast.AST) -> None:
        self.tree = tree
        self._parents: Dict[int, ast.AST] = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                self._parents[id(child)] = parent
        self.constants = module_string_constants(tree)
        self.int_constants = module_int_constants(tree)
        self.known_specs = known_fault_specs(tree)

        # Function index: (class or None, name) -> fn nodes; id(fn) -> fn.
        self._functions: Dict[Tuple[Optional[str], str], List[ast.AST]] = {}
        self._fn_by_id: Dict[int, ast.AST] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._functions.setdefault(
                    (self._nearest_class(node), node.name), []
                ).append(node)
                self._fn_by_id[id(node)] = node

        self.metric_sites: List[MetricSite] = []
        self.observe_sites: List[ObserveSite] = []
        self.kv_sites: List[KVSite] = []
        self.exit_sites: List[ExitSite] = []
        self.fault_sites: List[FaultSpecSite] = []

        self._bindings: Dict[str, Tuple[Optional[str], str]] = {}
        self._collect_metric_sites()
        self._collect_observe_sites()
        self._collect_kv_sites()
        self._collect_exit_sites()
        self._collect_fault_sites()

    # -- structure helpers ----------------------------------------------------
    def _nearest_class(self, node: ast.AST) -> Optional[str]:
        current = self._parents.get(id(node))
        while current is not None:
            if isinstance(current, ast.ClassDef):
                return current.name
            if isinstance(current, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # A class nested inside a function shadows; a method's
                # nearest class is found before its enclosing function.
                pass
            current = self._parents.get(id(current))
        return None

    def enclosing_fn(self, node: ast.AST) -> Optional[ast.AST]:
        current = self._parents.get(id(node))
        while current is not None:
            if isinstance(current, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return current
            current = self._parents.get(id(current))
        return None

    def _binding_key(self, expr: ast.AST, fn: Optional[ast.AST]) -> Optional[str]:
        chain = dotted(expr)
        if len(chain) == 2 and chain[0] == "self":
            cls = self._nearest_class(expr)
            if cls is None:
                return None
            return f"attr:{cls}.{chain[1]}"
        if len(chain) == 1:
            if fn is None:
                return f"global:{chain[0]}"
            return f"local:{id(fn)}:{chain[0]}"
        return None

    def _lookup_binding(
        self, expr: ast.AST, fn: Optional[ast.AST]
    ) -> Optional[Tuple[Optional[str], str]]:
        key = self._binding_key(expr, fn)
        if key is not None and key in self._bindings:
            return self._bindings[key]
        # A plain local that was never assigned locally may be a module name.
        chain = dotted(expr)
        if len(chain) == 1:
            return self._bindings.get(f"global:{chain[0]}")
        return None

    # -- metric sites ----------------------------------------------------------
    def _metric_ctor(self, node: ast.AST) -> Optional[Tuple[Optional[str], str]]:
        """(pattern, kind) when `node` is a metric-creation call."""
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _METRIC_CTOR_ATTRS
        ):
            return None
        if not node.args:
            return None
        name_arg = node.args[0]
        # Only string-shaped first arguments read as metric names; this is
        # what keeps `collections.Counter(...)`-style homonyms out (those
        # are capitalized anyway) and skips e.g. `mock.counter(5)`.
        if not isinstance(
            name_arg, (ast.Constant, ast.JoinedStr, ast.Name, ast.BinOp)
        ):
            return None
        if isinstance(name_arg, ast.Constant) and not isinstance(
            name_arg.value, str
        ):
            return None
        return normalize_name(name_arg, self.constants), node.func.attr

    def _collect_metric_sites(self) -> None:
        for node in ast.walk(self.tree):
            ctor = self._metric_ctor(node)
            if ctor is None:
                continue
            pattern, kind = ctor
            self.metric_sites.append(MetricSite(pattern, kind, node.lineno))
            parent = self._parents.get(id(node))
            if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
                key = self._binding_key(
                    parent.targets[0], self.enclosing_fn(parent)
                )
                if key is not None:
                    self._bindings[key] = (pattern, kind)

    @staticmethod
    def _label_keys(
        call: ast.Call, method: str
    ) -> Optional[Tuple[str, ...]]:
        """The label-key set at one observe call: () when no labels are
        passed, sorted keys for a dict literal, None (unknown) otherwise.
        Signatures: inc(amount, labels) / dec(amount, labels) /
        set(value, labels) / observe(value, labels)."""
        labels: Optional[ast.AST] = None
        for kw in call.keywords:
            if kw.arg == "labels":
                labels = kw.value
        if labels is None and len(call.args) >= 2:
            labels = call.args[1]
        if labels is None or (
            isinstance(labels, ast.Constant) and labels.value is None
        ):
            return ()
        if isinstance(labels, ast.Dict) and all(
            isinstance(k, ast.Constant) and isinstance(k.value, str)
            for k in labels.keys
        ):
            return tuple(sorted(k.value for k in labels.keys))
        return None

    def _collect_observe_sites(self) -> None:
        for node in ast.walk(self.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _OBSERVE_ATTRS
            ):
                continue
            receiver = node.func.value
            resolved = self._metric_ctor(receiver)  # chained: ctor().inc()
            if resolved is None:
                resolved = self._lookup_binding(
                    receiver, self.enclosing_fn(node)
                )
            if resolved is None:
                continue  # .set()/.inc() on a non-metric (Event, counters…)
            pattern, kind = resolved
            self.observe_sites.append(
                ObserveSite(
                    pattern,
                    kind,
                    node.func.attr,
                    self._label_keys(node, node.func.attr),
                    node.lineno,
                )
            )

    # -- fleet-KV sites --------------------------------------------------------
    def _collect_kv_sites(self) -> None:
        for node in ast.walk(self.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.args
            ):
                continue
            attr = node.func.attr
            if attr in _KV_READ_ATTRS:
                side = "read"
            elif attr == "barrier":
                side = "barrier"
            elif attr in _KV_WRITE_ATTRS:
                chain = dotted(node.func.value)
                hint = (chain[-1] if chain else "").lower()
                if not any(h in hint for h in _KV_RECEIVER_HINTS):
                    continue  # queue.put(item) and friends
                side = "write"
            else:
                continue
            key_arg = node.args[0]
            pattern = normalize_name(key_arg, self.constants)
            if pattern is None and not isinstance(
                key_arg, (ast.Name, ast.Attribute)
            ):
                # A non-name, non-normalizable key (a call, arithmetic):
                # still a protocol site, still opaque.
                pattern = None
            self.kv_sites.append(KVSite(attr, side, pattern, node.lineno))

    # -- hard-exit sites -------------------------------------------------------
    def _collect_exit_sites(self) -> None:
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = dotted(node.func)
            if chain == ["os", "_exit"]:
                via = "os._exit"
            elif chain == ["sys", "exit"]:
                via = "sys.exit"
            else:
                continue
            code_name: Optional[str] = None
            code_value: Optional[int] = None
            if node.args:
                arg = node.args[0]
                arg_chain = dotted(arg)
                if arg_chain and arg_chain[-1].startswith("EXIT_CODE_"):
                    code_name = arg_chain[-1]
                    code_value = self.int_constants.get(code_name)
                elif isinstance(arg, ast.Constant) and isinstance(
                    arg.value, int
                ):
                    code_value = arg.value
            fn = self.enclosing_fn(node)
            self.exit_sites.append(
                ExitSite(via, code_name, code_value, node.lineno, id(fn) if fn else None)
            )

    # -- exit reachability -----------------------------------------------------
    def _calls_in(
        self, fn: ast.AST, before_line: Optional[int] = None
    ) -> List[ast.Call]:
        calls: List[ast.Call] = []
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and (
                before_line is None or node.lineno < before_line
            ):
                calls.append(node)
        return calls

    def flight_dump_reachable(self, site: ExitSite, depth: int = 3) -> bool:
        """Whether a flight-record dump (`dump_flight_record` by any dotted
        path) is statically reachable before this exit: among the calls
        preceding it in the enclosing function, or inside a module-local /
        self-method callee of one of those calls (to `depth` levels)."""
        fn = self._fn_by_id.get(site.fn_id) if site.fn_id else None
        if fn is None:
            return False
        return self._dump_in_calls(
            self._calls_in(fn, before_line=site.lineno + 1),
            self._nearest_class(fn),
            depth,
            seen=set(),
        )

    def _dump_in_calls(
        self,
        calls: Iterable[ast.Call],
        cls: Optional[str],
        depth: int,
        seen: Set[int],
    ) -> bool:
        callees: List[Tuple[Optional[str], str]] = []
        for call in calls:
            chain = dotted(call.func)
            if not chain:
                continue
            if chain[-1] == "dump_flight_record":
                return True
            if len(chain) == 2 and chain[0] == "self":
                callees.append((cls, chain[1]))
            elif len(chain) == 1:
                callees.append((None, chain[0]))
        if depth <= 0:
            return False
        for callee_cls, name in callees:
            for fn in self._functions.get((callee_cls, name), []):
                if id(fn) in seen:
                    continue
                seen.add(id(fn))
                if self._dump_in_calls(
                    self._calls_in(fn),
                    self._nearest_class(fn),
                    depth - 1,
                    seen,
                ):
                    return True
        return False

    def fn_references(self, fn_name: str) -> Set[str]:
        """All `EXIT_CODE_*`-shaped names referenced anywhere inside the
        module's function(s) named `fn_name` (the run_supervised coverage
        probe)."""
        names: Set[str] = set()
        for (cls, name), fns in self._functions.items():
            if name != fn_name:
                continue
            for fn in fns:
                for node in ast.walk(fn):
                    chain = dotted(node) if isinstance(node, (ast.Name, ast.Attribute)) else []
                    if chain and chain[-1].startswith("EXIT_CODE_"):
                        names.add(chain[-1])
        return names

    # -- fault-spec sites ------------------------------------------------------
    def _record_spec(self, node: ast.AST, lineno: int) -> None:
        pattern = normalize_name(node, self.constants)
        if pattern is None:
            self.fault_sites.append(FaultSpecSite((), "<dynamic>", lineno, False))
            return
        names, complete = parse_fault_spec(pattern)
        self.fault_sites.append(FaultSpecSite(names, pattern, lineno, complete))

    def _collect_fault_sites(self) -> None:
        for node in ast.walk(self.tree):
            # faultinject.configure("<spec>") — the bare-name collision with
            # observability.configure(config) is filtered by requiring a
            # spec-shaped (string-normalizable) first argument.
            if isinstance(node, ast.Call):
                chain = dotted(node.func)
                if (
                    chain
                    and chain[-1] == "configure"
                    and node.args
                    and normalize_name(node.args[0], self.constants) is not None
                ):
                    if len(chain) == 1 or "faultinject" in chain[:-1] or (
                        len(chain) == 2 and chain[0] not in ("observability",)
                    ):
                        self._record_spec(node.args[0], node.lineno)
                        continue
                # monkeypatch.setenv("STOIX_TPU_FAULT", spec) / os.environ
                # setdefault-style plumbing.
                if (
                    chain
                    and chain[-1] in ("setenv", "setdefault")
                    and len(node.args) >= 2
                    and isinstance(node.args[0], ast.Constant)
                    and node.args[0].value == _FAULT_ENV_VAR
                ):
                    self._record_spec(node.args[1], node.lineno)
                    continue
            # {"STOIX_TPU_FAULT": spec} dict literals (env blocks).
            if isinstance(node, ast.Dict):
                for key, value in zip(node.keys, node.values):
                    if (
                        isinstance(key, ast.Constant)
                        and key.value == _FAULT_ENV_VAR
                        and value is not None
                    ):
                        self._record_spec(value, value.lineno)
            # env["STOIX_TPU_FAULT"] = spec subscript assignment.
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target = node.targets[0]
                if (
                    isinstance(target, ast.Subscript)
                    and isinstance(target.slice, ast.Constant)
                    and target.slice.value == _FAULT_ENV_VAR
                ):
                    self._record_spec(node.value, node.lineno)
            # "arch.fault_spec=<spec>" override literals (launcher/bench/
            # soak job argv), including the spec-armed f-string form.
            spec_from_override = None
            if isinstance(node, (ast.Constant, ast.JoinedStr, ast.BinOp)):
                parent = self._parents.get(id(node))
                if isinstance(parent, (ast.Constant, ast.JoinedStr, ast.FormattedValue)):
                    continue  # inner parts are handled via their container
                normalized = normalize_name(node, self.constants)
                if normalized is not None and "fault_spec=" in normalized:
                    match = _FAULT_OVERRIDE.search(normalized)
                    if match:
                        spec_from_override = match.group(1)
            if spec_from_override is not None:
                names, complete = parse_fault_spec(spec_from_override)
                self.fault_sites.append(
                    FaultSpecSite(names, spec_from_override, node.lineno, complete)
                )

    # -- aggregation -----------------------------------------------------------
    def summary(self) -> Dict[str, int]:
        series = {s.pattern for s in self.metric_sites if s.pattern}
        return {
            "metric_sites": len(self.metric_sites),
            "series": len(series),
            "observe_sites": len(self.observe_sites),
            "kv_writes": sum(1 for s in self.kv_sites if s.side == "write"),
            "kv_reads": sum(1 for s in self.kv_sites if s.side == "read"),
            "exit_sites": len(self.exit_sites),
            "fault_sites": len(self.fault_sites),
        }


def for_context(ctx) -> ModuleOpsModel:
    """The memoized per-file model (`FileContext.memo`), shared by every
    STX019-023 check touching the same file."""
    return ctx.memo("opsmodel", lambda: ModuleOpsModel(ctx.tree))


def repo_summary(
    paths: Optional[Sequence[str]] = None, repo: Optional[str] = None
) -> Dict[str, int]:
    """Aggregate model sizes over a path set (launcher --preflight-only's
    ops-contracts row and the CLI's --statistics block): how many metric
    series, KV patterns, exit sites, and fault-spec sites the model actually
    sees — a silently-empty model (a refactor that renamed the idioms out
    from under the AST patterns) becomes visible instead of green."""
    from stoix_tpu.analysis import core as _core

    repo = repo or _core.REPO
    totals = {
        "files": 0,
        "metric_sites": 0,
        "series": 0,
        "observe_sites": 0,
        "kv_writes": 0,
        "kv_reads": 0,
        "exit_sites": 0,
        "fault_sites": 0,
    }
    series: Set[str] = set()
    for path in _core.iter_py_files(paths or ["stoix_tpu"], repo):
        try:
            with open(path) as f:
                tree = ast.parse(f.read())
        except (OSError, SyntaxError):
            continue
        totals["files"] += 1
        model = ModuleOpsModel(tree)
        for key, value in model.summary().items():
            if key != "series":
                totals[key] += value
        series |= {s.pattern for s in model.metric_sites if s.pattern}
    totals["series"] = len(series)
    return totals
