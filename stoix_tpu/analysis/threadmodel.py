"""Repo-wide static model of the host concurrency layer.

The Podracer half of the design is host threads moving trajectories, params,
and verdicts between devices: fleet heartbeat publishers/monitors, compile
watchdog timers, actor supervisors, the serve worker/batcher/hot-swap
threads. Each of those rides hand-enforced invariants ("atomic
single-reference param swap", "close() drains pending with a typed error so
no caller hangs", "stop() disarms the hard-exit timer"). This module gives
the STX014-STX017 rules one shared model of that layer, sibling to
`jitreach`/`meshmodel`/`configmodel` and memoized per `FileContext` the same
way:

  * **Spawn sites** — `threading.Thread(target=...)`, `threading.Timer(dt,
    fn)`, `ThreadPoolExecutor(...)`/`.submit(fn)` constructions, with their
    binding (local name, `self._attr`, module global, or anonymous), their
    statically-known daemon flag, whether the object escapes the module's
    sight (returned / passed onward / stored in a container), and every
    `.start()`/`.join()`/`.cancel()`/`.shutdown()` the binding receives.
  * **Thread roots** — the set of functions reachable from each spawn
    target, via the same module-local closure `jitreach` uses (Name loads,
    `self.method` attribute loads resolved within the enclosing class,
    `self._fn = wrapped(inner)` attribute aliases). The `<main>` root covers
    module-level code plus every function that is not exclusively
    thread-reachable: public/dunder names are assumed main-callable (module-
    local analysis cannot see external callers), underscore helpers
    referenced only from thread entries are thread-only.
  * **Lock ranges** — lock/condition/semaphore bindings
    (`threading.Lock()`-family constructors) and the statement line ranges
    over which each is held per function: `with lock:` bodies, plus lexical
    `acquire()`/`release()` pairs.
  * **Shared accesses** — reads, atomic single-reference writes, and
    MUTATING writes (`+=`, `self.x[k] = v`, `self.x.append(...)`,
    read-modify-write assigns) of self-attributes and module globals, each
    annotated with the locks held at that line. Attributes bound to
    internally-synchronized primitives (Event, Queue, the lock family) are
    exempt — the primitive IS the synchronization.
  * **Completion obligations** — values received from a queue-like handoff
    (`.get()`, `.next_batch()`) on which the receiving code later calls
    `set_result`/`set_error`/`set_exception` (directly, on iterated
    elements, or by passing them to a same-module helper that does): the
    futures a thread must resolve on EVERY path, exception paths included,
    or some caller blocks until its timeout.

Known blind spots (docs/DESIGN.md §2.5): cross-module flow (a lock or
future passed to another module's code is invisible, exactly jitreach's
boundary — the server/batcher split relies on the batcher's own internal
locking, which the batcher's module models), dynamic dispatch
(`getattr(self, name)()`), threads joined through containers or loop
variables (`for t in self._threads: t.join()` does not match a specific
binding), and happens-before established by `start()` ordering rather than
locks. Pure stdlib `ast`; no imports executed.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from stoix_tpu.analysis.jitreach import (
    _ModuleIndex,
    callee_name as _callee_name,
    walk_scope,
)

MAIN_ROOT = "<main>"

# threading constructors that spawn host work.
_THREAD_CTORS = {"Thread"}
_TIMER_CTORS = {"Timer"}
_EXECUTOR_CTORS = {"ThreadPoolExecutor", "ProcessPoolExecutor"}

# Lock-family constructors: `with X:` over one of these bindings is a held
# range. Condition IS a lock (its `with` acquires the underlying lock).
_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore", "Barrier"}
# Internally-synchronized primitives: attributes/globals bound to these are
# exempt from the shared-mutation model entirely (calling `.clear()` on an
# Event or `.put()` on a Queue is the sanctioned cross-thread idiom, not a
# torn write). Thread/Timer/executor bindings are exempt for the same
# reason — their methods are internally locked and `t.daemon = True` is the
# construction idiom; their cross-thread hazards are LIFECYCLE hazards,
# which STX017 owns.
_SAFE_CTORS = (
    _LOCK_CTORS
    | _THREAD_CTORS
    | _TIMER_CTORS
    | _EXECUTOR_CTORS
    | {
        "Event",
        "Queue",
        "LifoQueue",
        "PriorityQueue",
        "SimpleQueue",
    }
)

# Method names that mutate their receiver in place. `set`/`inc`/`observe`
# are deliberately absent: Event.set and the metrics objects are internally
# synchronized, and flagging them would bury the real races.
_MUTATORS = {
    "append",
    "appendleft",
    "extend",
    "extendleft",
    "insert",
    "add",
    "remove",
    "discard",
    "pop",
    "popleft",
    "popitem",
    "update",
    "clear",
    "setdefault",
    "sort",
    "reverse",
}

# Handoff receivers whose result may carry a completion obligation.
_RECEIPT_ATTRS = {"get", "get_nowait", "next_batch"}
_COMPLETE_RESULT = {"set_result"}
_COMPLETE_ERROR = {"set_error", "set_exception"}
_COMPLETE_ANY = _COMPLETE_RESULT | _COMPLETE_ERROR


def dotted(node: ast.AST) -> List[str]:
    """['self', '_cond'] for `self._cond`; [] when not a pure dotted chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return []


@dataclass
class SpawnSite:
    """One Thread/Timer/Executor construction."""

    kind: str  # "thread" | "timer" | "executor"
    lineno: int
    binding: Optional[str]  # canonical binding key; None = anonymous
    targets: Tuple[str, ...]  # entry callable simple names ("" for lambdas)
    entry_nodes: Tuple[ast.AST, ...] = ()
    daemon: bool = False
    escapes: bool = False  # returned/passed onward/stored beyond our sight
    started_inline: bool = False  # `threading.Thread(...).start()`


@dataclass
class LockRange:
    lock: str  # canonical lock key
    start: int
    end: int


@dataclass
class SharedAccess:
    key: str  # "attr:Class.name" | "global:name"
    lineno: int
    kind: str  # "read" | "write" (atomic single-reference) | "mutate"
    fn: Optional[ast.AST]  # innermost enclosing function; None = module level
    locks: FrozenSet[str] = frozenset()
    in_init: bool = False


@dataclass
class Obligation:
    """A receipt site whose value provably carries completion duties."""

    fn: ast.AST
    name: str  # the received binding
    lineno: int
    iterated: bool  # completions apply to elements (`for r in batch`)
    receipt: ast.Assign = None  # type: ignore[assignment]


@dataclass
class _BindingEvents:
    assigns: List[Tuple[int, int]] = field(default_factory=list)  # (line, fn id)
    starts: List[Tuple[int, int]] = field(default_factory=list)
    joins: List[int] = field(default_factory=list)
    cancels: List[int] = field(default_factory=list)
    shutdowns: List[int] = field(default_factory=list)
    ctx_managed: bool = False  # `with <binding>:` (executor auto-shutdown)


class ModuleThreadModel:
    """The per-module thread/lock/obligation model (build once per file via
    `for_context`; `ctx.memo` shares it across the STX014-017 rules)."""

    def __init__(self, tree: ast.AST) -> None:
        self.tree = tree
        self.index = _ModuleIndex(tree)
        self._parents: Dict[int, ast.AST] = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                self._parents[id(child)] = parent

        self._functions: List[ast.AST] = [
            n
            for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        self._fn_class: Dict[int, Optional[str]] = {}
        self._class_methods: Dict[str, Dict[str, List[ast.AST]]] = {}
        for fn in self._functions:
            cls = self._nearest_class(fn)
            self._fn_class[id(fn)] = cls
            parent = self._parents.get(id(fn))
            if isinstance(parent, ast.ClassDef):
                self._class_methods.setdefault(parent.name, {}).setdefault(
                    fn.name, []
                ).append(fn)

        # self._fn = jit(inner) style attribute aliases, per class.
        self._attr_aliases: Dict[Tuple[str, str], Set[str]] = {}
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
                continue
            target = node.targets[0]
            chain = dotted(target)
            if len(chain) == 2 and chain[0] == "self":
                cls = self._nearest_class(node)
                if cls is None:
                    continue
                wrapped = self.index._function_names_in(node.value)
                if wrapped:
                    self._attr_aliases.setdefault((cls, chain[1]), set()).update(wrapped)

        self._module_globals: Set[str] = set()
        self._safe_global: Set[str] = set()
        for stmt in getattr(tree, "body", []):
            if isinstance(stmt, ast.Assign):
                for target in stmt.targets:
                    if isinstance(target, ast.Name):
                        self._module_globals.add(target.id)
                        if self._is_safe_ctor(stmt.value):
                            self._safe_global.add(target.id)
            elif isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name):
                self._module_globals.add(stmt.target.id)
                if stmt.value is not None and self._is_safe_ctor(stmt.value):
                    self._safe_global.add(stmt.target.id)

        self.lock_keys: Set[str] = set()
        self._safe_attr_keys: Set[str] = set()
        self._collect_lock_and_safe_bindings()

        self.spawns: List[SpawnSite] = []
        self.bindings: Dict[str, _BindingEvents] = {}
        self._spawn_target_node_ids: Set[int] = set()
        self._collect_spawns()

        self.roots: Dict[str, Set[ast.AST]] = {}
        self._fn_roots: Dict[int, Set[str]] = {}
        self._compute_roots()

        self._ranges: Dict[int, List[LockRange]] = {}
        self._compute_lock_ranges()

        self.accesses: Dict[str, List[SharedAccess]] = {}
        self._collect_shared_accesses()

        self._completions_cache: Dict[int, Dict[str, Set[str]]] = {}
        self.obligations: List[Obligation] = []
        self._collect_obligations()

    # -- structure helpers ----------------------------------------------------
    def _nearest_class(self, node: ast.AST) -> Optional[str]:
        current = self._parents.get(id(node))
        while current is not None:
            if isinstance(current, ast.ClassDef):
                return current.name
            current = self._parents.get(id(current))
        return None

    def enclosing_fn(self, node: ast.AST) -> Optional[ast.AST]:
        current = self._parents.get(id(node))
        while current is not None:
            if isinstance(current, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return current
            current = self._parents.get(id(current))
        return None

    def class_of(self, fn: ast.AST) -> Optional[str]:
        return self._fn_class.get(id(fn))

    def resolve_method(self, cls: Optional[str], name: str) -> List[ast.AST]:
        out: List[ast.AST] = []
        if cls is not None:
            out.extend(self._class_methods.get(cls, {}).get(name, []))
            for alias in self._attr_aliases.get((cls, name), set()):
                out.extend(self.index.functions.get(alias, []))
        return out

    def _fn_assigned_names(self, fn: ast.AST) -> Set[str]:
        names: Set[str] = set()
        args = fn.args
        for p in (
            list(getattr(args, "posonlyargs", []))
            + list(args.args)
            + list(args.kwonlyargs)
            + ([args.vararg] if args.vararg else [])
            + ([args.kwarg] if args.kwarg else [])
        ):
            names.add(p.arg)
        for node in walk_scope(fn):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
                names.add(node.id)
        return names

    def binding_key(self, expr: ast.AST, fn: Optional[ast.AST]) -> Optional[str]:
        """Canonical key for a lock/thread/shared binding expression:
        `self._x` -> "attr:Class._x" (matched class-wide), a module-assigned
        name -> "global:x" (matched module-wide), a plain local ->
        "local:<fn>:x" (matched within the function)."""
        chain = dotted(expr)
        if len(chain) == 2 and chain[0] == "self":
            cls = self._nearest_class(expr) or (
                self.class_of(fn) if fn is not None else None
            )
            if cls is None:
                return None
            return f"attr:{cls}.{chain[1]}"
        if len(chain) == 1:
            name = chain[0]
            if fn is None:
                return f"global:{name}"
            if name in self._fn_assigned_names(fn):
                return f"local:{id(fn)}:{name}"
            if name in self._module_globals:
                return f"global:{name}"
            return f"local:{id(fn)}:{name}"
        return None

    # -- lock + safe-primitive bindings ---------------------------------------
    def _is_ctor(self, value: ast.AST, names: Set[str]) -> bool:
        return isinstance(value, ast.Call) and _callee_name(value.func) in names

    def _is_safe_ctor(self, value: ast.AST) -> bool:
        return self._is_ctor(value, _SAFE_CTORS)

    def _collect_lock_and_safe_bindings(self) -> None:
        for node in ast.walk(self.tree):
            targets: List[ast.AST] = []
            value: Optional[ast.AST] = None
            if isinstance(node, ast.Assign):
                targets, value = list(node.targets), node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            if value is None:
                continue
            for target in targets:
                key = self.binding_key(target, self.enclosing_fn(node))
                if key is None:
                    continue
                if self._is_ctor(value, _LOCK_CTORS):
                    self.lock_keys.add(key)
                if self._is_safe_ctor(value):
                    self._safe_attr_keys.add(key)

    # -- spawn sites -----------------------------------------------------------
    def _spawn_kind(self, call: ast.Call) -> Optional[str]:
        name = _callee_name(call.func)
        if name in _THREAD_CTORS:
            return "thread"
        if name in _TIMER_CTORS:
            return "timer"
        if name in _EXECUTOR_CTORS:
            return "executor"
        return None

    def _target_exprs(self, call: ast.Call, kind: str) -> List[ast.AST]:
        out: List[ast.AST] = []
        for kw in call.keywords:
            if kw.arg in ("target", "function"):
                out.append(kw.value)
        if not out and kind == "timer" and len(call.args) >= 2:
            out.append(call.args[1])
        if not out and kind == "thread" and len(call.args) >= 2:
            out.append(call.args[1])  # Thread(group, target, ...)
        return out

    def _resolve_entries(
        self, exprs: Sequence[ast.AST], site: ast.AST
    ) -> Tuple[Tuple[str, ...], Tuple[ast.AST, ...]]:
        names: List[str] = []
        nodes: List[ast.AST] = []
        cls = self._nearest_class(site)
        for expr in exprs:
            self._spawn_target_node_ids.add(id(expr))
            if isinstance(expr, ast.Lambda):
                names.append("<lambda>")
                nodes.append(expr)
                continue
            chain = dotted(expr)
            if len(chain) == 2 and chain[0] == "self":
                names.append(chain[1])
                nodes.extend(self.resolve_method(cls, chain[1]))
            elif len(chain) == 1:
                names.append(chain[0])
                nodes.extend(self.index.resolve(chain[0]))
        return tuple(names), tuple(nodes)

    def _collect_spawns(self) -> None:
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            kind = self._spawn_kind(node)
            if kind is None:
                continue
            targets, entry_nodes = self._resolve_entries(
                self._target_exprs(node, kind), node
            )
            daemon = any(
                kw.arg == "daemon"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value is True
                for kw in node.keywords
            )
            binding: Optional[str] = None
            escapes = False
            started_inline = False
            parent = self._parents.get(id(node))
            fn = self.enclosing_fn(node)
            if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
                binding = self.binding_key(parent.targets[0], fn)
                if binding is None:
                    escapes = True  # stored somewhere we cannot track
            elif isinstance(parent, ast.Attribute) and parent.attr == "start":
                started_inline = True
            elif isinstance(parent, ast.withitem):
                binding = (
                    self.binding_key(parent.optional_vars, fn)
                    if parent.optional_vars is not None
                    else None
                )
                if binding is not None:
                    self.bindings.setdefault(binding, _BindingEvents()).ctx_managed = True
                else:
                    escapes = True
            elif isinstance(parent, (ast.Return, ast.Yield, ast.Tuple, ast.List, ast.Dict)):
                escapes = True
            elif isinstance(parent, ast.Call):
                escapes = True  # passed straight into another callable
            else:
                escapes = True
            if binding is not None:
                events = self.bindings.setdefault(binding, _BindingEvents())
                events.assigns.append((node.lineno, id(fn) if fn else 0))
                # `X.daemon = True` after construction also makes it a daemon.
                if not daemon:
                    daemon = self._daemon_assigned(binding, fn)
                if self._binding_escapes(binding, fn):
                    escapes = True
            self.spawns.append(
                SpawnSite(
                    kind=kind,
                    lineno=node.lineno,
                    binding=binding,
                    targets=targets,
                    entry_nodes=entry_nodes,
                    daemon=daemon,
                    escapes=escapes,
                    started_inline=started_inline,
                )
            )
        # Lifecycle events on tracked bindings, module-wide.
        for node in ast.walk(self.tree):
            # (submit targets are discovered by _compute_roots' own walk —
            # _BindingEvents records lifecycle events only.)
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("start", "join", "cancel", "shutdown")
            ):
                continue
            fn = self.enclosing_fn(node)
            key = self.binding_key(node.func.value, fn)
            if key is None or key not in self.bindings:
                # attr keys are matched class-wide even when the event fires
                # in a different method than the assignment.
                continue
            events = self.bindings[key]
            if node.func.attr == "start":
                events.starts.append((node.lineno, id(fn) if fn else 0))
            elif node.func.attr == "join":
                events.joins.append(node.lineno)
            elif node.func.attr == "cancel":
                events.cancels.append(node.lineno)
            elif node.func.attr == "shutdown":
                events.shutdowns.append(node.lineno)
        # `with <executor binding>:` context management counts as shutdown.
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    key = self.binding_key(item.context_expr, self.enclosing_fn(node))
                    if key in self.bindings:
                        self.bindings[key].ctx_managed = True

    def _daemon_assigned(self, binding: str, fn: Optional[ast.AST]) -> bool:
        """`X.daemon = True` on this binding, scoped the way the binding key
        is scoped: a local's daemon-assign must live in the binding's own
        function (a same-named local elsewhere is a different thread), an
        attr binding's in any method of the same class, a global's anywhere
        at module reach."""
        if binding.startswith("attr:"):
            cls, attr = binding[len("attr:"):].split(".", 1)
            expected = ["self", attr]
        else:
            cls = attr = None
            expected = [binding.rsplit(":", 1)[-1]]
        if binding.startswith("local:") and fn is not None:
            nodes = walk_scope(fn)
        else:
            nodes = ast.walk(self.tree)
        for node in nodes:
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            chain = dotted(node.targets[0])
            if not (chain and chain[-1] == "daemon" and chain[:-1] == expected):
                continue
            if cls is not None and self._nearest_class(node) != cls:
                continue
            if isinstance(node.value, ast.Constant) and node.value.value is True:
                return True
        return False

    def _binding_escapes(self, binding: str, fn: Optional[ast.AST]) -> bool:
        """A tracked binding whose VALUE leaves the module's sight (returned,
        passed as a call argument, stored in a container) can be joined or
        cancelled by code we cannot see."""
        if binding.startswith("attr:"):
            simple = None
            attr = binding.split(".", 1)[1]
        else:
            simple = binding.rsplit(":", 1)[-1]
            attr = None
        scope: ast.AST = self.tree if fn is None else fn
        for node in walk_scope(scope) if fn is not None else ast.walk(self.tree):
            is_ref = False
            if simple is not None:
                is_ref = (
                    isinstance(node, ast.Name)
                    and node.id == simple
                    and isinstance(node.ctx, ast.Load)
                )
            elif attr is not None:
                chain = dotted(node) if isinstance(node, ast.Attribute) else []
                is_ref = chain == ["self", attr] and isinstance(
                    getattr(node, "ctx", None), ast.Load
                )
            if not is_ref:
                continue
            parent = self._parents.get(id(node))
            if isinstance(parent, (ast.Return, ast.Yield, ast.Tuple, ast.List, ast.Set)):
                return True
            if isinstance(parent, ast.Call) and node in parent.args:
                return True
            if isinstance(parent, ast.keyword):
                return True
        return False

    # -- roots -----------------------------------------------------------------
    def _closure(self, entries: Set[ast.AST], skip_ids: Set[int]) -> Set[ast.AST]:
        reachable = set(entries)
        frontier = list(entries)
        while frontier:
            fn = frontier.pop()
            cls = self.class_of(fn)
            for node in walk_scope(fn):
                if id(node) in skip_ids:
                    continue
                found: List[ast.AST] = []
                if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                    found.extend(self.index.resolve(node.id))
                elif (
                    isinstance(node, ast.Attribute)
                    and isinstance(node.ctx, ast.Load)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"
                ):
                    found.extend(self.resolve_method(cls, node.attr))
                elif isinstance(node, ast.Lambda):
                    found.append(node)
                for target in found:
                    if target not in reachable:
                        reachable.add(target)
                        frontier.append(target)
        return reachable

    def _compute_roots(self) -> None:
        thread_reachable: Set[ast.AST] = set()
        for spawn in self.spawns:
            if spawn.kind == "executor":
                continue
            entries = set(spawn.entry_nodes)
            if not entries:
                continue
            label = f"thread:{','.join(spawn.targets) or '<lambda>'}@{spawn.lineno}"
            reached = self._closure(entries, self._spawn_target_node_ids)
            self.roots[label] = reached
            thread_reachable |= reached
        # Executor submit targets are thread entries too.
        for node in ast.walk(self.tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "submit"
            ):
                key = self.binding_key(node.func.value, self.enclosing_fn(node))
                if key is not None and any(
                    s.binding == key and s.kind == "executor" for s in self.spawns
                ):
                    if node.args:
                        names, entry_nodes = self._resolve_entries([node.args[0]], node)
                        entries = set(entry_nodes)
                        if entries:
                            label = (
                                f"thread:{','.join(names) or '<lambda>'}@{node.lineno}"
                            )
                            reached = self._closure(
                                entries, self._spawn_target_node_ids
                            )
                            self.roots.setdefault(label, set()).update(reached)
                            thread_reachable |= reached

        # Main root: every function not exclusively thread-reachable. Public
        # and dunder names are assumed main-callable (external callers are
        # invisible to module-local analysis); underscore thread helpers are
        # main too when main-side code actually references them.
        def is_public(fn: ast.AST) -> bool:
            name = getattr(fn, "name", "")
            return not name.startswith("_") or (
                name.startswith("__") and name.endswith("__")
            )

        main: Set[ast.AST] = {
            fn for fn in self._functions if fn not in thread_reachable
        }
        main |= {fn for fn in self._functions if fn in thread_reachable and is_public(fn)}
        # Module-level references (excluding spawn-target expressions).
        module_entries: Set[ast.AST] = set()
        for node in walk_scope(self.tree):
            if id(node) in self._spawn_target_node_ids:
                continue
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                module_entries |= set(self.index.resolve(node.id))
        main |= module_entries
        self.roots[MAIN_ROOT] = self._closure(main, self._spawn_target_node_ids) | main

        for label, fns in self.roots.items():
            for fn in fns:
                self._fn_roots.setdefault(id(fn), set()).add(label)

    def roots_of(self, fn: Optional[ast.AST]) -> Set[str]:
        if fn is None:
            return {MAIN_ROOT}
        return self._fn_roots.get(id(fn), {MAIN_ROOT})

    @property
    def spawned_root_labels(self) -> Set[str]:
        return set(self.roots) - {MAIN_ROOT}

    def thread_reachable_fns(self) -> Set[ast.AST]:
        out: Set[ast.AST] = set()
        for label, fns in self.roots.items():
            if label != MAIN_ROOT:
                out |= fns
        return out

    # -- lock ranges -----------------------------------------------------------
    def _compute_lock_ranges(self) -> None:
        scopes: List[Tuple[Optional[ast.AST], ast.AST]] = [(None, self.tree)]
        scopes.extend((fn, fn) for fn in self._functions)
        for fn, scope in scopes:
            ranges: List[LockRange] = []
            pending_acquire: Dict[str, int] = {}
            end_line = max(
                (getattr(n, "end_lineno", 0) or 0 for n in ast.walk(scope)), default=0
            )
            for node in walk_scope(scope):
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        key = self.binding_key(item.context_expr, fn)
                        if key in self.lock_keys:
                            ranges.append(
                                LockRange(
                                    key,
                                    node.lineno,
                                    getattr(node, "end_lineno", node.lineno)
                                    or node.lineno,
                                )
                            )
                elif (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("acquire", "release")
                ):
                    key = self.binding_key(node.func.value, fn)
                    if key not in self.lock_keys:
                        continue
                    if node.func.attr == "acquire":
                        pending_acquire.setdefault(key, node.lineno)
                    else:
                        start = pending_acquire.pop(key, None)
                        if start is not None:
                            ranges.append(LockRange(key, start, node.lineno))
            for key, start in pending_acquire.items():
                ranges.append(LockRange(key, start, end_line))
            self._ranges[id(fn) if fn is not None else 0] = ranges

    def held_at(self, fn: Optional[ast.AST], lineno: int) -> FrozenSet[str]:
        ranges = self._ranges.get(id(fn) if fn is not None else 0, [])
        return frozenset(r.lock for r in ranges if r.start <= lineno <= r.end)

    def lock_ranges(self, fn: Optional[ast.AST]) -> List[LockRange]:
        return self._ranges.get(id(fn) if fn is not None else 0, [])

    # -- shared accesses -------------------------------------------------------
    def _is_init_method(self, fn: ast.AST) -> bool:
        parent = self._parents.get(id(fn))
        return isinstance(parent, ast.ClassDef) and fn.name in (
            "__init__",
            "__new__",
            "__post_init__",
        )

    def _classify_attr_access(self, node: ast.Attribute) -> Optional[str]:
        parent = self._parents.get(id(node))
        if isinstance(node.ctx, ast.Store):
            if isinstance(parent, ast.AugAssign):
                return "mutate"
            if isinstance(parent, ast.Assign):
                # Read-modify-write: the RHS reads the same attribute.
                chain = dotted(node)
                for sub in ast.walk(parent.value):
                    if isinstance(sub, ast.Attribute) and dotted(sub) == chain:
                        return "mutate"
                return "write"
            if isinstance(parent, (ast.Tuple, ast.List)):
                grand = self._parents.get(id(parent))
                if isinstance(grand, ast.Assign):
                    # Element-wise pairing: `a, self.x = self.x, v` assigns a
                    # fully-built value to self.x — atomic.
                    value = grand.value
                    if isinstance(value, (ast.Tuple, ast.List)) and len(
                        value.elts
                    ) == len(parent.elts):
                        idx = parent.elts.index(node)
                        chain = dotted(node)
                        for sub in ast.walk(value.elts[idx]):
                            if isinstance(sub, ast.Attribute) and dotted(sub) == chain:
                                return "mutate"
                        return "write"
                return "write"
            return "write"
        if isinstance(node.ctx, ast.Del):
            return "mutate"
        # Load context: look for in-place mutation through the load.
        if isinstance(parent, ast.Attribute):
            grand = self._parents.get(id(parent))
            if isinstance(getattr(parent, "ctx", None), (ast.Store, ast.Del)):
                return "mutate"  # self.x.y = ...
            if (
                isinstance(grand, ast.Call)
                and grand.func is parent
                and parent.attr in _MUTATORS
            ):
                return "mutate"  # self.x.append(...)
        if isinstance(parent, ast.Subscript) and isinstance(
            getattr(parent, "ctx", None), (ast.Store, ast.Del)
        ):
            return "mutate"  # self.x[k] = ...
        return "read"

    def _collect_shared_accesses(self) -> None:
        # Self-attributes, attributed to the innermost enclosing function.
        for node in ast.walk(self.tree):
            if (
                isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
            ):
                fn = self.enclosing_fn(node)
                if fn is None:
                    continue
                cls = self.class_of(fn)
                if cls is None:
                    continue
                key = f"attr:{cls}.{node.attr}"
                if key in self._safe_attr_keys or key in self.lock_keys:
                    continue
                kind = self._classify_attr_access(node)
                if kind is None:
                    continue
                self.accesses.setdefault(key, []).append(
                    SharedAccess(
                        key=key,
                        lineno=node.lineno,
                        kind=kind,
                        fn=fn,
                        locks=self.held_at(fn, node.lineno),
                        in_init=self._is_init_method(fn),
                    )
                )
        # Module globals: `global X` writes and in-place mutations.
        for fn in self._functions:
            declared: Set[str] = set()
            for node in walk_scope(fn):
                if isinstance(node, ast.Global):
                    declared.update(node.names)
            for node in walk_scope(fn):
                key = None
                kind = None
                if isinstance(node, ast.Name) and node.id in self._module_globals:
                    if node.id in self._safe_global:
                        continue
                    if isinstance(node.ctx, ast.Store):
                        if node.id not in declared:
                            continue  # a local shadow, not the global
                        kind = "write"
                    elif isinstance(node.ctx, ast.Load):
                        parent = self._parents.get(id(node))
                        kind = "read"
                        if (
                            isinstance(parent, ast.Attribute)
                            and parent.attr in _MUTATORS
                        ):
                            grand = self._parents.get(id(parent))
                            if isinstance(grand, ast.Call) and grand.func is parent:
                                kind = "mutate"
                        elif isinstance(parent, ast.Subscript) and isinstance(
                            getattr(parent, "ctx", None), (ast.Store, ast.Del)
                        ):
                            kind = "mutate"
                    if kind is not None:
                        key = f"global:{node.id}"
                elif isinstance(node, ast.AugAssign) and isinstance(
                    node.target, ast.Name
                ):
                    if (
                        node.target.id in declared
                        and node.target.id in self._module_globals
                        and node.target.id not in self._safe_global
                    ):
                        key = f"global:{node.target.id}"
                        kind = "mutate"
                if key is not None and kind is not None:
                    self.accesses.setdefault(key, []).append(
                        SharedAccess(
                            key=key,
                            lineno=node.lineno,
                            kind=kind,
                            fn=fn,
                            locks=self.held_at(fn, node.lineno),
                        )
                    )

    # -- completion obligations ------------------------------------------------
    @staticmethod
    def _iter_element(target: ast.AST, it: ast.AST, names: Set[str]) -> Optional[Tuple[str, str]]:
        """(element_name, iterated_name) for `for e in X` / `for i, e in
        enumerate(X)` over a watched name X, else None."""
        iterated: Optional[str] = None
        if isinstance(it, ast.Name) and it.id in names:
            iterated = it.id
        elif (
            isinstance(it, ast.Call)
            and _callee_name(it.func) == "enumerate"
            and len(it.args) >= 1
            and isinstance(it.args[0], ast.Name)
            and it.args[0].id in names
        ):
            iterated = it.args[0].id
            if isinstance(target, ast.Tuple) and len(target.elts) == 2:
                target = target.elts[1]
        if iterated is None or not isinstance(target, ast.Name):
            return None
        return target.id, iterated

    def param_completions(self, fn: ast.AST) -> Dict[str, Set[str]]:
        """{param -> {"result","error"}} completions this function performs on
        its own parameters (directly or on iterated elements)."""
        cached = self._completions_cache.get(id(fn))
        if cached is not None:
            return cached
        params = set()
        args = fn.args
        for p in list(getattr(args, "posonlyargs", [])) + list(args.args):
            params.add(p.arg)
        aliases: Dict[str, str] = {}  # loop element -> iterated param
        out: Dict[str, Set[str]] = {}
        for node in walk_scope(fn):
            if isinstance(node, (ast.For, ast.AsyncFor, ast.comprehension)):
                pair = self._iter_element(node.target, node.iter, params)
                if pair is not None:
                    aliases[pair[0]] = pair[1]
        for node in walk_scope(fn):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _COMPLETE_ANY
                and isinstance(node.func.value, ast.Name)
            ):
                continue
            receiver = node.func.value.id
            param = receiver if receiver in params else aliases.get(receiver)
            if param is None:
                continue
            out.setdefault(param, set()).add(
                "result" if node.func.attr in _COMPLETE_RESULT else "error"
            )
        self._completions_cache[id(fn)] = out
        return out

    def completion_kinds_for(
        self, fn: ast.AST, node: ast.AST, name: str, elem_aliases: Set[str]
    ) -> Set[str]:
        """Completion kinds an AST node performs on obligation `name` (or its
        iterated elements), including one-level helper calls
        (`self._complete(batch, ...)` where _complete completes its param)."""
        kinds: Set[str] = set()
        watched = {name} | elem_aliases
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            if (
                isinstance(sub.func, ast.Attribute)
                and sub.func.attr in _COMPLETE_ANY
                and isinstance(sub.func.value, ast.Name)
                and sub.func.value.id in watched
            ):
                kinds.add(
                    "result" if sub.func.attr in _COMPLETE_RESULT else "error"
                )
                continue
            # Helper call receiving the obligation positionally.
            helpers: List[ast.AST] = []
            if isinstance(sub.func, ast.Name):
                helpers = list(self.index.resolve(sub.func.id))
            elif (
                isinstance(sub.func, ast.Attribute)
                and isinstance(sub.func.value, ast.Name)
                and sub.func.value.id == "self"
            ):
                helpers = self.resolve_method(self.class_of(fn), sub.func.attr)
            if not helpers:
                continue
            for pos, arg in enumerate(sub.args):
                if not (isinstance(arg, ast.Name) and arg.id in watched):
                    continue
                for helper in helpers:
                    h_params = [
                        p.arg
                        for p in list(getattr(helper.args, "posonlyargs", []))
                        + list(helper.args.args)
                    ]
                    if h_params and h_params[0] == "self":
                        h_params = h_params[1:]
                    if pos < len(h_params):
                        completed = self.param_completions(helper).get(
                            h_params[pos], set()
                        )
                        kinds |= completed
        return kinds

    def _collect_obligations(self) -> None:
        thread_fns = self.thread_reachable_fns()
        for fn in self._functions:
            if fn not in thread_fns:
                continue
            for node in walk_scope(fn):
                if not (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)
                    and isinstance(node.value.func, ast.Attribute)
                    and node.value.func.attr in _RECEIPT_ATTRS
                ):
                    continue
                name = node.targets[0].id
                elem_aliases = self.element_aliases(fn, name)
                kinds: Set[str] = set()
                for later in walk_scope(fn):
                    if getattr(later, "lineno", 0) <= node.lineno:
                        continue
                    kinds |= self.completion_kinds_for(fn, later, name, elem_aliases)
                    if kinds:
                        break
                if kinds:
                    self.obligations.append(
                        Obligation(
                            fn=fn,
                            name=name,
                            lineno=node.lineno,
                            iterated=bool(elem_aliases),
                            receipt=node,
                        )
                    )

    def element_aliases(self, fn: ast.AST, name: str) -> Set[str]:
        """Loop/comprehension targets iterating `name` within `fn` (plain
        iteration and `enumerate(name)` tuple targets)."""
        out: Set[str] = set()
        for node in walk_scope(fn):
            if isinstance(node, (ast.For, ast.AsyncFor, ast.comprehension)):
                pair = self._iter_element(node.target, node.iter, {name})
                if pair is not None:
                    out.add(pair[0])
        return out

    # -- summary ---------------------------------------------------------------
    def summary(self) -> Dict[str, int]:
        return {
            "spawns": len(self.spawns),
            "roots": len(self.spawned_root_labels),
            "locks": len(self.lock_keys),
            "shared": len(self.accesses),
            "obligations": len(self.obligations),
        }


def for_context(ctx) -> ModuleThreadModel:
    """The memoized per-file accessor every STX014-017 rule goes through —
    the model is built once per scanned file, like ModuleMeshModel."""
    return ctx.memo("threadmodel", lambda: ModuleThreadModel(ctx.tree))


def repo_summary(paths: Optional[Sequence[str]] = None, repo: Optional[str] = None) -> Dict[str, int]:
    """Aggregate model sizes over a path set (launcher --preflight-only's
    concurrency row and the CLI's --statistics block): how many thread
    spawns, lock bindings, and completion obligations the model actually
    sees — a silently-empty model (a refactor that renamed the idioms out
    from under the AST patterns) becomes visible instead of green."""
    from stoix_tpu.analysis import core as _core

    repo = repo or _core.REPO
    totals = {"files": 0, "spawns": 0, "roots": 0, "locks": 0, "shared": 0, "obligations": 0}
    for path in _core.iter_py_files(paths or ["stoix_tpu"], repo):
        try:
            with open(path) as f:
                tree = ast.parse(f.read())
        except (OSError, SyntaxError):
            continue
        totals["files"] += 1
        for key, value in ModuleThreadModel(tree).summary().items():
            totals[key] += value
    return totals
