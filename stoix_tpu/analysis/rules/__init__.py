"""Rule registry: importing this package registers every rule module.

Adding a rule = adding one module here that calls
`stoix_tpu.analysis.core.register(Rule(...))` at import time with its id,
rationale, checker, allowlist, and fixture snippets. Order fields pin the
historical per-file finding order the scripts/lint.py shim output relies on.
"""

from stoix_tpu.analysis.rules import core_checks  # noqa: F401 — registers F401/HYG
from stoix_tpu.analysis.rules import stx001_host_sync  # noqa: F401
from stoix_tpu.analysis.rules import stx002_observability  # noqa: F401
from stoix_tpu.analysis.rules import stx003_swallowed_exceptions  # noqa: F401
from stoix_tpu.analysis.rules import stx004_unbounded_blocking  # noqa: F401
from stoix_tpu.analysis.rules import stx005_prng_discipline  # noqa: F401
from stoix_tpu.analysis.rules import stx006_host_transfer  # noqa: F401
from stoix_tpu.analysis.rules import stx007_collective_axes  # noqa: F401
from stoix_tpu.analysis.rules import stx008_donation  # noqa: F401
from stoix_tpu.analysis.rules import stx009_config_crosscheck  # noqa: F401
from stoix_tpu.analysis.rules import stx010_spec_validity  # noqa: F401
from stoix_tpu.analysis.rules import stx011_shardmap_contract  # noqa: F401
from stoix_tpu.analysis.rules import stx012_recompile_hazard  # noqa: F401
from stoix_tpu.analysis.rules import stx013_host_divergence  # noqa: F401
from stoix_tpu.analysis.rules import stx014_shared_mutation  # noqa: F401
from stoix_tpu.analysis.rules import stx015_lock_blocking  # noqa: F401
from stoix_tpu.analysis.rules import stx016_completion  # noqa: F401
from stoix_tpu.analysis.rules import stx017_thread_lifecycle  # noqa: F401
from stoix_tpu.analysis.rules import stx018_exit_codes  # noqa: F401
from stoix_tpu.analysis.rules import stx019_metric_discipline  # noqa: F401
from stoix_tpu.analysis.rules import stx020_kv_keyspace  # noqa: F401
from stoix_tpu.analysis.rules import stx021_hard_exit  # noqa: F401
from stoix_tpu.analysis.rules import stx022_fault_spec  # noqa: F401
from stoix_tpu.analysis.rules import stx023_stale_crossref  # noqa: F401
