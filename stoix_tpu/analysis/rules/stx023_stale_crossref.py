"""STX023 — doc cross-references must resolve: §2.x -> docs/DESIGN.md,
STXnnn -> the rule registry.

The repo's docstrings and markdown cite design sections (`§2.6`) and lint
rules (`STX018`) as load-bearing pointers — they are how a reader finds
the contract a module implements. Sections get renumbered and rules get
added; nothing checks the pointers, and PR 16 already fixed one stale ref
by hand. Tree-scoped (docs/DESIGN.md §2.5):

  * every `§2.<n>` reference in a scanned module/class/function docstring
    must name a section heading that exists in `docs/DESIGN.md`;
  * every `STX<nnn>` id in a docstring must be a registered rule;
  * the same checks run over `README.md` and `docs/*.md` read from disk
    (they are not part of the .py scan), anchored at the markdown line.

String literals that are not docstrings (fixture snippets, messages) are
out of scope on purpose — fixtures legitimately mention fake rule ids.
"""

from __future__ import annotations

import ast
import functools
import glob
import os
import re
from typing import Iterator, List, Set, Tuple

from stoix_tpu.analysis.core import Finding, Rule, TreeContext, register

_SECTION_REF = re.compile(r"§2\.(\d+)")
_RULE_REF = re.compile(r"STX(\d{3})")
_HEADING = re.compile(r"^#{2,4}\s+(?:§\s*)?2\.(\d+)\b")


@functools.lru_cache(maxsize=8)
def _design_sections(repo: str) -> Tuple[str, ...]:
    """The `2.<n>` section numbers docs/DESIGN.md actually declares."""
    path = os.path.join(repo, "docs", "DESIGN.md")
    sections: Set[str] = set()
    try:
        with open(path) as f:
            for line in f:
                match = _HEADING.match(line)
                if match:
                    sections.add(match.group(1))
    except OSError:
        pass
    return tuple(sorted(sections))


def _registered_rule_ids() -> Set[str]:
    from stoix_tpu.analysis.core import get_rules

    return {rule.id for rule in get_rules()}


def _docstrings(tree: ast.AST) -> Iterator[Tuple[int, str]]:
    """(first lineno, text) of every module/class/function docstring."""
    for node in ast.walk(tree):
        if isinstance(
            node,
            (ast.Module, ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef),
        ):
            body = getattr(node, "body", [])
            if (
                body
                and isinstance(body[0], ast.Expr)
                and isinstance(body[0].value, ast.Constant)
                and isinstance(body[0].value.value, str)
            ):
                yield body[0].value.lineno, body[0].value.value


def _ref_findings(
    rule: Rule,
    rel: str,
    base_lineno: int,
    text: str,
    sections: Set[str],
    rule_ids: Set[str],
) -> List[Finding]:
    findings: List[Finding] = []
    for offset, line in enumerate(text.splitlines()):
        lineno = base_lineno + offset
        for match in _SECTION_REF.finditer(line):
            if match.group(1) not in sections:
                findings.append(
                    Finding(
                        rule.id,
                        rel,
                        lineno,
                        f"stale cross-reference: §2.{match.group(1)} is "
                        f"not a section heading in docs/DESIGN.md "
                        f"(STX023)",
                    )
                )
        for match in _RULE_REF.finditer(line):
            if f"STX{match.group(1)}" not in rule_ids:
                findings.append(
                    Finding(
                        rule.id,
                        rel,
                        lineno,
                        f"stale cross-reference: STX{match.group(1)} is "
                        f"not a registered analysis rule (STX023)",
                    )
                )
    return findings


def _check_tree(rule: Rule, tree_ctx: TreeContext) -> List[Finding]:
    sections = set(_design_sections(tree_ctx.repo))
    if not sections:
        return []  # no DESIGN.md (a bare fixture repo) — nothing to check
    rule_ids = _registered_rule_ids()
    findings: List[Finding] = []
    for ctx in sorted(tree_ctx.files, key=lambda c: c.rel):
        for base_lineno, text in _docstrings(ctx.tree):
            for finding in _ref_findings(
                rule, ctx.rel, base_lineno, text, sections, rule_ids
            ):
                if not ctx.noqa(finding.line, rule.id):
                    findings.append(finding)
    # Markdown surfaces, read from disk (not part of the .py scan). Only
    # curated docs — working notes (ISSUE/CHANGES/ROADMAP) narrate history
    # and may cite sections that postdate or predate the current DESIGN.
    md_paths = [os.path.join(tree_ctx.repo, "README.md")] + sorted(
        glob.glob(os.path.join(tree_ctx.repo, "docs", "*.md"))
    )
    for path in md_paths:
        try:
            with open(path) as f:
                text = f.read()
        except OSError:
            continue
        rel = os.path.relpath(path, tree_ctx.repo)
        findings.extend(
            _ref_findings(rule, rel, 1, text, sections, rule_ids)
        )
    return findings


RULE = register(
    Rule(
        id="STX023",
        order=109,
        title="doc cross-references resolve",
        rationale="Docstring and markdown pointers to design sections and "
        "rule ids are how readers find the governing contract; sections "
        "get renumbered and rules added, and a stale pointer misdirects "
        "exactly when it matters. PR 16 fixed one such drift by hand — "
        "this makes the class mechanical.",
        check_tree=_check_tree,
        flag_snippets=(
            # A renumbered-away section reference.
            '"""Window accounting (docs/DESIGN.md §2.99)."""\n\n'
            "X = 1\n",
            # An unregistered rule id in a function docstring.
            "def gate():\n"
            '    """Pinned by STX901 fixtures."""\n'
            "    return 0\n",
        ),
        clean_snippets=(
            # Live section + live rule id.
            '"""Exit codes (docs/DESIGN.md §2.6), enforced by '
            'STX018."""\n\nX = 1\n',
            # Non-docstring strings may cite anything (fixture snippets).
            "def fixtures():\n"
            '    return "see §2.99 and STX901 for the bad case"\n',
        ),
    )
)
