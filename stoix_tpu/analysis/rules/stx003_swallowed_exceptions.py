"""STX003 — no swallowed exceptions.

`stoix_tpu/` library code must not catch a BROAD exception type (bare
`except:`, `except Exception`, `except BaseException`) and do nothing with it
(`pass`/`...` body). Silently eaten failures are how a wedged actor or a
half-written checkpoint turns into a 180s-timeout mystery — either narrow the
type (e.g. `except queue.Empty`), handle it (log/counter/re-raise), or carry
a `# noqa` with a reason on the except line.

Allowlisted: resilience/faultinject.py (the chaos layer must never let its
own bookkeeping mask the failure it is injecting).

Checker migrated unchanged from scripts/lint.py (PR 3).
"""

from __future__ import annotations

import ast
import os
from typing import List

from stoix_tpu.analysis.core import FileContext, Finding, Rule, register

_ALLOWLIST = frozenset({os.path.join("stoix_tpu", "resilience", "faultinject.py")})
_BROAD_EXCEPTION_NAMES = {"Exception", "BaseException"}


def _is_broad_handler(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:  # bare `except:`
        return True
    types = handler.type.elts if isinstance(handler.type, ast.Tuple) else [handler.type]
    for node in types:
        if isinstance(node, ast.Name) and node.id in _BROAD_EXCEPTION_NAMES:
            return True
    return False


def _body_swallows(handler: ast.ExceptHandler) -> bool:
    for stmt in handler.body:
        if isinstance(stmt, ast.Pass):
            continue
        if (
            isinstance(stmt, ast.Expr)
            and isinstance(stmt.value, ast.Constant)
            and stmt.value.value is Ellipsis
        ):
            continue
        return False
    return True


def _check(rule: Rule, ctx: FileContext) -> List[Finding]:
    rel = ctx.rel
    if not rel.startswith("stoix_tpu" + os.sep) or rel in _ALLOWLIST:
        return []
    findings = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not (_is_broad_handler(node) and _body_swallows(node)):
            continue
        if "noqa" in ctx.line(node.lineno):
            continue
        findings.append(
            Finding(
                "STX003",
                rel,
                node.lineno,
                "broad exception swallowed (`except "
                "Exception: pass`) in library code — narrow the type, handle "
                "it, or add a reasoned noqa (STX003)",
            )
        )
    return findings


RULE = register(
    Rule(
        id="STX003",
        order=40,
        title="no swallowed exceptions",
        rationale="A broad except with an empty body converts a real failure "
        "into a silent hang or wrong result; narrow it, handle it, or carry "
        "a reasoned noqa.",
        allowlist=_ALLOWLIST,
        check_file=_check,
        flag_snippets=(
            "try:\n    x()\nexcept Exception:\n    pass\n"
            "try:\n    x()\nexcept:\n    pass\n"
            "try:\n    x()\nexcept (ValueError, BaseException):\n    ...\n"
            "try:\n    x()\nexcept Exception as e:\n    pass\n",
        ),
        clean_snippets=(
            "try:\n    x()\nexcept queue.Empty:\n    pass\n"
            "try:\n    x()\nexcept Exception:\n    log.error('boom')\n"
            "try:\n    x()\nexcept Exception:  # noqa: STX003 — reason\n    pass\n",
        ),
    )
)
