"""Core hygiene checks migrated unchanged from scripts/lint.py: unused
imports (F401) and whitespace/line-length hygiene (W191/W291 errors, E501
warning). Syntax (E999) lives in core.py because a file that does not parse
short-circuits every other rule.

These keep the historical `"noqa" in line` substring suppression and the
historical absolute display paths so the scripts/lint.py shim output stays
byte-identical.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from typing import List, Tuple

from stoix_tpu.analysis.core import ERROR, WARNING, FileContext, Finding, Rule, register

MAX_LINE = 100

# Modules where a dangling import is part of the public re-export surface.
REEXPORT_FILES = {"__init__.py"}


class _ImportCollector(ast.NodeVisitor):
    def __init__(self) -> None:
        self.imports: List[Tuple[str, int]] = []  # (bound name, lineno)

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            name = alias.asname or alias.name.split(".")[0]
            self.imports.append((name, node.lineno))

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "__future__":
            return
        for alias in node.names:
            if alias.name == "*":
                continue
            name = alias.asname or alias.name
            self.imports.append((name, node.lineno))


def _check_unused_imports(rule: Rule, ctx: FileContext) -> List[Finding]:
    if os.path.basename(ctx.path) in REEXPORT_FILES:
        return []
    collector = _ImportCollector()
    collector.visit(ctx.tree)
    if not collector.imports:
        return []

    used: set = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
    # Names referenced in __all__ strings and doc/annotation strings.
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            used.update(node.value.replace(".", " ").replace("[", " ").split())

    findings = []
    for name, lineno in collector.imports:
        if name in used or name.startswith("_"):
            continue
        if "noqa" in ctx.line(lineno):
            continue
        findings.append(
            Finding("F401", ctx.path, lineno, f"unused import '{name}' (F401)")
        )
    return findings


RULE_F401 = register(
    Rule(
        id="F401",
        order=10,
        title="unused imports",
        rationale="An import nothing references is dead weight and usually a "
        "leftover from a refactor; flake8-F401 equivalent, AST based.",
        check_file=_check_unused_imports,
        flag_snippets=("import os\n\n\nX = 1\n",),
        clean_snippets=(
            "import os\n\nX = os.sep\n",
            "import os  # noqa\n\nX = 1\n",
        ),
    )
)


def _check_hygiene(rule: Rule, ctx: FileContext) -> List[Finding]:
    findings: List[Finding] = []
    for i, line in enumerate(ctx.lines, 1):
        stripped = line.rstrip("\n")
        indent = stripped[: len(stripped) - len(stripped.lstrip())]
        if "\t" in indent:
            findings.append(Finding("W191", ctx.path, i, "tab in indentation (W191)"))
        if stripped != stripped.rstrip():
            findings.append(
                Finding("W291", ctx.path, i, "trailing whitespace (W291)")
            )
        if len(stripped) > MAX_LINE and "http" not in stripped and "noqa" not in stripped:
            findings.append(
                Finding(
                    "E501",
                    ctx.path,
                    i,
                    f"line too long ({len(stripped)} > {MAX_LINE}) (E501)",
                    severity=WARNING,
                )
            )
    return findings


RULE_HYGIENE = register(
    Rule(
        id="HYG",
        order=60,
        finding_ids=("W191", "W291", "E501"),
        title="whitespace hygiene",
        rationale="No tabs in indentation (W191) and no trailing whitespace "
        "(W291) as errors; lines over 100 columns (E501) as warnings.",
        severity=ERROR,
        check_file=_check_hygiene,
        flag_snippets=("def f():\n\treturn 1\n",),
        clean_snippets=("def f():\n    return 1\n",),
    )
)


# Codes whose suppression must be auditable: the JAX-aware rules, where a
# noqa waives a correctness tripwire (legacy F401/E501/STX001-004 keep their
# historical reason-optional substring semantics — migrated unchanged).
_REASON_REQUIRED = {
    "STX005",
    "STX006",
    "STX007",
    "STX008",
    "STX009",
    "STX010",
    "STX011",
    "STX012",
    "STX013",
    "STX014",
    "STX015",
    "STX016",
    "STX017",
    "STX018",
    "STX019",
    "STX020",
    "STX021",
    "STX022",
    "STX023",
}
_NOQA_DIRECTIVE = re.compile(r"#\s*noqa\b:?\s*([^#]*)", re.IGNORECASE)
_NOQA_CODE = re.compile(r"[A-Z]+[0-9]+")


def _check_noqa_reasons(rule: Rule, ctx: FileContext) -> List[Finding]:
    """The noqa policy's teeth: a coded `# noqa: STX005` suppressing one of
    the JAX-aware rules MUST carry a one-line reason after an em-dash
    (`# noqa: STX005 — fixed fan-out`), or it is itself a finding.

    Tokenizer-based, not textual: only real COMMENT tokens count, so
    docstrings and fixture-snippet string literals that mention noqa
    directives never trip the rule."""
    findings: List[Finding] = []
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(ctx.source).readline))
    except (tokenize.TokenError, IndentationError):
        return []
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _NOQA_DIRECTIVE.search(tok.string)
        if not m:
            continue
        head, dash, reason = m.group(1).partition("—")
        codes = set(_NOQA_CODE.findall(head))
        needing = sorted(codes & _REASON_REQUIRED)
        if not needing:
            continue
        if dash and reason.strip():
            continue
        findings.append(
            Finding(
                "NOQA",
                ctx.rel,
                tok.start[0],
                f"coded noqa for {'/'.join(needing)} without a reason — the "
                f"policy (docs/DESIGN.md §2.5) requires "
                f"`# noqa: {needing[0]} — <why>` so the waiver is auditable "
                f"(NOQA)",
            )
        )
    return findings


RULE_NOQA = register(
    Rule(
        id="NOQA",
        order=65,
        title="reasoned noqa policy",
        rationale="A suppression of a correctness tripwire (STX005+) with no "
        "recorded reason is indistinguishable from a silenced bug; the "
        "reason makes every waiver reviewable.",
        check_file=_check_noqa_reasons,
        flag_snippets=("x = q_get()  # noqa: STX005\n",),
        clean_snippets=(
            "x = q_get()  # noqa: STX005 — fixed fan-out, keys independent\n",
            "y = 1  # noqa\n",  # the bare legacy escape hatch is exempt
            "z = 2  # noqa: F401\n",  # legacy codes stay reason-optional
        ),
    )
)
