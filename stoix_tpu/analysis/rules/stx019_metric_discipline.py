"""STX019 — metric naming/typing/label discipline over the opsmodel.

The ~82 `stoix_tpu_*` series are the repo's operational API: dashboards,
the /metricsz endpoint, bench assertions, and the fleet skew exporter all
key on the *names*. Nothing type-checks a name, so the failure modes are
silent: a counter created without `_total` breaks Prometheus conventions
(and any rate() query written against the convention); the same name
created as two different kinds in two modules raises TypeError only when
both paths run in one process — in production, at 3am; two observe sites
disagreeing on label keys split one logical series into disjoint
un-joinable ones; and a name built dynamically is invisible to every grep
and to this gate. Backed by `analysis/opsmodel.py` (docs/DESIGN.md §2.5):

  * file-scoped: every creation-site name must normalize to a pattern
    (module-level string constants resolve; f-string holes become `{}`)
    matching the `stoix_tpu_<area>_<name>` charset; `_total` iff counter.
  * tree-scoped: one name must keep ONE metric kind across the whole scan,
    and every observe site of a series must use the same label-key set
    (label dicts that are not literals are out of model — a documented
    blind spot).
"""

from __future__ import annotations

import re
from typing import Dict, List, Tuple

from stoix_tpu.analysis.core import (
    FileContext,
    Finding,
    Rule,
    TreeContext,
    register,
)
from stoix_tpu.analysis import opsmodel

# `stoix_tpu_<area>_<name>`: at least two segments after the prefix,
# lowercase/digit charset. Normalized `{}` holes count as one segment.
_CHARSET = re.compile(r"^stoix_tpu_[a-z0-9]+(_[a-z0-9]+)+$")


def _charset_ok(pattern: str) -> bool:
    return bool(_CHARSET.match(pattern.replace("{}", "x")))


def _check_file(rule: Rule, ctx: FileContext) -> List[Finding]:
    model = opsmodel.for_context(ctx)
    findings: List[Finding] = []
    for site in model.metric_sites:
        if ctx.noqa(site.lineno, rule.id):
            continue
        if site.pattern is None:
            findings.append(
                Finding(
                    rule.id,
                    ctx.rel,
                    site.lineno,
                    f"metric name at this {site.kind}() creation does not "
                    f"normalize to a pattern — build names from literals, "
                    f"f-strings, or module-level constants so the series "
                    f"stays grep-able and lintable (STX019)",
                )
            )
            continue
        if not _charset_ok(site.pattern):
            findings.append(
                Finding(
                    rule.id,
                    ctx.rel,
                    site.lineno,
                    f"metric name '{site.pattern}' does not match the "
                    f"stoix_tpu_<area>_<name> convention "
                    f"(lowercase/digits, >=2 segments after the prefix) "
                    f"(STX019)",
                )
            )
        if site.kind == "counter" and not site.pattern.endswith("_total"):
            findings.append(
                Finding(
                    rule.id,
                    ctx.rel,
                    site.lineno,
                    f"counter '{site.pattern}' lacks the `_total` suffix — "
                    f"rate() queries and the Prometheus convention key on "
                    f"it (STX019)",
                )
            )
        elif site.kind != "counter" and site.pattern.endswith("_total"):
            findings.append(
                Finding(
                    rule.id,
                    ctx.rel,
                    site.lineno,
                    f"{site.kind} '{site.pattern}' carries the `_total` "
                    f"suffix reserved for counters (STX019)",
                )
            )
    return findings


def _check_tree(rule: Rule, tree_ctx: TreeContext) -> List[Finding]:
    findings: List[Finding] = []
    # pattern -> ordered [(rel, ctx, MetricSite)]; (rel, lineno) order makes
    # "first declaration wins" deterministic across files.
    creations: Dict[str, List[Tuple[str, FileContext, object]]] = {}
    observes: Dict[str, List[Tuple[str, FileContext, object]]] = {}
    for ctx in sorted(tree_ctx.files, key=lambda c: c.rel):
        model = opsmodel.for_context(ctx)
        for site in model.metric_sites:
            if site.pattern is not None:
                creations.setdefault(site.pattern, []).append(
                    (ctx.rel, ctx, site)
                )
        for site in model.observe_sites:
            if site.pattern is not None and site.label_keys is not None:
                observes.setdefault(site.pattern, []).append(
                    (ctx.rel, ctx, site)
                )
    for pattern, sites in creations.items():
        canonical = sites[0][2].kind
        for rel, ctx, site in sites[1:]:
            if site.kind == canonical or ctx.noqa(site.lineno, rule.id):
                continue
            findings.append(
                Finding(
                    rule.id,
                    rel,
                    site.lineno,
                    f"'{pattern}' created as {site.kind} here but as "
                    f"{canonical} at {sites[0][0]}:{sites[0][2].lineno} — "
                    f"one name, one metric kind, repo-wide (the registry "
                    f"raises TypeError only when both paths meet in one "
                    f"process) (STX019)",
                )
            )
    for pattern, sites in observes.items():
        canonical = sites[0][2].label_keys
        for rel, ctx, site in sites[1:]:
            if site.label_keys == canonical or ctx.noqa(site.lineno, rule.id):
                continue
            findings.append(
                Finding(
                    rule.id,
                    rel,
                    site.lineno,
                    f"'{pattern}' observed with label keys "
                    f"{list(site.label_keys)} here but "
                    f"{list(canonical)} at "
                    f"{sites[0][0]}:{sites[0][2].lineno} — disagreeing "
                    f"label-key sets split one logical series into "
                    f"un-joinable ones (STX019)",
                )
            )
    return findings


RULE = register(
    Rule(
        id="STX019",
        order=105,
        title="metric naming/typing/label discipline",
        rationale="Metric names are the operational API dashboards and "
        "bench assertions key on; nothing type-checks them, so a kind "
        "conflict or label drift between two modules only surfaces when "
        "both paths meet in one production process. The opsmodel makes "
        "every creation/observe site comparable statically.",
        check_file=_check_file,
        check_tree=_check_tree,
        flag_snippets=(
            # Counter without `_total`.
            "from stoix_tpu.observability import get_registry\n\n\n"
            "def arm():\n"
            '    get_registry().counter("stoix_tpu_loop_drops", "d").inc()\n',
            # Charset violation: single segment after the prefix.
            "from stoix_tpu.observability import get_registry\n\n\n"
            "def arm(registry):\n"
            '    registry.gauge("stoix_tpu_depth", "queue depth")\n',
            # Non-normalizable name (built by a call).
            "def arm(registry, name):\n"
            '    registry.gauge("stoix_tpu_" + name.strip(), "h")\n',
            # Kind conflict inside one module (tree half).
            "def arm(registry):\n"
            '    registry.gauge("stoix_tpu_loop_lag_seconds", "g")\n'
            '    registry.counter("stoix_tpu_loop_lag_seconds", "c")\n',
            # Label-key drift between two observe sites (tree half).
            "def arm(registry):\n"
            '    g = registry.gauge("stoix_tpu_fleet_age_seconds", "g")\n'
            '    g.set(1.0, {"process": "0"})\n'
            '    g.set(2.0, {"host": "0"})\n',
        ),
        clean_snippets=(
            # The shipped idiom: counter with _total, f-string hole, one
            # label-key set, constants resolve.
            '_EVENTS = "stoix_tpu_compile_cache_events_total"\n\n\n'
            "def arm(registry, k):\n"
            "    registry.counter(_EVENTS, 'h').inc()\n"
            '    c = registry.counter(f"stoix_tpu_loop_{k}_total", "h")\n'
            '    c.inc(labels={"stage": "a"})\n'
            '    c.inc(2.0, {"stage": "b"})\n'
            '    registry.gauge("stoix_tpu_queue_depth", "d").set(1.0)\n',
            # `.set()` on a non-metric binding is not an observe site.
            "import threading\n\n\ndef arm():\n"
            "    event = threading.Event()\n    event.set()\n",
            # Dynamic label dicts are out of model, not violations.
            "def arm(registry, labels):\n"
            '    g = registry.gauge("stoix_tpu_fleet_age_seconds", "g")\n'
            '    g.set(1.0, labels)\n'
            '    g.set(2.0, {"process": "1"})\n'
            '    g.set(3.0, {"process": "2"})\n',
        ),
    )
)
