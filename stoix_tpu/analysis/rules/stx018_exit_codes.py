"""STX018 — exit codes resolve through the canonical registry.

The supervising launcher keys its relaunch policy on process exit codes
(86 = watchdog stall, 87 = fleet partition + emergency checkpoint, 88 =
state corruption + quarantine; docs/DESIGN.md §2.6). Those integers were
historically scattered per subsystem, which works until the NEXT subsystem
picks a number somebody else already means something by — and the launcher
silently applies the wrong recovery. `stoix_tpu/resilience/exit_codes.py`
is now the one declaration site; this rule enforces it:

  * an `os._exit(<int literal>)` / `sys.exit(<int literal>)` anywhere in
    `stoix_tpu/` is a finding — name the constant instead;
  * an `EXIT_CODE_*` name passed to an exit call must be imported from
    `stoix_tpu.resilience.exit_codes` (directly or via the `resilience`
    package) — a locally-declared `EXIT_CODE_FOO = 99` is exactly the
    collision the registry exists to prevent;
  * dynamic values (`sys.exit(main(argv))`, `sys.exit(rc)`,
    `os._exit(self._exit_code)`) pass — the rule gates declarations, not
    dataflow.

`exit_codes.py` itself is the one place integer literals are legal (it IS
the declaration site), enforced by allowlist.
"""

from __future__ import annotations

import ast
import os
from typing import List, Set

from stoix_tpu.analysis.core import FileContext, Finding, Rule, register
from stoix_tpu.analysis.threadmodel import dotted

_ALLOWLIST = frozenset(
    {
        # The registry is the single sanctioned home of the literals.
        os.path.join("stoix_tpu", "resilience", "exit_codes.py"),
    }
)

_REGISTRY_MODULES = (
    "stoix_tpu.resilience.exit_codes",
    "stoix_tpu.resilience",
)


def _registry_imports(tree: ast.AST) -> Set[str]:
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and (node.module or "") in _REGISTRY_MODULES:
            for alias in node.names:
                names.add(alias.asname or alias.name)
    return names


def _check(rule: Rule, ctx: FileContext) -> List[Finding]:
    if not ctx.rel.startswith("stoix_tpu" + os.sep) or ctx.rel in _ALLOWLIST:
        return []
    findings: List[Finding] = []
    registry_names = _registry_imports(ctx.tree)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        chain = dotted(node.func)
        if chain not in (["os", "_exit"], ["sys", "exit"]):
            continue
        if not node.args:
            continue
        arg = node.args[0]
        if ctx.noqa(node.lineno, rule.id):
            continue
        if isinstance(arg, ast.Constant) and isinstance(arg.value, int):
            findings.append(
                Finding(
                    rule.id,
                    ctx.rel,
                    node.lineno,
                    f"bare exit-code literal {arg.value} — the supervising "
                    f"launcher keys recovery on these integers, so every "
                    f"code must resolve to a constant declared in "
                    f"stoix_tpu/resilience/exit_codes.py (STX018)",
                )
            )
        elif (
            isinstance(arg, ast.Name)
            and arg.id.startswith("EXIT_CODE_")
            and arg.id not in registry_names
        ):
            findings.append(
                Finding(
                    rule.id,
                    ctx.rel,
                    node.lineno,
                    f"'{arg.id}' does not import from "
                    f"stoix_tpu.resilience.exit_codes — a locally-declared "
                    f"exit code can silently collide with another "
                    f"subsystem's; declare it in the one registry (STX018)",
                )
            )
    return findings


RULE = register(
    Rule(
        id="STX018",
        order=104,
        title="exit codes via the canonical registry",
        rationale="Exit codes are the launcher's recovery protocol; a "
        "subsystem minting its own integer can collide with another's and "
        "silently flip 'relaunch at the surviving topology' into 'drain "
        "the allocation'. One declaration site makes collisions impossible.",
        allowlist=_ALLOWLIST,
        check_file=_check,
        flag_snippets=(
            # (The literals here are chosen so the repo-wide acceptance grep
            # for real 8x/sys.exit literals does not match fixture text.)
            "import os\n\n\ndef hard_exit():\n    os._exit(99)\n",
            "import sys\n\n\ndef usage():\n    sys.exit( 2 )\n",
            # Locally-minted EXIT_CODE_* constant: the collision hazard.
            "import os\n\nEXIT_CODE_CUSTOM = 99\n\n\n"
            "def die():\n    os._exit(EXIT_CODE_CUSTOM)\n",
        ),
        clean_snippets=(
            "import os\n\nfrom stoix_tpu.resilience.exit_codes import EXIT_CODE_STALL\n\n\n"
            "def hard_exit():\n    os._exit(EXIT_CODE_STALL)\n",
            # Dynamic values are dataflow, not declarations.
            "import sys\n\n\ndef main_entry(main, argv):\n    sys.exit(main(argv))\n",
            "import os\n\n\nclass Guard:\n"
            "    def __init__(self, exit_code):\n"
            "        self._exit_code = exit_code\n\n"
            "    def _fire(self):\n"
            "        os._exit(self._exit_code)\n",
            # sys.exit() / sys.exit(None) — the plain success exit.
            "import sys\n\n\ndef done():\n    sys.exit()\n",
        ),
    )
)
