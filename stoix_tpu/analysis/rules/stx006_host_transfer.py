"""STX006 — no host transfers inside jit-reachable code.

Inside a function that flows into `jax.jit`/`shard_map`/`lax.scan`/`jax.pmap`
(resolved per module by stoix_tpu.analysis.jitreach), the following force a
device→host sync or a trace-time error and must not appear:

  - `.item()` on anything (concrete-value readback),
  - `float(x)` / `int(x)` / `bool(x)` on a traced value (Python scalar
    coercion aborts tracing; static config scalars — `float(config.a.b)`,
    literals — are exempt),
  - `np.*(...)` calls on traced arrays (numpy forces materialization; dtype
    constructors like `np.float32(...)` are static and exempt),
  - `jax.device_get(...)`,
  - `jax.debug.print/callback/breakpoint(...)` without a reasoned noqa (they
    are legal but insert host callbacks on the accelerator critical path —
    the one-jitted-program design makes that a silent pipeline stall).

The jit-reachability resolution and its blind spots are documented in
docs/DESIGN.md §2.5.
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional

from stoix_tpu.analysis import jitreach
from stoix_tpu.analysis.core import FileContext, Finding, Rule, register

# np.* callees that produce static scalars/dtypes, not array materialization.
_NP_STATIC = {
    "float16",
    "float32",
    "float64",
    "int8",
    "int16",
    "int32",
    "int64",
    "uint8",
    "uint16",
    "uint32",
    "uint64",
    "bool_",
    "dtype",
    "finfo",
    "iinfo",
}
_SCALAR_CASTS = {"float", "int", "bool"}
_CONFIG_ROOTS = {"config", "cfg", "self"}


def _root_name(node: ast.AST) -> Optional[str]:
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _scope_bound_names(fn: ast.AST) -> set:
    """Names bound INSIDE this function's own scope: parameters plus any
    assignment/loop/with target. A name bound here holds (potentially) traced
    data; a free variable closed over from a non-traced setup scope is a
    trace-time constant (`num_samples`, `eval_max_steps`, ...)."""
    bound = set()
    args = getattr(fn, "args", None)
    if args is not None:
        for a in args.args + args.posonlyargs + args.kwonlyargs:
            bound.add(a.arg)
        if args.vararg:
            bound.add(args.vararg.arg)
        if args.kwarg:
            bound.add(args.kwarg.arg)
    for node in jitreach.walk_scope(fn):
        if isinstance(node, ast.Name) and isinstance(node.ctx, (ast.Store,)):
            bound.add(node.id)
    return bound


def _is_static_cast_arg(arg: ast.AST, bound: set) -> bool:
    """True when float()/int() is provably operating on a static host value:
    literals, attribute chains rooted at a config object (hyperparameters
    read at trace time — `float(config.system.gamma)`), and free variables
    captured from an enclosing non-traced setup scope."""
    if isinstance(arg, ast.Constant):
        return True
    if isinstance(arg, ast.Name):
        return arg.id not in bound
    if isinstance(arg, (ast.Attribute, ast.Subscript)):
        # Shape/dtype metadata of a traced array is a trace-time static —
        # int(x.shape[0]) is the standard static-shape idiom, not a readback.
        probe = arg
        while isinstance(probe, (ast.Attribute, ast.Subscript)):
            if isinstance(probe, ast.Attribute) and probe.attr in (
                "shape",
                "ndim",
                "dtype",
                "size",
            ):
                return True
            probe = probe.value
        root = _root_name(arg)
        return root in _CONFIG_ROOTS or (root is not None and root not in bound)
    if isinstance(arg, ast.Call):
        # float(config.system.get("x", 1.0)), int(len(...)), int(np.prod(shape))
        root = _root_name(arg.func)
        callee = arg.func.attr if isinstance(arg.func, ast.Attribute) else (
            arg.func.id if isinstance(arg.func, ast.Name) else ""
        )
        return root in _CONFIG_ROOTS or callee in {"len", "get", "prod"}
    if isinstance(arg, ast.BinOp):
        return _is_static_cast_arg(arg.left, bound) and _is_static_cast_arg(arg.right, bound)
    if isinstance(arg, ast.BoolOp):
        return all(_is_static_cast_arg(v, bound) for v in arg.values)
    return False


def _findings_in_function(rule: Rule, ctx: FileContext, fn: ast.AST) -> List[Finding]:
    findings: List[Finding] = []
    bound = _scope_bound_names(fn)

    def flag(node: ast.AST, what: str) -> None:
        if ctx.noqa(node.lineno, rule.id):
            return
        findings.append(
            Finding(
                rule.id,
                ctx.rel,
                node.lineno,
                f"{what} inside a jit-reachable function — forces a host "
                f"sync/transfer inside the compiled program (STX006)",
            )
        )

    for node in jitreach.walk_scope(fn):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if isinstance(func, ast.Attribute):
            if func.attr == "item" and not node.args and not node.keywords:
                flag(node, "`.item()` readback")
                continue
            root = _root_name(func)
            if root in ("np", "numpy") and func.attr not in _NP_STATIC:
                flag(node, f"numpy call `np.{func.attr}(...)` on traced values")
                continue
            if root == "jax" and func.attr == "device_get":
                flag(node, "`jax.device_get(...)`")
                continue
            receiver = func.value
            if (
                isinstance(receiver, ast.Attribute)
                and receiver.attr == "debug"
                and _root_name(receiver) == "jax"
            ):
                flag(node, f"`jax.debug.{func.attr}(...)` host callback")
                continue
        elif isinstance(func, ast.Name) and func.id in _SCALAR_CASTS:
            if len(node.args) == 1 and not node.keywords:
                if not _is_static_cast_arg(node.args[0], bound):
                    flag(node, f"`{func.id}(...)` scalar coercion of a traced value")
    return findings


def _check(rule: Rule, ctx: FileContext) -> List[Finding]:
    if not ctx.rel.startswith("stoix_tpu" + os.sep):
        return []
    findings: List[Finding] = []
    for fn in sorted(
        jitreach.reachable_jit_functions(ctx.tree), key=lambda n: n.lineno
    ):
        findings.extend(_findings_in_function(rule, ctx, fn))
    # One finding per line (a reachable helper can be reached twice).
    seen = set()
    unique = []
    for f in sorted(findings, key=lambda f: f.line):
        if (f.line, f.message) not in seen:
            seen.add((f.line, f.message))
            unique.append(f)
    return unique


RULE = register(
    Rule(
        id="STX006",
        order=80,
        title="no host transfers in jit",
        rationale="A hidden .item()/float()/np.* inside the jitted learn step "
        "either aborts tracing or, worse, inserts a device→host sync per step "
        "that serializes the whole pipeline.",
        check_file=_check,
        flag_snippets=(
            # .item() inside a scanned step function.
            "import jax\n\n\ndef build(step):\n"
            "    def _step(state, _):\n"
            "        loss = state.loss.item()\n"
            "        return state, loss\n"
            "    return jax.lax.scan(_step, step, None, 8)\n",
            # float() on a traced value inside a jitted function.
            "import jax\n\n\n@jax.jit\ndef f(x):\n"
            "    return float(x) + 1.0\n",
            # np.* materialization inside a shard_mapped learner.
            "import numpy as np\nfrom stoix_tpu.parallel.mesh import shard_map\n\n\n"
            "def make(mesh, specs):\n"
            "    def learner(state):\n"
            "        return np.asarray(state)\n"
            "    return shard_map(learner, mesh=mesh, in_specs=specs, out_specs=specs)\n",
        ),
        clean_snippets=(
            # Static config scalars at trace time are fine.
            "import jax\n\n\n@jax.jit\ndef f(x, config):\n"
            "    return x * float(config.system.gamma)\n",
            # Host code (not jit-reachable) may do host things.
            "import numpy as np\n\n\ndef metrics(state):\n"
            "    return float(np.asarray(state.loss).item())\n",
            # A reasoned noqa keeps an intentional debug callback.
            "import jax\n\n\n@jax.jit\ndef f(x):\n"
            "    jax.debug.print('x={x}', x=x)  # noqa: STX006 — temp debug\n"
            "    return x\n",
        ),
    )
)
