"""STX008 — donated-buffer misuse.

When a function is jitted with `donate_argnums`, the caller hands the
argument's buffers to XLA for reuse: reading the SAME variable after the call
is a use-after-free that jax only sometimes catches (a deleted-buffer error
on a good day, silently recycled memory inside a wedged runtime on a bad
one). The pipelined runner's whole snapshot discipline exists because of this
(docs/DESIGN.md §2.1, systems/anakin.py `shardmap_learner`).

Detection: file-wide, find bindings `step = jax.jit(fn, donate_argnums=...)`
(and `@partial(jax.jit, donate_argnums=...)` decorated defs); then, per
scope, a `Name` passed at a donated position whose value is loaded again
after the call — without an intervening rebind — is flagged. Rebinding
(`state = step(state)`) is the blessed idiom and resets tracking. Three
donation-declaration forms resolve (the first two closed PR 5's documented
blind spot):

  * `donate_argnames=("state",)` — mapped to positions through the wrapped
    function's signature when it resolves module-locally, and matched against
    KEYWORD arguments at call sites either way;
  * `jax.jit(fn, **donate)` / `@partial(jax.jit, **donate)` where `donate`
    is assigned a dict literal anywhere in the file — including the runner's
    kill-switch idiom `{} if os.environ.get(...) else {"donate_argnums":
    (0,)}`. The donating branch is taken (donation OFF is the degraded mode;
    a read-after-donate is a bug whenever the switch is on);
  * positional/keyword literal `donate_argnums=` as before.

Blind spots (docs/DESIGN.md §2.5): donation kwargs built outside the file or
via dict() calls/unpacking-of-unpacking, aliasing, and cross-function
escapes. The rule is a tripwire for the common refactor accident, not a
proof of safety.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from stoix_tpu.analysis.core import FileContext, Finding, Rule, register
from stoix_tpu.analysis.jitreach import _ModuleIndex
from stoix_tpu.analysis.jitreach import assigned_names as _assigned_names
from stoix_tpu.analysis.jitreach import callee_name as _callee_name
from stoix_tpu.analysis.jitreach import literal_int_set as _literal_ints
from stoix_tpu.analysis.jitreach import literal_str_set as _literal_strs
from stoix_tpu.analysis.jitreach import positional_params as _positional_params
from stoix_tpu.analysis.jitreach import walk_scope as _walk_scope


class _Donor:
    """Donated positions AND parameter names of one jitted binding, cross-
    mapped through the wrapped signature when it resolves (so positional and
    keyword call sites are both covered)."""

    def __init__(
        self, positions: Set[int], names: Set[str], params: Optional[List[str]]
    ) -> None:
        self.positions = set(positions)
        self.names = set(names)
        if params is not None:
            self.positions |= {params.index(n) for n in names if n in params}
            self.names |= {params[i] for i in positions if i < len(params)}


def _dict_donation(node: ast.AST) -> Tuple[Set[int], Set[str]]:
    """Donation markers in any dict LITERAL inside `node` — resolves the
    kill-switch idiom `{} if os.environ.get(...) else {"donate_argnums":
    (0,)}` by taking the donating branch (the mode the code must be safe in)."""
    nums: Set[int] = set()
    names: Set[str] = set()
    for d in ast.walk(node):
        if not isinstance(d, ast.Dict):
            continue
        for key, value in zip(d.keys, d.values):
            if not (isinstance(key, ast.Constant) and isinstance(key.value, str)):
                continue
            if key.value == "donate_argnums":
                nums |= _literal_ints(value) or set()
            elif key.value == "donate_argnames":
                names |= _literal_strs(value) or set()
    return nums, names


def _scope_kws_map(
    scope: ast.AST, base: Dict[str, Tuple[Set[int], Set[str]]]
) -> Dict[str, Tuple[Set[int], Set[str]]]:
    """name -> donation markers for variables assigned a donation-dict
    expression IN THIS SCOPE (nested defs excluded), over `base` (the module
    map) — an unrelated function's local `kws` must not contaminate a
    same-named binding elsewhere."""
    out = dict(base)
    for node in _walk_scope(scope):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        target = node.targets[0]
        if not isinstance(target, ast.Name):
            continue
        nums, names = _dict_donation(node.value)
        if nums or names:
            prior = out.get(target.id, (set(), set()))
            out[target.id] = (prior[0] | nums, prior[1] | names)
    return out


def _donation_markers(
    call: ast.Call, kws_map: Dict[str, Tuple[Set[int], Set[str]]]
) -> Tuple[Set[int], Set[str]]:
    nums: Set[int] = set()
    names: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            nums |= _literal_ints(kw.value) or set()
        elif kw.arg == "donate_argnames":
            names |= _literal_strs(kw.value) or set()
        elif kw.arg is None and isinstance(kw.value, ast.Name):
            extra_nums, extra_names = kws_map.get(kw.value.id, (set(), set()))
            nums |= extra_nums
            names |= extra_names
    return nums, names


def _donating_bindings(tree: ast.AST, index: _ModuleIndex) -> Dict[str, _Donor]:
    """name -> donor info, for jit-with-donation bindings and
    @partial(jax.jit, ...)/@jax.jit(...) decorated functions, covering
    literal donate_argnums=, donate_argnames=, and resolvable `**kws`
    (resolved scope-aware: the enclosing function's bindings over the
    module's)."""
    donors: Dict[str, _Donor] = {}

    def handle_scope(scope: ast.AST, kws_map: Dict[str, Tuple[Set[int], Set[str]]]) -> None:
        for node in _walk_scope(scope):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target = node.targets[0]
                value = node.value
                if (
                    isinstance(target, ast.Name)
                    and isinstance(value, ast.Call)
                    and _callee_name(value.func) == "jit"
                ):
                    nums, names = _donation_markers(value, kws_map)
                    if not nums and not names:
                        continue
                    params: Optional[List[str]] = None
                    if value.args and isinstance(value.args[0], ast.Name):
                        defs = index.functions.get(value.args[0].id, [])
                        if len(defs) == 1:
                            params = _positional_params(defs[0])
                    donors[target.id] = _Donor(nums, names, params)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for deco in node.decorator_list:
                    if isinstance(deco, ast.Call) and _callee_name(deco.func) in (
                        "jit",
                        "partial",
                    ):
                        if _callee_name(deco.func) != "jit" and not any(
                            _callee_name(a) == "jit" for a in deco.args
                        ):
                            continue
                        nums, names = _donation_markers(deco, kws_map)
                        if nums or names:
                            donors[node.name] = _Donor(
                                nums, names, _positional_params(node)
                            )

    module_map = _scope_kws_map(tree, {})
    handle_scope(tree, module_map)
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            handle_scope(node, _scope_kws_map(node, module_map))
    return donors


class _DonationFlow:
    """Per-scope statement-ordered scan: donated names -> first donation site;
    a later load before a rebind is a use-after-donate."""

    def __init__(self, rule: Rule, ctx: FileContext, donors: Dict[str, _Donor]) -> None:
        self.rule = rule
        self.ctx = ctx
        self.donors = donors
        self.findings: List[Finding] = []

    def _expr_events(self, expr: ast.AST) -> List[Tuple[int, int, str, str, str]]:
        """(lineno, col, kind, name, extra) events inside one expression, in
        source order. kind: 'load' | 'donate'."""
        events: List[Tuple[int, int, str, str, str]] = []
        stack = [expr]
        donated_nodes: Set[ast.AST] = set()
        calls: List[ast.Call] = []
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            if isinstance(node, ast.Call):
                calls.append(node)
            stack.extend(ast.iter_child_nodes(node))
        for call in calls:
            fname = _callee_name(call.func)
            donor = self.donors.get(fname)
            if donor is None or not isinstance(call.func, ast.Name):
                continue
            donated_args: List[ast.Name] = []
            for pos in donor.positions:
                if pos < len(call.args) and isinstance(call.args[pos], ast.Name):
                    donated_args.append(call.args[pos])
            for kw in call.keywords:
                if kw.arg in donor.names and isinstance(kw.value, ast.Name):
                    donated_args.append(kw.value)
            for arg in donated_args:
                donated_nodes.add(arg)
                events.append(
                    (
                        call.end_lineno or call.lineno,
                        getattr(call, "end_col_offset", 0),
                        "donate",
                        arg.id,
                        fname,
                    )
                )
        for node in ast.walk(expr):
            if (
                isinstance(node, ast.Name)
                and isinstance(node.ctx, ast.Load)
                and node not in donated_nodes
            ):
                events.append((node.lineno, node.col_offset, "load", node.id, ""))
        events.sort(key=lambda e: (e[0], e[1]))
        return events

    def run(self, body: List[ast.stmt]) -> None:
        self.state: Dict[str, Tuple[int, str]] = {}
        self._block(body)

    def _apply_expr(self, expr: ast.AST) -> None:
        # Two passes: first discover donations (to know which loads matter),
        # then replay events in order.
        for lineno, _col, kind, name, via in self._expr_events(expr):
            if kind == "donate":
                self.state[name] = (lineno, via)
            elif kind == "load" and name in self.state:
                donated_line, via = self.state[name]
                if lineno >= donated_line and not self.ctx.noqa(lineno, self.rule.id):
                    self.findings.append(
                        Finding(
                            self.rule.id,
                            self.ctx.rel,
                            lineno,
                            f"'{name}' is read after being donated to "
                            f"'{via}' at line {donated_line} "
                            f"— donated buffers may already be reused; "
                            f"snapshot before the call or rebind the result "
                            f"(STX008)",
                        )
                    )
                    del self.state[name]

    def _reset(self, target: ast.AST) -> None:
        for name in _assigned_names(target):
            self.state.pop(name, None)

    def _block(self, body: List[ast.stmt]) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue
            if isinstance(stmt, ast.Assign):
                self._apply_expr(stmt.value)
                for target in stmt.targets:
                    self._reset(target)
            elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                if stmt.value is not None:
                    self._apply_expr(stmt.value)
                self._reset(stmt.target)
            elif isinstance(stmt, ast.If):
                self._apply_expr(stmt.test)
                saved = dict(self.state)
                self._block(stmt.body)
                self.state = dict(saved)
                self._block(stmt.orelse)
                # Conservative merge: donation survives a branch only if it
                # survived the else-branch state we are left with.
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._apply_expr(stmt.iter)
                self._reset(stmt.target)
                self._block(stmt.body)
                self._block(stmt.orelse)
            elif isinstance(stmt, ast.While):
                self._apply_expr(stmt.test)
                self._block(stmt.body)
                self._block(stmt.orelse)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    self._apply_expr(item.context_expr)
                    if item.optional_vars is not None:
                        self._reset(item.optional_vars)
                self._block(stmt.body)
            elif isinstance(stmt, ast.Try):
                self._block(stmt.body)
                for handler in stmt.handlers:
                    self._block(handler.body)
                self._block(stmt.orelse)
                self._block(stmt.finalbody)
            else:
                for child in ast.iter_child_nodes(stmt):
                    if isinstance(child, (ast.expr,)):
                        self._apply_expr(child)


def _check(rule: Rule, ctx: FileContext) -> List[Finding]:
    if not ctx.rel.startswith("stoix_tpu" + os.sep):
        return []
    index = ctx.memo("module_index", lambda: _ModuleIndex(ctx.tree))
    donors = _donating_bindings(ctx.tree, index)
    if not donors:
        return []
    findings: List[Finding] = []
    scopes: List[List[ast.stmt]] = [getattr(ctx.tree, "body", [])]
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scopes.append(node.body)
    for scope in scopes:
        flow = _DonationFlow(rule, ctx, donors)
        flow.run(scope)
        findings.extend(flow.findings)
    return findings


RULE = register(
    Rule(
        id="STX008",
        order=95,
        title="donated-buffer misuse",
        rationale="Reading a variable after passing it as a donated argument "
        "is a use-after-free on its HBM buffers; the runner's snapshot "
        "discipline exists precisely to prevent this.",
        check_file=_check,
        flag_snippets=(
            # Read-after-donate of the un-rebound variable.
            "import jax\n\nstep = jax.jit(update, donate_argnums=(0,))\n\n\n"
            "def run(state, batch):\n"
            "    out = step(state, batch)\n"
            "    loss = state.loss\n"
            "    return out, loss\n",
            # donate_argnames: resolved through the wrapped signature, so the
            # POSITIONAL callsite is still covered.
            "import jax\n\n\ndef update(state, batch):\n"
            "    return state\n\n\n"
            'step = jax.jit(update, donate_argnames=("state",))\n\n\n'
            "def run(state, batch):\n"
            "    out = step(state, batch)\n"
            "    return out, state.loss\n",
            # The **donate kill-switch idiom (runner.py/anakin.py): the
            # donating branch is taken — donation-on must be safe.
            "import jax, os\n\ndonate = {} if os.environ.get('NO_DONATE') "
            "else {'donate_argnums': (0,)}\nstep = jax.jit(update, **donate)\n\n\n"
            "def run(state):\n"
            "    out = step(state)\n"
            "    return out, state\n",
        ),
        clean_snippets=(
            # Rebinding the result is the blessed idiom.
            "import jax\n\nstep = jax.jit(update, donate_argnums=(0,))\n\n\n"
            "def run(state, batch):\n"
            "    state = step(state, batch)\n"
            "    return state.loss\n",
            # Non-donated positions are free to be re-read.
            "import jax\n\nstep = jax.jit(update, donate_argnums=(0,))\n\n\n"
            "def run(state, batch):\n"
            "    out = step(state, batch)\n"
            "    return out, batch.shape\n",
            # donate_argnames with the result rebound; the non-donated batch
            # keyword stays readable.
            "import jax\n\n\ndef update(state, batch):\n"
            "    return state\n\n\n"
            'step = jax.jit(update, donate_argnames=("state",))\n\n\n'
            "def run(state, batch):\n"
            "    state = step(state, batch=batch)\n"
            "    return state, batch.shape\n",
        ),
    )
)
