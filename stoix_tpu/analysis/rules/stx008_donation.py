"""STX008 — donated-buffer misuse.

When a function is jitted with `donate_argnums`, the caller hands the
argument's buffers to XLA for reuse: reading the SAME variable after the call
is a use-after-free that jax only sometimes catches (a deleted-buffer error
on a good day, silently recycled memory inside a wedged runtime on a bad
one). The pipelined runner's whole snapshot discipline exists because of this
(docs/DESIGN.md §2.1, systems/anakin.py `shardmap_learner`).

Detection: file-wide, find bindings `step = jax.jit(fn, donate_argnums=...)`
(and `@partial(jax.jit, donate_argnums=...)` decorated defs) with a LITERAL
argnums; then, per scope, a `Name` passed at a donated position whose value
is loaded again after the call — without an intervening rebind — is flagged.
Rebinding (`state = step(state)`) is the blessed idiom and resets tracking.

Blind spots (docs/DESIGN.md §2.5): `donate_argnums` built dynamically
(`**donate` — the runner's kill-switch pattern), donation through
`donate_argnames`, aliasing, and cross-function escapes. The rule is a
tripwire for the common refactor accident, not a proof of safety.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from stoix_tpu.analysis.core import FileContext, Finding, Rule, register
from stoix_tpu.analysis.jitreach import assigned_names as _assigned_names
from stoix_tpu.analysis.jitreach import callee_name as _callee_name


def _literal_argnums(call: ast.Call) -> Optional[Set[int]]:
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        value = kw.value
        if isinstance(value, ast.Constant) and isinstance(value.value, int):
            return {value.value}
        if isinstance(value, (ast.Tuple, ast.List)):
            out = set()
            for elt in value.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                    out.add(elt.value)
                else:
                    return None
            return out
    return None


def _donating_bindings(tree: ast.AST) -> Dict[str, Set[int]]:
    """name -> donated positions, for jit-with-donation bindings and
    @partial(jax.jit, donate_argnums=...) decorated functions."""
    donors: Dict[str, Set[int]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
            value = node.value
            if (
                isinstance(target, ast.Name)
                and isinstance(value, ast.Call)
                and _callee_name(value.func) == "jit"
            ):
                argnums = _literal_argnums(value)
                if argnums:
                    donors[target.id] = argnums
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for deco in node.decorator_list:
                if isinstance(deco, ast.Call) and _callee_name(deco.func) in (
                    "jit",
                    "partial",
                ):
                    argnums = _literal_argnums(deco)
                    if argnums and (
                        _callee_name(deco.func) == "jit"
                        or any(_callee_name(a) == "jit" for a in deco.args)
                    ):
                        donors[node.name] = argnums
    return donors


class _DonationFlow:
    """Per-scope statement-ordered scan: donated names -> first donation site;
    a later load before a rebind is a use-after-donate."""

    def __init__(self, rule: Rule, ctx: FileContext, donors: Dict[str, Set[int]]) -> None:
        self.rule = rule
        self.ctx = ctx
        self.donors = donors
        self.findings: List[Finding] = []

    def _expr_events(self, expr: ast.AST) -> List[Tuple[int, int, str, str, str]]:
        """(lineno, col, kind, name, extra) events inside one expression, in
        source order. kind: 'load' | 'donate'."""
        events: List[Tuple[int, int, str, str, str]] = []
        stack = [expr]
        donated_nodes: Set[ast.AST] = set()
        calls: List[ast.Call] = []
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            if isinstance(node, ast.Call):
                calls.append(node)
            stack.extend(ast.iter_child_nodes(node))
        for call in calls:
            fname = _callee_name(call.func)
            positions = self.donors.get(fname)
            if not positions or not isinstance(call.func, ast.Name):
                continue
            for pos in positions:
                if pos < len(call.args) and isinstance(call.args[pos], ast.Name):
                    arg = call.args[pos]
                    donated_nodes.add(arg)
                    events.append(
                        (
                            call.end_lineno or call.lineno,
                            getattr(call, "end_col_offset", 0),
                            "donate",
                            arg.id,
                            fname,
                        )
                    )
        for node in ast.walk(expr):
            if (
                isinstance(node, ast.Name)
                and isinstance(node.ctx, ast.Load)
                and node not in donated_nodes
            ):
                events.append((node.lineno, node.col_offset, "load", node.id, ""))
        events.sort(key=lambda e: (e[0], e[1]))
        return events

    def run(self, body: List[ast.stmt]) -> None:
        self.state: Dict[str, Tuple[int, str]] = {}
        self._block(body)

    def _apply_expr(self, expr: ast.AST) -> None:
        # Two passes: first discover donations (to know which loads matter),
        # then replay events in order.
        for lineno, _col, kind, name, via in self._expr_events(expr):
            if kind == "donate":
                self.state[name] = (lineno, via)
            elif kind == "load" and name in self.state:
                donated_line, via = self.state[name]
                if lineno >= donated_line and not self.ctx.noqa(lineno, self.rule.id):
                    self.findings.append(
                        Finding(
                            self.rule.id,
                            self.ctx.rel,
                            lineno,
                            f"'{name}' is read after being donated to "
                            f"'{via}' (donate_argnums) at line {donated_line} "
                            f"— donated buffers may already be reused; "
                            f"snapshot before the call or rebind the result "
                            f"(STX008)",
                        )
                    )
                    del self.state[name]

    def _reset(self, target: ast.AST) -> None:
        for name in _assigned_names(target):
            self.state.pop(name, None)

    def _block(self, body: List[ast.stmt]) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue
            if isinstance(stmt, ast.Assign):
                self._apply_expr(stmt.value)
                for target in stmt.targets:
                    self._reset(target)
            elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                if stmt.value is not None:
                    self._apply_expr(stmt.value)
                self._reset(stmt.target)
            elif isinstance(stmt, ast.If):
                self._apply_expr(stmt.test)
                saved = dict(self.state)
                self._block(stmt.body)
                self.state = dict(saved)
                self._block(stmt.orelse)
                # Conservative merge: donation survives a branch only if it
                # survived the else-branch state we are left with.
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._apply_expr(stmt.iter)
                self._reset(stmt.target)
                self._block(stmt.body)
                self._block(stmt.orelse)
            elif isinstance(stmt, ast.While):
                self._apply_expr(stmt.test)
                self._block(stmt.body)
                self._block(stmt.orelse)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    self._apply_expr(item.context_expr)
                    if item.optional_vars is not None:
                        self._reset(item.optional_vars)
                self._block(stmt.body)
            elif isinstance(stmt, ast.Try):
                self._block(stmt.body)
                for handler in stmt.handlers:
                    self._block(handler.body)
                self._block(stmt.orelse)
                self._block(stmt.finalbody)
            else:
                for child in ast.iter_child_nodes(stmt):
                    if isinstance(child, (ast.expr,)):
                        self._apply_expr(child)


def _check(rule: Rule, ctx: FileContext) -> List[Finding]:
    if not ctx.rel.startswith("stoix_tpu" + os.sep):
        return []
    donors = _donating_bindings(ctx.tree)
    if not donors:
        return []
    findings: List[Finding] = []
    scopes: List[List[ast.stmt]] = [getattr(ctx.tree, "body", [])]
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scopes.append(node.body)
    for scope in scopes:
        flow = _DonationFlow(rule, ctx, donors)
        flow.run(scope)
        findings.extend(flow.findings)
    return findings


RULE = register(
    Rule(
        id="STX008",
        order=95,
        title="donated-buffer misuse",
        rationale="Reading a variable after passing it as a donated argument "
        "is a use-after-free on its HBM buffers; the runner's snapshot "
        "discipline exists precisely to prevent this.",
        check_file=_check,
        flag_snippets=(
            # Read-after-donate of the un-rebound variable.
            "import jax\n\nstep = jax.jit(update, donate_argnums=(0,))\n\n\n"
            "def run(state, batch):\n"
            "    out = step(state, batch)\n"
            "    loss = state.loss\n"
            "    return out, loss\n",
        ),
        clean_snippets=(
            # Rebinding the result is the blessed idiom.
            "import jax\n\nstep = jax.jit(update, donate_argnums=(0,))\n\n\n"
            "def run(state, batch):\n"
            "    state = step(state, batch)\n"
            "    return state.loss\n",
            # Non-donated positions are free to be re-read.
            "import jax\n\nstep = jax.jit(update, donate_argnums=(0,))\n\n\n"
            "def run(state, batch):\n"
            "    out = step(state, batch)\n"
            "    return out, batch.shape\n",
        ),
    )
)
