"""STX015 — blocking call while holding a lock.

A `.get()`/`.result()`/`.join()`/`.wait()` executed lexically inside a
held-lock range is the classic deadlock shape: the blocked holder waits on
a peer that needs the very lock it is holding (or, with a timeout, turns
every contending thread's latency into the timeout). The threadmodel's
lock-held ranges (`with lock:` bodies plus `acquire()`/`release()` pairs)
supply the regions; the call set is STX004's blocking attributes plus the
bounded forms (`join`/`result`/`get_blocking`/`barrier`/`wait`) — bounded
or not, sleeping inside a critical section serializes the system on the
slowest waiter.

Exempt, deliberately:

  * `cond.wait()`/`wait_for()` ON the held condition itself — the condition
    variable RELEASES its lock while waiting; that is the entire point of
    the batcher's `with self._cond: self._cond.wait(...)` idiom.
  * Calls with positional arguments (`d.get(key)`, `", ".join(parts)`):
    statically ambiguous with the non-blocking dict/str methods, exactly
    the STX004 screening.
  * `block=False` forms — they never block.
"""

from __future__ import annotations

import ast
import os
from typing import List

from stoix_tpu.analysis import threadmodel
from stoix_tpu.analysis.core import FileContext, Finding, Rule, register

# STX004's unbounded set plus the bounded blocking forms the issue names.
_BLOCKING_ATTRS = {"get", "result", "join", "get_blocking", "barrier", "wait", "wait_for"}
# Attributes exempt when called on the HELD lock object itself.
_SAME_OBJECT_OK = {"wait", "wait_for"}
_ALLOWLIST: frozenset = frozenset()


def _check(rule: Rule, ctx: FileContext) -> List[Finding]:
    if not ctx.rel.startswith("stoix_tpu" + os.sep) or ctx.rel in _ALLOWLIST:
        return []
    model = threadmodel.for_context(ctx)
    if not model.lock_keys:
        return []
    findings: List[Finding] = []
    scopes = [None] + list(
        n
        for n in ast.walk(ctx.tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    )
    for fn in scopes:
        scope = ctx.tree if fn is None else fn
        for node in threadmodel.walk_scope(scope):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _BLOCKING_ATTRS
            ):
                continue
            held = model.held_at(fn, node.lineno)
            if not held:
                continue
            if node.args:
                continue  # positionally-keyed forms are ambiguous (STX004)
            kwargs = {kw.arg: kw.value for kw in node.keywords}
            block = kwargs.get("block")
            if isinstance(block, ast.Constant) and block.value is False:
                continue
            receiver = model.binding_key(node.func.value, fn)
            if (
                receiver in held
                and node.func.attr in _SAME_OBJECT_OK
            ):
                continue  # condition-variable wait releases the held lock
            if ctx.noqa(node.lineno, rule.id):
                continue
            findings.append(
                Finding(
                    rule.id,
                    ctx.rel,
                    node.lineno,
                    f"blocking `.{node.func.attr}()` while holding "
                    f"{'/'.join(sorted(k.split(':', 1)[1] for k in held))} — "
                    f"a peer that needs this lock to make progress deadlocks "
                    f"against the holder; move the wait outside the critical "
                    f"section or use the condition-variable idiom (STX015)",
                )
            )
    findings.sort(key=lambda f: f.line)
    return findings


RULE = register(
    Rule(
        id="STX015",
        order=101,
        title="blocking while holding a lock",
        rationale="Sleeping inside a critical section either deadlocks "
        "outright (the producer needs the consumer's lock) or serializes "
        "every contender on the slowest waiter; waits belong outside the "
        "lock, or on the lock's own condition variable.",
        allowlist=_ALLOWLIST,
        check_file=_check,
        flag_snippets=(
            # Queue get inside a held lock: producer needs the lock to put.
            "import threading\n\n\nclass Worker:\n"
            "    def __init__(self, q):\n"
            "        self._lock = threading.Lock()\n"
            "        self._q = q\n\n"
            "    def step(self):\n"
            "        with self._lock:\n"
            "            item = self._q.get(timeout=1.0)\n"
            "        return item\n",
            # join() while holding the registry lock.
            "import threading\n\n_lock = threading.Lock()\n\n\n"
            "def stop(worker):\n"
            "    with _lock:\n"
            "        worker.join(timeout=5.0)\n",
            # future.result inside acquire/release pairing.
            "import threading\n\n\nclass Pool:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n\n"
            "    def flush(self, fut):\n"
            "        self._lock.acquire()\n"
            "        out = fut.result(timeout=2.0)\n"
            "        self._lock.release()\n"
            "        return out\n",
        ),
        clean_snippets=(
            # The condition-variable idiom: wait ON the held condition.
            "import threading\n\n\nclass Batcher:\n"
            "    def __init__(self):\n"
            "        self._cond = threading.Condition()\n"
            "        self._pending = []\n\n"
            "    def next_batch(self, timeout):\n"
            "        with self._cond:\n"
            "            if not self._pending:\n"
            "                self._cond.wait(timeout=timeout)\n"
            "            return list(self._pending)\n",
            # The wait happens after the critical section.
            "import threading\n\n\nclass Worker:\n"
            "    def __init__(self, q):\n"
            "        self._lock = threading.Lock()\n"
            "        self._q = q\n"
            "        self._closed = False\n\n"
            "    def step(self):\n"
            "        with self._lock:\n"
            "            closed = self._closed\n"
            "        if closed:\n"
            "            return None\n"
            "        return self._q.get(timeout=1.0)\n",
            # dict.get under a lock is a keyed read, not a wait.
            "import threading\n\n\nclass Registry:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._table = {}\n\n"
            "    def lookup(self, key):\n"
            "        with self._lock:\n"
            "            return self._table.get(key)\n",
            # block=False never blocks.
            "import threading\n\n\nclass Drainer:\n"
            "    def __init__(self, q):\n"
            "        self._lock = threading.Lock()\n"
            "        self._q = q\n\n"
            "    def drain_one(self):\n"
            "        with self._lock:\n"
            "            return self._q.get(block=False)\n",
        ),
    )
)
