"""STX002 — observability ownership.

`stoix_tpu/` library code must not use bare `print(` (status lines go through
`observability.get_logger`, metrics through the registry — stdout belongs to
machine-readable output contracts) nor declare ad-hoc module-level stats
accumulators (ALL_CAPS names bound to empty `{}`/`dict()` — the
`LAST_RUN_STATS` pattern; publish to the metrics registry and expose an
`observability.RunStats` view instead).

Allowlisted: utils/logger.py (the ConsoleSink IS the console), sweep.py
(JSON-lines stdout contract), and analysis/__main__.py (the lint gate's own
CLI — its stdout is the findings contract CI parses). scripts/ and bench.py
are not library code.

Checker migrated unchanged from scripts/lint.py (PR 2).
"""

from __future__ import annotations

import ast
import os
from typing import List

from stoix_tpu.analysis.core import FileContext, Finding, Rule, register

_ALLOWLIST = frozenset(
    {
        os.path.join("stoix_tpu", "utils", "logger.py"),
        os.path.join("stoix_tpu", "sweep.py"),
        os.path.join("stoix_tpu", "analysis", "__main__.py"),
    }
)


def _is_empty_dict_value(node: ast.AST) -> bool:
    if isinstance(node, ast.Dict) and not node.keys:
        return True
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Name)
        and node.func.id == "dict"
        and not node.args
        and not node.keywords
    )


def _check(rule: Rule, ctx: FileContext) -> List[Finding]:
    rel = ctx.rel
    if not rel.startswith("stoix_tpu" + os.sep) or rel in _ALLOWLIST:
        return []
    findings = []

    def _line_ok(lineno: int) -> bool:
        return "noqa" in ctx.line(lineno)

    for node in ast.walk(ctx.tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "print"
            and not _line_ok(node.lineno)
        ):
            findings.append(
                Finding(
                    "STX002",
                    rel,
                    node.lineno,
                    "bare print() in library code — use "
                    "observability.get_logger (status) or the metrics registry "
                    "(STX002)",
                )
            )
    # Module-level ALL_CAPS empty-dict accumulators (body-level only: class
    # attributes and function locals are fine).
    for node in getattr(ctx.tree, "body", []):
        targets, value = [], None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        for target in targets:
            if (
                isinstance(target, ast.Name)
                and target.id.isupper()
                and value is not None
                and _is_empty_dict_value(value)
                and not _line_ok(node.lineno)
            ):
                findings.append(
                    Finding(
                        "STX002",
                        rel,
                        node.lineno,
                        f"ad-hoc module-level stats dict "
                        f"'{target.id}' — publish to the metrics registry and "
                        f"expose an observability.RunStats view (STX002)",
                    )
                )
    return findings


RULE = register(
    Rule(
        id="STX002",
        order=30,
        title="observability ownership",
        rationale="stdout belongs to machine-readable contracts and ad-hoc "
        "module-level stats dicts bypass the metrics registry every exporter "
        "reads; route status through get_logger and stats through RunStats.",
        allowlist=_ALLOWLIST,
        check_file=_check,
        flag_snippets=(
            'print("hello")\n',
            "LAST_RUN_STATS: dict = {}\nOTHER = dict()\n",
        ),
        clean_snippets=(
            'print("x")  # noqa: STX002\n'
            "cache = {}\n"
            "TABLE = {'a': 1}\n"
            "STATS = RunStats()\n"
            "class C:\n    BUF = {}\n"
            "def f():\n    ACC = {}\n    print\n",
        ),
    )
)
