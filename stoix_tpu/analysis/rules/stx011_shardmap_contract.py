"""STX011 — shard_map contract checks (arity + replication claims).

Two contracts every `shard_map(fn, mesh=..., in_specs=..., out_specs=...)`
must honor, both checked statically against the mesh model:

  1. **in_specs arity vs the wrapped function's signature.** A literal
     `in_specs` tuple must be satisfiable by `fn`'s positional parameters
     (resolved module-locally like jitreach does, `functools.partial`-aware:
     bound arguments drop out of the count). Passing 2 specs to a 3-arg
     per-shard function is a TypeError only at trace time — on the
     multi-device launch, after minutes of setup.

  2. **out_specs replication claims.** An out leaf that is a CLOSED literal
     spec not naming mesh axis A claims the output is REPLICATED over A. If
     any in leaf shards over A and the wrapped function's body (transitively
     through module-local helpers) contains no collective reduction over A
     (`psum`/`pmean`/... with axis A, or any helper taking an
     `axis_name(s)=` literal naming A), each shard computes its own value and
     jax stitches shard 0's — the silent-wrong-answer class. `check_vma=True`
     catches this at trace time; this rule catches it at lint time, and
     `check_vma=False` sites (the Anakin update-batch pattern) have no other
     net at all.

Conservative by construction: unresolvable `fn` expressions, opaque/variable
specs, and bodies containing a collective with a VARIABLE axis (axis-generic
library code like ring_attention) skip the corresponding check rather than
guess.
"""

from __future__ import annotations

import ast
import os
from typing import FrozenSet, List, Optional, Set, Tuple

from stoix_tpu.analysis import meshmodel
from stoix_tpu.analysis.core import FileContext, Finding, Rule, register
from stoix_tpu.analysis.jitreach import _ModuleIndex, callee_name as _callee_name
from stoix_tpu.analysis.rules.stx007_collective_axes import _AXIS_KWARGS, _COLLECTIVES


def _resolve_wrapped(
    index: _ModuleIndex, expr: Optional[ast.AST]
) -> Tuple[Optional[List[ast.AST]], int, FrozenSet[str]]:
    """(function nodes, n positional args partial-bound, kw names bound)."""
    if expr is None:
        return None, 0, frozenset()
    if isinstance(expr, ast.Lambda):
        return [expr], 0, frozenset()
    if isinstance(expr, ast.Name):
        defs = index.functions.get(expr.id)
        if defs:
            return list(defs), 0, frozenset()
        return None, 0, frozenset()
    if (
        isinstance(expr, ast.Call)
        and _callee_name(expr.func) == "partial"
        and expr.args
    ):
        inner, n_pos, kws = _resolve_wrapped(index, expr.args[0])
        if inner is None:
            return None, 0, frozenset()
        bound_kws = frozenset(kw.arg for kw in expr.keywords if kw.arg)
        return inner, n_pos + len(expr.args) - 1, kws | bound_kws
    return None, 0, frozenset()


def _param_bounds(
    fn: ast.AST, n_bound_pos: int, bound_kws: FrozenSet[str]
) -> Tuple[int, Optional[int]]:
    """(required, maximum) positional-arg count after partial binding;
    maximum is None for *args."""
    args = fn.args
    params = list(getattr(args, "posonlyargs", [])) + list(args.args)
    n_defaults = len(args.defaults)
    flagged = [
        (p.arg, i >= len(params) - n_defaults) for i, p in enumerate(params)
    ]
    flagged = flagged[n_bound_pos:]
    flagged = [(name, has_default) for name, has_default in flagged if name not in bound_kws]
    required = sum(1 for _name, has_default in flagged if not has_default)
    maximum = None if args.vararg else len(flagged)
    return required, maximum


def _fn_label(expr: Optional[ast.AST]) -> str:
    if isinstance(expr, ast.Name):
        return f"'{expr.id}'"
    if isinstance(expr, ast.Lambda):
        return "<lambda>"
    if isinstance(expr, ast.Call) and expr.args and isinstance(expr.args[0], ast.Name):
        return f"'{expr.args[0].id}'"
    return "<wrapped function>"


def _axis_value_literals(node: ast.AST) -> Tuple[List[str], bool]:
    """(axis literals, fully_literal) for an axis_name(s) value. A variable
    (or a tuple with variable entries) is not fully literal — the body may
    reduce over ANY axis through it."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value], True
    if isinstance(node, (ast.Tuple, ast.List)):
        literals = [
            elt.value
            for elt in node.elts
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str)
        ]
        return literals, len(literals) == len(node.elts)
    return [], False


def _collective_axes(
    index: _ModuleIndex, roots: List[ast.AST]
) -> Tuple[Set[str], bool]:
    """(axis literals reduced over, wildcard) reachable from `roots`.

    Walks each root's whole subtree (nested defs included — the minibatch/
    epoch closures live inside the per-shard body) and follows references to
    module-local functions (the reward-stats-helper idiom). A collective or
    axis_name(s)= kwarg holding a VARIABLE sets wildcard: the body may reduce
    over any axis, so no replication claim can be disproved.
    """
    axes: Set[str] = set()
    wildcard = False
    visited: Set[int] = set()
    stack = list(roots)
    while stack:
        fn = stack.pop()
        if id(fn) in visited:
            continue
        visited.add(id(fn))
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                stack.extend(index.functions.get(node.id, []))
            if not isinstance(node, ast.Call):
                continue
            callee = _callee_name(node.func)
            if callee in _COLLECTIVES and len(node.args) >= 2:
                literals, fully = _axis_value_literals(node.args[1])
                axes.update(literals)
                if not fully:
                    wildcard = True
            for kw in node.keywords:
                if kw.arg in _AXIS_KWARGS:
                    literals, fully = _axis_value_literals(kw.value)
                    axes.update(literals)
                    if not fully:
                        wildcard = True
    return axes, wildcard


def _check(rule: Rule, ctx: FileContext) -> List[Finding]:
    if not ctx.rel.startswith("stoix_tpu" + os.sep):
        return []
    model = meshmodel.for_context(ctx)
    if not model.shard_map_sites:
        return []
    index = ctx.memo("module_index", lambda: _ModuleIndex(ctx.tree))
    findings: List[Finding] = []
    for site in model.shard_map_sites:
        lineno = site.call.lineno
        if ctx.noqa(lineno, rule.id):
            continue
        defs, n_pos, bound_kws = _resolve_wrapped(index, site.fn_expr)
        label = _fn_label(site.fn_expr)

        # 1. in_specs tuple arity vs the wrapped signature. Flag only when
        # EVERY resolved candidate def rejects the arity (same-name redefs).
        if site.in_top_arity is not None and defs:
            bounds = [_param_bounds(fn, n_pos, bound_kws) for fn in defs]
            arity = site.in_top_arity
            if all(
                arity < required or (maximum is not None and arity > maximum)
                for required, maximum in bounds
            ):
                required, maximum = bounds[0]
                expect = (
                    str(required)
                    if maximum == required
                    else f"{required}..{maximum if maximum is not None else '*'}"
                )
                findings.append(
                    Finding(
                        rule.id,
                        ctx.rel,
                        lineno,
                        f"shard_map in_specs has {arity} entries but {label} "
                        f"takes {expect} positional argument(s) — this "
                        f"TypeErrors only at trace time on the real launch "
                        f"(STX011)",
                    )
                )

        # 2. out_specs replication claims vs reductions in the body.
        in_axes = {a for leaf in site.in_leaves for a, _ in leaf.literal_axes()}
        if not in_axes or not defs:
            continue
        closed_out = [leaf for leaf in site.out_leaves if leaf.closed]
        if not closed_out:
            continue
        body_axes, wildcard = _collective_axes(index, defs)
        if wildcard:
            continue
        unreduced = sorted(
            axis
            for axis in in_axes
            if axis not in body_axes
            and any(not leaf.mentions(axis) for leaf in closed_out)
        )
        for axis in unreduced:
            findings.append(
                Finding(
                    rule.id,
                    ctx.rel,
                    lineno,
                    f"shard_map out_specs claim replication over mesh axis "
                    f"'{axis}' but {label} contains no collective reduction "
                    f"over '{axis}' — each shard computes a different value "
                    f"and the result is silently wrong on a multi-device "
                    f"run (STX011)",
                )
            )
    return findings


RULE = register(
    Rule(
        id="STX011",
        order=97,
        title="shard_map contract (arity + replication claims)",
        rationale="An in_specs tuple the wrapped signature cannot accept "
        "TypeErrors at trace time; an out_specs claiming replication with "
        "no reduction over the sharded axis returns shard-0's value as if "
        "it were global — the silent-wrong-answer class check_vma=False "
        "sites have no other net for.",
        check_file=_check,
        flag_snippets=(
            # Arity: two specs into a three-arg per-shard function.
            "from jax.sharding import PartitionSpec as P\n"
            "from stoix_tpu.parallel.mesh import shard_map\n\n\n"
            "def per_shard(state, batch, key):\n"
            "    return state\n\n\n"
            "def build(mesh):\n"
            "    return shard_map(per_shard, mesh=mesh,\n"
            '                     in_specs=(P(), P("data")), out_specs=P())\n',
            # Replication claimed with no reduction over the sharded axis.
            "from jax.sharding import PartitionSpec as P\n"
            "from stoix_tpu.parallel.mesh import shard_map\n\n\n"
            "def per_shard(batch):\n"
            "    return batch.mean()\n\n\n"
            "def build(mesh):\n"
            "    return shard_map(per_shard, mesh=mesh,\n"
            '                     in_specs=(P("data"),), out_specs=P())\n',
        ),
        clean_snippets=(
            # The blessed pattern: pmean over the sharded axis before a
            # replicated output; arity satisfiable via the default.
            "import jax\nfrom jax.sharding import PartitionSpec as P\n"
            "from stoix_tpu.parallel.mesh import shard_map\n\n\n"
            "def per_shard(batch, scale=1.0):\n"
            '    return jax.lax.pmean(batch.mean() * scale, axis_name="data")\n\n\n'
            "def build(mesh):\n"
            "    return shard_map(per_shard, mesh=mesh,\n"
            '                     in_specs=(P("data"),), out_specs=P())\n',
            # Output stays sharded: no replication claim to prove.
            "from jax.sharding import PartitionSpec as P\n"
            "from stoix_tpu.parallel.mesh import shard_map\n\n\n"
            "def per_shard(batch):\n"
            "    return batch * 2\n\n\n"
            "def build(mesh):\n"
            "    return shard_map(per_shard, mesh=mesh,\n"
            '                     in_specs=(P("data"),), out_specs=P("data"))\n',
            # Reduction via a module-local helper taking axis_names=.
            "from jax.sharding import PartitionSpec as P\n"
            "from stoix_tpu.parallel.mesh import shard_map\n"
            "from stoix_tpu.resilience import guards\n\n\n"
            "def per_shard(batch):\n"
            '    out, _ = guards.guard_update("skip", new=batch, old=batch,\n'
            '                                 axis_names=("data",))\n'
            "    return out\n\n\n"
            "def build(mesh):\n"
            "    return shard_map(per_shard, mesh=mesh,\n"
            '                     in_specs=(P("data"),), out_specs=P())\n',
        ),
    )
)
