"""STX022 — the fault-spec vocabulary and its uses must agree, both ways.

Fault injection is string-keyed: `faultinject._KNOWN` declares the
vocabulary, and tests/bench/soak/launcher arm specs via
`faultinject.configure("...")`, the `STOIX_TPU_FAULT` env var, or
`arch.fault_spec=` overrides. Both directions fail silently today: a spec
literal outside `_KNOWN` raises only when that code path actually runs
(PR 12's `swap_poison` shipped inert and was a drive-time discovery), and
a `_KNOWN` entry no test arms is a chaos drill that exists on paper only.
Backed by `analysis/opsmodel.py` fault-spec sites (spec strings parsed
from every arming form, constants resolved, dynamic name parts skipped;
docs/DESIGN.md §2.5):

  * file-scoped: every statically-parsable spec name at a use site must
    be in the vocabulary (the module's own `_KNOWN` if it defines one,
    else `resilience/faultinject.py`'s);
  * tree-scoped: every `_KNOWN` entry must be armed by at least one
    scanned test file — anchored at the `_KNOWN` entry so the fix site
    is the vocabulary, not a grep. Skipped when the scan includes no
    test files (a partial scan proves nothing about coverage).
"""

from __future__ import annotations

import ast
import functools
import os
from typing import List, Optional, Set, Tuple

from stoix_tpu.analysis.core import (
    FileContext,
    Finding,
    Rule,
    TreeContext,
    register,
)
from stoix_tpu.analysis import opsmodel

_FAULTINJECT_REL = os.path.join("stoix_tpu", "resilience", "faultinject.py")


@functools.lru_cache(maxsize=8)
def _disk_vocabulary(repo: str) -> Tuple[str, ...]:
    try:
        with open(os.path.join(repo, _FAULTINJECT_REL)) as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError):
        return ()
    return opsmodel.known_fault_specs(tree)


def _is_test_file(rel: str) -> bool:
    return rel.startswith("tests" + os.sep) or os.path.basename(
        rel
    ).startswith("test_")


def _check_file(rule: Rule, ctx: FileContext) -> List[Finding]:
    model = opsmodel.for_context(ctx)
    if not model.fault_sites:
        return []
    vocab = set(model.known_specs or _disk_vocabulary(ctx.repo))
    if not vocab:
        return []
    findings: List[Finding] = []
    for site in model.fault_sites:
        if ctx.noqa(site.lineno, rule.id):
            continue
        unknown = sorted(set(site.names) - vocab)
        for name in unknown:
            findings.append(
                Finding(
                    rule.id,
                    ctx.rel,
                    site.lineno,
                    f"fault spec '{name}' is not in faultinject._KNOWN — "
                    f"this arms nothing and fails only when the path "
                    f"runs (the inert-swap_poison class) (STX022)",
                )
            )
    return findings


def _known_entry_lines(ctx: FileContext) -> dict:
    """spec name -> lineno of its `_KNOWN` tuple entry (anchor points)."""
    lines = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
            if isinstance(target, ast.Name) and target.id == "_KNOWN":
                value = node.value
                if isinstance(value, (ast.Tuple, ast.List)):
                    for elt in value.elts:
                        if isinstance(elt, ast.Constant) and isinstance(
                            elt.value, str
                        ):
                            lines[elt.value] = elt.lineno
    return lines


def _check_tree(rule: Rule, tree_ctx: TreeContext) -> List[Finding]:
    vocab_ctx: Optional[FileContext] = None
    covered: Set[str] = set()
    any_tests = False
    for ctx in sorted(tree_ctx.files, key=lambda c: c.rel):
        model = opsmodel.for_context(ctx)
        if model.known_specs and (
            vocab_ctx is None or ctx.rel == _FAULTINJECT_REL
        ):
            vocab_ctx = ctx
        if _is_test_file(ctx.rel):
            any_tests = True
            for site in model.fault_sites:
                covered |= set(site.names)
    if vocab_ctx is None or not any_tests:
        return []
    entry_lines = _known_entry_lines(vocab_ctx)
    model = opsmodel.for_context(vocab_ctx)
    findings: List[Finding] = []
    for name in model.known_specs:
        if name in covered:
            continue
        lineno = entry_lines.get(name, 1)
        if vocab_ctx.noqa(lineno, rule.id):
            continue
        findings.append(
            Finding(
                rule.id,
                vocab_ctx.rel,
                lineno,
                f"fault spec '{name}' is declared in _KNOWN but no test "
                f"arms it — a chaos drill that exists on paper only "
                f"(STX022)",
            )
        )
    return findings


RULE = register(
    Rule(
        id="STX022",
        order=108,
        title="fault-spec vocabulary/use agreement",
        rationale="Fault injection is string-keyed with no compile-time "
        "check in either direction: a typo'd spec arms nothing until the "
        "drill runs, and a declared spec no test arms is untested chaos "
        "machinery. Parsing every arming form statically closes both "
        "gaps.",
        check_file=_check_file,
        check_tree=_check_tree,
        flag_snippets=(
            # Typo'd spec name at a use site (setenv form).
            '_KNOWN = ("actor_crash", "queue_stall")\n\n\n'
            "def test_drill(monkeypatch):\n"
            '    monkeypatch.setenv("STOIX_TPU_FAULT", "actor_cras:3")\n',
            # Unknown spec via an override literal (argv form).
            '_KNOWN = ("host_stall",)\n\n\n'  # noqa: STX022 — fixture text, not an armed spec
            "def job():\n"
            '    return ["arch.fault_spec=host_stal:2,host_stall"]\n',
        ),
        clean_snippets=(
            # Known names in every arming form; dynamic name parts and the
            # null spec are out of model, not violations.
            '_KNOWN = ("actor_crash", "host_stall", "shrink")\n'
            'DRILL = "actor_crash:2,shrink"\n\n\n'
            "def arm(monkeypatch, configure, stall_s, action, w):\n"
            "    configure(DRILL)\n"
            '    monkeypatch.setenv("STOIX_TPU_FAULT", "host_stall:1")\n'
            '    env = {"STOIX_TPU_FAULT": "shrink:1"}\n'
            '    argv = ["arch.fault_spec=~", "host_stall:%d" % stall_s]\n'
            '    argv.append(f"arch.fault_spec={action}:{w}")\n'
            "    return env, argv\n",
            # No fault traffic at all.
            "def test_nothing():\n    assert 1 + 1 == 2\n",
        ),
    )
)
