"""STX017 — thread/timer/executor lifecycle discipline.

Silent thread death and leaked background work are the concurrency bugs
that never crash anything — they just wedge shutdown, keep a process alive
after SIGTERM, or fire a hard-exit timer long after the run it was guarding
completed. Four checks over the threadmodel's spawn sites and binding
events (all module-local; a binding that escapes the module's sight —
returned from a factory, passed onward — is exempt, ownership transferred):

  * **Non-daemon thread never joined**: a `threading.Thread(...)` without
    `daemon=True`, started, whose binding receives no `.join()` anywhere in
    its scope — process exit will block on it forever. Daemon threads are
    exempt (the interpreter may reap them), which is exactly why the repo's
    supervised actors and pollers are all daemon + explicit join/stop.
  * **Timer armed with no reachable cancel()**: every armed
    `threading.Timer` needs a disarm path (the watchdog discipline:
    "stop() disarms the hard-exit timer") — a timer nobody can cancel WILL
    fire, including after the condition it guarded resolved.
  * **Executor never shut down**: a `ThreadPoolExecutor` binding with no
    `.shutdown()` and no `with` management leaks its workers.
  * **start() twice on one object**: a second `.start()` on the same
    binding with no intervening re-construction raises RuntimeError at
    runtime — in a supervisor that is the restart-path bug (factories must
    build a FRESH thread per restart).
"""

from __future__ import annotations

import os
from typing import List

from stoix_tpu.analysis import threadmodel
from stoix_tpu.analysis.core import FileContext, Finding, Rule, register

_ALLOWLIST: frozenset = frozenset()


def _display(binding: str) -> str:
    if binding.startswith("attr:"):
        return "self." + binding.split(".", 1)[1]
    return binding.rsplit(":", 1)[-1]


def _check(rule: Rule, ctx: FileContext) -> List[Finding]:
    if not ctx.rel.startswith("stoix_tpu" + os.sep) or ctx.rel in _ALLOWLIST:
        return []
    model = threadmodel.for_context(ctx)
    if not model.spawns:
        return []
    findings: List[Finding] = []

    for spawn in model.spawns:
        if spawn.escapes:
            continue  # ownership transferred (factory return, call arg...)
        events = model.bindings.get(spawn.binding) if spawn.binding else None
        started = spawn.started_inline or bool(events and events.starts)
        if not started:
            continue  # armed elsewhere (or never) — not this module's leak
        lineno = spawn.lineno
        if ctx.noqa(lineno, rule.id):
            continue
        if spawn.kind == "thread" and not spawn.daemon:
            if spawn.started_inline or not (events and events.joins):
                findings.append(
                    Finding(
                        rule.id,
                        ctx.rel,
                        lineno,
                        "non-daemon thread started but never joined on any "
                        "path — interpreter exit blocks on it forever; join "
                        "it in the owner's close()/stop(), or make it a "
                        "daemon with an explicit stop event (STX017)",
                    )
                )
        elif spawn.kind == "timer":
            if spawn.started_inline or not (events and events.cancels):
                findings.append(
                    Finding(
                        rule.id,
                        ctx.rel,
                        lineno,
                        "Timer armed with no reachable cancel() — it WILL "
                        "fire, including after the condition it guards has "
                        "resolved; every armed timer needs a disarm path "
                        "(the watchdog's stop()-cancels-the-hard-exit "
                        "discipline) (STX017)",
                    )
                )
        elif spawn.kind == "executor":
            if not (events and (events.shutdowns or events.ctx_managed)):
                findings.append(
                    Finding(
                        rule.id,
                        ctx.rel,
                        lineno,
                        "executor is never shut down — its worker threads "
                        "outlive the work; use `with` or call shutdown() "
                        "(STX017)",
                    )
                )

    # Executors are "started" by construction, not .start(): re-check the
    # never-started ones the loop above skipped.
    for spawn in model.spawns:
        if spawn.kind != "executor" or spawn.escapes or spawn.binding is None:
            continue
        events = model.bindings.get(spawn.binding)
        if events and (events.shutdowns or events.ctx_managed):
            continue
        if events and events.starts:
            continue  # already reported above
        if ctx.noqa(spawn.lineno, rule.id):
            continue
        findings.append(
            Finding(
                rule.id,
                ctx.rel,
                spawn.lineno,
                "executor is never shut down — its worker threads outlive "
                "the work; use `with` or call shutdown() (STX017)",
            )
        )

    # start() twice on one object without re-construction in between.
    for binding, events in model.bindings.items():
        by_fn: dict = {}
        for line, fn_id in events.starts:
            by_fn.setdefault(fn_id, []).append(line)
        assigns = sorted(events.assigns)
        for fn_id, lines in by_fn.items():
            lines.sort()
            for first, second in zip(lines, lines[1:]):
                rebound = any(
                    a_fn == fn_id and first < a_line <= second
                    for a_line, a_fn in assigns
                )
                if rebound:
                    continue
                if ctx.noqa(second, rule.id):
                    continue
                findings.append(
                    Finding(
                        rule.id,
                        ctx.rel,
                        second,
                        f"second start() on '{_display(binding)}' (first at "
                        f"line {first}) with no re-construction in between — "
                        f"threads are single-use; RuntimeError at runtime "
                        f"(STX017)",
                    )
                )
    findings.sort(key=lambda f: f.line)
    return findings


RULE = register(
    Rule(
        id="STX017",
        order=103,
        title="thread/timer/executor lifecycle",
        rationale="A non-daemon thread nobody joins wedges process exit; a "
        "timer nobody can cancel fires after its reason is gone; an executor "
        "nobody shuts down leaks workers; a reused Thread object raises. "
        "Each is invisible until shutdown or restart, the worst time.",
        allowlist=_ALLOWLIST,
        check_file=_check,
        flag_snippets=(
            # Non-daemon thread, started, never joined.
            "import threading\n\n\nclass Runner:\n"
            "    def __init__(self):\n"
            "        self._t = threading.Thread(target=self._run)\n\n"
            "    def start(self):\n"
            "        self._t.start()\n\n"
            "    def _run(self):\n"
            "        pass\n",
            # Timer armed, no cancel anywhere.
            "import threading\n\n\nclass Guard:\n"
            "    def arm(self, grace_s):\n"
            "        self._timer = threading.Timer(grace_s, self._fire)\n"
            "        self._timer.start()\n\n"
            "    def _fire(self):\n"
            "        pass\n",
            # start() twice on one object.
            "import threading\n\n\ndef restart(target):\n"
            "    t = threading.Thread(target=target, daemon=True)\n"
            "    t.start()\n"
            "    t.join(timeout=1.0)\n"
            "    t.start()\n",
            # Executor never shut down.
            "from concurrent.futures import ThreadPoolExecutor\n\n\n"
            "def fan_out(jobs):\n"
            "    pool = ThreadPoolExecutor(max_workers=4)\n"
            "    return [pool.submit(j) for j in jobs]\n",
        ),
        clean_snippets=(
            # Daemon + stop event + join: the poller discipline.
            "import threading\n\n\nclass Poller:\n"
            "    def __init__(self):\n"
            "        self._stop = threading.Event()\n"
            "        self._t = threading.Thread(target=self._run, daemon=True)\n\n"
            "    def start(self):\n"
            "        self._t.start()\n\n"
            "    def stop(self):\n"
            "        self._stop.set()\n"
            "        self._t.join(timeout=2.0)\n\n"
            "    def _run(self):\n"
            "        while not self._stop.wait(1.0):\n"
            "            pass\n",
            # Timer with a disarm path (the watchdog shape).
            "import threading\n\n\nclass Guard:\n"
            "    def arm(self, grace_s):\n"
            "        self._timer = threading.Timer(grace_s, self._fire)\n"
            "        self._timer.daemon = True\n"
            "        self._timer.start()\n\n"
            "    def disarm(self):\n"
            "        if self._timer is not None:\n"
            "            self._timer.cancel()\n\n"
            "    def _fire(self):\n"
            "        pass\n",
            # Factory return transfers ownership — the supervisor's idiom.
            "import threading\n\n\ndef actor_factory(actor_id, run):\n"
            "    def make():\n"
            "        return threading.Thread(target=run, name=f'actor-{actor_id}', daemon=True)\n"
            "    return make\n",
            # Restart with a FRESH construction between starts.
            "import threading\n\n\ndef restart(target):\n"
            "    t = threading.Thread(target=target, daemon=True)\n"
            "    t.start()\n"
            "    t.join(timeout=1.0)\n"
            "    t = threading.Thread(target=target, daemon=True)\n"
            "    t.start()\n",
            # Context-managed executor shuts down on exit.
            "from concurrent.futures import ThreadPoolExecutor\n\n\n"
            "def fan_out(jobs):\n"
            "    with ThreadPoolExecutor(max_workers=4) as pool:\n"
            "        return [f.result(timeout=30.0) for f in [pool.submit(j) for j in jobs]]\n",
        ),
    )
)
