"""STX007 — collective axis-name consistency.

Every axis-name LITERAL passed to a collective (`lax.pmean/psum/pmax/...`) or
to a stoix helper taking `axis_names=(...)` must be an axis that actually
exists: either declared by an enclosing-file `jax.vmap`/`jax.pmap`
(`axis_name="batch"`) or defined as a mesh axis by `stoix_tpu/parallel/`
(`create_mesh({"data": -1})`, tensor-parallel "model", ...).

This is the typo that only explodes on a multi-device run: on one device an
unbound `axis_name="dataa"` can silently reduce over nothing or fail deep in
compilation after minutes of tracing; on an 8-device TPU allocation it is a
burned allocation. The Podracer/Anakin style (everything in one jitted
program) makes the failure surface exactly at launch time — this rule moves
it to lint time.

Mesh-axis discovery is static: `stoix_tpu/parallel/*.py` is parsed for
dict-literal mesh specs (str keys, int sizes), `PartitionSpec` string
literals, and `axis*=`-parameter string defaults. Axis names passed as
VARIABLES (library helpers like `ring_attention(..., axis_name)`) are out of
scope — only literals are checked, so there are no false positives from
axis-generic code.
"""

from __future__ import annotations

import ast
import os
from typing import List, Set, Tuple

from stoix_tpu.analysis.core import FileContext, Finding, Rule, register
from stoix_tpu.analysis.jitreach import callee_name as _callee_name

_COLLECTIVES = {
    "pmean",
    "psum",
    "pmax",
    "pmin",
    "all_gather",
    "all_to_all",
    "ppermute",
    "pshuffle",
    "psum_scatter",
    "pswapaxes",
    "axis_index",
}
_DECLARING = {"vmap", "pmap"}
_AXIS_KWARGS = {"axis_name", "axis_names"}

_axes_cache: dict = {}


def declared_axes(repo: str) -> Set[str]:
    """Axis names that exist anywhere in the package: mesh axes parsed from
    stoix_tpu/parallel/*.py plus every `vmap/pmap(axis_name="...")` literal
    under stoix_tpu/ (the in-shard "batch" axis is declared by the shared
    off_policy_core/system files and consumed by siblings — declarations are
    a package-wide convention, uses are checked per literal). Cached per
    repo path."""
    cached = _axes_cache.get(repo)
    if cached is not None:
        return cached
    axes: Set[str] = set()
    package_dir = os.path.join(repo, "stoix_tpu")
    for root, dirs, files in os.walk(package_dir):
        dirs[:] = [d for d in dirs if d not in ("__pycache__", "configs")]
        in_parallel = os.path.basename(root) == "parallel" or os.sep + "parallel" in root
        for name in sorted(files):
            if not name.endswith(".py"):
                continue
            try:
                with open(os.path.join(root, name)) as f:
                    tree = ast.parse(f.read())
            except (OSError, SyntaxError):
                continue
            axes |= _file_declared_axes(tree)
            if not in_parallel:
                continue
            for node in ast.walk(tree):
                # {"data": -1} style mesh specs.
                if isinstance(node, ast.Dict):
                    keys_ok = node.keys and all(
                        isinstance(k, ast.Constant) and isinstance(k.value, str)
                        for k in node.keys
                    )
                    vals_ok = all(
                        isinstance(v, ast.Constant) and isinstance(v.value, int)
                        or isinstance(v, ast.UnaryOp)
                        for v in node.values
                    )
                    if keys_ok and vals_ok:
                        axes.update(k.value for k in node.keys)
                # P("model") / PartitionSpec("data") literals.
                elif isinstance(node, ast.Call) and _callee_name(node.func) in (
                    "P",
                    "PartitionSpec",
                ):
                    for arg in node.args:
                        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                            axes.add(arg.value)
                # def data_sharding(..., axis: str = "data") parameter defaults.
                elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    args = node.args
                    pos_with_defaults = (
                        zip(args.args[len(args.args) - len(args.defaults):], args.defaults)
                        if args.defaults
                        else []
                    )
                    for param, default in [
                        *pos_with_defaults,
                        *zip(args.kwonlyargs, args.kw_defaults),
                    ]:
                        if (
                            default is not None
                            and param.arg.startswith("axis")
                            and isinstance(default, ast.Constant)
                            and isinstance(default.value, str)
                        ):
                            axes.add(default.value)
    _axes_cache[repo] = axes
    return axes


def _file_declared_axes(tree: ast.AST) -> Set[str]:
    """Axis names declared by vmap/pmap axis_name= literals in this file."""
    declared: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _callee_name(node.func) in _DECLARING:
            for kw in node.keywords:
                if kw.arg == "axis_name" and isinstance(kw.value, ast.Constant):
                    if isinstance(kw.value.value, str):
                        declared.add(kw.value.value)
    return declared


def _literal_axis_names(node: ast.AST) -> List[Tuple[str, int]]:
    """(axis, lineno) for every string literal in an axis_name(s) value."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [(node.value, node.lineno)]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                out.append((elt.value, elt.lineno))
        return out
    return []


def _axis_uses(call: ast.Call) -> List[Tuple[str, int]]:
    callee = _callee_name(call.func)
    uses: List[Tuple[str, int]] = []
    if callee in _COLLECTIVES:
        # axis_name may also be the second positional arg (pmean(x, "data")).
        if len(call.args) >= 2:
            uses.extend(_literal_axis_names(call.args[1]))
        if callee == "axis_index" and len(call.args) == 1:
            uses.extend(_literal_axis_names(call.args[0]))
    for kw in call.keywords:
        if kw.arg in _AXIS_KWARGS:
            uses.extend(_literal_axis_names(kw.value))
    return uses


def _check(rule: Rule, ctx: FileContext) -> List[Finding]:
    if not ctx.rel.startswith("stoix_tpu" + os.sep):
        return []
    known = declared_axes(ctx.repo) | _file_declared_axes(ctx.tree)
    findings = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        callee = _callee_name(node.func)
        if callee in _DECLARING:
            continue  # vmap/pmap axis_name= declares, never uses
        for axis, lineno in _axis_uses(node):
            if axis in known or ctx.noqa(lineno, rule.id):
                continue
            declared = ", ".join(sorted(known)) or "<none>"
            findings.append(
                Finding(
                    rule.id,
                    ctx.rel,
                    lineno,
                    f"collective axis name '{axis}' is not declared by any "
                    f"vmap/pmap under stoix_tpu/ nor defined as a mesh "
                    f"axis by stoix_tpu/parallel/ (known: {declared}) — this "
                    f"typo only explodes on a multi-device run (STX007)",
                )
            )
    return findings


RULE = register(
    Rule(
        id="STX007",
        order=90,
        title="collective axis-name consistency",
        rationale="An axis_name literal no mesh or vmap declares compiles on "
        "one device and fails (or silently no-ops) on eight; catching it at "
        "lint time saves the TPU allocation the launch would burn.",
        check_file=_check,
        flag_snippets=(
            # The classic typo: pmean over a misspelled mesh axis.
            "import jax\n\n\ndef learner(grads):\n"
            '    return jax.lax.pmean(grads, axis_name="dataa")\n',
            # axis_names tuple with one bad entry (guards/helper idiom).
            "from stoix_tpu.resilience import guards\n\n\ndef step(new, old):\n"
            '    return guards.guard_update("skip", new=new, old=old,\n'
            '                               axis_names=("batch", "dat"))\n',
            # The gossip-group typo: "groups" is not the learner-group axis
            # ("group", declared by parallel/gossip.py and arch/gossip.yaml).
            "import jax\n\n\ndef gossip_round(params):\n"
            '    return jax.lax.pmean(params, axis_name="groups")\n',
        ),
        clean_snippets=(
            # Mesh axis from parallel/ + vmap-declared in-file axis.
            "import jax\n\n\ndef make(step):\n"
            '    batched = jax.vmap(step, axis_name="batch")\n'
            "    def learner(grads):\n"
            '        grads = jax.lax.pmean(grads, axis_name="batch")\n'
            '        return jax.lax.pmean(grads, axis_name="data")\n'
            "    return learner, batched\n",
            # Axis passed as a VARIABLE is axis-generic library code: skipped.
            "import jax\n\n\ndef reduce_over(x, axis_name):\n"
            "    return jax.lax.psum(x, axis_name)\n",
            # Near-miss to the "groups" typo above: the real learner-group
            # axis, reduced within a group then indexed across groups — both
            # literals resolve against the gossip mesh declarations.
            "import jax\n\n\ndef grouped_learner(grads):\n"
            '    grads = jax.lax.pmean(grads, axis_name="data")\n'
            '    gid = jax.lax.axis_index("group")\n'
            "    return grads, gid\n",
        ),
    )
)
