"""STX005 — PRNG key discipline.

Two failure modes the single-jitted-program style makes silent:

  1. **Key reuse**: the same key variable consumed by two or more
     `jax.random.*` sampling calls (or `dist.sample(seed=key)`) without an
     intervening `split`/`fold_in` rebinding. The program runs, the
     distributions are correlated, and training quality quietly degrades —
     nothing ever raises.
  2. **Discarded split**: `jax.random.split(key)` as a bare expression
     statement. The caller paid for a split and kept using the old key —
     almost always a refactor leftover that reintroduces (1).

Detection is a control-flow-aware linear scan per function scope: each
branch of an `if` is analysed from a copy of the incoming state and merged
conservatively (so one consume in each arm of an if/else does NOT flag);
`for`/`while` bodies are analysed twice, which catches the loop-carried reuse
of a key that is never re-split inside the loop. Consumption is recognised
as (a) a `Name` in the first positional argument (or `key=`/`seed=`/`rng=`
keyword) of a `jax.random.<sampler>` call, and (b) a `Name` passed as a
`seed=`/`key=`/`rng=` keyword to ANY call (the `dist.sample(seed=k)` idiom).

Known blind spots (docs/DESIGN.md §2.5): keys threaded through pytrees or
attributes (`state.key`), cross-function flow, and aliasing (`k2 = k`).
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Tuple

from stoix_tpu.analysis.core import FileContext, Finding, Rule, register
from stoix_tpu.analysis.jitreach import assigned_names as _assigned_names

# jax.random functions that DERIVE or construct keys rather than consuming
# randomness: not a "use" for the reuse check.
_NON_CONSUMING = {
    "split",
    "fold_in",
    "PRNGKey",
    "key",
    "key_data",
    "wrap_key_data",
    "key_impl",
    "clone",
}
_KEY_KWARGS = {"seed", "key", "rng"}


def _random_fn_name(func: ast.AST) -> Optional[str]:
    """'normal' for jax.random.normal / random.normal / jrandom.normal.

    np.random.* / numpy.random.* are NOT key-based (their first argument is a
    distribution parameter, not a PRNG key) and must never match; a bare
    `random.<fn>` receiver is treated as the `from jax import random` idiom —
    stdlib-`random` module calls inside stoix_tpu/ would be a bug anyway
    (host-side nondeterminism the whole design avoids)."""
    if not isinstance(func, ast.Attribute):
        return None
    receiver = func.value
    if isinstance(receiver, ast.Attribute) and receiver.attr == "random":
        root = receiver.value
        if isinstance(root, ast.Name) and root.id in ("np", "numpy"):
            return None
        return func.attr
    if isinstance(receiver, ast.Name) and "random" in receiver.id:
        if receiver.id in ("np_random", "numpy_random"):
            return None
        return func.attr
    return None


class _KeyFlow:
    """Per-scope linear scan with branch-aware state merging.

    State maps a variable name to the line of its first un-reset consumption
    (None = not consumed since the last rebind)."""

    def __init__(self, ctx: FileContext, rule_id: str) -> None:
        self.ctx = ctx
        self.rule_id = rule_id
        self.findings: List[Finding] = []

    # -- expression-level event extraction ----------------------------------

    def _consumed_names(self, expr: ast.AST) -> List[Tuple[str, int, str]]:
        """(name, lineno, called_fn) for every key consumption in `expr`.
        Nested lambda/def bodies are skipped (separate scopes)."""
        out: List[Tuple[str, int, str]] = []
        stack = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            if isinstance(node, ast.Call):
                fn = _random_fn_name(node.func)
                if fn is not None and fn not in _NON_CONSUMING:
                    # A jax.random sampler: the key is the first positional
                    # arg or a key-ish keyword.
                    if node.args and isinstance(node.args[0], ast.Name):
                        out.append((node.args[0].id, node.lineno, f"jax.random.{fn}"))
                    for kw in node.keywords:
                        if kw.arg in _KEY_KWARGS and isinstance(kw.value, ast.Name):
                            out.append((kw.value.id, node.lineno, f"jax.random.{fn}"))
                elif fn is None:
                    # Any other call consuming a key through a key-ish keyword
                    # (the `dist.sample(seed=key)` idiom).
                    for kw in node.keywords:
                        if kw.arg in _KEY_KWARGS and isinstance(kw.value, ast.Name):
                            callee = (
                                node.func.attr
                                if isinstance(node.func, ast.Attribute)
                                else node.func.id
                                if isinstance(node.func, ast.Name)
                                else "call"
                            )
                            out.append((kw.value.id, node.lineno, f"{callee}({kw.arg}=...)"))
            stack.extend(ast.iter_child_nodes(node))
        return out

    def _discarded_splits(self, stmt: ast.stmt) -> List[int]:
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            fn = _random_fn_name(stmt.value.func)
            if fn == "split":
                return [stmt.value.lineno]
        return []

    # -- statement walker ----------------------------------------------------

    def _consume(self, state: Dict[str, Optional[int]], name: str, lineno: int, via: str) -> None:
        first = state.get(name)
        if first is not None:
            if not self.ctx.noqa(lineno, self.rule_id):
                self.findings.append(
                    Finding(
                        self.rule_id,
                        self.ctx.rel,
                        lineno,
                        f"PRNG key '{name}' reused by {via} without an "
                        f"intervening jax.random.split (first consumed at line "
                        f"{first}) — correlated randomness (STX005)",
                    )
                )
            return  # report each reused key once per scope, at first reuse
        state[name] = lineno

    def _reset(self, state: Dict[str, Optional[int]], names: List[str]) -> None:
        for name in names:
            state[name] = None

    def _exprs_of(self, stmt: ast.stmt) -> List[ast.AST]:
        """Value expressions of a simple statement (targets handled separately)."""
        if isinstance(stmt, ast.Assign):
            return [stmt.value]
        if isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            return [stmt.value] if stmt.value is not None else []
        if isinstance(stmt, (ast.Expr, ast.Return)):
            return [stmt.value] if stmt.value is not None else []
        if isinstance(stmt, (ast.Assert, ast.Delete, ast.Raise, ast.Global, ast.Nonlocal)):
            return [c for c in ast.iter_child_nodes(stmt)]
        return []

    def _apply_events(self, state: Dict[str, Optional[int]], expr: ast.AST) -> None:
        for name, lineno, via in sorted(
            self._consumed_names(expr), key=lambda t: t[1]
        ):
            self._consume(state, name, lineno, via)

    def run_block(self, body: List[ast.stmt], state: Dict[str, Optional[int]]) -> None:
        for stmt in body:
            for lineno in self._discarded_splits(stmt):
                if not self.ctx.noqa(lineno, self.rule_id):
                    self.findings.append(
                        Finding(
                            self.rule_id,
                            self.ctx.rel,
                            lineno,
                            "result of jax.random.split discarded — the caller "
                            "keeps using the unsplit key (STX005)",
                        )
                    )
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue  # nested scopes are analysed separately
            if isinstance(stmt, ast.If):
                self._apply_events(state, stmt.test)
                branch_states = []
                for branch in (stmt.body, stmt.orelse):
                    sub = dict(state)
                    self.run_block(branch, sub)
                    branch_states.append(sub)
                self._merge(state, branch_states)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._apply_events(state, stmt.iter)
                self._reset(state, _assigned_names(stmt.target))
                # Two passes catch loop-carried reuse of a never-re-split key.
                self.run_block(stmt.body, state)
                self.run_block(stmt.body, state)
                self.run_block(stmt.orelse, state)
            elif isinstance(stmt, ast.While):
                self._apply_events(state, stmt.test)
                self.run_block(stmt.body, state)
                self.run_block(stmt.body, state)
                self.run_block(stmt.orelse, state)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    self._apply_events(state, item.context_expr)
                    if item.optional_vars is not None:
                        self._reset(state, _assigned_names(item.optional_vars))
                self.run_block(stmt.body, state)
            elif isinstance(stmt, ast.Try):
                sub = dict(state)
                self.run_block(stmt.body, sub)
                branch_states = [sub]
                for handler in stmt.handlers:
                    hstate = dict(state)
                    self.run_block(handler.body, hstate)
                    branch_states.append(hstate)
                self._merge(state, branch_states)
                self.run_block(stmt.orelse, state)
                self.run_block(stmt.finalbody, state)
            else:
                for expr in self._exprs_of(stmt):
                    self._apply_events(state, expr)
                if isinstance(stmt, ast.Assign):
                    for target in stmt.targets:
                        self._reset(state, _assigned_names(target))
                elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                    self._reset(state, _assigned_names(stmt.target))

    def _merge(
        self, state: Dict[str, Optional[int]], branches: List[Dict[str, Optional[int]]]
    ) -> None:
        """OR-merge complete post-branch states (each branch started from a
        copy of the incoming state): consumed-after iff any branch left the
        key consumed. When EVERY branch reset the key (the re-split-in-both-
        arms idiom), the merged state must be reset too — falling back to the
        pre-branch record here would flag correct code."""
        names = set(state)
        for b in branches:
            names |= set(b)
        for name in names:
            linenos = [b.get(name) for b in branches if b.get(name) is not None]
            state[name] = min(linenos) if linenos else None


def _check(rule: Rule, ctx: FileContext) -> List[Finding]:
    if not ctx.rel.startswith("stoix_tpu" + os.sep):
        return []
    flow = _KeyFlow(ctx, rule.id)
    # Module body is one scope; every function (nested included) is its own.
    flow.run_block(getattr(ctx.tree, "body", []), {})
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            flow.run_block(node.body, {})
    return flow.findings


RULE = register(
    Rule(
        id="STX005",
        order=70,
        title="PRNG key discipline",
        rationale="Reusing a consumed key correlates samples across calls and "
        "never raises; a discarded split means the old key keeps being used. "
        "Both train wrong silently on every device at once.",
        check_file=_check,
        flag_snippets=(
            # Key reuse: same key sampled twice, no re-split.
            "import jax\n\n\ndef sample(key):\n"
            "    a = jax.random.normal(key, (2,))\n"
            "    b = jax.random.uniform(key, (2,))\n"
            "    return a + b\n",
            # Discarded split result.
            "import jax\n\n\ndef sample(key):\n"
            "    jax.random.split(key)\n"
            "    return jax.random.normal(key, (2,))\n",
            # seed= reuse through a distribution sample call.
            "import jax\n\n\ndef act(dist, key):\n"
            "    a = dist.sample(seed=key)\n"
            "    b = dist.sample(seed=key)\n"
            "    return a, b\n",
        ),
        clean_snippets=(
            # The canonical re-split idiom.
            "import jax\n\n\ndef sample(key):\n"
            "    key, sub = jax.random.split(key)\n"
            "    a = jax.random.normal(sub, (2,))\n"
            "    key, sub = jax.random.split(key)\n"
            "    b = jax.random.uniform(sub, (2,))\n"
            "    return a + b\n",
            # One consume per if/else arm is NOT reuse.
            "import jax\n\n\ndef sample(key, flag):\n"
            "    if flag:\n"
            "        return jax.random.normal(key, (2,))\n"
            "    else:\n"
            "        return jax.random.uniform(key, (2,))\n",
            # Fan-out into distinct keys.
            "import jax\n\n\ndef sample(key):\n"
            "    k1, k2 = jax.random.split(key)\n"
            "    return jax.random.normal(k1, (2,)) + jax.random.normal(k2, (2,))\n",
        ),
    )
)
