"""STX012 — recompile hazards that defeat the (persistent) compile cache.

ROADMAP item 3 wants a persistent XLA compilation cache + AOT export so a
64-host fleet launch pays one compile, not 64 — which only helps if the code
does not churn trace-cache keys by construction. Four hazard classes, all
statically checkable (the taxonomy in docs/DESIGN.md §2.5):

  (a) **jit-in-loop** — `jax.jit(...)`/`jax.pmap(...)` constructed inside a
      `for`/`while` body: every iteration builds a FRESH callable with an
      empty trace cache, so every iteration retraces (and at best re-hashes
      into the persistent cache). Hoist to setup scope or memoize (the
      `parallel.fetch_global_async` LRU is the blessed pattern).
  (b) **loop-varying static** — a call to a jit-with-`static_argnums/names`
      binding passing the enclosing loop's variable at a static position:
      one full recompile per iteration, silently.
  (c) **non-hashable static** — a list/dict/set (literal or comprehension)
      at a static position: `TypeError: unhashable` at call time, i.e. at
      launch, after the batch was scheduled.
  (d) **static index out of range** — `static_argnums` naming a position the
      wrapped function does not have (the refactor that removed a parameter
      but not the argnums): fails at call time, or worse, after a signature
      reshuffle silently marks the WRONG argument static.

Deliberately out of scope (weak-typed Python scalars as traced args do NOT
churn the cache; config reads inside jit-reachable code are trace-time
constants and belong to STX009's cross-check): see the DESIGN §2.5 taxonomy
for what was evaluated and rejected.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from stoix_tpu.analysis.core import FileContext, Finding, Rule, register
from stoix_tpu.analysis.jitreach import _ModuleIndex, callee_name as _callee_name
from stoix_tpu.analysis.jitreach import annotate_parents as _annotate_parents
from stoix_tpu.analysis.jitreach import literal_int_set as _literal_ints
from stoix_tpu.analysis.jitreach import literal_str_set as _literal_strs
from stoix_tpu.analysis.jitreach import positional_params as _positional_params

_JIT_CTORS = {"jit", "pmap"}
_NON_HASHABLE = (
    ast.List,
    ast.Dict,
    ast.Set,
    ast.ListComp,
    ast.DictComp,
    ast.SetComp,
)


def _static_markers(call: ast.Call) -> Tuple[Optional[Set[int]], Optional[Set[str]]]:
    nums: Optional[Set[int]] = None
    names: Optional[Set[str]] = None
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            nums = _literal_ints(kw.value)
        elif kw.arg == "static_argnames":
            names = _literal_strs(kw.value)
    return nums, names


class _StaticBinding:
    """One jitted callable with literal static markers, by local name."""

    def __init__(
        self,
        name: str,
        argnums: Set[int],
        argnames: Set[str],
        params: Optional[List[str]],
    ) -> None:
        self.name = name
        self.params = params  # wrapped def's positional params, when resolved
        self.positions = set(argnums)
        self.names = set(argnames)
        if params is not None:
            # Cross-map so positional AND keyword callsites are both covered.
            self.names |= {params[i] for i in argnums if i < len(params)}
            self.positions |= {params.index(n) for n in argnames if n in params}


def _collect_bindings(
    rule: Rule, ctx: FileContext, index: _ModuleIndex
) -> Tuple[Dict[str, _StaticBinding], List[Finding]]:
    """Static-marked jit bindings plus (d) out-of-range findings."""
    bindings: Dict[str, _StaticBinding] = {}
    findings: List[Finding] = []

    def handle(name: str, jit_call: ast.Call, fn_expr: Optional[ast.AST]) -> None:
        nums, names = _static_markers(jit_call)
        if not nums and not names:
            return
        params: Optional[List[str]] = None
        defs: List[ast.AST] = []
        if isinstance(fn_expr, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs = [fn_expr]
        elif isinstance(fn_expr, ast.Name):
            defs = list(index.functions.get(fn_expr.id, []))
        if len(defs) == 1:
            params = _positional_params(defs[0])
            # *args absorbs any static position — no out-of-range claim.
            has_vararg = defs[0].args.vararg is not None
            for pos in sorted(nums or ()):
                if has_vararg:
                    break
                if pos >= len(params) and not ctx.noqa(jit_call.lineno, rule.id):
                    findings.append(
                        Finding(
                            rule.id,
                            ctx.rel,
                            jit_call.lineno,
                            f"static_argnums position {pos} is out of range "
                            f"for the wrapped function ({len(params)} "
                            f"positional parameter(s)) — a refactor hazard "
                            f"that fails (or marks the wrong argument "
                            f"static) at call time (STX012)",
                        )
                    )
        bindings[name] = _StaticBinding(name, nums or set(), names or set(), params)

    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
            value = node.value
            if (
                isinstance(target, ast.Name)
                and isinstance(value, ast.Call)
                and _callee_name(value.func) in _JIT_CTORS
            ):
                handle(target.id, value, value.args[0] if value.args else None)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for deco in node.decorator_list:
                if not isinstance(deco, ast.Call):
                    continue
                callee = _callee_name(deco.func)
                is_jit = callee in _JIT_CTORS or (
                    callee == "partial"
                    and any(_callee_name(a) in _JIT_CTORS for a in deco.args)
                )
                if is_jit:
                    handle(node.name, deco, node)
    return bindings, findings


def _enclosing_loops(
    node: ast.AST, parents: Dict[int, ast.AST]
) -> List[ast.AST]:
    """ALL for/while statements between `node` and its enclosing function —
    an OUTER loop's counter reaching a static position from inside a nested
    minibatch/epoch loop is the same one-recompile-per-outer-iteration
    hazard (a function boundary means the loops do not re-execute the node)."""
    loops: List[ast.AST] = []
    current = parents.get(id(node))
    while current is not None:
        if isinstance(current, (ast.For, ast.AsyncFor, ast.While)):
            loops.append(current)
        elif isinstance(
            current, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.Module)
        ):
            break
        current = parents.get(id(current))
    return loops


def _loop_targets(loop: ast.AST) -> FrozenSet[str]:
    """Names that vary per iteration: the for-target, loop-carried updates
    (`i += 1` / `i = i + 1` — the while-counter idiom), and anything whose
    assignment RHS transitively derives from those (`width = i * 2`). A name
    assigned a loop-INVARIANT value inside the body (`width = 64`) is a
    constant that compiles exactly once — flagging it at a static position
    would fail correct code."""
    from stoix_tpu.analysis.jitreach import assigned_names

    varying: Set[str] = set()
    if isinstance(loop, (ast.For, ast.AsyncFor)):
        varying |= set(assigned_names(loop.target))
    assigns: List[Tuple[Set[str], Set[str]]] = []  # (targets, RHS load-names)
    for node in ast.walk(loop):
        if isinstance(node, ast.Assign):
            first = node.targets[0] if node.targets else None
            if (
                len(node.targets) == 1
                and isinstance(first, (ast.Tuple, ast.List))
                and isinstance(node.value, (ast.Tuple, ast.List))
                and len(first.elts) == len(node.value.elts)
                and not any(isinstance(e, ast.Starred) for e in first.elts)
            ):
                # `w, block = i, 64` pairs element-wise: only `w` derives
                # from the iteration, `block` stays a loop-invariant constant.
                for t_elt, v_elt in zip(first.elts, node.value.elts):
                    assigns.append((set(assigned_names(t_elt)), _names_in(v_elt)))
                continue
            targets: Set[str] = set()
            for target in node.targets:
                targets |= set(assigned_names(target))
            assigns.append((targets, _names_in(node.value)))
        elif isinstance(node, ast.AugAssign):
            # `i += 1` carries across iterations — inherently loop-varying.
            varying |= set(assigned_names(node.target))
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            assigns.append((set(assigned_names(node.target)), _names_in(node.value)))
    # Self-referential plain assigns (`i = i + 1`) are loop-carried too.
    for targets, rhs in assigns:
        if targets & rhs:
            varying |= targets
    # Fixpoint: a target deriving from any varying name is itself varying.
    changed = True
    while changed:
        changed = False
        for targets, rhs in assigns:
            if rhs & varying and not targets <= varying:
                varying |= targets
                changed = True
    return frozenset(varying)


def _names_in(expr: ast.AST) -> Set[str]:
    return {
        n.id
        for n in ast.walk(expr)
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
    }


def _static_args_at_call(
    call: ast.Call, binding: _StaticBinding
) -> List[Tuple[ast.AST, str]]:
    """(expr, how) for every argument landing at a static position."""
    out: List[Tuple[ast.AST, str]] = []
    for pos in binding.positions:
        if pos < len(call.args) and not isinstance(call.args[pos], ast.Starred):
            out.append((call.args[pos], f"position {pos}"))
    for kw in call.keywords:
        if kw.arg and kw.arg in binding.names:
            out.append((kw.value, f"argument '{kw.arg}'"))
    return out


def _check(rule: Rule, ctx: FileContext) -> List[Finding]:
    if not ctx.rel.startswith("stoix_tpu" + os.sep):
        return []
    index = ctx.memo("module_index", lambda: _ModuleIndex(ctx.tree))
    bindings, findings = _collect_bindings(rule, ctx, index)
    parents = ctx.memo("parents", lambda: _annotate_parents(ctx.tree))

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        callee = _callee_name(node.func)

        # (a) jit/pmap constructed inside a loop body.
        if callee in _JIT_CTORS:
            if _enclosing_loops(node, parents) and not ctx.noqa(node.lineno, rule.id):
                findings.append(
                    Finding(
                        rule.id,
                        ctx.rel,
                        node.lineno,
                        f"jax.{callee}() constructed inside a loop builds a "
                        f"fresh callable with an empty trace cache every "
                        f"iteration — hoist to setup scope or memoize like "
                        f"parallel.fetch_global_async (STX012)",
                    )
                )
            continue

        # (b)/(c): callsites of static-marked bindings.
        binding = bindings.get(callee) if isinstance(node.func, ast.Name) else None
        if binding is None:
            continue
        loop_vars: FrozenSet[str] = frozenset().union(
            *(_loop_targets(loop) for loop in _enclosing_loops(node, parents))
        )
        for expr, where in _static_args_at_call(node, binding):
            if ctx.noqa(expr.lineno, rule.id):
                continue
            if isinstance(expr, _NON_HASHABLE):
                findings.append(
                    Finding(
                        rule.id,
                        ctx.rel,
                        expr.lineno,
                        f"non-hashable value at static {where} of "
                        f"'{binding.name}' — static arguments are dict keys "
                        f"of the trace cache and TypeError at call time "
                        f"(STX012)",
                    )
                )
            elif loop_vars and (_names_in(expr) & loop_vars):
                findings.append(
                    Finding(
                        rule.id,
                        ctx.rel,
                        expr.lineno,
                        f"loop variable flows into static {where} of "
                        f"'{binding.name}' — one full XLA recompile per "
                        f"iteration, defeating the (persistent) compile "
                        f"cache (STX012)",
                    )
                )
    findings.sort(key=lambda f: f.line)
    return findings


RULE = register(
    Rule(
        id="STX012",
        order=98,
        title="recompile hazards (trace-cache churn)",
        rationale="A jit built per loop iteration, a loop counter at a "
        "static position, a non-hashable static, or an out-of-range "
        "static_argnums each turn the compile cache into a per-step "
        "compile — invisible on a CPU smoke test, ruinous on a 64-host "
        "fleet launch.",
        check_file=_check,
        flag_snippets=(
            # (a) jit constructed per iteration.
            "import jax\n\n\ndef run(fns, x):\n"
            "    outs = []\n"
            "    for f in fns:\n"
            "        outs.append(jax.jit(f)(x))\n"
            "    return outs\n",
            # (b) the loop counter lands at a static position.
            "import jax\n\nstep = jax.jit(update, static_argnums=(1,))\n\n\n"
            "def run(state, n):\n"
            "    for i in range(n):\n"
            "        state = step(state, i)\n"
            "    return state\n",
            # (c) non-hashable static.
            "import jax\n\nstep = jax.jit(update, static_argnums=(1,))\n\n\n"
            "def run(state):\n"
            "    return step(state, [64, 64])\n",
            # (d) static position a refactor removed.
            "import jax\n\n\ndef update(state):\n"
            "    return state\n\n\nstep = jax.jit(update, static_argnums=(2,))\n",
        ),
        clean_snippets=(
            # jit at setup scope, called (not built) in the loop, with a
            # hashable module-constant static.
            "import jax\n\nBLOCK = (64, 64)\n"
            "step = jax.jit(update, static_argnums=(1,))\n\n\n"
            "def run(state, n):\n"
            "    for _ in range(n):\n"
            "        state = step(state, BLOCK)\n"
            "    return state\n",
            # In-range static on a resolvable def; tuple literal is hashable.
            "import jax\n\n\ndef update(state, block):\n"
            "    return state\n\n\nstep = jax.jit(update, static_argnums=(1,))\n"
            "out = step(init, (8, 8))\n",
        ),
    )
)
