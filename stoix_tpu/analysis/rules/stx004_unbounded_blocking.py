"""STX004 — no unbounded blocking calls.

`stoix_tpu/` library code must not call zero-argument `.get()`
(queue.Queue.get — dict.get always takes a key), `.result()` (concurrent
futures), or `.join()` (threads — string join always takes an iterable) with
no timeout. Every indefinite wait is a latent hang: a dead peer turns it into
the wedged process the launch-hardening layer (docs/DESIGN.md §2.4) exists to
kill. Pass a timeout (and handle expiry), or carry a reasoned `# noqa` for a
wait that is intentionally infinite.

Allowlisted: none today — the file allowlist exists for future
provably-supervised waits.

Checker migrated unchanged from scripts/lint.py (PR 4).
"""

from __future__ import annotations

import ast
import os
from typing import List

from stoix_tpu.analysis.core import FileContext, Finding, Rule, register

# AST heuristic: a zero-argument call of one of these attribute names cannot
# be the bounded/keyed variant (dict.get(key), "sep".join(parts),
# t.join(timeout)) — it is a wait that never returns if the other side is
# dead. Calls WITH arguments are only flagged when they name block=... without
# a timeout (queue.get(block=True)).
_BLOCKING_ATTRS = {"get", "result", "join"}
_ALLOWLIST: frozenset = frozenset()  # files whose infinite waits are supervised


def _check(rule: Rule, ctx: FileContext) -> List[Finding]:
    rel = ctx.rel
    if not rel.startswith("stoix_tpu" + os.sep) or rel in _ALLOWLIST:
        return []
    findings = []
    for node in ast.walk(ctx.tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _BLOCKING_ATTRS
        ):
            continue
        kwargs = {kw.arg: kw.value for kw in node.keywords}
        if node.args or kwargs:
            # Positional args mean dict.get(key)/str.join(parts)/
            # join(timeout)/get(block, timeout) — ambiguous or bounded. With
            # keywords, only block=<not False> WITHOUT timeout= is provably
            # an unbounded wait (block=False never blocks).
            if "timeout" in kwargs or node.args:
                continue
            block = kwargs.get("block")
            if block is None or (
                isinstance(block, ast.Constant) and block.value is False
            ):
                continue
        if "noqa" in ctx.line(node.lineno):
            continue
        findings.append(
            Finding(
                "STX004",
                rel,
                node.lineno,
                f"unbounded blocking call `.{node.func.attr}()` "
                f"without a timeout — a dead peer turns this into a wedged process; "
                f"pass a timeout and handle expiry, or noqa a provably-supervised "
                f"infinite wait (STX004)",
            )
        )
    return findings


RULE = register(
    Rule(
        id="STX004",
        order=50,
        title="no unbounded blocking calls",
        rationale="A .get()/.result()/.join() with no timeout never returns "
        "once the producing peer dies; bounded waits with handled expiry are "
        "what keep a degraded run diagnosable instead of wedged.",
        allowlist=_ALLOWLIST,
        check_file=_check,
        flag_snippets=(
            "x = q.get()\n"
            "y = fut.result()\n"
            "t.join()\n"
            "z = q.get(block=True)\n",
        ),
        clean_snippets=(
            "x = q.get(timeout=1.0)\n"
            "y = fut.result(timeout=5)\n"
            "t.join(2.0)\n"
            "s = ', '.join(parts)\n"
            "v = d.get('key')\n"
            "w = q.get(True, 1.0)\n"
            "n = q.get(block=False)\n"
            "m = q.get()  # noqa: STX004 — supervised drain loop\n",
        ),
    )
)
