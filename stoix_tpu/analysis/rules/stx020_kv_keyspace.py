"""STX020 — the fleet-KV keyspace must pair writers with readers.

The fleet coordination protocol is a tiny KV store with no schema: one
module `put`s `hb/<pid>` and another polls it with `try_get`; the vote
path `put`s `vote/<window>/<pid>` and `get_blocking`s every peer's; the
ops-metrics aggregator round-trips `ometrics/<pid>`. The contract lives
entirely in f-string key spelling, so a one-character drift between the
writer and the reader produces no error anywhere — heartbeats age out and
declare a partition, a vote blocks until its deadline, aggregate metrics
silently show one host. Backed by `analysis/opsmodel.py` key patterns
(f-string holes normalized to `{}`; docs/DESIGN.md §2.5), tree-scoped over
`stoix_tpu/` only (`FakeFleetStore` traffic in tests is exempt by scope):

  * a written pattern no reader matches anywhere is a dead write;
  * a `get_blocking` on a pattern no writer matches is a
    deadlock-until-timeout;
  * generic transport wrappers whose key is a bare parameter, and
    `barrier` rendezvous keys, are modeled but not contract-checked
    (documented blind spots).
"""

from __future__ import annotations

import os
from typing import List

from stoix_tpu.analysis.core import Finding, Rule, TreeContext, register
from stoix_tpu.analysis import opsmodel


def _check_tree(rule: Rule, tree_ctx: TreeContext) -> List[Finding]:
    prefix = "stoix_tpu" + os.sep
    writes = []  # (pattern, rel, ctx, site)
    reads = []
    for ctx in sorted(tree_ctx.files, key=lambda c: c.rel):
        if not ctx.rel.startswith(prefix):
            continue
        model = opsmodel.for_context(ctx)
        for site in model.kv_sites:
            if site.pattern is None:
                continue
            if site.side == "write":
                writes.append((site.pattern, ctx.rel, ctx, site))
            elif site.side == "read":
                reads.append((site.pattern, ctx.rel, ctx, site))
    findings: List[Finding] = []
    for pattern, rel, ctx, site in writes:
        if ctx.noqa(site.lineno, rule.id):
            continue
        if not any(opsmodel.patterns_match(pattern, r[0]) for r in reads):
            findings.append(
                Finding(
                    rule.id,
                    rel,
                    site.lineno,
                    f"dead write: KV pattern '{pattern}' is put here but "
                    f"no try_get/get_blocking anywhere in stoix_tpu/ "
                    f"matches it — either the reader drifted or the write "
                    f"is vestigial traffic on the coordination store "
                    f"(STX020)",
                )
            )
    for pattern, rel, ctx, site in reads:
        if site.op != "get_blocking" or ctx.noqa(site.lineno, rule.id):
            continue
        if not any(opsmodel.patterns_match(pattern, w[0]) for w in writes):
            findings.append(
                Finding(
                    rule.id,
                    rel,
                    site.lineno,
                    f"get_blocking on KV pattern '{pattern}' that no put "
                    f"anywhere in stoix_tpu/ matches — this blocks until "
                    f"its deadline every time (STX020)",
                )
            )
    return findings


RULE = register(
    Rule(
        id="STX020",
        order=106,
        title="fleet-KV writer/reader pairing",
        rationale="The fleet protocol's schema is f-string key spelling; "
        "a writer/reader drift produces no error, just a partition verdict "
        "or a vote that blocks to its deadline. Pattern-matching both "
        "sides statically catches the drift at lint time.",
        check_tree=_check_tree,
        flag_snippets=(
            # Dead write: nobody reads the pattern.
            "class Publisher:\n"
            "    def publish(self, store, pid, blob):\n"
            '        store.put(f"heartbeat/{pid}", blob)\n'
            '        value = store.try_get(f"hb/{pid}")\n'
            "        return value\n",
            # get_blocking on a never-written pattern.
            "class Voter:\n"
            "    def collect(self, store, window, pid):\n"
            '        store.put(f"vote/{window}/{pid}", "y")\n'
            '        return store.get_blocking(f"ballot/{window}/{pid}")\n',
        ),
        clean_snippets=(
            # Writer and reader agree (the shipped hb/vote idiom).
            "class Coordinator:\n"
            "    def beat(self, store, pid, blob):\n"
            '        store.put(f"hb/{pid}", blob)\n'
            "    def poll(self, store, peers):\n"
            '        return [store.try_get(f"hb/{p}") for p in peers]\n',
            # A literal read matches a holed write pattern.
            "class Tracker:\n"
            "    def publish(self, store, pid):\n"
            '        store.put(f"ometrics/{pid}", "x")\n'
            "    def scrape_self(self, store):\n"
            '        return store.get_blocking("ometrics/0", timeout=1)\n',
            # Generic transport wrappers (bare-parameter keys) and queue
            # payload puts are out of scope.
            "class Store:\n"
            "    def put(self, key, value):\n"
            "        self._backend.put(key, value)\n"
            "    def enqueue(self, queue, item):\n"
            "        queue.put(item)\n",
        ),
    )
)
