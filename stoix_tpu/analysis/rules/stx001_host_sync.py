"""STX001 — host-sync ownership.

Anakin system files must not call `jax.block_until_ready` /
`checkpointer.wait()` / `wait_until_finished` — the pipelined runner
(systems/runner.py) owns ALL host-sync points, so future systems stay off the
accelerator critical path by construction. Sebulba files are exempt: their
actor/learner threads own their syncs.

Checker migrated unchanged from scripts/lint.py (PR 1).
"""

from __future__ import annotations

import ast
import os
from typing import List

from stoix_tpu.analysis.core import FileContext, Finding, Rule, register

# Host-sync calls that stall the accelerator; only the shared runner (which
# schedules them off the critical path) may contain them. Sebulba system files
# are exempt — their actor/learner threads own their own sync points.
_HOST_SYNC_OWNER = os.path.join("stoix_tpu", "systems", "runner.py")


def _receiver_names(node: ast.AST) -> List[str]:
    """All identifier parts of a dotted receiver: self.checkpointer ->
    ['self', 'checkpointer']."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return parts


def _is_host_sync_call(node: ast.Call) -> bool:
    fn = node.func
    if isinstance(fn, ast.Attribute):
        if fn.attr in ("block_until_ready", "wait_until_finished"):
            return True
        # <anything named like a checkpointer>.wait(...) — including
        # attribute-qualified receivers (self.checkpointer.wait(),
        # setup.ckpt.wait()).
        if fn.attr == "wait":
            return any(
                "checkpoint" in part.lower() or "ckpt" in part.lower()
                for part in _receiver_names(fn.value)
            )
        return False
    return isinstance(fn, ast.Name) and fn.id == "block_until_ready"


def _check(rule: Rule, ctx: FileContext) -> List[Finding]:
    rel = ctx.rel
    systems_prefix = os.path.join("stoix_tpu", "systems") + os.sep
    if not rel.startswith(systems_prefix) or rel == _HOST_SYNC_OWNER:
        return []
    if "sebulba" in rel.split(os.sep):
        return []
    findings = []
    # AST-based (not substring): docstrings/comments DISCUSSING these calls
    # must not trip the gate.
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or not _is_host_sync_call(node):
            continue
        if "noqa" in ctx.line(node.lineno):
            continue
        findings.append(
            Finding(
                "STX001",
                rel,
                node.lineno,
                "host-sync call in an Anakin system file — the "
                "pipelined runner (systems/runner.py) owns all host-sync points (STX001)",
            )
        )
    return findings


RULE = register(
    Rule(
        id="STX001",
        order=20,
        title="host-sync ownership",
        rationale="A block_until_ready / checkpoint wait inside a system file "
        "stalls the accelerator pipeline the runner carefully keeps one "
        "window deep; the runner owns every host-sync point.",
        allowlist=frozenset({_HOST_SYNC_OWNER}),
        check_file=_check,
        flag_snippets=(
            "def run():\n"
            "    self.checkpointer.wait()\n"
            "    setup.ckpt.wait()\n"
            "    jax.block_until_ready(state)\n",
        ),
        clean_snippets=(
            # A non-checkpointer .wait() must NOT trip the gate.
            "def run():\n    lock.wait()\n",
        ),
        fixture_rel="stoix_tpu/systems/_probe_system.py",
    )
)
