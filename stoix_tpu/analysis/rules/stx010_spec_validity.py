"""STX010 — sharding-spec validity against the mesh it flows with.

Every axis literal in a `P(...)`/`PartitionSpec(...)` — whether it rides a
`NamedSharding`, a `shard_map` `in_specs`/`out_specs`, a
`with_sharding_constraint`, or a bare state-spec NamedTuple — must name an
axis that can exist:

  * when the governing mesh is statically resolvable in the same module
    (`learner_mesh = Mesh(devs, ("data",))` then
    `NamedSharding(learner_mesh, P("model"))`), the axis must be an axis of
    THAT mesh — "model" existing on some other mesh elsewhere does not save
    it;
  * otherwise (mesh is a function parameter, built from config, ...) the
    axis must exist in the repo-wide universe of declared mesh axes
    (`meshmodel.mesh_axis_universe`): an axis no mesh constructor, parallel/
    dict spec, or configs YAML `mesh:` block anywhere declares cannot be
    valid on any path.

Spec arity is additionally checked against statically-known array rank: a
`make_array_from_single_device_arrays(shape, NamedSharding(mesh, spec), ...)`
whose shape is a literal tuple must not carry a spec with more entries than
the shape has dims (jax raises at runtime — on the multi-device run the CPU
fallback never exercises).

Unlike STX007 (collective axis names, which vmap/pmap declare), vmap axes are
NOT valid PartitionSpec axes here: `P("batch")` over the in-shard vmap axis
is exactly the confusion the mesh model exists to catch. Axis slots holding
VARIABLES (`P(None, axis)` in axis-generic library code) are skipped per
slot, never guessed.
"""

from __future__ import annotations

import os
from typing import List, Optional, Set, Tuple

from stoix_tpu.analysis import meshmodel
from stoix_tpu.analysis.core import FileContext, Finding, Rule, register


def _axis_findings(
    rule: Rule, ctx: FileContext, use: meshmodel.SpecUse, universe
) -> List[Tuple[str, Finding]]:
    """(axis, finding) pairs — the axis rides alongside so the caller's
    line+axis dedup never has to re-parse it out of the rendered message."""
    findings: List[Tuple[str, Finding]] = []
    if use.mesh is not None:
        allowed = use.mesh.axes
        where = use.mesh.describe()
    else:
        allowed = universe
        where = (
            "any mesh constructor, stoix_tpu/parallel/ spec, or configs "
            f"YAML mesh block (known axes: {', '.join(sorted(universe)) or '<none>'})"
        )
    for axis, lineno in use.spec.literal_axes():
        if axis in allowed or ctx.noqa(lineno, rule.id):
            continue
        findings.append(
            (
                axis,
                Finding(
                    rule.id,
                    ctx.rel,
                    lineno,
                    f"sharding spec names axis '{axis}' which is not declared "
                    f"by {where} — this spec only explodes (or silently "
                    f"misplaces data) on a real multi-device run (STX010)",
                ),
            )
        )
    return findings


def _rank_finding(
    rule: Rule, ctx: FileContext, use: meshmodel.SpecUse
) -> Optional[Finding]:
    if use.rank is None or use.spec.opaque or use.spec.arity <= use.rank:
        return None
    if ctx.noqa(use.spec.lineno, rule.id):
        return None
    return Finding(
        rule.id,
        ctx.rel,
        use.spec.lineno,
        f"sharding spec has {use.spec.arity} entries but the array it is "
        f"applied to has rank {use.rank} — jax rejects a PartitionSpec "
        f"longer than the array rank at runtime (STX010)",
    )


def _check(rule: Rule, ctx: FileContext) -> List[Finding]:
    if not ctx.rel.startswith("stoix_tpu" + os.sep):
        return []
    model = meshmodel.for_context(ctx)
    if not model.spec_uses:
        return []
    universe = meshmodel.mesh_axis_universe(ctx.repo)
    findings: List[Finding] = []
    seen: Set[Tuple[int, str]] = set()
    # Mesh-governed uses first: their finding is strictly more specific than
    # the universe fallback for the same literal (a spec binding consumed by
    # several sites is checked once per site; dedupe by line+axis).
    ordered = sorted(model.spec_uses, key=lambda u: u.mesh is None)
    for use in ordered:
        for axis, f in _axis_findings(rule, ctx, use, universe):
            if (f.line, axis) not in seen:
                seen.add((f.line, axis))
                findings.append(f)
        rank_f = _rank_finding(rule, ctx, use)
        if rank_f is not None and (rank_f.line, "<rank>") not in seen:
            seen.add((rank_f.line, "<rank>"))
            findings.append(rank_f)
    findings.sort(key=lambda f: f.line)
    return findings


RULE = register(
    Rule(
        id="STX010",
        order=96,
        title="sharding-spec validity vs governing mesh",
        rationale="A P() axis the governing mesh (or any mesh) never "
        "declares, or a spec longer than the array's rank, compiles fine on "
        "the single-device CPU fallback and fails — or silently misplaces "
        "data — on the multi-device run the spec exists for.",
        check_file=_check,
        flag_snippets=(
            # Axis valid SOMEWHERE but not on the mesh this spec flows with:
            # the mesh-local resolution STX007 cannot do.
            "import numpy as np\nfrom jax.sharding import Mesh, NamedSharding, "
            "PartitionSpec as P\n\n\ndef place(devices, params):\n"
            '    learner_mesh = Mesh(np.array(devices), ("data",))\n'
            '    return NamedSharding(learner_mesh, P("model"))\n',
            # The classic typo against the repo universe (mesh unresolvable).
            "from jax.sharding import NamedSharding, PartitionSpec as P\n\n\n"
            "def sharding(mesh):\n"
            '    return NamedSharding(mesh, P("dtaa"))\n',
            # Spec arity exceeding the statically-known global shape rank.
            "import jax\nfrom jax.sharding import NamedSharding, "
            "PartitionSpec as P\n\n\ndef assemble(mesh, shards):\n"
            "    return jax.make_array_from_single_device_arrays(\n"
            '        (8,), NamedSharding(mesh, P("data", None)), shards\n'
            "    )\n",
            # A population mesh declares ("pop", "data") — an axis from some
            # OTHER mesh still cannot ride a spec governed by it.
            "import numpy as np\nfrom jax.sharding import Mesh, NamedSharding, "
            "PartitionSpec as P\n\n\ndef place_population(devices, members):\n"
            '    pop_mesh = Mesh(np.array(devices).reshape(2, -1), ("pop", "data"))\n'
            '    return NamedSharding(pop_mesh, P("model"))\n',
            # The gossip mesh declares ("group", "data") — "pop" belongs to
            # the population mesh and cannot ride a group-governed spec.
            "import numpy as np\nfrom jax.sharding import Mesh, NamedSharding, "
            "PartitionSpec as P\n\n\ndef place_groups(devices, stacks):\n"
            "    gossip_mesh = Mesh(np.array(devices).reshape(2, -1), "
            '("group", "data"))\n'
            '    return NamedSharding(gossip_mesh, P("pop", "data"))\n',
        ),
        clean_snippets=(
            # Matching mesh-local axis + universe axis through a parameter.
            "import numpy as np\nfrom jax.sharding import Mesh, NamedSharding, "
            "PartitionSpec as P\n\n\ndef place(devices, mesh, params):\n"
            '    learner_mesh = Mesh(np.array(devices), ("data",))\n'
            '    a = NamedSharding(learner_mesh, P("data"))\n'
            '    b = NamedSharding(mesh, P(None, "data"))\n'
            "    return a, b\n",
            # Axis passed as a VARIABLE slot is axis-generic library code.
            "from jax.sharding import NamedSharding, PartitionSpec as P\n\n\n"
            "def seq_sharding(mesh, axis):\n"
            "    return NamedSharding(mesh, P(None, axis))\n",
            # Arity within the literal rank.
            "import jax\nfrom jax.sharding import NamedSharding, "
            "PartitionSpec as P\n\n\ndef assemble(mesh, shards):\n"
            "    return jax.make_array_from_single_device_arrays(\n"
            '        (8, 4), NamedSharding(mesh, P("data", None)), shards\n'
            "    )\n",
            # The population axis (stoix_tpu/population): "pop" is declared
            # by configs/arch/population.yaml's mesh block, so a
            # parameter-mesh spec over it resolves through the repo universe.
            "from jax.sharding import NamedSharding, PartitionSpec as P\n\n\n"
            "def population_sharding(mesh):\n"
            '    return NamedSharding(mesh, P("pop", "data"))\n',
            # Near-miss to the flagged gossip snippet: the SAME ("group",
            # "data") mesh, now with the spec its axes actually govern —
            # mesh-local resolution accepts what the universe alone would.
            "import numpy as np\nfrom jax.sharding import Mesh, NamedSharding, "
            "PartitionSpec as P\n\n\ndef place_groups(devices, stacks):\n"
            "    gossip_mesh = Mesh(np.array(devices).reshape(2, -1), "
            '("group", "data"))\n'
            '    return NamedSharding(gossip_mesh, P("group", "data"))\n',
        ),
    )
)
