"""STX016 — completion obligations must survive exceptions.

The serve/fleet contract, checked instead of remembered: when a thread
dequeues pending requests (futures) it OWNS their completion — every path
out of the region between receipt and resolution, exception paths included,
must complete each future (with a typed error on failure), or the caller
that submitted it blocks until its timeout with no evidence of what died.
This is where TorchBeast-style dynamic-batching servers historically hide
their worst bug: the worker thread dies, every later caller hangs.

Mechanics (threadmodel): a RECEIPT is `x = <handoff>.get()/next_batch()`
inside a thread-reachable function; the receipt carries an obligation when
the function later completes `x` (or its iterated elements) via
`set_result`/`set_error`/`set_exception` — directly or through a
same-module helper (`self._complete(batch, ...)`). The rule then requires
every statement between the receipt and the last completion point that can
raise (contains a call) to sit inside a `try` whose handler — or `finally`
— error-completes the obligation. `try/finally` completion counts: a
finally that fails leftover requests is the drain idiom.

NOT flagged: receipts whose values carry no futures (the evaluator's
`(params, key, t)` work tuples), guard statements that cannot raise
(`if not batch: continue`), and cheap introspection calls (`len`,
`is_set`, `empty`, `qsize`, `done`).
"""

from __future__ import annotations

import ast
import os
from typing import List, Set

from stoix_tpu.analysis import threadmodel
from stoix_tpu.analysis.core import FileContext, Finding, Rule, register

_ALLOWLIST: frozenset = frozenset()

# Calls that cannot meaningfully raise mid-region: builtins and cheap state
# probes. Everything else is assumed able to raise.
_SAFE_CALLS = {
    "len",
    "isinstance",
    "int",
    "float",
    "str",
    "bool",
    "min",
    "max",
    "list",
    "tuple",
    "dict",
    "range",
    "is_set",
    "empty",
    "qsize",
    "done",
    "perf_counter",
    "monotonic",
}


def _risky(stmt: ast.stmt) -> bool:
    for node in ast.walk(stmt):
        if isinstance(node, ast.Call):
            callee = threadmodel.dotted(node.func)
            leaf = callee[-1] if callee else ""
            if leaf not in _SAFE_CALLS:
                return True
        elif isinstance(node, (ast.Raise, ast.Assert)):
            return True
    return False


def _check(rule: Rule, ctx: FileContext) -> List[Finding]:
    if not ctx.rel.startswith("stoix_tpu" + os.sep) or ctx.rel in _ALLOWLIST:
        return []
    model = threadmodel.for_context(ctx)
    findings: List[Finding] = []
    for obligation in model.obligations:
        fn = obligation.fn
        name = obligation.name
        elems = model.element_aliases(fn, name)

        # Protected tries: a Try whose handler or finally error-completes
        # the obligation covers every statement lexically inside it.
        protected_spans = []
        completion_lines: List[int] = []
        for node in threadmodel.walk_scope(fn):
            if isinstance(node, ast.Try):
                protects = False
                for handler in node.handlers:
                    kinds: Set[str] = set()
                    for stmt in handler.body:
                        kinds |= model.completion_kinds_for(fn, stmt, name, elems)
                    if "error" in kinds:
                        protects = True
                for stmt in node.finalbody:
                    if "error" in model.completion_kinds_for(fn, stmt, name, elems):
                        protects = True
                if protects:
                    protected_spans.append(
                        (node.lineno, getattr(node, "end_lineno", node.lineno))
                    )
            kinds = model.completion_kinds_for(fn, node, name, elems) if isinstance(
                node, ast.Call
            ) else set()
            if kinds:
                completion_lines.append(node.lineno)
        if not completion_lines:
            continue
        region_end = max(completion_lines)

        def covered(lineno: int) -> bool:
            return any(start <= lineno <= end for start, end in protected_spans)

        exposed: List[ast.stmt] = []
        for node in threadmodel.walk_scope(fn):
            if not isinstance(node, ast.stmt) or node is obligation.receipt:
                continue
            lineno = getattr(node, "lineno", 0)
            if not (obligation.lineno < lineno <= region_end):
                continue
            if isinstance(node, (ast.Try, ast.With, ast.AsyncWith, ast.If, ast.For, ast.While)):
                continue  # judged by their inner statements
            if covered(lineno):
                continue
            if _risky(node):
                exposed.append(node)
        if not exposed:
            continue
        if ctx.noqa(obligation.lineno, rule.id):
            continue
        first = min(getattr(s, "lineno", 0) for s in exposed)
        findings.append(
            Finding(
                rule.id,
                ctx.rel,
                obligation.lineno,
                f"'{name}' carries completion obligations, but the statement "
                f"at line {first} can raise before they are resolved and no "
                f"enclosing try completes them with a typed error — the "
                f"submitting caller would block until its timeout with no "
                f"evidence; wrap the region in try/except (or finally) that "
                f"set_error()s every pending request (STX016)",
            )
        )
    findings.sort(key=lambda f: f.line)
    return findings


RULE = register(
    Rule(
        id="STX016",
        order=102,
        title="future/queue completion obligations",
        rationale="A thread that dies between dequeuing a future and "
        "resolving it leaves its caller blocked until timeout with no "
        "evidence of what happened; the no-caller-hangs contract requires a "
        "typed-error completion on every exception path.",
        allowlist=_ALLOWLIST,
        check_file=_check,
        flag_snippets=(
            # The canonical hang: compute between receipt and completion,
            # no except path completes the future.
            "import threading\n\n\nclass Server:\n"
            "    def __init__(self, batcher, engine):\n"
            "        self._batcher = batcher\n"
            "        self._engine = engine\n"
            "        self._worker = threading.Thread(target=self._loop, daemon=True)\n\n"
            "    def _loop(self):\n"
            "        while True:\n"
            "            batch = self._batcher.next_batch(idle_timeout=0.1)\n"
            "            out = self._engine.infer(batch)\n"
            "            for request in batch:\n"
            "                request.set_result(out)\n",
            # A handler exists but completes nothing — the caller still hangs.
            "import threading\n\n\nclass Server:\n"
            "    def __init__(self, q, engine, log):\n"
            "        self._q = q\n"
            "        self._engine = engine\n"
            "        self._log = log\n"
            "        self._worker = threading.Thread(target=self._loop, daemon=True)\n\n"
            "    def _loop(self):\n"
            "        while True:\n"
            "            request = self._q.get(timeout=1.0)\n"
            "            try:\n"
            "                request.set_result(self._engine.infer(request))\n"
            "            except Exception:\n"
            "                self._log.error('batch failed')\n",
        ),
        clean_snippets=(
            # The sanctioned shape: except completes with a typed error.
            "import threading\n\n\nclass Server:\n"
            "    def __init__(self, batcher, engine):\n"
            "        self._batcher = batcher\n"
            "        self._engine = engine\n"
            "        self._worker = threading.Thread(target=self._loop, daemon=True)\n\n"
            "    def _loop(self):\n"
            "        while True:\n"
            "            batch = self._batcher.next_batch(idle_timeout=0.1)\n"
            "            if not batch:\n"
            "                continue\n"
            "            try:\n"
            "                out = self._engine.infer(batch)\n"
            "                for request in batch:\n"
            "                    request.set_result(out)\n"
            "            except Exception as exc:\n"
            "                for request in batch:\n"
            "                    request.set_error(exc)\n",
            # try/finally drain is recognized too.
            "import threading\n\n\nclass Server:\n"
            "    def __init__(self, q, engine):\n"
            "        self._q = q\n"
            "        self._engine = engine\n"
            "        self._worker = threading.Thread(target=self._loop, daemon=True)\n\n"
            "    def _loop(self):\n"
            "        while True:\n"
            "            request = self._q.get(timeout=1.0)\n"
            "            try:\n"
            "                request.set_result(self._engine.infer(request))\n"
            "            finally:\n"
            "                if not request.done():\n"
            "                    request.set_error(RuntimeError('worker died'))\n",
            # A receipt with no futures carries no obligation (evaluator).
            "import threading\n\n\nclass Evaluator:\n"
            "    def __init__(self, q, evaluate, sink):\n"
            "        self._q = q\n"
            "        self._evaluate = evaluate\n"
            "        self._sink = sink\n"
            "        self._t = threading.Thread(target=self._run, daemon=True)\n\n"
            "    def _run(self):\n"
            "        while True:\n"
            "            work = self._q.get(timeout=1.0)\n"
            "            self._sink(self._evaluate(work))\n",
        ),
    )
)
