"""STX013 — host-divergence hazards on the multi-host SPMD path.

Multi-host JAX is single-program-multiple-data: every process must execute
the SAME sequence of compiled programs with the SAME trace-time constants,
or collectives deadlock / silently mix mismatched values. A value that
differs per host — wall-clock time, unseeded RNG draws, environment
variables, filesystem listings — is fine for logging, and poison the moment
it reaches a traced program or a cross-host collective. Two detection modes:

  1. **Trace-time divergence**: a divergent source CALLED inside
     jit-reachable code (per `jitreach`). Each host traces a different
     constant into the HLO → different programs → the all-reduce that
     "should" line up deadlocks, usually minutes into a pod launch.

  2. **Host-to-device taint**: a variable assigned from a divergent source
     (module scope taints flow into function scopes) that is later passed as
     an argument to a known-jitted binding or a cross-host collective helper
     (`process_allgather`, `fetch_global`, raw `psum`/`pmean`...). Rebinding
     from an untainted expression clears the taint.

NOT flagged, deliberately: `jax.distributed.initialize(...)` consuming
`os.environ` (the blessed SLURM coordination idiom — every host reads
DIFFERENT process ids by design), divergent values that stay host-side
(telemetry timestamps), and `jax.random.*` (keyed, deterministic). Cross-
module flow is the usual jitreach blind spot; resilience/faultinject.py is
allowlisted — injecting divergence is its job.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from stoix_tpu.analysis.core import FileContext, Finding, Rule, register
from stoix_tpu.analysis.jitreach import (
    _ModuleIndex,
    all_param_names as _all_param_names,
    assigned_names as _assigned_names,
    callee_name as _callee_name,
    reachable_jit_functions,
    walk_scope,
)
from stoix_tpu.analysis.rules.stx007_collective_axes import _COLLECTIVES

_ALLOWLIST = frozenset(
    {
        # Injecting per-host divergence (nan_loss at a step, wedges, crashes)
        # is this module's entire purpose.
        os.path.join("stoix_tpu", "resilience", "faultinject.py"),
    }
)

_TIME_FNS = {
    "time",
    "time_ns",
    "monotonic",
    "monotonic_ns",
    "perf_counter",
    "perf_counter_ns",
}
_OS_FNS = {"getenv", "urandom", "getpid", "listdir", "uname"}
_MISC = {
    ("glob", "glob"),
    ("socket", "gethostname"),
    ("uuid", "uuid1"),
    ("uuid", "uuid4"),
}
_JIT_CTORS = {"jit", "pmap"}
# Callees whose RESULT is a jitted/collective callable when bound to a name.
_JITTED_FACTORIES = {"shardmap_learner", "aot_warmup"}
_COLLECTIVE_HELPERS = {
    "process_allgather",
    "fetch_global",
    "fetch_global_async",
    "broadcast_one_to_all",
}


def _dotted(node: ast.AST) -> List[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return list(reversed(parts))


def _jax_aliases(tree: ast.AST) -> FrozenSet[str]:
    """Names this module binds to jax submodules: `from jax import random`
    makes the bare name `random` KEYED jax.random, which the stdlib-random
    heuristic must not flag (the rule's own exemption)."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            module = node.module or ""
            if module == "jax" or module.startswith("jax."):
                for alias in node.names:
                    names.add(alias.asname or alias.name)
        elif isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.startswith("jax.") and alias.asname:
                    names.add(alias.asname)
    return frozenset(names)


def _divergent_call(call: ast.Call, jax_names: FrozenSet[str]) -> Optional[str]:
    """A label when this call draws a per-host-divergent value."""
    chain = _dotted(call.func)
    if not chain:
        return None
    root, leaf = chain[0], chain[-1]
    if root in jax_names:
        # `from jax import random` / `import jax.random as random`: keyed,
        # deterministic, shared-seed — deliberately NOT divergent.
        return None
    if root == "time" and leaf in _TIME_FNS and len(chain) == 2:
        return f"time.{leaf}()"
    if root == "os" and leaf in _OS_FNS:
        return f"os.{leaf}()"
    if chain[:2] == ["os", "environ"] and len(chain) == 3:  # os.environ.get
        return "os.environ"
    if root == "random" and len(chain) == 2:
        return f"random.{leaf}()"
    if root in ("np", "numpy") and len(chain) >= 3 and chain[1] == "random":
        if leaf == "default_rng" and (call.args or call.keywords):
            # A SEEDED generator is deterministic per seed; if the seed
            # itself is divergent, the taint rides the seed expression.
            return None
        return f"{root}.random.{leaf}()"
    if "datetime" in chain and leaf in ("now", "utcnow", "today"):
        return f"datetime.{leaf}()"
    if leaf == "open" and len(chain) == 1:
        return "open()"
    if (root, leaf) in _MISC:
        return f"{root}.{leaf}()"
    return None


def _divergent_expr(
    expr: ast.AST, jax_names: FrozenSet[str]
) -> Optional[Tuple[str, int]]:
    """(label, lineno) of the first divergent source inside an expression
    (calls and `os.environ[...]` subscripts)."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            label = _divergent_call(node, jax_names)
            if label:
                return label, node.lineno
        elif isinstance(node, ast.Subscript):
            if _dotted(node.value)[:2] == ["os", "environ"]:
                return "os.environ", node.lineno
    return None


def _sink_names(tree: ast.AST) -> Set[str]:
    """Local names whose CALL dispatches a traced program: jit/pmap bindings,
    factory-wrapped learners, and @jax.jit-decorated defs."""
    sinks: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
            value = node.value
            if isinstance(target, ast.Name) and isinstance(value, ast.Call):
                if _callee_name(value.func) in _JIT_CTORS | _JITTED_FACTORIES:
                    sinks.add(target.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for deco in node.decorator_list:
                callee = _callee_name(deco.func if isinstance(deco, ast.Call) else deco)
                if callee in _JIT_CTORS:
                    sinks.add(node.name)
                elif isinstance(deco, ast.Call) and callee == "partial":
                    if any(_callee_name(a) in _JIT_CTORS for a in deco.args):
                        sinks.add(node.name)
    return sinks


class _TaintScan:
    """Statement-ordered taint propagation through one scope."""

    def __init__(
        self,
        rule: Rule,
        ctx: FileContext,
        sinks: Set[str],
        jax_names: FrozenSet[str],
        initial: Optional[Dict[str, Tuple[str, int]]] = None,
    ) -> None:
        self.rule = rule
        self.ctx = ctx
        self.sinks = sinks
        self.jax_names = jax_names
        self.state: Dict[str, Tuple[str, int]] = dict(initial or {})
        self.findings: List[Finding] = []

    def _expr_taint(self, expr: ast.AST) -> Optional[Tuple[str, int]]:
        source = _divergent_expr(expr, self.jax_names)
        if source:
            return source
        for node in ast.walk(expr):
            if (
                isinstance(node, ast.Name)
                and isinstance(node.ctx, ast.Load)
                and node.id in self.state
            ):
                return self.state[node.id]
        return None

    def _check_sink_calls(self, expr: ast.AST) -> None:
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            callee = _callee_name(node.func)
            is_sink = (
                (isinstance(node.func, ast.Name) and callee in self.sinks)
                or callee in _COLLECTIVE_HELPERS
                or callee in _COLLECTIVES
            )
            if not is_sink:
                continue
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                taint = self._expr_taint(arg)
                if taint and not self.ctx.noqa(node.lineno, self.rule.id):
                    label, src_line = taint
                    self.findings.append(
                        Finding(
                            self.rule.id,
                            self.ctx.rel,
                            node.lineno,
                            f"per-host-divergent value from {label} (line "
                            f"{src_line}) flows into '{callee}' — SPMD hosts "
                            f"would trace/reduce different values and "
                            f"deadlock or silently diverge (STX013)",
                        )
                    )
                    break

    def run(self, body: List[ast.stmt]) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue
            if isinstance(stmt, ast.Assign):
                self._check_sink_calls(stmt.value)
                taint = self._expr_taint(stmt.value)
                for target in stmt.targets:
                    for name in _assigned_names(target):
                        if taint:
                            self.state[name] = taint
                        else:
                            self.state.pop(name, None)
            elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                if stmt.value is not None:
                    self._check_sink_calls(stmt.value)
                    taint = self._expr_taint(stmt.value)
                    for name in _assigned_names(stmt.target):
                        if taint:
                            self.state[name] = taint
                        elif not isinstance(stmt, ast.AugAssign):
                            self.state.pop(name, None)
            elif isinstance(stmt, ast.If):
                self._check_sink_calls(stmt.test)
                saved = dict(self.state)
                self.run(stmt.body)
                body_state = self.state
                self.state = dict(saved)
                self.run(stmt.orelse)
                # Join: tainted on EITHER path stays tainted (an else-branch
                # rebind must not launder the if-branch's divergent value).
                for name, taint in body_state.items():
                    self.state.setdefault(name, taint)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._check_sink_calls(stmt.iter)
                self.run(stmt.body)
                self.run(stmt.orelse)
            elif isinstance(stmt, ast.While):
                self._check_sink_calls(stmt.test)
                self.run(stmt.body)
                self.run(stmt.orelse)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    self._check_sink_calls(item.context_expr)
                    if item.optional_vars is not None:
                        # `with open(p) as f:` — the withitem binding carries
                        # the context expression's taint (reads of `f` are the
                        # dominant filesystem-source idiom).
                        taint = self._expr_taint(item.context_expr)
                        for name in _assigned_names(item.optional_vars):
                            if taint:
                                self.state[name] = taint
                            else:
                                self.state.pop(name, None)
                self.run(stmt.body)
            elif isinstance(stmt, ast.Try):
                self.run(stmt.body)
                for handler in stmt.handlers:
                    self.run(handler.body)
                self.run(stmt.orelse)
                self.run(stmt.finalbody)
            else:
                for child in ast.iter_child_nodes(stmt):
                    if isinstance(child, ast.expr):
                        self._check_sink_calls(child)


def _check(rule: Rule, ctx: FileContext) -> List[Finding]:
    if not ctx.rel.startswith("stoix_tpu" + os.sep) or ctx.rel in _ALLOWLIST:
        return []
    findings: List[Finding] = []
    jax_names = _jax_aliases(ctx.tree)

    # Mode 1: divergent sources inside jit-reachable code (trace-time bake).
    for fn in reachable_jit_functions(ctx.tree):
        for node in walk_scope(fn):
            label = None
            if isinstance(node, ast.Call):
                label = _divergent_call(node, jax_names)
            elif isinstance(node, ast.Subscript):
                if _dotted(node.value)[:2] == ["os", "environ"]:
                    label = "os.environ"
            if label and not ctx.noqa(node.lineno, rule.id):
                findings.append(
                    Finding(
                        rule.id,
                        ctx.rel,
                        node.lineno,
                        f"{label} inside jit-reachable code bakes a "
                        f"DIFFERENT trace-time constant on every SPMD host "
                        f"— the compiled programs (and their collectives) "
                        f"no longer match across the pod (STX013)",
                    )
                )

    # Mode 2: host-side taint reaching a jitted call or collective helper.
    sinks = _sink_names(ctx.tree)
    module_scan = _TaintScan(rule, ctx, sinks, jax_names)
    module_scan.run(getattr(ctx.tree, "body", []))
    findings.extend(module_scan.findings)
    module_taint = dict(module_scan.state)
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # Parameters shadow module-scope taint: a same-named argument is a
            # fresh caller-supplied value, not the tainted module global.
            params = _all_param_names(node.args)
            initial = {k: v for k, v in module_taint.items() if k not in params}
            scan = _TaintScan(rule, ctx, sinks, jax_names, initial=initial)
            scan.run(node.body)
            findings.extend(scan.findings)
    findings.sort(key=lambda f: f.line)
    return findings


RULE = register(
    Rule(
        id="STX013",
        order=99,
        title="host-divergence hazards (SPMD)",
        rationale="A wall-clock, env-var, RNG, or filesystem value reaching "
        "a traced program or collective makes SPMD hosts execute different "
        "programs — the multi-host failure that presents as a deadlocked "
        "all-reduce minutes into a pod launch.",
        allowlist=_ALLOWLIST,
        check_file=_check,
        flag_snippets=(
            # Trace-time bake inside jit-reachable code.
            "import jax\nimport time\n\n\n@jax.jit\ndef step(x):\n"
            "    return x * time.time()\n",
            # Host-side env-var taint reaching a jitted call.
            "import jax\nimport os\n\nstep = jax.jit(update)\n\n\n"
            "def run(state):\n"
            '    boost = float(os.environ.get("BOOST", "1.0"))\n'
            "    return step(state, boost)\n",
            # STDLIB random (unseeded, per-host) reaching a jitted call.
            "import jax\nimport random\n\nstep = jax.jit(update)\n\n\n"
            "def run(state):\n"
            "    noise = random.random()\n"
            "    return step(state, noise)\n",
        ),
        clean_snippets=(
            # Wall-clock for host-side telemetry never reaches a program.
            "import time\n\nfrom stoix_tpu.observability import get_logger\n\n\n"
            "def log_window(metrics):\n"
            "    t0 = time.perf_counter()\n"
            '    get_logger("x").info("window at %.1f: %s", t0, metrics)\n'
            "    return t0\n",
            # Keyed jax.random is deterministic; config-fed seeds are shared.
            "import jax\n\nstep = jax.jit(update)\n\n\n"
            "def run(state, config):\n"
            "    key = jax.random.PRNGKey(int(config.arch.seed))\n"
            "    return step(state, key)\n",
            # `from jax import random` is STILL jax.random, not the stdlib.
            "import jax\nfrom jax import random\n\nstep = jax.jit(update)\n\n\n"
            "def run(state, key):\n"
            "    key, sub = random.split(key)\n"
            "    return step(state, sub)\n",
            # The blessed SLURM coordination idiom is NOT a sink.
            "import jax\nimport os\n\n\ndef init_distributed():\n"
            '    coord = os.environ.get("JAX_COORDINATOR_ADDRESS")\n'
            "    if coord:\n"
            "        jax.distributed.initialize(coordinator_address=coord)\n",
        ),
    )
)
