"""STX009 — config↔code cross-check.

Two directions, one model (stoix_tpu.analysis.configmodel):

  1. **Unknown-key reads** (the real bugs): a strict attribute-chain read
     `config.a.b.c` in systems/runner/evaluator code whose dotted path no
     YAML under `stoix_tpu/configs/` defines and no code ever assigns. The
     Config class is permanently struct-off, so a typo'd read raises
     AttributeError only on the code path that executes it — possibly twenty
     minutes into a TPU run, or never in tests. Reported at the read site.
  2. **Dead YAML keys**: a leaf key no code ever reads (strictly,
     tolerantly via `.get`/`getattr`, or by reading an enclosing subtree).
     Dead keys rot: they document behavior the code no longer has.
     Reported at the YAML definition site.

Liveness and definition are computed over the WHOLE repo (stoix_tpu/,
bench.py, scaling_bench.py, tests/) regardless of which paths the invocation
scanned, so partial-path runs cannot fabricate dead-key findings; the rule
itself only runs when the scan covers stoix_tpu/ code.

Subtree semantics keep the rule honest about its blind spots (documented in
docs/DESIGN.md §2.5): reading an ancestor (`config.env` handed to a factory)
marks the whole subtree live; any dict carrying `_target_` is consumed by
`config.instantiate()` and its subtree is exempt from dead-key analysis;
`.get(...)` with a non-literal key marks the enclosing node live.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Set, Tuple

from stoix_tpu.analysis import configmodel
from stoix_tpu.analysis.core import FileContext, Finding, Rule, TreeContext, register
from stoix_tpu.analysis.core import iter_py_files

Path = Tuple[str, ...]

# Code whose config reads are REPORTED when unknown (the per-system surface
# the issue targets); everything scanned still contributes liveness/writes.
_REPORT_PREFIXES = (
    os.path.join("stoix_tpu", "systems") + os.sep,
    os.path.join("stoix_tpu", "sebulba") + os.sep,
    "stoix_tpu" + os.sep + "evaluator.py",
    "stoix_tpu" + os.sep + "sweep.py",
    "stoix_tpu" + os.sep + "launcher.py",
)
# The whole-repo scan STX009 always performs for definitions and liveness.
_SCAN_ROOTS = ("stoix_tpu", "tests", "bench.py", "scaling_bench.py", "sweep.py")

# Dead-key allowlist: dotted key (or dotted prefix ending in '.') -> reason.
# Keys here are intentionally kept although no code reads them today; every
# entry needs a reason a reviewer can audit.
DEAD_KEY_ALLOWLIST: Dict[str, str] = {}  # noqa: STX002 — audited allowlist constant, not a stats accumulator


def _allowlisted(dotted: str) -> bool:
    for entry in DEAD_KEY_ALLOWLIST:
        if dotted == entry or (entry.endswith(".") and dotted.startswith(entry)):
            return True
    return False


def _collect_repo_accesses(
    repo: str, cached: Dict[str, FileContext]
) -> Tuple[configmodel.ConfigAccesses, List[Tuple[str, configmodel.ConfigAccesses]]]:
    """(merged accesses for liveness, per-file accesses for reporting)."""
    merged = configmodel.ConfigAccesses()
    per_file: List[Tuple[str, configmodel.ConfigAccesses]] = []
    for path in iter_py_files(_SCAN_ROOTS, repo):
        rel = os.path.relpath(path, repo)
        ctx = cached.get(rel)
        if ctx is not None:
            tree = ctx.tree
        else:
            try:
                with open(path) as f:
                    tree = ast.parse(f.read())
            except (OSError, SyntaxError):
                continue
        accesses = configmodel.collect_config_accesses(tree)
        per_file.append((rel, accesses))
        merged.strict.extend(accesses.strict)
        merged.tolerant.extend(accesses.tolerant)
        merged.writes.update(accesses.writes)
    return merged, per_file


def _is_defined(path: Path, keys: configmodel.ConfigKeySet, writes: Set[Path]) -> bool:
    if keys.defines(path) or keys.under_target(path):
        return True
    for w in writes:
        if path[: len(w)] == w or w[: len(path)] == path:
            return True
    return False


def _is_live(leaf: Path, reads: Set[Path], writes: Set[Path]) -> bool:
    for r in reads | writes:
        if leaf[: len(r)] != r:
            continue
        # Exact read, or an ancestor-SUBTREE read (config.logger.kwargs
        # handed to a consumer). A bare group read (`config.system` passed
        # around) does not confer liveness: group mounts are the composition
        # skeleton, and counting them would mark every key alive.
        if len(r) == len(leaf) or len(r) >= 2:
            return True
    return False


def _check_tree(rule: Rule, ctx: TreeContext) -> List[Finding]:
    if not ctx.scans_package():
        return []
    keys = configmodel.load_config_keys(ctx.repo)
    if not keys.nodes:
        return []  # no configs tree (scratch invocation)
    cached = {f.rel: f for f in ctx.files}
    merged, per_file = _collect_repo_accesses(ctx.repo, cached)
    read_paths: Set[Path] = {p for p, _ in merged.strict} | {
        p for p, _ in merged.tolerant
    }

    findings: List[Finding] = []
    # Direction 1: unknown-key reads in the per-system surface. Only SCANNED
    # files are reported (their FileContext exists, so noqa is honored) —
    # a partial-path run must not emit findings, nor ignore suppressions, in
    # files the invocation never looked at. The whole-repo access collection
    # above still feeds definitions/liveness regardless of scan scope.
    for rel, accesses in per_file:
        file_ctx = cached.get(rel)
        if file_ctx is None or not rel.startswith(_REPORT_PREFIXES):
            continue
        reported: Set[Tuple[Path, int]] = set()
        for path, lineno in accesses.strict:
            if _is_defined(path, keys, merged.writes):
                continue
            if (path, lineno) in reported:
                continue
            reported.add((path, lineno))
            if file_ctx.noqa(lineno, rule.id):
                continue
            dotted = ".".join(path)
            findings.append(
                Finding(
                    rule.id,
                    rel,
                    lineno,
                    f"config read '{dotted}' matches no key defined under "
                    f"stoix_tpu/configs/ and is never assigned by code — "
                    f"typo'd config access raises AttributeError only when "
                    f"this path executes (STX009)",
                )
            )

    # Direction 2: dead YAML keys (reported once per defining file).
    for leaf, sites in sorted(keys.leaves.items()):
        if keys.under_target(leaf):
            continue
        dotted = ".".join(leaf)
        if _allowlisted(dotted):
            continue
        if _is_live(leaf, read_paths, merged.writes):
            continue
        for rel_yaml, line in sites:
            findings.append(
                Finding(
                    rule.id,
                    rel_yaml,
                    line,
                    f"config key '{dotted}' is never read by any stoix_tpu "
                    f"code — dead config; delete it or allowlist it with a "
                    f"reason in stx009_config_crosscheck.DEAD_KEY_ALLOWLIST "
                    f"(STX009)",
                )
            )
    return findings


RULE = register(
    Rule(
        id="STX009",
        order=110,
        title="config↔code cross-check",
        rationale="struct-off configs mean a typo'd read only fails on the "
        "executing code path and a stale YAML key never fails at all; the "
        "cross-check makes both a lint error.",
        check_tree=_check_tree,
    )
)
