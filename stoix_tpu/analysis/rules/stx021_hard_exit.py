"""STX021 — hard exits carrying recovery codes must leave evidence, and
the supervisor must dispatch every registered code.

The codes >= 86 (stall / fleet partition / state corruption / elastic
resize; docs/DESIGN.md §2.6) are the supervised-recovery protocol: each
names a failure the launcher reacts to, and each is diagnosed *post
mortem* from the flight record the dying process dumps. An `os._exit`
skips every finally/atexit, so the dump only happens if the exit path
calls it explicitly — deleting that call breaks triage silently (the
process still dies with the right code; the evidence just never lands).
Backed by `analysis/opsmodel.py` exit sites (docs/DESIGN.md §2.5), scoped
to `stoix_tpu/`:

  * every exit site whose code resolves to >= 86 (via the module's own
    `EXIT_CODE_*` constants or `resilience/exit_codes.py`) must have a
    `dump_flight_record` call statically preceding it in the same
    function, or inside a module-local / self-method callee of a
    preceding call (depth-limited; dynamic codes like
    `os._exit(self._exit_code)` are out of model — documented blind
    spots);
  * a module defining `run_supervised` must reference every registered
    non-zero `EXIT_CODE_*` name inside it — `exit_codes.REGISTRY` is the
    single source of truth, so registering a new code without teaching
    the supervision dispatch about it is a lint error, not a 3am
    surprise.
"""

from __future__ import annotations

import ast
import functools
import os
from typing import Dict, List

from stoix_tpu.analysis.core import FileContext, Finding, Rule, register
from stoix_tpu.analysis import opsmodel

_HARD_EXIT_FLOOR = 86


@functools.lru_cache(maxsize=8)
def _registry_codes(repo: str) -> Dict[str, int]:
    """EXIT_CODE_* name -> value from the canonical registry module."""
    path = os.path.join(repo, "stoix_tpu", "resilience", "exit_codes.py")
    try:
        with open(path) as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError):
        return {}
    return {
        name: value
        for name, value in opsmodel.module_int_constants(tree).items()
        if name.startswith("EXIT_CODE_")
    }


def _check_file(rule: Rule, ctx: FileContext) -> List[Finding]:
    if not ctx.rel.startswith("stoix_tpu" + os.sep):
        return []
    model = opsmodel.for_context(ctx)
    local_codes = {
        name: value
        for name, value in model.int_constants.items()
        if name.startswith("EXIT_CODE_")
    }
    findings: List[Finding] = []
    for site in model.exit_sites:
        if ctx.noqa(site.lineno, rule.id):
            continue
        value = site.code_value
        if value is None and site.code_name is not None:
            value = local_codes.get(site.code_name)
            if value is None:
                value = _registry_codes(ctx.repo).get(site.code_name)
        if value is None or value < _HARD_EXIT_FLOOR:
            continue
        if not model.flight_dump_reachable(site):
            label = site.code_name or str(value)
            findings.append(
                Finding(
                    rule.id,
                    ctx.rel,
                    site.lineno,
                    f"{site.via}({label}) carries recovery code {value} "
                    f"but no dump_flight_record call statically precedes "
                    f"it in this function or its local callees — the "
                    f"process dies with the right code and no evidence "
                    f"(STX021)",
                )
            )
    # Supervision coverage: run_supervised must name every registered
    # non-zero code (handled-and-relaunched or explicitly final).
    supervised_fns = [
        node
        for node in ast.walk(ctx.tree)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
        and node.name == "run_supervised"
    ]
    if supervised_fns:
        registry = local_codes or _registry_codes(ctx.repo)
        required = {
            name for name, value in registry.items() if value != 0
        }
        referenced = model.fn_references("run_supervised")
        missing = sorted(required - referenced)
        fn = supervised_fns[0]
        if missing and not ctx.noqa(fn.lineno, rule.id):
            findings.append(
                Finding(
                    rule.id,
                    ctx.rel,
                    fn.lineno,
                    f"run_supervised does not dispatch "
                    f"{', '.join(missing)} — every registered non-zero "
                    f"exit code (exit_codes.REGISTRY is the source of "
                    f"truth) must be named handled-or-final here "
                    f"(STX021)",
                )
            )
    return findings


RULE = register(
    Rule(
        id="STX021",
        order=107,
        title="hard-exit flight-record + supervision coverage",
        rationale="os._exit skips every finally, so the flight-record "
        "dump the post-mortem depends on only happens if the exit path "
        "calls it first; and a registered recovery code the supervisor "
        "does not dispatch turns a designed recovery into an unexplained "
        "final exit.",
        check_file=_check_file,
        flag_snippets=(
            # The dump deleted from a corruption exit.
            "import os\n\nEXIT_CODE_STATE_CORRUPTION = 88\n\n\n"
            "def hook(exc_type, exc, tb):\n"
            "    os._exit(EXIT_CODE_STATE_CORRUPTION)\n",
            # run_supervised missing a registered code.
            "EXIT_CODE_STALL = 86\nEXIT_CODE_FLEET_PARTITION = 87\n\n\n"
            "def run_supervised(run, max_relaunches):\n"
            "    while True:\n"
            "        rc = run()\n"
            "        if rc != EXIT_CODE_FLEET_PARTITION:\n"
            "            return rc\n",
        ),
        clean_snippets=(
            # Dump precedes the exit in the same function.
            "import os\n\nfrom stoix_tpu.observability import flightrec\n\n"
            "EXIT_CODE_STALL = 86\n\n\n"
            "def shoot():\n"
            '    flightrec.dump_flight_record(None, reason="stall")\n'
            "    os._exit(EXIT_CODE_STALL)\n",
            # Dump inside a preceding self-method callee (the fleet idiom).
            "import os\n\nEXIT_CODE_FLEET_PARTITION = 87\n\n\n"
            "class Fleet:\n"
            "    def _evidence(self, reason):\n"
            "        dump_flight_record(None, reason=reason)\n"
            "    def _hard_exit(self):\n"
            '        self._evidence("partition")\n'
            "        os._exit(EXIT_CODE_FLEET_PARTITION)\n",
            # Codes below the recovery floor need no flight record.
            "import os\n\nEXIT_CODE_FAILURE = 1\n\n\n"
            "def die():\n    os._exit(EXIT_CODE_FAILURE)\n",
            # run_supervised naming the full local registry.
            "EXIT_CODE_STALL = 86\nEXIT_CODE_FLEET_PARTITION = 87\n\n\n"
            "def run_supervised(run, max_relaunches):\n"
            "    final = {EXIT_CODE_STALL: 'stall — triage first'}\n"
            "    while True:\n"
            "        rc = run()\n"
            "        if rc != EXIT_CODE_FLEET_PARTITION:\n"
            "            return (rc, final.get(rc))\n",
        ),
    )
)
