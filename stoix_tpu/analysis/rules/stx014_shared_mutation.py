"""STX014 — unsynchronized shared mutation across thread roots.

A self-attribute (or module global) MUTATED in place — `self.x += 1`,
`self.pending.append(r)`, `self.table[k] = v`, `self.x = f(self.x)` — from
one thread root while another root reads or writes it, with no common lock
held, is a torn-state bug: the interleaving that loses an update shows up
once a week under production load and never in a unit test. The threadmodel
(analysis/threadmodel.py) supplies the roots, the lock-held ranges, and the
access classification; this rule flags the mutating access.

Deliberately NOT flagged — the repo's sanctioned designs must pass:

  * **The atomic single-reference discipline** (ParameterServer /
    InferenceEngine.set_params): a plain `self.x = <fully built value>`
    assignment is one bytecode-level reference store under the GIL, and a
    plain unlocked read of it sees either the old or the new complete value.
    Only MUTATING writes flag; plain writes and reads never do on their own.
  * **Pre-publication writes**: anything inside `__init__`/`__new__`/
    `__post_init__` runs before the object is visible to a second thread.
  * **Internally-synchronized primitives**: attributes bound to Event/Queue/
    the lock family (`self._stop.clear()` is the idiom, not a race).
  * **Locked-writer / atomic-reader splits**: a mutation under lock L racing
    a plain READ that holds no lock is the engine's params-version pattern —
    safe for reference reads; flagged only when BOTH sides mutate under
    disjoint (or no) locks.

Blind spots (docs/DESIGN.md §2.5): cross-module sharing, happens-before via
`start()` ordering, and locks threaded through call arguments.
"""

from __future__ import annotations

import os
from typing import List

from stoix_tpu.analysis import threadmodel
from stoix_tpu.analysis.core import FileContext, Finding, Rule, register
from stoix_tpu.analysis.threadmodel import MAIN_ROOT

_ALLOWLIST: frozenset = frozenset()


def _check(rule: Rule, ctx: FileContext) -> List[Finding]:
    if not ctx.rel.startswith("stoix_tpu" + os.sep) or ctx.rel in _ALLOWLIST:
        return []
    model = threadmodel.for_context(ctx)
    if not model.spawned_root_labels:
        return []  # no second thread in this module, nothing to race
    findings: List[Finding] = []
    for key, accesses in model.accesses.items():
        for write in accesses:
            if write.kind != "mutate" or write.in_init:
                continue
            w_roots = model.roots_of(write.fn)
            for other in accesses:
                if other is write or other.in_init:
                    continue
                o_roots = model.roots_of(other.fn)
                pair_roots = w_roots | o_roots
                # Needs two distinct roots with a spawned thread involved
                # (two main-only accesses are plain sequential code).
                if len(pair_roots) < 2 or pair_roots == {MAIN_ROOT}:
                    continue
                if w_roots == o_roots == {MAIN_ROOT}:
                    continue
                if write.locks & other.locks:
                    continue  # a common lock serializes the pair
                # Locked mutation vs plain unlocked read = the sanctioned
                # atomic-reader split; a mutation race needs the mutation
                # itself unlocked, or two mutations under disjoint locks.
                if write.locks and other.kind != "mutate":
                    continue
                if ctx.noqa(write.lineno, rule.id):
                    break
                attr = key.split(":", 1)[1]
                findings.append(
                    Finding(
                        rule.id,
                        ctx.rel,
                        write.lineno,
                        f"in-place mutation of shared '{attr}' with no lock "
                        f"common to its other accessors (e.g. line "
                        f"{other.lineno}) — thread roots "
                        f"{'/'.join(sorted(pair_roots))} can interleave and "
                        f"tear this state; hold one lock on both sides, or "
                        f"rebuild the value and install it with a single "
                        f"reference assignment (STX014)",
                    )
                )
                break
    findings.sort(key=lambda f: f.line)
    return findings


RULE = register(
    Rule(
        id="STX014",
        order=100,
        title="unsynchronized shared mutation",
        rationale="An in-place mutation of state shared across thread roots "
        "with no common lock loses updates under exactly the production "
        "interleavings a CPU unit test never produces; the sanctioned "
        "alternatives are a common lock or the single-reference "
        "atomic-assignment discipline.",
        allowlist=_ALLOWLIST,
        check_file=_check,
        flag_snippets=(
            # Worker thread appends, caller drains — no lock anywhere.
            "import threading\n\n\nclass Collector:\n"
            "    def __init__(self):\n"
            "        self._items = []\n"
            "        self._worker = threading.Thread(target=self._run, daemon=True)\n\n"
            "    def _run(self):\n"
            "        while True:\n"
            "            self._items.append(self._poll())\n\n"
            "    def drain(self):\n"
            "        out = list(self._items)\n"
            "        self._items.clear()\n"
            "        return out\n",
            # Counter increment from two roots under no lock.
            "import threading\n\n\nclass Stats:\n"
            "    def __init__(self):\n"
            "        self.n = 0\n"
            "        self._t = threading.Thread(target=self._run, daemon=True)\n\n"
            "    def _run(self):\n"
            "        self.n += 1\n\n"
            "    def bump(self):\n"
            "        self.n += 1\n",
            # Two mutations under DIFFERENT locks do not serialize.
            "import threading\n\n\nclass Split:\n"
            "    def __init__(self):\n"
            "        self._a = threading.Lock()\n"
            "        self._b = threading.Lock()\n"
            "        self._q = []\n"
            "        self._t = threading.Thread(target=self._run, daemon=True)\n\n"
            "    def _run(self):\n"
            "        with self._a:\n"
            "            self._q.append(1)\n\n"
            "    def push(self, item):\n"
            "        with self._b:\n"
            "            self._q.append(item)\n",
        ),
        clean_snippets=(
            # The atomic single-reference swap discipline (engine.set_params).
            "import threading\n\n\nclass Engine:\n"
            "    def __init__(self, params):\n"
            "        self._params = params\n"
            "        self._t = threading.Thread(target=self._swap_loop, daemon=True)\n\n"
            "    def _swap_loop(self):\n"
            "        fresh = self._load()\n"
            "        self._params = fresh\n\n"
            "    def infer(self, x):\n"
            "        params = self._params\n"
            "        return params, x\n",
            # A common lock on both sides serializes the mutation.
            "import threading\n\n\nclass Collector:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._items = []\n"
            "        self._worker = threading.Thread(target=self._run, daemon=True)\n\n"
            "    def _run(self):\n"
            "        with self._lock:\n"
            "            self._items.append(self._poll())\n\n"
            "    def drain(self):\n"
            "        with self._lock:\n"
            "            out = list(self._items)\n"
            "            self._items.clear()\n"
            "        return out\n",
            # Event methods are internally synchronized — never a race.
            "import threading\n\n\nclass Poller:\n"
            "    def __init__(self):\n"
            "        self._stop = threading.Event()\n"
            "        self._t = threading.Thread(target=self._run, daemon=True)\n\n"
            "    def _run(self):\n"
            "        while not self._stop.wait(1.0):\n"
            "            self._sample()\n\n"
            "    def start(self):\n"
            "        self._stop.clear()\n\n"
            "    def stop(self):\n"
            "        self._stop.set()\n",
            # Locked writer vs atomic reference reader (params_version).
            "import threading\n\n\nclass Versioned:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._version = 0\n"
            "        self._t = threading.Thread(target=self._run, daemon=True)\n\n"
            "    def _run(self):\n"
            "        with self._lock:\n"
            "            self._version += 1\n\n"
            "    def version(self):\n"
            "        return self._version\n",
        ),
    )
)
