"""Rule framework for the first-party static-analysis gate.

This module is deliberately dependency-free (stdlib `ast` + `os` only — no
jax, no numpy): the gate must be runnable from a SLURM prolog or CI box that
has never allocated an accelerator, and `launcher.py --preflight-only` calls
it in-process before any backend probe.

Concepts:

  - `Finding`: one diagnostic (rule id, display path, line, message,
    severity). `render()` reproduces the exact text the historical
    `scripts/lint.py` printed, so the shim stays byte-identical.
  - `Rule`: one registered check. A rule is *file-scoped* (`check_file` runs
    per parsed file) and/or *tree-scoped* (`check_tree` runs once per
    invocation — STX009's config cross-check). Each rule carries its
    rationale, a file allowlist, and fixture snippets (`flag_snippets` must
    produce >=1 finding; `clean_snippets` must produce none) that
    tests/test_lint.py replays.
  - noqa policy: a bare `# noqa` suppresses every rule on that line; a coded
    `# noqa: STX005` suppresses only the listed rules and MUST carry a
    one-line reason after an em-dash (`# noqa: STX005 — fixed fan-out`).
    The legacy rules (F401/E501/STX001-004) keep their historical substring
    semantics unchanged; new rules (STX005+) use `Noqa.suppresses`.
"""

from __future__ import annotations

import ast
import os
import py_compile
import re
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

ERROR = "error"
WARNING = "warning"

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
DEFAULT_PATHS = ["stoix_tpu", "tests", "scripts", "bench.py", "__graft_entry__.py"]

_NOQA_RE = re.compile(r"#\s*noqa\b:?\s*([^#]*)", re.IGNORECASE)
_CODE_RE = re.compile(r"[A-Z]+[0-9]+")


def noqa_suppresses(line: str, rule_id: str) -> bool:
    """Code-aware noqa: bare `# noqa` suppresses everything; `# noqa: CODES`
    suppresses only the listed codes. Used by STX005+ (legacy rules keep
    their historical `"noqa" in line` substring check, migrated unchanged)."""
    m = _NOQA_RE.search(line)
    if not m:
        return False
    codes = _CODE_RE.findall(m.group(1).split("—")[0])
    return not codes or rule_id in codes


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # display path (legacy convention: abs for core checks, repo-relative for STX rules)
    line: int  # 0 = whole-file finding (no :line in the rendered text)
    message: str  # includes the trailing "(STXnnn)" tag, as historically printed
    severity: str = ERROR

    def render(self) -> str:
        if self.line:
            return f"{self.path}:{self.line}: {self.message}"
        return f"{self.path}: {self.message}"

    def to_json(self) -> Dict[str, object]:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "severity": self.severity,
        }


@dataclass
class FileContext:
    """Everything a file-scoped rule needs; parsed once, shared by all rules."""

    repo: str
    path: str  # absolute
    rel: str  # repo-relative (os.sep separators)
    source: str
    lines: List[str]
    tree: ast.AST
    # Per-file memo shared across rules for derived models that several rules
    # rebuild identically (ModuleMeshModel, jitreach._ModuleIndex) — the same
    # reason the parsed tree itself is shared.
    cache: Dict[str, object] = field(default_factory=dict)

    def line(self, lineno: int) -> str:
        return self.lines[lineno - 1] if 0 < lineno <= len(self.lines) else ""

    def noqa(self, lineno: int, rule_id: str) -> bool:
        return noqa_suppresses(self.line(lineno), rule_id)

    def memo(self, key: str, build: Callable[[], object]) -> object:
        value = self.cache.get(key)
        if value is None:
            value = build()
            self.cache[key] = value
        return value


@dataclass
class TreeContext:
    """Context for whole-tree rules (one run per invocation)."""

    repo: str
    files: List[FileContext]  # every file the invocation scanned

    def scans_package(self) -> bool:
        prefix = "stoix_tpu" + os.sep
        return any(f.rel.startswith(prefix) for f in self.files)


@dataclass
class Rule:
    id: str
    title: str
    rationale: str
    allowlist: frozenset = frozenset()  # repo-relative paths exempt from the rule
    severity: str = ERROR
    # Execution/printing position; preserves the historical per-file finding
    # order (F401, STX001..004, hygiene) the scripts/lint.py shim pins.
    order: int = 100
    # Finding ids this rule emits (hygiene keeps the legacy W191/W291/E501
    # sub-ids); defaults to (id,). Fixture tests match against these.
    finding_ids: Tuple[str, ...] = ()

    def __post_init__(self) -> None:
        if not self.finding_ids:
            self.finding_ids = (self.id,)
    check_file: Optional[Callable[["Rule", FileContext], List[Finding]]] = None
    check_tree: Optional[Callable[["Rule", TreeContext], List[Finding]]] = None
    # Fixture snippets replayed by tests: every flag snippet must yield >=1
    # finding with this rule's id; every clean snippet must yield none. They
    # are checked as if saved at `fixture_rel` (rules are path-scoped).
    flag_snippets: Tuple[str, ...] = ()
    clean_snippets: Tuple[str, ...] = ()
    fixture_rel: str = "stoix_tpu/_analysis_probe.py"
    # Extra warning-severity findings are allowed from an error-severity rule
    # (hygiene emits both); `severity` is the default for its findings.

    def run_on_source(
        self, source: str, rel: Optional[str] = None, repo: str = REPO
    ) -> List[Finding]:
        """Run this rule alone against an in-memory snippet (fixture tests).
        Tree-scoped checks run too, over a one-file tree — so rules whose
        contract is inherently cross-module (STX019/020/022/023) still ship
        replayable in-module fixtures."""
        rel = rel or self.fixture_rel
        ctx = FileContext(
            repo=repo,
            path=os.path.join(repo, rel),
            rel=rel.replace("/", os.sep),
            source=source,
            lines=source.splitlines(),
            tree=ast.parse(source),
        )
        findings = list(self.check_file(self, ctx)) if self.check_file else []
        if self.check_tree is not None:
            findings.extend(self.check_tree(self, TreeContext(repo, [ctx])))
        return findings


# ---------------------------------------------------------------------------
# Registry


_registry: "Dict[str, Rule]" = {}


def register(rule: Rule) -> Rule:
    if rule.id in _registry:
        raise ValueError(f"duplicate rule id {rule.id}")
    _registry[rule.id] = rule
    return rule


def get_rules() -> List[Rule]:
    """All registered rules, ordered by their `order` field (legacy print order)."""
    from stoix_tpu.analysis import rules as _rules  # noqa: F401 — registration side effect

    return sorted(_registry.values(), key=lambda r: r.order)


def get_rule(rule_id: str) -> Rule:
    for rule in get_rules():
        if rule.id == rule_id:
            return rule
    raise KeyError(rule_id)


# ---------------------------------------------------------------------------
# Runner


def iter_py_files(paths: Iterable[str], repo: str = REPO) -> Iterable[str]:
    for p in paths:
        full = os.path.join(repo, p)
        if os.path.isfile(full) and full.endswith(".py"):
            yield full
        elif os.path.isdir(full):
            # Legacy walk order (dirs unsorted, files sorted) — keeps the
            # scripts/lint.py shim output byte-identical.
            for root, _dirs, files in os.walk(full):
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)


def changed_paths(repo: str = REPO) -> Optional[List[str]]:
    """Repo-relative .py files changed vs HEAD (staged, unstaged, untracked),
    restricted to the DEFAULT_PATHS scan surface — the `--changed-only`
    selection that keeps the gate fast as the rule count grows.

    Returns None when git is unavailable or the tree is not a work tree
    (an exported tarball on a CI box). Callers treat None AND an empty list
    as "run the full scan" — a clean checkout means the change under test is
    already committed, so a vacuous 0-file pass would be a fake gate;
    degrade to MORE coverage, never silently to less.
    """
    import subprocess

    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", "HEAD"],
            capture_output=True,
            text=True,
            cwd=repo,
            timeout=30,
        )
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            capture_output=True,
            text=True,
            cwd=repo,
            timeout=30,
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    if diff.returncode != 0:
        return None
    names = set(diff.stdout.splitlines())
    if untracked.returncode == 0:
        names |= set(untracked.stdout.splitlines())
    scan_files = {p for p in DEFAULT_PATHS if p.endswith(".py")}
    scan_dirs = tuple(p + "/" for p in DEFAULT_PATHS if not p.endswith(".py"))
    out = []
    for name in sorted(names):
        if not name.endswith(".py"):
            continue
        if name not in scan_files and not name.startswith(scan_dirs):
            continue
        if os.path.isfile(os.path.join(repo, name)):  # deletions drop out
            out.append(name)
    return out


def _select_rules(
    select: Optional[Sequence[str]], ignore: Optional[Sequence[str]]
) -> List[Rule]:
    rules = get_rules()
    known = {r.id for r in rules}
    if select:
        wanted = {s.upper() for s in select}
        unknown = wanted - known
        if unknown:
            raise KeyError(f"unknown rule id(s): {', '.join(sorted(unknown))}")
        rules = [r for r in rules if r.id in wanted]
    if ignore:
        dropped = {s.upper() for s in ignore}
        unknown = dropped - known
        if unknown:
            # A typo'd --ignore must not silently waive nothing while the CI
            # invocation looks configured.
            raise KeyError(f"unknown rule id(s): {', '.join(sorted(unknown))}")
        rules = [r for r in rules if r.id not in dropped]
    return rules


def syntax_findings(path: str) -> List[Finding]:
    """py_compile gate; a file that does not parse gets ONLY this finding."""
    try:
        py_compile.compile(path, doraise=True)
        return []
    except py_compile.PyCompileError as exc:
        return [Finding("E999", path, 0, f"syntax error: {exc.msg}")]


def run_paths(
    paths: Optional[Sequence[str]] = None,
    select: Optional[Sequence[str]] = None,
    ignore: Optional[Sequence[str]] = None,
    repo: str = REPO,
    with_tree_rules: bool = True,
) -> Tuple[List[Finding], int]:
    """Run the selected rules over `paths`; returns (findings, files scanned).

    Findings keep the historical order: per file, rules in registration
    order; tree-scoped rules run once at the end. `with_tree_rules=False`
    skips them — required under --changed-only, where the partial file set
    would make STX009's never-read analysis see phantom dead keys."""
    rules = _select_rules(select, ignore)
    findings: List[Finding] = []
    contexts: List[FileContext] = []
    n_files = 0
    scan = paths if paths is not None else DEFAULT_PATHS
    for path in iter_py_files(scan, repo):
        n_files += 1
        with open(path) as f:
            source = f.read()
        syntax = syntax_findings(path)
        if syntax:
            findings.extend(syntax)
            continue
        ctx = FileContext(
            repo=repo,
            path=path,
            rel=os.path.relpath(path, repo),
            source=source,
            lines=source.splitlines(),
            tree=ast.parse(source),
        )
        contexts.append(ctx)
        for rule in rules:
            # Rule.allowlist and the scope checks INSIDE each checker read
            # the same module-level constant (e.g. stx002._ALLOWLIST), so the
            # two layers cannot drift; the central skip exists so a future
            # rule that declares an allowlist without re-checking it inside
            # its checker still honors it.
            if rule.check_file is not None and ctx.rel not in rule.allowlist:
                findings.extend(rule.check_file(rule, ctx))
    if with_tree_rules:
        tree_ctx = TreeContext(repo=repo, files=contexts)
        for rule in rules:
            if rule.check_tree is not None:
                findings.extend(rule.check_tree(rule, tree_ctx))
    return findings, n_files


def split_severity(findings: Sequence[Finding]) -> Tuple[List[Finding], List[Finding]]:
    errors = [f for f in findings if f.severity == ERROR]
    warnings = [f for f in findings if f.severity == WARNING]
    return errors, warnings
