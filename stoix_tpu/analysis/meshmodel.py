"""Repo-wide static model of mesh construction and sharding expressions.

The scale arc (pod-scale Sebulba, gossip learner groups, the unified
mesh-role abstraction) rewrites the device-placement layer of ~37 files that
use `PartitionSpec`/`NamedSharding`/`shard_map`. A misspelled mesh axis or a
spec/rank mismatch compiles fine on the CPU fallback and only explodes on a
real multi-device run — or worse, silently replicates where it should reduce.
This module gives the STX010-STX013 rules one shared model of BOTH sides:

  Declaration side — which mesh axes exist, and per mesh binding, which axes
  THAT mesh has:

    * `Mesh(dev_array, ("data", "seq"))` / `jax.make_mesh(shape, axis_names)`
      axis-name tuple literals, anywhere in the scanned tree;
    * `create_mesh({"data": -1, "model": 2})` dict-literal specs (the
      stoix_tpu/parallel factory), plus the `{str: int}` dict-literal mesh
      specs inside `stoix_tpu/parallel/` itself (the factory's own default);
    * `mesh:` mapping keys in `stoix_tpu/configs/**/*.yaml` (runner.py builds
      the mesh from `config.arch.mesh`, so YAML is a declaration site);
    * vmap/pmap `axis_name=` literals are deliberately NOT part of the
      PartitionSpec universe — a vmap axis is not a mesh axis, which is
      exactly the conflation STX007 tolerates and STX010 does not.

  Use side — every sharding expression, resolved through the same
  module-local name machinery as `jitreach.py`:

    * `P(...)`/`PartitionSpec(...)` literals (entries: axis literal, `None`,
      tuple-of-axes dims, or unresolvable expressions — tracked per slot);
    * spec variables (`seq_spec = P(None, axis)`) resolved module-wide;
    * `NamedSharding(mesh, spec)` — the spec is checked against the axes of
      the mesh it statically flows with when the mesh binding resolves to a
      constructor with literal axes, else against the repo-wide universe;
    * `shard_map(fn, mesh=..., in_specs=..., out_specs=...)` sites with the
      wrapped-callee expression kept for signature/body checks (STX011);
    * `with_sharding_constraint(x, spec)` and
      `make_array_from_single_device_arrays(shape, sharding, arrays)` (with
      the literal-tuple shape rank when statically known, for arity checks).

Known blind spots (docs/DESIGN.md §2.5): meshes built from config at runtime
(`create_mesh(dict(config.arch.mesh))` falls back to the universe, which the
YAML scan keeps honest), meshes threaded through containers or attributes
(`self.mesh`), axis names passed as variables (axis-generic library code is
skipped per slot, never guessed), and specs constructed by helpers in other
modules. Pure stdlib `ast` + `yaml`; no jax import.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from stoix_tpu.analysis.jitreach import all_param_names as _all_param_names
from stoix_tpu.analysis.jitreach import annotate_parents as _annotate_parents
from stoix_tpu.analysis.jitreach import assigned_names as _assigned_names
from stoix_tpu.analysis.jitreach import callee_name as _callee_name
from stoix_tpu.analysis.jitreach import literal_str_set as _literal_str_set

_SPEC_CTORS = {"P", "PartitionSpec"}
_MESH_CTORS = {"Mesh", "make_mesh", "create_mesh"}
# Declaration scan covers every path the gate lints plus the top-level bench
# entry points that build real meshes (scaling_bench is not in DEFAULT_PATHS).
_DECL_SCAN_PATHS = (
    "stoix_tpu",
    "tests",
    "scripts",
    "bench.py",
    "scaling_bench.py",
    "__graft_entry__.py",
)


# ---------------------------------------------------------------------------
# Spec parsing


@dataclass(frozen=True)
class SpecEntry:
    """One positional slot of a `P(...)`: the axis literals it names (a slot
    may shard over several axes via a tuple) and whether the slot resolved."""

    axes: Tuple[Tuple[str, int], ...]  # (axis, lineno) literals in this slot
    known: bool  # False: slot holds a variable/expression we cannot resolve


@dataclass
class SpecInfo:
    """A parsed sharding spec (`P("data", None)` → two entries)."""

    lineno: int
    entries: List[SpecEntry] = field(default_factory=list)
    opaque: bool = False  # the whole spec expression was unresolvable

    @property
    def arity(self) -> int:
        return len(self.entries)

    @property
    def closed(self) -> bool:
        """Every slot statically resolved — absence of an axis is meaningful
        (the spec genuinely claims replication over axes it does not name)."""
        return not self.opaque and all(e.known for e in self.entries)

    def literal_axes(self) -> List[Tuple[str, int]]:
        out: List[Tuple[str, int]] = []
        for entry in self.entries:
            out.extend(entry.axes)
        return out

    def mentions(self, axis: str) -> bool:
        return any(a == axis for a, _ in self.literal_axes())


def is_spec_call(node: ast.AST) -> bool:
    return isinstance(node, ast.Call) and _callee_name(node.func) in _SPEC_CTORS


# Names a binding target rebinds. `mesh.x = ...`/`mesh[i] = ...` mutate, they
# do not rebind the base name, so Attribute/Subscript yield nothing — which is
# exactly jitreach.assigned_names' contract.
_target_names = _assigned_names


def parse_spec_call(call: ast.Call) -> SpecInfo:
    """Parse one `P(...)` call into per-slot entries."""
    info = SpecInfo(lineno=call.lineno)
    if call.keywords or any(isinstance(a, ast.Starred) for a in call.args):
        # P(*dims) / unexpected kwargs: arity and absence claims unreliable.
        info.opaque = True
        return info
    for arg in call.args:
        if isinstance(arg, ast.Constant) and arg.value is None:
            info.entries.append(SpecEntry(axes=(), known=True))
        elif isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            info.entries.append(SpecEntry(axes=((arg.value, arg.lineno),), known=True))
        elif isinstance(arg, (ast.Tuple, ast.List)):
            axes: List[Tuple[str, int]] = []
            known = True
            for elt in arg.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                    axes.append((elt.value, elt.lineno))
                else:
                    known = False
            info.entries.append(SpecEntry(axes=tuple(axes), known=known))
        else:
            # A variable slot (`P(None, axis)`): arity still counts, the slot
            # could name any axis — never guess, never claim absence.
            info.entries.append(SpecEntry(axes=(), known=False))
    return info


# ---------------------------------------------------------------------------
# Mesh-constructor parsing


def _literal_str_tuple(node: ast.AST) -> Optional[FrozenSet[str]]:
    strs = _literal_str_set(node)
    return None if strs is None else frozenset(strs)


def _literal_axis_dict(node: ast.AST) -> Optional[FrozenSet[str]]:
    """`{"data": -1, "model": 2}` → {"data", "model"} (int/-N sizes only)."""
    if not isinstance(node, ast.Dict) or not node.keys:
        return None
    axes = set()
    for key, value in zip(node.keys, node.values):
        if not (isinstance(key, ast.Constant) and isinstance(key.value, str)):
            return None
        if not (
            (isinstance(value, ast.Constant) and isinstance(value.value, int))
            or isinstance(value, ast.UnaryOp)
        ):
            return None
        axes.add(key.value)
    return frozenset(axes)


def mesh_ctor_axes(node: ast.AST) -> Optional[FrozenSet[str]]:
    """Axis names a mesh-constructor expression declares, when literal.

    `Mesh(arr, ("data",))`, `jax.make_mesh(shape, ("data", "model"))` (or
    `axis_names=`), `create_mesh({"data": -1})` (or `axes=`). None when the
    expression is not a mesh constructor or its axes are not literal.
    """
    if not isinstance(node, ast.Call):
        return None
    callee = _callee_name(node.func)
    if callee == "Mesh" or callee == "make_mesh":
        for kw in node.keywords:
            if kw.arg == "axis_names":
                return _literal_str_tuple(kw.value)
        if len(node.args) >= 2:
            return _literal_str_tuple(node.args[1])
        return None
    if callee == "create_mesh":
        for kw in node.keywords:
            if kw.arg == "axes":
                return _literal_axis_dict(kw.value)
        if node.args:
            return _literal_axis_dict(node.args[0])
        return None
    return None


# ---------------------------------------------------------------------------
# Repo-wide axis universe (cached per repo, like STX007's declared_axes)


_universe_cache: Dict[str, FrozenSet[str]] = {}


def mesh_axis_universe(repo: str) -> FrozenSet[str]:
    """Every mesh axis any scanned file (or config YAML) declares.

    The fallback oracle for specs whose governing mesh is not statically
    resolvable: an axis in NO mesh constructor, parallel/ dict spec, or YAML
    `mesh:` block anywhere cannot be valid on any path.
    """
    cached = _universe_cache.get(repo)
    if cached is not None:
        return cached
    axes: Set[str] = set()
    for rel in _DECL_SCAN_PATHS:
        full = os.path.join(repo, rel)
        files: List[str] = []
        if os.path.isfile(full) and full.endswith(".py"):
            files = [full]
        elif os.path.isdir(full):
            for root, dirs, names in os.walk(full):
                dirs[:] = [d for d in dirs if d != "__pycache__"]
                files.extend(
                    os.path.join(root, n) for n in sorted(names) if n.endswith(".py")
                )
        for path in files:
            try:
                with open(path) as f:
                    tree = ast.parse(f.read())
            except (OSError, SyntaxError):
                continue
            in_parallel = os.sep + "parallel" in path
            for node in ast.walk(tree):
                declared = mesh_ctor_axes(node)
                if declared:
                    axes |= declared
                elif in_parallel:
                    # The factory's own default spec ({"data": -1} inside
                    # create_mesh's body) is a bare dict literal.
                    bare = _literal_axis_dict(node)
                    if bare:
                        axes |= bare
    axes |= _yaml_mesh_axes(repo)
    out = frozenset(axes)
    _universe_cache[repo] = out
    return out


def _yaml_mesh_axes(repo: str) -> Set[str]:
    """Keys of every `mesh:` mapping under stoix_tpu/configs/ — runner.py
    builds the mesh from `config.arch.mesh`, so YAML declares axes too."""
    try:
        import yaml
    except ImportError:  # the gate must degrade, not crash, without pyyaml
        return set()
    axes: Set[str] = set()
    configs = os.path.join(repo, "stoix_tpu", "configs")
    for root, _dirs, names in os.walk(configs):
        for name in sorted(names):
            if not name.endswith((".yaml", ".yml")):
                continue
            try:
                with open(os.path.join(root, name)) as f:
                    data = yaml.safe_load(f.read()) or {}
            except (OSError, yaml.YAMLError):
                continue
            stack = [data]
            while stack:
                current = stack.pop()
                if not isinstance(current, dict):
                    continue
                for key, value in current.items():
                    if key == "mesh" and isinstance(value, dict):
                        axes.update(k for k in value if isinstance(k, str))
                    elif isinstance(value, dict):
                        stack.append(value)
    return axes


# ---------------------------------------------------------------------------
# Per-module model


@dataclass
class MeshRef:
    """A statically-resolved mesh a spec flows with."""

    axes: FrozenSet[str]
    lineno: int  # binding/constructor line, for the finding message
    name: str = ""  # the variable name when bound ("" for inline ctors)

    def describe(self) -> str:
        where = f"'{self.name}' (line {self.lineno})" if self.name else f"line {self.lineno}"
        return f"mesh {where} with axes {{{', '.join(sorted(self.axes))}}}"


@dataclass
class SpecUse:
    """One sharding expression at its use site.

    mesh is None when the governing mesh is not statically resolvable (check
    axis literals against the repo universe instead); rank is the statically
    known rank of the array the spec applies to, when any (only
    `make_array_from_single_device_arrays` with a literal shape today).
    """

    spec: SpecInfo
    context: str  # "P", "NamedSharding", "in_specs", "out_specs", ...
    mesh: Optional[MeshRef] = None
    rank: Optional[int] = None


@dataclass
class ShardMapSite:
    """One `shard_map(fn, mesh=..., in_specs=..., out_specs=...)` call."""

    call: ast.Call
    fn_expr: Optional[ast.AST]
    mesh: Optional[MeshRef]
    in_specs_expr: Optional[ast.AST]
    out_specs_expr: Optional[ast.AST]
    in_top_arity: Optional[int]  # len() of a literal in_specs tuple, else None
    in_leaves: List[SpecInfo] = field(default_factory=list)
    out_leaves: List[SpecInfo] = field(default_factory=list)


def for_context(ctx) -> "ModuleMeshModel":
    """The per-file model, memoized on the FileContext so every consuming rule
    (STX010/STX011) shares one build — and one "parents" map with STX012."""
    parents = ctx.memo("parents", lambda: _annotate_parents(ctx.tree))
    return ctx.memo("meshmodel", lambda: ModuleMeshModel(ctx.tree, parents=parents))


class ModuleMeshModel:
    """Mesh bindings, spec bindings, and every sharding use site of one file."""

    def __init__(
        self, tree: ast.AST, parents: Optional[Dict[int, ast.AST]] = None
    ) -> None:
        self.tree = tree
        # name -> (axes, lineno); a name rebound to meshes with different
        # axes keeps the UNION (conservative: only axes in neither flag).
        self.mesh_bindings: Dict[str, MeshRef] = {}
        self._mesh_unresolved: Set[str] = set()
        # Spec names get the same rebind-poisoning discipline as mesh names:
        # a name resolves to a P(...) literal only when EVERY module-wide
        # binding of it is that single spec literal — any other binding
        # (helper call, rebind, loop/with/tuple target) makes it ambiguous
        # and uses fall back to an opaque leaf instead of a stale or
        # other-scope spec (which would raise error-severity false STX010s).
        self.spec_bindings: Dict[str, SpecInfo] = {}
        self._spec_unresolved: Set[str] = set()
        # Parent links, so resolve_mesh can see that a mesh NAME at a use
        # site is a parameter of its enclosing function — a fresh caller
        # value that must NOT resolve to some other scope's local binding.
        self._parents = parents if parents is not None else _annotate_parents(tree)
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
            ):
                target = node.targets[0]
                axes = mesh_ctor_axes(node.value)
                if axes is not None:
                    prior = self.mesh_bindings.get(target.id)
                    merged = axes | prior.axes if prior else axes
                    self.mesh_bindings[target.id] = MeshRef(
                        axes=frozenset(merged), lineno=node.lineno, name=target.id
                    )
                else:
                    # Any other RHS — a mesh ctor with non-literal axes, a
                    # helper call, a same-scope rebind (`mesh = widen(mesh)`),
                    # or an unrelated same-named local in another scope —
                    # makes the NAME ambiguous module-wide: uses fall back to
                    # the universe rather than a stale/other-scope binding.
                    self._mesh_unresolved.add(target.id)
                if is_spec_call(node.value):
                    if target.id in self.spec_bindings:
                        # Two spec-literal bindings of one name: whichever the
                        # walk met first is stale on the other's paths.
                        self._spec_unresolved.add(target.id)
                    else:
                        self.spec_bindings[target.id] = parse_spec_call(node.value)
                else:
                    self._spec_unresolved.add(target.id)
            elif isinstance(node, ast.Assign):
                for target in node.targets:
                    self._poison(_target_names(target))
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                self._poison(_target_names(node.target))
            elif isinstance(node, (ast.For, ast.AsyncFor, ast.comprehension)):
                self._poison(_target_names(node.target))
            elif isinstance(node, ast.withitem) and node.optional_vars is not None:
                self._poison(_target_names(node.optional_vars))
            elif isinstance(node, ast.NamedExpr):
                self._poison(_target_names(node.target))
        self._collect_sites()

    def _poison(self, names) -> None:
        """A non-constructor binding form makes a name ambiguous for BOTH
        mesh and spec resolution module-wide."""
        names = list(names)
        self._mesh_unresolved.update(names)
        self._spec_unresolved.update(names)

    # -- resolution helpers -------------------------------------------------

    def _is_param_of_enclosing_fn(self, name_node: ast.Name) -> bool:
        current = self._parents.get(id(name_node))
        while current is not None:
            if isinstance(current, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                if name_node.id in _all_param_names(current.args):
                    return True
            current = self._parents.get(id(current))
        return False

    def resolve_mesh(self, expr: Optional[ast.AST]) -> Optional[MeshRef]:
        if expr is None:
            return None
        inline = mesh_ctor_axes(expr)
        if inline is not None:
            return MeshRef(axes=inline, lineno=expr.lineno)
        if isinstance(expr, ast.Name):
            bound = self.mesh_bindings.get(expr.id)
            if (
                bound is not None
                and expr.id not in self._mesh_unresolved
                # A parameter shadows a same-named binding in ANOTHER scope:
                # the caller's mesh is unknown — fall back to the universe.
                and not self._is_param_of_enclosing_fn(expr)
            ):
                return bound
        return None

    def flatten_spec_expr(self, expr: ast.AST, depth: int = 0) -> List[SpecInfo]:
        """Leaf SpecInfos of a (possibly composite) spec expression.

        Composites follow the repo idiom: tuples/lists of specs, NamedTuple
        constructor calls whose arguments are specs
        (`CoreLearnerState(P(), P("data"), ...)`), dict values, and names
        bound to spec literals module-wide. Anything else is one opaque leaf.
        """
        if depth > 6:
            return [SpecInfo(lineno=getattr(expr, "lineno", 0), opaque=True)]
        if is_spec_call(expr):
            return [parse_spec_call(expr)]
        if isinstance(expr, (ast.Tuple, ast.List)):
            out: List[SpecInfo] = []
            for elt in expr.elts:
                out.extend(self.flatten_spec_expr(elt, depth + 1))
            return out
        if isinstance(expr, ast.Dict):
            out = []
            for value in expr.values:
                out.extend(self.flatten_spec_expr(value, depth + 1))
            return out
        if isinstance(expr, ast.Call):
            # NamedTuple/dataclass state-spec constructors: specs ride the args.
            parts: List[SpecInfo] = []
            for arg in list(expr.args) + [kw.value for kw in expr.keywords]:
                parts.extend(self.flatten_spec_expr(arg, depth + 1))
            if parts:
                return parts
            return [SpecInfo(lineno=expr.lineno, opaque=True)]
        if isinstance(expr, ast.Name):
            bound = self.spec_bindings.get(expr.id)
            if (
                bound is not None
                and expr.id not in self._spec_unresolved
                # A parameter shadows a same-named spec in ANOTHER scope: the
                # caller's spec is unknown — treat the leaf as opaque.
                and not self._is_param_of_enclosing_fn(expr)
            ):
                return [bound]
        return [SpecInfo(lineno=getattr(expr, "lineno", 0), opaque=True)]

    # -- site collection ----------------------------------------------------

    def _collect_sites(self) -> None:
        self.spec_uses: List[SpecUse] = []
        self.shard_map_sites: List[ShardMapSite] = []
        # ast node ids of P(...) calls consumed by a governed site, so the
        # final free-spec pass checks each literal exactly once. A spec
        # BINDING consumed by several sites is checked per consuming site
        # with that site's mesh (rules dedupe findings by line+axis).
        governed: Set[int] = set()

        def claim(expr: ast.AST) -> None:
            for node in ast.walk(expr):
                if is_spec_call(node):
                    governed.add(id(node))

        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = _callee_name(node.func)
            if callee == "NamedSharding":
                mesh_expr = node.args[0] if node.args else None
                spec_expr = node.args[1] if len(node.args) >= 2 else None
                for kw in node.keywords:
                    if kw.arg == "mesh":
                        mesh_expr = kw.value
                    elif kw.arg == "spec":
                        spec_expr = kw.value
                if spec_expr is None:
                    continue
                mesh = self.resolve_mesh(mesh_expr)
                claim(spec_expr)
                for spec in self.flatten_spec_expr(spec_expr):
                    self.spec_uses.append(SpecUse(spec, "NamedSharding", mesh=mesh))
            elif callee == "shard_map":
                self._collect_shard_map(node, claim)
            elif callee == "with_sharding_constraint":
                spec_expr = node.args[1] if len(node.args) >= 2 else None
                for kw in node.keywords:
                    if kw.arg in ("shardings", "spec"):
                        spec_expr = kw.value
                if spec_expr is None:
                    continue
                claim(spec_expr)
                for spec in self.flatten_spec_expr(spec_expr):
                    self.spec_uses.append(
                        SpecUse(spec, "with_sharding_constraint", mesh=None)
                    )
            elif callee == "make_array_from_single_device_arrays":
                shape_expr = node.args[0] if node.args else None
                sharding_expr = node.args[1] if len(node.args) >= 2 else None
                for kw in node.keywords:
                    if kw.arg == "shape":
                        shape_expr = kw.value
                    elif kw.arg == "sharding":
                        sharding_expr = kw.value
                rank = None
                if isinstance(shape_expr, (ast.Tuple, ast.List)):
                    rank = len(shape_expr.elts)
                if sharding_expr is None:
                    continue
                # The sharding is usually an inline NamedSharding(mesh, spec):
                # attach the rank to its spec leaves; the NamedSharding branch
                # above re-checks axis validity for the same leaves, so only
                # rank rides this use (context keeps findings deduplicable).
                mesh = None
                spec_expr = sharding_expr
                if (
                    isinstance(sharding_expr, ast.Call)
                    and _callee_name(sharding_expr.func) == "NamedSharding"
                    and len(sharding_expr.args) >= 2
                ):
                    mesh = self.resolve_mesh(sharding_expr.args[0])
                    spec_expr = sharding_expr.args[1]
                else:
                    claim(spec_expr)
                for spec in self.flatten_spec_expr(spec_expr):
                    self.spec_uses.append(
                        SpecUse(spec, "make_array_shape", mesh=mesh, rank=rank)
                    )

        # Free P(...) literals: checked against the universe exactly once.
        for node in ast.walk(self.tree):
            if is_spec_call(node) and id(node) not in governed:
                self.spec_uses.append(SpecUse(parse_spec_call(node), "P", mesh=None))

    def _collect_shard_map(self, node: ast.Call, claim) -> None:
        fn_expr = node.args[0] if node.args else None
        mesh_expr = node.args[1] if len(node.args) >= 2 else None
        in_expr = node.args[2] if len(node.args) >= 3 else None
        out_expr = node.args[3] if len(node.args) >= 4 else None
        for kw in node.keywords:
            if kw.arg == "mesh":
                mesh_expr = kw.value
            elif kw.arg == "in_specs":
                in_expr = kw.value
            elif kw.arg == "out_specs":
                out_expr = kw.value
        mesh = self.resolve_mesh(mesh_expr)
        site = ShardMapSite(
            call=node,
            fn_expr=fn_expr,
            mesh=mesh,
            in_specs_expr=in_expr,
            out_specs_expr=out_expr,
            in_top_arity=(
                len(in_expr.elts) if isinstance(in_expr, (ast.Tuple, ast.List)) else None
            ),
        )
        for expr, context, leaves in (
            (in_expr, "in_specs", site.in_leaves),
            (out_expr, "out_specs", site.out_leaves),
        ):
            if expr is None:
                continue
            claim(expr)
            for spec in self.flatten_spec_expr(expr):
                leaves.append(spec)
                self.spec_uses.append(SpecUse(spec, context, mesh=mesh))
        self.shard_map_sites.append(site)
