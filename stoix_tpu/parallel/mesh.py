"""Global device mesh + sharding helpers — the heart of the TPU-native design.

The reference distributes with single-host `jax.pmap(axis_name="device")` and a
nested `vmap(axis_name="batch")` (reference ff_ppo.py:361-365,487-489,
SURVEY.md §2.3). Here there is ONE global `jax.sharding.Mesh` spanning every
chip in the job (multi-host included) with named axes:

    "data"   — environment / batch sharding; gradients pmean over it, riding
               ICI within a slice and DCN across slices.
    (more axes — "model", "sequence" — can be added per system; helpers below
    are axis-generic.)

Learner steps are written per-shard and wrapped with `jax.shard_map`; inputs
and learner state live as global arrays with NamedShardings, so checkpointing
saves globals directly and there is no `unreplicate_*` dance.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any, Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def shard_map(fn, mesh: Mesh, in_specs: Any, out_specs: Any, check_vma: bool = True):
    """`jax.shard_map` across JAX versions.

    Newer JAX exposes `jax.shard_map` with a `check_vma` validation toggle;
    older releases ship it as `jax.experimental.shard_map.shard_map` where the
    same toggle is spelled `check_rep`. Every stoix_tpu shard_map goes through
    this seam so the whole stack runs on both.

    Legacy caveat: old shard_map's autodiff TRANSPOSES a loss-level cross-shard
    pmean/psum to an axis-size-scaled gradient (2x on a 2-shard axis,
    regardless of check_rep). Differentiate per-shard and pmean the GRADS —
    the pattern every stoix_tpu learner uses — which is exact on both APIs;
    tests/test_tp.py::test_backward_matches_oracle covers the unsupported
    pattern and is skipped on legacy JAX.
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=check_vma
        )
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    return _legacy_shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=check_vma
    )


def create_mesh(
    axes: Optional[Dict[str, int]] = None, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    """Build a Mesh from {axis_name: size}; one size may be -1 (inferred).

    Defaults to a pure data-parallel mesh over all devices in the job
    (jax.devices() is global across hosts after jax.distributed.initialize).
    """
    devices = list(devices if devices is not None else jax.devices())
    axes = dict(axes or {"data": -1})
    sizes = list(axes.values())
    n = len(devices)
    if sizes.count(-1) > 1:
        raise ValueError("At most one mesh axis may be -1")
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1])) if len(sizes) > 1 else 1
        if n % known != 0:
            raise ValueError(f"{n} devices not divisible by fixed axes {axes}")
        sizes[sizes.index(-1)] = n // known
    if int(np.prod(sizes)) != n:
        raise ValueError(f"Mesh axes {dict(zip(axes, sizes))} do not cover {n} devices")
    dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, tuple(axes.keys()))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def data_sharding(mesh: Mesh, axis: str = "data", rank_axis: int = 0) -> NamedSharding:
    """Shard leading (or given) array axis over a mesh axis."""
    spec = [None] * (rank_axis + 1)
    spec[rank_axis] = axis
    return NamedSharding(mesh, P(*spec))


def shard_leading_axis(tree: Any, mesh: Mesh, axis: str = "data") -> Any:
    """Device-put a host pytree with its leading axis sharded over `axis`."""
    sharding = NamedSharding(mesh, P(axis))

    def put(x: Any) -> jax.Array:
        x = jax.numpy.asarray(x)
        spec = P(*([axis] + [None] * (x.ndim - 1)))
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree.map(put, tree)


def replicate(tree: Any, mesh: Mesh) -> Any:
    sharding = NamedSharding(mesh, P())
    return jax.tree.map(lambda x: jax.device_put(jax.numpy.asarray(x), sharding), tree)


def axis_size(mesh: Mesh, axis: str) -> int:
    return int(mesh.shape[axis])


def assemble_global_array(
    per_device_arrays: Sequence[jax.Array], mesh: Mesh, axis: str = "data",
    array_axis: int = 0,
) -> jax.Array:
    """Build one global array from per-device shards without host concat —
    the Sebulba trajectory hand-off primitive (replaces the reference's
    `jax.device_put_sharded`, sebulba/ff_ppo.py:263; see SURVEY.md §7.1.3).

    `array_axis` names the array dimension the shards tile (and the mesh
    axis shards): 0 for leading-axis items (the replay service's transition
    ingestion), 1 for `[T, E]` trajectories whose ENV axis is split across
    learner devices — assembling those on axis 0 would concatenate
    different devices' trajectories along TIME, which silently corrupts
    every cross-step computation downstream (GAE bootstrapping across the
    device seam).
    """
    shard = per_device_arrays[0]
    global_shape = list(shard.shape)
    global_shape[array_axis] = shard.shape[array_axis] * len(per_device_arrays)
    spec_slots: list = [None] * shard.ndim
    spec_slots[array_axis] = axis
    spec = P(*spec_slots)
    return jax.make_array_from_single_device_arrays(
        tuple(global_shape), NamedSharding(mesh, spec), list(per_device_arrays)
    )


# LRU of jitted replicate-identities: move-to-end on hit, evict ONE oldest
# entry at capacity (never clear wholesale — dropping the entire cache on the
# 65th signature would silently recompile every signature thereafter).
_FETCH_GLOBAL_CACHE: "OrderedDict[Any, Any]" = OrderedDict()
_FETCH_GLOBAL_CACHE_SIZE = 64


def fetch_global_async(tree: Any, mesh: Mesh) -> Any:
    """DISPATCH the device half of a global fetch without touching the host.

    Single-process: the tree is returned as-is — device arrays fetch directly
    at materialize() time. Multi-process: enqueue the replicate collective
    (sharded globals span non-addressable devices and cannot be fetched
    directly) and return the still-on-device replicated tree; every process
    must call this, it runs a collective. Splitting dispatch from the host
    copy lets the pipelined Anakin host loop enqueue the collective BEFORE the
    next `learn` dispatch, so materialize() never queues behind a full
    training window. The jitted identity is memoized per tree signature so
    repeated host-loop calls hit the compile cache.
    """
    if jax.process_count() == 1:
        return tree
    leaves, treedef = jax.tree.flatten(tree)
    cache_key = (treedef, tuple((l.shape, str(l.dtype)) for l in leaves), id(mesh))
    fn = _FETCH_GLOBAL_CACHE.get(cache_key)
    if fn is None:
        while len(_FETCH_GLOBAL_CACHE) >= _FETCH_GLOBAL_CACHE_SIZE:
            _FETCH_GLOBAL_CACHE.popitem(last=False)
        shardings = jax.tree.map(lambda _: NamedSharding(mesh, P()), tree)
        fn = jax.jit(lambda t: t, out_shardings=shardings)
        _FETCH_GLOBAL_CACHE[cache_key] = fn
    else:
        _FETCH_GLOBAL_CACHE.move_to_end(cache_key)
    return fn(tree)


def materialize(tree: Any) -> Any:
    """Host-materialize a (possibly in-flight) device tree as numpy — the
    blocking half of fetch_global_async. Blocks only until the arrays' own
    producers finish, not until the whole device queue drains."""
    return jax.tree.map(np.asarray, tree)


def fetch_global(tree: Any, mesh: Mesh) -> Any:
    """Bring (possibly sharded) global arrays to the host as numpy.

    Distinct from distributed.process_allgather, which gathers HOST-LOCAL
    values. Synchronous convenience wrapper; the pipelined host loop uses the
    fetch_global_async / materialize halves separately.
    """
    return materialize(fetch_global_async(tree, mesh))
