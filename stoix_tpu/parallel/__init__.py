from stoix_tpu.parallel.gossip import (
    GossipError,
    GossipPlan,
    GossipSettings,
    build_gossip_plan,
    mixing_matrix,
)
from stoix_tpu.parallel.distributed import (
    is_coordinator,
    maybe_initialize_distributed,
    process_allgather,
)
from stoix_tpu.parallel.mesh import (
    assemble_global_array,
    fetch_global,
    fetch_global_async,
    materialize,
    shard_map,
    axis_size,
    create_mesh,
    data_sharding,
    replicate,
    replicated_sharding,
    shard_leading_axis,
)
from stoix_tpu.parallel.roles import (
    MeshRoles,
    MeshRolesError,
    RoleAssignment,
    resolve_assignments,
)

__all__ = [
    "GossipError",
    "GossipPlan",
    "GossipSettings",
    "build_gossip_plan",
    "mixing_matrix",
    "is_coordinator",
    "maybe_initialize_distributed",
    "process_allgather",
    "MeshRoles",
    "MeshRolesError",
    "RoleAssignment",
    "resolve_assignments",
    "assemble_global_array",
    "fetch_global",
    "fetch_global_async",
    "materialize",
    "shard_map",
    "axis_size",
    "create_mesh",
    "data_sharding",
    "replicate",
    "replicated_sharding",
    "shard_leading_axis",
]
