"""Tensor-parallel building blocks (Megatron-style column/row split).

The reference has no model parallelism (SURVEY.md §2.3 — its actor-critic
nets are small), so this module is a beyond-parity capability for scaling
WIDE torsos over a mesh "model" axis: the classic two-matmul pattern where

  - the FIRST Dense is COLUMN-parallel: each shard holds W1[:, shard] and
    produces its slice of the hidden activation (no communication), and
  - the SECOND Dense is ROW-parallel: each shard holds W2[shard, :] and
    contributes a partial product, combined with ONE psum over "model"
    (riding ICI on real hardware).

One collective per block instead of per layer; the hidden dimension (where
the parameters and FLOPs are) never materializes unsharded. Functions take
explicit per-shard parameter slices and are designed to run INSIDE
`jax.shard_map` with the model axis in scope; `init_column_row_params`
builds the per-shard slices from a global init for placement via
`NamedSharding(mesh, P(...))`.

Composable with the data axis: inputs batch-sharded over "data" and weights
sharded over "model" give the standard 2-D DP x TP layout.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp


class ColumnRowParams(NamedTuple):
    """Per-shard parameter slices for one column->row parallel block.

    w1: [d_in, d_hidden/m]   (column shard)
    b1: [d_hidden/m]
    w2: [d_hidden/m, d_out]  (row shard)
    b2: [d_out]              (replicated; added AFTER the psum on shard 0's
                              contribution semantics — here added once
                              post-psum, so stored replicated)
    """

    w1: jax.Array
    b1: jax.Array
    w2: jax.Array
    b2: jax.Array


def init_column_row_params(
    key: jax.Array,
    d_in: int,
    d_hidden: int,
    d_out: int,
    num_shards: int,
    dtype: jnp.dtype = jnp.float32,
) -> ColumnRowParams:
    """Global parameters with a LEADING shard axis on the split dimensions:
    w1 [m, d_in, d_hidden/m], w2 [m, d_hidden/m, d_out] — place with
    `NamedSharding(mesh, P("model"))` on the leading axis. Inside shard_map
    each shard sees a SINGLETON leading axis (shard_map splits, it does not
    squeeze); column_row_block strips it."""
    if d_hidden % num_shards:
        raise ValueError(f"d_hidden {d_hidden} not divisible by {num_shards} shards")
    k1, k2 = jax.random.split(key)
    local = d_hidden // num_shards
    scale1 = 1.0 / jnp.sqrt(jnp.asarray(d_in, jnp.float32))
    scale2 = 1.0 / jnp.sqrt(jnp.asarray(d_hidden, jnp.float32))
    return ColumnRowParams(
        w1=(jax.random.normal(k1, (num_shards, d_in, local), dtype) * scale1),
        b1=jnp.zeros((num_shards, local), dtype),
        w2=(jax.random.normal(k2, (num_shards, local, d_out), dtype) * scale2),
        b2=jnp.zeros((d_out,), dtype),
    )


def column_row_block(
    params: ColumnRowParams,
    x: jax.Array,
    axis_name: str = "model",
    activation: Optional[Callable[[jax.Array], jax.Array]] = None,
) -> jax.Array:
    """Apply the column->row parallel block to x [..., d_in] INSIDE shard_map.

    params holds THIS shard's slices, with the singleton leading shard axis
    shard_map leaves in place (stripped here so gradients keep the in_specs
    shape). Exactly one psum over `axis_name`.
    """
    activation = activation or jax.nn.relu
    w1, b1, w2 = params.w1, params.b1, params.w2
    if w1.ndim == 3:  # singleton per-shard axis from in_specs P("model")
        w1, b1, w2 = w1[0], b1[0], w2[0]
    hidden = activation(x @ w1 + b1)  # [..., d_hidden/m], local
    partial = hidden @ w2  # [..., d_out], partial sum
    return jax.lax.psum(partial, axis_name) + params.b2


def reference_block(
    params: ColumnRowParams, x: jax.Array, activation=None
) -> jax.Array:
    """Unsharded oracle over the stacked global params (testing/validation):
    concatenate the shard slices back into the full matrices."""
    activation = activation or jax.nn.relu
    w1 = jnp.concatenate(list(params.w1), axis=-1)  # [d_in, d_hidden]
    b1 = jnp.concatenate(list(params.b1), axis=-1)  # [d_hidden]
    w2 = jnp.concatenate(list(params.w2), axis=0)  # [d_hidden, d_out]
    hidden = activation(x @ w1 + b1)
    return hidden @ w2 + params.b2


def tp_specs() -> Tuple:
    """(in_specs params, data spec) helpers for the common shard_map call."""
    from jax.sharding import PartitionSpec as P

    return (
        ColumnRowParams(w1=P("model"), b1=P("model"), w2=P("model"), b2=P()),
        P("data"),
    )
