"""Multi-host initialisation and host-side coordination.

The reference explicitly does not support multi-host (reference
sebulba/ff_ppo.py:808-810 asserts local == global devices; README.md:57).
Here multi-host is first-class: call `maybe_initialize_distributed()` before
any JAX computation; the global mesh then spans all processes and collectives
ride ICI within a slice / DCN across slices automatically via shardings.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np


def maybe_initialize_distributed(config: Optional[Any] = None) -> None:
    """Initialise jax.distributed when running under a multi-process launcher.

    Controlled by (in priority order) config.arch.distributed fields or the
    standard env vars (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES /
    JAX_PROCESS_ID, or a cloud-TPU environment where jax.distributed can
    auto-detect). No-op for single-process runs.

    A HALF-configured launch — num_processes > 1 declared (config or env)
    but no coordinator address anywhere — raises ConfigValidationError
    instead of silently falling back to single-process: the old behavior let
    a "pod" run train 1/N of the batch with every collective a local no-op
    and NO error anywhere, which is the worst possible failure mode (wrong
    numbers, green dashboards).
    """
    dist_cfg = None
    if config is not None:
        dist_cfg = getattr(getattr(config, "arch", None), "distributed", None)

    coordinator = os.environ.get("JAX_COORDINATOR_ADDRESS")
    if dist_cfg and dist_cfg.get("coordinator_address"):
        coordinator = dist_cfg["coordinator_address"]

    if coordinator is None:
        declared = None
        source = None
        if dist_cfg and dist_cfg.get("num_processes") not in (None, "~"):
            declared, source = dist_cfg.get("num_processes"), "arch.distributed.num_processes"
        elif os.environ.get("JAX_NUM_PROCESSES"):
            declared, source = os.environ["JAX_NUM_PROCESSES"], "JAX_NUM_PROCESSES"
        if declared is not None and int(declared) > 1:
            from stoix_tpu.resilience.errors import ConfigValidationError

            raise ConfigValidationError(
                [
                    f"{source}={declared} declares a multi-process launch but "
                    f"no coordinator address is set (JAX_COORDINATOR_ADDRESS "
                    f"or arch.distributed.coordinator_address): refusing to "
                    f"silently run single-process — this 'pod' would train "
                    f"1/{int(declared)} of the batch with every cross-host "
                    f"collective a local no-op and no error anywhere"
                ]
            )
        return  # single process (or an environment where auto-detect is unsafe)

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=int(
            (dist_cfg or {}).get("num_processes", os.environ.get("JAX_NUM_PROCESSES", 1))
        ),
        process_id=int(
            (dist_cfg or {}).get(
                "process_id",
                os.environ.get("JAX_PROCESS_ID", os.environ.get("SLURM_PROCID", 0)),
            )
        ),
    )


def is_coordinator() -> bool:
    """True on process 0 — gate logging/checkpointing/eval-printing on this."""
    return jax.process_index() == 0


def process_allgather(x: Any) -> Any:
    """Gather host-local values across processes (fully-replicated result).

    Equivalent to jax.experimental.multihost_utils.process_allgather; used for
    cross-host metric aggregation in the host loop.
    """
    if jax.process_count() == 1:
        return jax.tree.map(np.asarray, x)
    from jax.experimental import multihost_utils

    return multihost_utils.process_allgather(x)
