"""Gossip-averaged learner groups (docs/DESIGN.md §2.12).

"Gossip-based Actor-Learner Architectures" (arxiv 1906.04585) decouples a
pod's throughput from its slowest slice: the dense gradient all-reduce runs
WITHIN a learner group only, and groups exchange parameters through a sparse,
periodic gossip average instead of a fleet-wide collective. A straggling
group delays its neighbours by one mixing edge, not the whole pod.

This module is the group-mixing half. The grouped learner itself is plain
ff_ppo on a ("group", "data") mesh: inside shard_map the learner's
`pmean(axis_name="data")` reduces within the group automatically, because
shard_map scopes named-axis collectives to the mesh axes they name — no
learner change at all. What remains is averaging the per-group parameter
stacks, and that is ONE mixing-matrix contraction:

    params'[g] = sum_h W[g, h] * params[h]        W: [G, G] doubly stochastic

GSPMD partitions the einsum over the P("group") sharding, inserting exactly
the cross-group collective the topology implies. Topologies:

  ring         W = (1-w)·I + (w/2)·(R + Rᵀ)       (R = one-step rotation;
                                                    G == 2 collapses to the
                                                    single shared edge)
  all_pairs    W = (1-w)·I + (w/G)·1               (dense average, the
                                                    synchronous limit)
  random_peer  W = (1-w)·I + w·R^s,  s ~ U[1, G)   (one random directed edge
                                                    per group per round; s is
                                                    derived in-graph from the
                                                    round index, so EVERY
                                                    round reuses one compiled
                                                    program)

All three are doubly stochastic, so the group-mean of the parameters is
invariant under mixing and repeated rounds contract the groups toward
consensus at rate governed by W's spectral gap.

Bit-identity contract (pinned, tests/test_gossip.py): with ONE group the
step is the IDENTITY — returned un-dispatched, not computed — because even
W = [[1.0]] would evaluate `(1-w)·p + w·p`, which is NOT bitwise `p` under
float arithmetic. A single-group gossip run is therefore the lockstep path.
"""

from __future__ import annotations

import os
from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# The canonical learner-group mesh axis. The P literal below is what
# registers "group" in the static mesh-axis universe the analysis rules
# check collectives and sharding specs against (STX007/STX010) — the YAML
# half of the declaration lives in configs/arch/gossip.yaml's mesh block.
GROUP_AXIS = "group"
GROUP_SPEC = P("group")

TOPOLOGIES = ("ring", "all_pairs", "random_peer")


class GossipError(ValueError):
    """Invalid arch.gossip block or grouped-mesh configuration."""


class GossipSettings(NamedTuple):
    """Resolved `arch.gossip` config block (defaults applied)."""

    enabled: bool
    interval: int  # gossip every N eval windows
    topology: str  # ring | all_pairs | random_peer
    mixing_weight: float  # w in (0, 1]: how far toward the neighbours to move
    average_opt_states: bool  # mix optimizer state alongside params
    seed: int  # random_peer edge stream seed


class GossipPlan(NamedTuple):
    """What the Anakin runner needs to dispatch gossip: a jitted step (None
    when the mix is the identity — one group), the window cadence, and the
    shape facts bench.py reports."""

    step: Optional[Callable[[Any, jax.Array], Any]]
    interval: int
    topology: str
    num_groups: int
    mixing_weight: float
    average_opt_states: bool


def settings_from_config(config: Any) -> GossipSettings:
    block = dict((config.get("arch") or {}).get("gossip") or {})
    settings = GossipSettings(
        enabled=bool(block.get("enabled", False)),
        interval=int(block.get("interval", 1)),
        topology=str(block.get("topology", "ring")),
        mixing_weight=float(block.get("mixing_weight", 0.5)),
        average_opt_states=bool(block.get("average_opt_states", False)),
        seed=int(block.get("seed", 0)),
    )
    if settings.interval < 1:
        raise GossipError(
            f"arch.gossip.interval must be >= 1 (got {settings.interval})"
        )
    if settings.topology not in TOPOLOGIES:
        raise GossipError(
            f"arch.gossip.topology must be one of {TOPOLOGIES} "
            f"(got '{settings.topology}')"
        )
    if not (0.0 < settings.mixing_weight <= 1.0):
        raise GossipError(
            "arch.gossip.mixing_weight must be in (0, 1] "
            f"(got {settings.mixing_weight})"
        )
    return settings


def validate_grouped_config(config: Any, mesh: Mesh) -> GossipSettings:
    """Cross-checks for a grouped-learner run; returns the resolved settings.

    Raised findings mirror the population runner's refusals: subsystems that
    assume REPLICATED learner state cannot run over a state sharded on the
    group axis."""
    if GROUP_AXIS not in mesh.axis_names:
        raise GossipError(
            f"grouped learner training needs a '{GROUP_AXIS}' mesh axis; "
            f"arch.mesh declares {dict(mesh.shape)} — compose with arch=gossip "
            "(or add group to arch.mesh)"
        )
    settings = settings_from_config(config)
    num_groups = int(mesh.shape[GROUP_AXIS])
    if num_groups > 1 and not settings.enabled:
        raise GossipError(
            f"arch.mesh declares {num_groups} learner groups but "
            "arch.gossip.enabled=false: the groups would train forever "
            "WITHOUT exchanging parameters (set arch.gossip.enabled=true, or "
            "use group: 1)"
        )
    if bool(((config.get("arch") or {}).get("integrity") or {}).get("enabled", False)):
        raise GossipError(
            "arch.integrity.enabled=true is not supported under grouped "
            "training: the sentinel's replica fingerprints assume replicated "
            "state, but each group owns DIFFERENT params between gossip "
            "rounds (docs/DESIGN.md §2.12)"
        )
    if bool(config.arch.get("fused_eval", False)):
        raise GossipError(
            "arch.fused_eval is not supported under grouped training (the "
            "evaluator serves group 0's slice, selected outside the learn "
            "program)"
        )
    return settings


def mixing_matrix(
    settings: GossipSettings, num_groups: int, round_idx: jax.Array
) -> jax.Array:
    """The [G, G] doubly-stochastic mixing matrix for one gossip round.

    `round_idx` may be traced: random_peer derives its shift in-graph
    (fold_in + randint + dynamic roll), so the topology's randomness never
    forces a recompile."""
    w = settings.mixing_weight  # already a host float (settings_from_config)
    eye = jnp.eye(num_groups, dtype=jnp.float32)
    if settings.topology == "all_pairs":
        dense = jnp.full((num_groups, num_groups), 1.0 / num_groups, jnp.float32)
        return (1.0 - w) * eye + w * dense
    if settings.topology == "ring":
        right = jnp.roll(eye, 1, axis=1)
        if num_groups == 2:
            # Left and right neighbour are the SAME group: one edge, full w.
            return (1.0 - w) * eye + w * right
        left = jnp.roll(eye, -1, axis=1)
        return (1.0 - w) * eye + (w / 2.0) * (right + left)
    # random_peer: one directed edge per group, shared shift s in [1, G).
    edge_key = jax.random.fold_in(jax.random.PRNGKey(settings.seed), round_idx)
    shift = jax.random.randint(edge_key, (), 1, num_groups)
    return (1.0 - w) * eye + w * jnp.roll(eye, shift, axis=1)


def _mix_leaf(matrix: jax.Array, leaf: jax.Array) -> jax.Array:
    """Contract the leading [G] axis with the mixing matrix. Integer leaves
    (optax step counters) pass through — they are identical across groups by
    construction and averaging them in float would corrupt the dtype."""
    if not jnp.issubdtype(leaf.dtype, jnp.inexact):
        return leaf
    mixed = jnp.tensordot(matrix, leaf.astype(jnp.float32), axes=1)
    return mixed.astype(leaf.dtype)


def build_gossip_plan(
    config: Any, mesh: Mesh, state_specs: Any = None
) -> Optional[GossipPlan]:
    """Build the jitted gossip step for a grouped learner state.

    The state must expose `.params` and `.opt_states` (`PPOLearnerState` and
    every Anakin learner state do) with a leading [G] axis sharded
    P("group"). Returns None when gossip is disabled; returns a plan with
    `step=None` for ONE group (identity — see the module docstring's
    bit-identity contract)."""
    settings = settings_from_config(config)
    if not settings.enabled:
        return None
    if GROUP_AXIS not in mesh.axis_names:
        raise GossipError(
            f"arch.gossip.enabled=true needs a '{GROUP_AXIS}' mesh axis; "
            f"arch.mesh declares {dict(mesh.shape)}"
        )
    num_groups = int(mesh.shape[GROUP_AXIS])
    plan_facts = dict(
        interval=settings.interval,
        topology=settings.topology,
        num_groups=num_groups,
        mixing_weight=settings.mixing_weight,
        average_opt_states=settings.average_opt_states,
    )
    if num_groups == 1:
        return GossipPlan(step=None, **plan_facts)

    def _gossip(state: Any, round_idx: jax.Array) -> Any:
        matrix = mixing_matrix(settings, num_groups, round_idx)
        mix = lambda tree: jax.tree.map(lambda x: _mix_leaf(matrix, x), tree)
        state = state._replace(params=mix(state.params))
        if settings.average_opt_states:
            state = state._replace(opt_states=mix(state.opt_states))
        return state

    jit_kwargs: dict = {}
    if state_specs is not None:
        # Pin the output back onto the grouped specs so the next learn
        # dispatch consumes it with zero resharding (GSPMD would otherwise be
        # free to replicate the einsum result).
        jit_kwargs["out_shardings"] = jax.tree.map(
            lambda spec: NamedSharding(mesh, spec),
            state_specs,
            is_leaf=lambda s: isinstance(s, P),
        )
    if not os.environ.get("STOIX_TPU_NO_DONATE"):
        # Same donation contract as the learner (systems/anakin.py): the host
        # loop never reads the pre-gossip state again, and the snapshot the
        # runner takes afterwards copies the gossip OUTPUT.
        jit_kwargs["donate_argnums"] = (0,)
    return GossipPlan(step=jax.jit(_gossip, **jit_kwargs), **plan_facts)
