"""MeshRoles — ONE description of which devices play which role.

Before this module, three subsystems each invented their own device
bookkeeping:

  * Anakin (`systems/runner.py`) built a global mesh straight from
    `arch.mesh` and implicitly ran every role (act / learn / evaluate) on
    every device;
  * Sebulba (`systems/ppo/sebulba/ff_ppo.py`, `systems/q_learning/sebulba/
    ff_dqn.py`) indexed `jax.devices()` with `arch.actor.device_ids` /
    `arch.learner.device_ids` / `arch.evaluator_device_id` and hand-rolled
    the learner mesh;
  * serve (`serve/server.py`) silently used whatever jax's default device
    was.

`MeshRoles` replaces all three: it is constructed ONCE from `arch.mesh` +
`arch.roles` (with back-compat derivation from the legacy Sebulba keys and
the architecture name when `arch.roles` is absent), validated as a whole
(ids in range, act/learn either colocated or disjoint — never a partial
overlap), and consumed by the Anakin runner, the Sebulba device split, the
replay service's data axis (via the learn mesh), the serve path, and the
population runner (`stoix_tpu/population`, whose learn role owns the
("pop", "data") mesh).

Config shape (docs/DESIGN.md §2.11):

    arch:
      mesh: {data: -1}          # axes of the LEARN role's mesh
      roles: ~                  # ~ = derive from architecture_name + legacy
                                # keys; or an explicit mapping:
      # roles:
      #   act:      {device_ids: [0]}
      #   learn:    {device_ids: [1, 2, 3], mesh: {data: -1}}
      #   evaluate: {device_ids: [0]}
      #   serve:    {device_ids: [0]}

Role semantics: `act` and `learn` are the PRIMARY roles — they must either
be colocated (identical device sets, the Anakin/population shape) or
disjoint (the Sebulba split); a partial overlap is always a config bug.
`evaluate` and `serve` are rider roles that may alias any device.

The resolution half (`resolve_assignments`) is deliberately jax-free so
`resilience/preflight.py` can validate a split against the PROBED device
count without touching jax in the parent process; `MeshRoles` materializes
actual `jax.Device` objects and meshes lazily.
"""

from __future__ import annotations

from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

ROLE_ACT = "act"
ROLE_LEARN = "learn"
ROLE_EVALUATE = "evaluate"
ROLE_SERVE = "serve"
ROLE_NAMES = (ROLE_ACT, ROLE_LEARN, ROLE_EVALUATE, ROLE_SERVE)

# Primary roles partition the compute; rider roles may alias any device.
PRIMARY_ROLES = (ROLE_ACT, ROLE_LEARN)


class MeshRolesError(ValueError):
    """A role assignment that cannot be satisfied; carries ALL findings."""

    def __init__(self, findings: Sequence[str]):
        self.findings = list(findings)
        super().__init__(
            "mesh-role assignment invalid:\n  - " + "\n  - ".join(self.findings)
        )


class RoleAssignment(NamedTuple):
    """One role's share of the job: which device ids it owns (None = all
    devices in the job) and which mesh axes its programs run over."""

    role: str
    device_ids: Optional[Tuple[int, ...]]  # None = every device
    axes: Dict[str, int]

    def resolved_ids(self, device_count: int) -> Tuple[int, ...]:
        if self.device_ids is None:
            return tuple(range(device_count))
        return self.device_ids


def _as_id_tuple(raw: Any) -> Optional[Tuple[int, ...]]:
    if raw is None:
        return None
    return tuple(int(i) for i in raw)


def resolve_assignments(
    config: Any, device_count: Optional[int] = None
) -> Dict[str, RoleAssignment]:
    """Resolve `arch.roles` (or the legacy per-architecture keys) into role
    assignments, validating the partition invariants. Pure host logic — no
    jax import — so preflight can run it against the probed device count.

    Raises MeshRolesError listing EVERY finding at once (the preflight
    discipline: one run reports the whole config's problems).
    """
    arch = config.get("arch") or {}
    mesh_axes = dict(arch.get("mesh") or {"data": -1})
    arch_name = str(arch.get("architecture_name", "anakin"))
    explicit = arch.get("roles") or None

    findings: List[str] = []
    assignments: Dict[str, RoleAssignment] = {}

    if explicit:
        for role, spec in dict(explicit).items():
            if role not in ROLE_NAMES:
                findings.append(
                    f"arch.roles names unknown role '{role}' "
                    f"(known: {', '.join(ROLE_NAMES)})"
                )
                continue
            spec = spec or {}
            axes = dict(spec.get("mesh") or {})
            if role == ROLE_LEARN and not axes:
                axes = dict(mesh_axes)
            assignments[role] = RoleAssignment(
                role, _as_id_tuple(spec.get("device_ids")), axes
            )
        if ROLE_LEARN not in assignments:
            findings.append("arch.roles must assign the 'learn' role")
    elif arch_name == "sebulba":
        actor_ids = _as_id_tuple((arch.get("actor") or {}).get("device_ids")) or ()
        learner_ids = _as_id_tuple((arch.get("learner") or {}).get("device_ids")) or ()
        eval_id = int(arch.get("evaluator_device_id", 0))
        if not actor_ids or not learner_ids:
            findings.append(
                "arch.actor.device_ids and arch.learner.device_ids must both "
                "be non-empty"
            )
        assignments[ROLE_ACT] = RoleAssignment(ROLE_ACT, actor_ids, {})
        assignments[ROLE_LEARN] = RoleAssignment(
            ROLE_LEARN, learner_ids, {"data": -1}
        )
        assignments[ROLE_EVALUATE] = RoleAssignment(
            ROLE_EVALUATE, (eval_id,), {"data": 1}
        )
    elif arch_name == "serve":
        # Serving owns one device by default (the pre-MeshRoles behavior:
        # jax's default device, which is device 0).
        assignments[ROLE_SERVE] = RoleAssignment(ROLE_SERVE, (0,), {})
    else:
        # Anakin / population: every role colocated on the whole mesh.
        for role in (ROLE_ACT, ROLE_LEARN, ROLE_EVALUATE):
            assignments[role] = RoleAssignment(role, None, dict(mesh_axes))

    # --- invariants, against the probed count when one is known -------------
    if device_count is not None:
        bad = sorted(
            {
                i
                for a in assignments.values()
                if a.device_ids is not None
                for i in a.device_ids
                if not 0 <= i < device_count
            }
        )
        if bad:
            by_role = {
                a.role: list(a.device_ids)
                for a in assignments.values()
                if a.device_ids is not None
            }
            findings.append(
                f"device ids {bad} out of range for the {device_count} probed "
                f"devices (roles: {by_role})"
            )

    act = assignments.get(ROLE_ACT)
    learn = assignments.get(ROLE_LEARN)
    if act is not None and learn is not None:
        # device_ids=None means "every device": resolvable against a known
        # device count, and against an explicit peer it is the full range —
        # so the only unresolvable pairing is one-None with no count.
        act_ids = learn_ids = None
        if act.device_ids is not None and learn.device_ids is not None:
            act_ids, learn_ids = set(act.device_ids), set(learn.device_ids)
        elif device_count is not None:
            act_ids = set(act.resolved_ids(device_count))
            learn_ids = set(learn.resolved_ids(device_count))
        if act_ids is not None and act_ids != learn_ids and act_ids & learn_ids:
            findings.append(
                f"act and learn roles partially overlap on device ids "
                f"{sorted(act_ids & learn_ids)} — primary roles must be "
                "either colocated (identical sets) or disjoint"
            )

    for a in assignments.values():
        sizes = list(a.axes.values())
        if sizes.count(-1) > 1:
            findings.append(
                f"role '{a.role}': at most one mesh axis may be -1, got {a.axes}"
            )

    if findings:
        raise MeshRolesError(findings)
    return assignments


def elastic_mesh_axes(
    axes: Optional[Dict[str, int]], device_count: int
) -> Dict[str, int]:
    """Re-derive a mesh axis spec for a DIFFERENT device count (the elastic
    relaunch path, docs/DESIGN.md §2.14). Pure host logic — no jax — so the
    supervising launcher can compute the survivor topology before spawning.

    A `-1` axis already absorbs whatever count the child probes, so the spec
    passes through untouched. When every axis is pinned, the `data` axis is
    rescaled to fit (the population shape: `{pop: P, data: -1→fixed}`); a
    count the fixed axes cannot divide is refused rather than silently
    truncated — the caller must shrink the other axes (e.g. the population)
    first.
    """
    if device_count < 1:
        raise MeshRolesError(
            [f"cannot derive a mesh for {device_count} devices"]
        )
    axes = dict(axes or {"data": -1})
    if any(size == -1 for size in axes.values()):
        return axes
    fixed = 1
    for name, size in axes.items():
        if name != "data":
            fixed *= int(size)
    if "data" not in axes:
        raise MeshRolesError(
            [
                f"mesh axes {axes} have no -1 axis and no 'data' axis to "
                f"rescale for {device_count} devices"
            ]
        )
    if fixed < 1 or device_count % fixed != 0:
        raise MeshRolesError(
            [
                f"mesh axes {axes} cannot be rescaled to {device_count} "
                f"devices: the non-data axes multiply to {fixed}, which does "
                f"not divide {device_count}"
            ]
        )
    rescaled = dict(axes)
    rescaled["data"] = device_count // fixed
    return rescaled


class MeshRoles:
    """Materialized role → devices/mesh mapping for this process's job.

    The single device-bookkeeping object consumed by the Anakin runner
    (learn mesh), the Sebulba split (act/learn/evaluate devices + learn
    mesh), the replay service (the learn mesh's data axis), serve (the serve
    device), and the population runner (("pop", "data") learn mesh).
    """

    def __init__(self, assignments: Dict[str, RoleAssignment], devices: Sequence[Any]):
        self._assignments = dict(assignments)
        self._devices = list(devices)

    @classmethod
    def from_config(cls, config: Any, devices: Optional[Sequence[Any]] = None) -> "MeshRoles":
        if devices is None:
            import jax

            devices = jax.devices()
        devices = list(devices)
        return cls(resolve_assignments(config, device_count=len(devices)), devices)

    # -- queries --------------------------------------------------------------
    @property
    def roles(self) -> Tuple[str, ...]:
        return tuple(self._assignments)

    def has_role(self, role: str) -> bool:
        return role in self._assignments

    def assignment(self, role: str) -> RoleAssignment:
        if role not in self._assignments:
            raise MeshRolesError(
                [f"role '{role}' is not assigned (assigned: {', '.join(self.roles)})"]
            )
        return self._assignments[role]

    def role_device_ids(self, role: str) -> Tuple[int, ...]:
        return self.assignment(role).resolved_ids(len(self._devices))

    def role_devices(self, role: str) -> List[Any]:
        return [self._devices[i] for i in self.role_device_ids(role)]

    def device(self, role: str) -> Any:
        return self.role_devices(role)[0]

    def role_mesh(self, role: str):
        """The role's mesh: its axes over its devices. Roles declared without
        axes get a pure data-parallel mesh over their devices."""
        from stoix_tpu.parallel.mesh import create_mesh

        a = self.assignment(role)
        axes = dict(a.axes) or {"data": -1}
        return create_mesh(axes, devices=self.role_devices(role))

    def learn_mesh(self):
        return self.role_mesh(ROLE_LEARN)

    def colocated(self, role_a: str, role_b: str) -> bool:
        return set(self.role_device_ids(role_a)) == set(self.role_device_ids(role_b))

    def describe(self) -> str:
        parts = []
        for role, a in self._assignments.items():
            ids = a.resolved_ids(len(self._devices))
            axes = f" axes={a.axes}" if a.axes else ""
            parts.append(f"{role}=[{','.join(map(str, ids))}]{axes}")
        return " ".join(parts)
