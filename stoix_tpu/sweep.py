"""Hyperparameter search — the reference's Optuna-sweeper equivalent
(reference configs/default/anakin/hyperparameter_sweep.yaml: Optuna TPE
multirun over a search space). Optuna is not a dependency here; this module
provides random + grid search over dotted-override spaces with the same
maximize-final-eval-return objective.

Usage:
    python -m stoix_tpu.sweep --module stoix_tpu.systems.ppo.anakin.ff_ppo \
        --default default/anakin/default_ff_ppo.yaml --trials 8 \
        --space system.actor_lr=loguniform:1e-5,1e-2 \
                system.ent_coef=uniform:0.0,0.05 \
                system.epochs=choice:2,4,8 \
        --set env=cartpole arch.total_timesteps=1e6
"""

from __future__ import annotations

import argparse
import importlib
import itertools
import json
import random
from typing import Any, Dict, List, Tuple

from stoix_tpu.utils import config as config_lib


def _coerce(raw: str):
    """Typed choice values: ints, then floats (incl. '3e-4'), else strings."""
    for cast in (int, float):
        try:
            return cast(raw)
        except ValueError:
            continue
    return raw


def parse_space(entries: List[str]) -> Dict[str, Tuple[str, list]]:
    """'key=kind:a,b,...' -> {key: (kind, args)}; kinds: uniform, loguniform,
    choice, int."""
    space = {}
    for entry in entries:
        key, spec = entry.split("=", 1)
        kind, _, raw = spec.partition(":")
        args = [_coerce(a) for a in raw.split(",")] if raw else []
        space[key] = (kind, args)
    return space


def sample_point(space: Dict[str, Tuple[str, list]], rng: random.Random) -> Dict[str, Any]:
    point = {}
    for key, (kind, args) in space.items():
        if kind == "uniform":
            lo, hi = float(args[0]), float(args[1])
            point[key] = rng.uniform(lo, hi)
        elif kind == "loguniform":
            import math

            lo, hi = math.log(float(args[0])), math.log(float(args[1]))
            point[key] = math.exp(rng.uniform(lo, hi))
        elif kind == "int":
            point[key] = rng.randint(int(args[0]), int(args[1]))
        elif kind == "choice":
            point[key] = rng.choice(args)
        else:
            raise ValueError(f"Unknown space kind '{kind}' for {key}")
    return point


def grid_points(space: Dict[str, Tuple[str, list]]) -> List[Dict[str, Any]]:
    keys = list(space)
    choices = []
    for key in keys:
        kind, args = space[key]
        if kind != "choice":
            raise ValueError("grid search requires choice: spaces only")
        choices.append(args)
    return [dict(zip(keys, combo)) for combo in itertools.product(*choices)]


def run_sweep(
    module: str,
    default: str,
    space: Dict[str, Tuple[str, list]],
    fixed_overrides: List[str],
    trials: int = 8,
    method: str = "random",
    seed: int = 0,
) -> Dict[str, Any]:
    mod = importlib.import_module(module)
    rng = random.Random(seed)
    points = (
        grid_points(space) if method == "grid" else [sample_point(space, rng) for _ in range(trials)]
    )

    results = []
    for i, point in enumerate(points):
        cfg = config_lib.compose(config_lib.default_config_dir(), default, fixed_overrides)
        # Apply sampled values TYPED (stringifying small floats like 1e-05 and
        # re-parsing via YAML 1.1 would silently turn them into strings).
        for k, v in point.items():
            config_lib._set_dotted(cfg, k, v)
        score = mod.run_experiment(cfg)
        results.append({"trial": i, "params": point, "score": float(score)})
        print(json.dumps(results[-1]), flush=True)

    best = max(results, key=lambda r: r["score"])
    print(json.dumps({"best": best}), flush=True)
    return best


def main(argv: List[str] | None = None) -> Dict[str, Any]:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--module", required=True)
    parser.add_argument("--default", required=True, help="default yaml under configs/")
    parser.add_argument("--trials", type=int, default=8)
    parser.add_argument("--method", choices=["random", "grid"], default="random")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--space", nargs="+", required=True)
    parser.add_argument("--set", nargs="*", default=[], dest="overrides",
                        help="fixed key=value overrides")
    args = parser.parse_args(argv)
    return run_sweep(
        args.module,
        args.default,
        parse_space(args.space),
        args.overrides,
        trials=args.trials,
        method=args.method,
        seed=args.seed,
    )


if __name__ == "__main__":
    main()
