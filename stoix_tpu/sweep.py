"""Hyperparameter search — the reference's Optuna-sweeper equivalent
(reference configs/default/anakin/hyperparameter_sweep.yaml: Optuna TPE
multirun over a search space). Optuna is not a dependency here; this module
provides random, grid, and first-party TPE search over dotted-override spaces
with the same maximize-final-eval-return objective.

TPE (Bergstra et al. 2011, the sampler the reference's Optuna config selects):
after `n_startup` random trials, observed points split into good (top gamma
quantile by score) and bad; numeric params get Parzen (Gaussian-kernel)
densities l(x) over good and g(x) over bad, candidates are drawn from l and
ranked by l/g; choice params use smoothed count ratios.

Backends (docs/DESIGN.md §2.11):
    sequential — one compile+train per trial point (the historical shape);
    population — the whole grid/TPE batch maps onto ONE mesh-parallel
        population run (stoix_tpu/population): every point becomes a member
        on the ("pop", "data") mesh, trained in a single jitted program. The
        results JSON schema is identical; `score` is the member's final
        fitness (mean completed-episode return of the last eval window on
        the training envs) and `wall_s` is the shared run wall. Requires
        every swept key to be a liftable hparam
        (population.hparams.LIFTABLE_HPARAMS) and the ff_ppo module.

Every trial record carries `wall_s` (per-trial wall-clock seconds) and
`error` (None, or {type, message} — the typed failure reason; a failed trial
scores -inf explicitly instead of silently folding into _finite_score, and
serializes as `"score": null` so the results lines stay strict RFC-8259
JSON — json.dumps would otherwise print the -Infinity token).

Usage:
    python -m stoix_tpu.sweep --module stoix_tpu.systems.ppo.anakin.ff_ppo \
        --default default/anakin/default_ff_ppo.yaml --trials 8 \
        --method tpe \
        --space system.actor_lr=loguniform:1e-5,1e-2 \
                system.ent_coef=uniform:0.0,0.05 \
                system.epochs=choice:2,4,8 \
        --set env=cartpole arch.total_timesteps=1e6
"""

from __future__ import annotations

import argparse
import importlib
import itertools
import json
import random
import time
from typing import Any, Dict, List, Optional, Tuple

from stoix_tpu.utils import config as config_lib


def _coerce(raw: str):
    """Typed choice values: ints, then floats (incl. '3e-4'), else strings."""
    for cast in (int, float):
        try:
            return cast(raw)
        except ValueError:
            continue
    return raw


def parse_space(entries: List[str]) -> Dict[str, Tuple[str, list]]:
    """'key=kind:a,b,...' -> {key: (kind, args)}; kinds: uniform, loguniform,
    choice, int."""
    space = {}
    for entry in entries:
        key, spec = entry.split("=", 1)
        kind, _, raw = spec.partition(":")
        args = [_coerce(a) for a in raw.split(",")] if raw else []
        space[key] = (kind, args)
    return space


def sample_point(space: Dict[str, Tuple[str, list]], rng: random.Random) -> Dict[str, Any]:
    point = {}
    for key, (kind, args) in space.items():
        if kind == "uniform":
            lo, hi = float(args[0]), float(args[1])
            point[key] = rng.uniform(lo, hi)
        elif kind == "loguniform":
            import math

            lo, hi = math.log(float(args[0])), math.log(float(args[1]))
            point[key] = math.exp(rng.uniform(lo, hi))
        elif kind == "int":
            point[key] = rng.randint(int(args[0]), int(args[1]))
        elif kind == "choice":
            point[key] = rng.choice(args)
        else:
            raise ValueError(f"Unknown space kind '{kind}' for {key}")
    return point


def _finite_score(r: Dict[str, Any]) -> float:
    """NaN scores (diverged trials) rank BELOW every finite score — a NaN sort
    key would otherwise scramble the good/bad split and could even surface the
    diverged trial as 'best'. None (the serialized form of a non-finite score,
    see _trial_record) ranks the same."""
    import math

    s = r["score"]
    if s is None:
        return -math.inf
    s = float(s)
    return s if math.isfinite(s) else -math.inf


def _parzen_logpdf(x: float, centers: List[float], sigma: float) -> float:
    import math

    if sigma <= 0:
        sigma = 1e-12
    acc = 0.0
    for c in centers:
        acc += math.exp(-0.5 * ((x - c) / sigma) ** 2)
    return math.log(max(acc / (len(centers) * sigma), 1e-300))


def tpe_next_point(
    space: Dict[str, Tuple[str, list]],
    history: List[Dict[str, Any]],
    rng: random.Random,
    n_startup: int = 5,
    gamma: float = 0.25,
    n_candidates: int = 24,
) -> Dict[str, Any]:
    """Propose the next trial point by the TPE l(x)/g(x) criterion."""
    import math

    if len(history) < n_startup:
        return sample_point(space, rng)
    ranked = sorted(history, key=lambda r: -_finite_score(r))
    n_good = max(1, int(len(ranked) * gamma))
    good, bad = ranked[:n_good], ranked[n_good:] or ranked[:n_good]

    point: Dict[str, Any] = {}
    for key, (kind, args) in space.items():
        gvals = [r["params"][key] for r in good]
        bvals = [r["params"][key] for r in bad]
        if kind == "choice":
            weights = []
            for a in args:
                lg = (gvals.count(a) + 1.0) / (len(gvals) + len(args))
                lb = (bvals.count(a) + 1.0) / (len(bvals) + len(args))
                weights.append(lg / lb)
            point[key] = rng.choices(args, weights=weights)[0]
            continue
        log_scale = kind == "loguniform"
        conv = math.log if log_scale else float
        lo, hi = conv(float(args[0])), conv(float(args[1]))
        g_centers = [conv(float(v)) for v in gvals]
        b_centers = [conv(float(v)) for v in bvals]
        # Scott-style bandwidth on the search width, shrinking with samples.
        sigma = (hi - lo) * max(0.08, 1.0 / math.sqrt(len(g_centers) + 1))
        best_x, best_ratio = None, -math.inf
        for _ in range(n_candidates):
            x = min(max(rng.gauss(rng.choice(g_centers), sigma), lo), hi)
            ratio = _parzen_logpdf(x, g_centers, sigma) - _parzen_logpdf(x, b_centers, sigma)
            if ratio > best_ratio:
                best_x, best_ratio = x, ratio
        value = math.exp(best_x) if log_scale else best_x
        point[key] = int(round(value)) if kind == "int" else value
    return point


def grid_points(space: Dict[str, Tuple[str, list]]) -> List[Dict[str, Any]]:
    keys = list(space)
    choices = []
    for key in keys:
        kind, args = space[key]
        if kind != "choice":
            raise ValueError("grid search requires choice: spaces only")
        choices.append(args)
    return [dict(zip(keys, combo)) for combo in itertools.product(*choices)]


def _trial_record(
    trial: int,
    point: Dict[str, Any],
    score: float,
    wall_s: float,
    error: Optional[Dict[str, str]] = None,
) -> Dict[str, Any]:
    """ONE results-JSON schema for both backends: params + score + wall-clock
    + typed failure reason (None on success). A non-finite score (failed or
    diverged trial) is recorded as None — json.dumps would otherwise emit the
    non-RFC-8259 tokens -Infinity/NaN and every strict consumer (jq,
    JSON.parse) would reject the whole results line."""
    import math

    score = float(score)
    return {
        "trial": trial,
        "params": point,
        "score": score if math.isfinite(score) else None,
        "wall_s": round(float(wall_s), 3),
        "error": error,
    }


def run_sweep(
    module: str,
    default: str,
    space: Dict[str, Tuple[str, list]],
    fixed_overrides: List[str],
    trials: int = 8,
    method: str = "random",
    seed: int = 0,
    backend: str = "sequential",
) -> Dict[str, Any]:
    if backend == "population":
        return run_population_sweep(
            module, default, space, fixed_overrides,
            trials=trials, method=method, seed=seed,
        )
    if backend != "sequential":
        raise ValueError(f"unknown sweep backend '{backend}' (sequential|population)")
    mod = importlib.import_module(module)
    rng = random.Random(seed)  # noqa: STX005 — stdlib int seed (the population dispatch above returns)
    if method == "grid":
        points: List[Any] = grid_points(space)
    elif method == "tpe":
        points = [None] * trials  # proposed adaptively from the history below
    else:
        points = [sample_point(space, rng) for _ in range(trials)]

    results = []
    for i, point in enumerate(points):
        if point is None:
            point = tpe_next_point(space, results, rng)
        cfg = config_lib.compose(config_lib.default_config_dir(), default, fixed_overrides)
        # Apply sampled values TYPED (stringifying small floats like 1e-05 and
        # re-parsing via YAML 1.1 would silently turn them into strings).
        for k, v in point.items():
            config_lib._set_dotted(cfg, k, v)
        start = time.perf_counter()
        try:
            score = float(mod.run_experiment(cfg))
            error = None
        except Exception as exc:  # noqa: BLE001 — one diverged/misconfigured
            # trial must not kill the sweep; the typed reason rides the
            # results JSON and the trial scores -inf EXPLICITLY (never a
            # silent _finite_score fold).
            score = float("-inf")
            error = {"type": type(exc).__name__, "message": str(exc)}
        results.append(
            _trial_record(i, point, score, time.perf_counter() - start, error)
        )
        print(json.dumps(results[-1]), flush=True)

    best = max(results, key=_finite_score)
    print(json.dumps({"best": best}), flush=True)
    return best


POPULATION_MODULES = ("stoix_tpu.systems.ppo.anakin.ff_ppo",)


def batch_points(
    space: Dict[str, Tuple[str, list]], trials: int, method: str, seed: int
) -> List[Dict[str, Any]]:
    """The whole batch of trial points, decided UP FRONT (one population run
    trains them all simultaneously — there is no sequential history for TPE
    to adapt on, so tpe degenerates to its random-startup phase here)."""
    rng = random.Random(seed)
    if method == "grid":
        return grid_points(space)
    return [sample_point(space, rng) for _ in range(trials)]


def run_population_sweep(
    module: str,
    default: str,
    space: Dict[str, Tuple[str, list]],
    fixed_overrides: List[str],
    trials: int = 8,
    method: str = "random",
    seed: int = 0,
) -> Dict[str, Any]:
    """Map a grid/TPE batch onto ONE mesh-parallel population run
    (stoix_tpu/population, docs/DESIGN.md §2.11): every trial point becomes a
    population member; one compile, one train, P scores."""
    from stoix_tpu.population import (
        LIFTABLE_HPARAMS,
        run_population_experiment,
        LAST_POPULATION_STATS,
    )

    if module not in POPULATION_MODULES:
        raise ValueError(
            f"--backend population supports {', '.join(POPULATION_MODULES)} "
            f"(got {module}): the population runner threads hparams through "
            "ff_ppo's vmapped learner"
        )
    unliftable = sorted(k for k in space if k not in LIFTABLE_HPARAMS)
    if unliftable:
        raise ValueError(
            f"--backend population cannot lift {', '.join(unliftable)} onto "
            f"the pop axis; liftable keys: {', '.join(sorted(LIFTABLE_HPARAMS))}"
        )

    points = batch_points(space, trials, method, seed)
    cfg = config_lib.compose(
        config_lib.default_config_dir(), default,
        ["arch=population", *fixed_overrides],
    )
    config_lib._set_dotted(cfg, "arch.population.size", len(points))
    # Typed per-member value lists, keyed by the dotted path (the same typed
    # injection discipline as the sequential backend).
    config_lib._set_dotted(
        cfg,
        "arch.population.hparams",
        {key: [point[key] for point in points] for key in space},
    )

    start = time.perf_counter()
    error: Optional[Dict[str, str]] = None
    fitness: List[float] = []
    try:
        run_population_experiment(cfg)
        fitness = list(LAST_POPULATION_STATS.get("member_fitness") or [])
    except Exception as exc:  # noqa: BLE001 — the population trains as ONE
        # program, so a failure is shared: every trial records the same typed
        # reason (the sequential backend's schema, P times).
        error = {"type": type(exc).__name__, "message": str(exc)}
    wall = time.perf_counter() - start
    if error is None and len(fitness) != len(points):
        # The run completed but the runner's stats don't cover the members —
        # a runner contract violation, reported as its own typed reason
        # rather than masquerading as a training failure (or IndexError-ing
        # out of the success path).
        error = {
            "type": "PopulationStatsError",
            "message": (
                f"member_fitness has {len(fitness)} entries for "
                f"{len(points)} members"
            ),
        }
    results = [
        _trial_record(
            i, point,
            fitness[i] if error is None else float("-inf"),
            wall, error,
        )
        for i, point in enumerate(points)
    ]
    for record in results:
        print(json.dumps(record), flush=True)
    best = max(results, key=_finite_score)
    print(json.dumps({"best": best}), flush=True)
    return best


def main(argv: List[str] | None = None) -> Dict[str, Any]:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--module", required=True)
    parser.add_argument("--default", required=True, help="default yaml under configs/")
    parser.add_argument("--trials", type=int, default=8)
    parser.add_argument("--method", choices=["random", "grid", "tpe"], default="random")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--backend", choices=["sequential", "population"], default="sequential",
        help="sequential = one run per trial; population = the whole batch "
        "as ONE mesh-parallel population run (stoix_tpu/population)",
    )
    parser.add_argument("--space", nargs="+", required=True)
    parser.add_argument("--set", nargs="*", default=[], dest="overrides",
                        help="fixed key=value overrides")
    args = parser.parse_args(argv)
    return run_sweep(
        args.module,
        args.default,
        parse_space(args.space),
        args.overrides,
        trials=args.trials,
        method=args.method,
        seed=args.seed,
        backend=args.backend,
    )


if __name__ == "__main__":
    main()
