"""Hyperparameter search — the reference's Optuna-sweeper equivalent
(reference configs/default/anakin/hyperparameter_sweep.yaml: Optuna TPE
multirun over a search space). Optuna is not a dependency here; this module
provides random, grid, and first-party TPE search over dotted-override spaces
with the same maximize-final-eval-return objective.

TPE (Bergstra et al. 2011, the sampler the reference's Optuna config selects):
after `n_startup` random trials, observed points split into good (top gamma
quantile by score) and bad; numeric params get Parzen (Gaussian-kernel)
densities l(x) over good and g(x) over bad, candidates are drawn from l and
ranked by l/g; choice params use smoothed count ratios.

Usage:
    python -m stoix_tpu.sweep --module stoix_tpu.systems.ppo.anakin.ff_ppo \
        --default default/anakin/default_ff_ppo.yaml --trials 8 \
        --method tpe \
        --space system.actor_lr=loguniform:1e-5,1e-2 \
                system.ent_coef=uniform:0.0,0.05 \
                system.epochs=choice:2,4,8 \
        --set env=cartpole arch.total_timesteps=1e6
"""

from __future__ import annotations

import argparse
import importlib
import itertools
import json
import random
from typing import Any, Dict, List, Tuple

from stoix_tpu.utils import config as config_lib


def _coerce(raw: str):
    """Typed choice values: ints, then floats (incl. '3e-4'), else strings."""
    for cast in (int, float):
        try:
            return cast(raw)
        except ValueError:
            continue
    return raw


def parse_space(entries: List[str]) -> Dict[str, Tuple[str, list]]:
    """'key=kind:a,b,...' -> {key: (kind, args)}; kinds: uniform, loguniform,
    choice, int."""
    space = {}
    for entry in entries:
        key, spec = entry.split("=", 1)
        kind, _, raw = spec.partition(":")
        args = [_coerce(a) for a in raw.split(",")] if raw else []
        space[key] = (kind, args)
    return space


def sample_point(space: Dict[str, Tuple[str, list]], rng: random.Random) -> Dict[str, Any]:
    point = {}
    for key, (kind, args) in space.items():
        if kind == "uniform":
            lo, hi = float(args[0]), float(args[1])
            point[key] = rng.uniform(lo, hi)
        elif kind == "loguniform":
            import math

            lo, hi = math.log(float(args[0])), math.log(float(args[1]))
            point[key] = math.exp(rng.uniform(lo, hi))
        elif kind == "int":
            point[key] = rng.randint(int(args[0]), int(args[1]))
        elif kind == "choice":
            point[key] = rng.choice(args)
        else:
            raise ValueError(f"Unknown space kind '{kind}' for {key}")
    return point


def _finite_score(r: Dict[str, Any]) -> float:
    """NaN scores (diverged trials) rank BELOW every finite score — a NaN sort
    key would otherwise scramble the good/bad split and could even surface the
    diverged trial as 'best'."""
    import math

    s = float(r["score"])
    return s if math.isfinite(s) else -math.inf


def _parzen_logpdf(x: float, centers: List[float], sigma: float) -> float:
    import math

    if sigma <= 0:
        sigma = 1e-12
    acc = 0.0
    for c in centers:
        acc += math.exp(-0.5 * ((x - c) / sigma) ** 2)
    return math.log(max(acc / (len(centers) * sigma), 1e-300))


def tpe_next_point(
    space: Dict[str, Tuple[str, list]],
    history: List[Dict[str, Any]],
    rng: random.Random,
    n_startup: int = 5,
    gamma: float = 0.25,
    n_candidates: int = 24,
) -> Dict[str, Any]:
    """Propose the next trial point by the TPE l(x)/g(x) criterion."""
    import math

    if len(history) < n_startup:
        return sample_point(space, rng)
    ranked = sorted(history, key=lambda r: -_finite_score(r))
    n_good = max(1, int(len(ranked) * gamma))
    good, bad = ranked[:n_good], ranked[n_good:] or ranked[:n_good]

    point: Dict[str, Any] = {}
    for key, (kind, args) in space.items():
        gvals = [r["params"][key] for r in good]
        bvals = [r["params"][key] for r in bad]
        if kind == "choice":
            weights = []
            for a in args:
                lg = (gvals.count(a) + 1.0) / (len(gvals) + len(args))
                lb = (bvals.count(a) + 1.0) / (len(bvals) + len(args))
                weights.append(lg / lb)
            point[key] = rng.choices(args, weights=weights)[0]
            continue
        log_scale = kind == "loguniform"
        conv = math.log if log_scale else float
        lo, hi = conv(float(args[0])), conv(float(args[1]))
        g_centers = [conv(float(v)) for v in gvals]
        b_centers = [conv(float(v)) for v in bvals]
        # Scott-style bandwidth on the search width, shrinking with samples.
        sigma = (hi - lo) * max(0.08, 1.0 / math.sqrt(len(g_centers) + 1))
        best_x, best_ratio = None, -math.inf
        for _ in range(n_candidates):
            x = min(max(rng.gauss(rng.choice(g_centers), sigma), lo), hi)
            ratio = _parzen_logpdf(x, g_centers, sigma) - _parzen_logpdf(x, b_centers, sigma)
            if ratio > best_ratio:
                best_x, best_ratio = x, ratio
        value = math.exp(best_x) if log_scale else best_x
        point[key] = int(round(value)) if kind == "int" else value
    return point


def grid_points(space: Dict[str, Tuple[str, list]]) -> List[Dict[str, Any]]:
    keys = list(space)
    choices = []
    for key in keys:
        kind, args = space[key]
        if kind != "choice":
            raise ValueError("grid search requires choice: spaces only")
        choices.append(args)
    return [dict(zip(keys, combo)) for combo in itertools.product(*choices)]


def run_sweep(
    module: str,
    default: str,
    space: Dict[str, Tuple[str, list]],
    fixed_overrides: List[str],
    trials: int = 8,
    method: str = "random",
    seed: int = 0,
) -> Dict[str, Any]:
    mod = importlib.import_module(module)
    rng = random.Random(seed)
    if method == "grid":
        points: List[Any] = grid_points(space)
    elif method == "tpe":
        points = [None] * trials  # proposed adaptively from the history below
    else:
        points = [sample_point(space, rng) for _ in range(trials)]

    results = []
    for i, point in enumerate(points):
        if point is None:
            point = tpe_next_point(space, results, rng)
        cfg = config_lib.compose(config_lib.default_config_dir(), default, fixed_overrides)
        # Apply sampled values TYPED (stringifying small floats like 1e-05 and
        # re-parsing via YAML 1.1 would silently turn them into strings).
        for k, v in point.items():
            config_lib._set_dotted(cfg, k, v)
        score = mod.run_experiment(cfg)
        results.append({"trial": i, "params": point, "score": float(score)})
        print(json.dumps(results[-1]), flush=True)

    best = max(results, key=_finite_score)
    print(json.dumps({"best": best}), flush=True)
    return best


def main(argv: List[str] | None = None) -> Dict[str, Any]:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--module", required=True)
    parser.add_argument("--default", required=True, help="default yaml under configs/")
    parser.add_argument("--trials", type=int, default=8)
    parser.add_argument("--method", choices=["random", "grid", "tpe"], default="random")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--space", nargs="+", required=True)
    parser.add_argument("--set", nargs="*", default=[], dest="overrides",
                        help="fixed key=value overrides")
    args = parser.parse_args(argv)
    return run_sweep(
        args.module,
        args.default,
        parse_space(args.space),
        args.overrides,
        trials=args.trials,
        method=args.method,
        seed=args.seed,
    )


if __name__ == "__main__":
    main()
