"""First-party maximal-coordinate rigid-body physics in JAX.

The reference delegates continuous-control physics to the external `brax`
suite (reference stoix/utils/make_env.py ENV_MAKERS["brax"], configs
stoix/configs/env/brax/ant.yaml); this module is the TPU-native stand-in: a
small force-based rigid-body engine in the style of classical game physics
(spring joints + penalty contacts + semi-implicit Euler), written so a whole
batch of worlds advances as fused elementwise/scatter ops inside the rollout
`lax.scan` — no per-env Python, no dynamic shapes.

Design (TPU-first):
  - State is struct-of-arrays over bodies: pos [nb,3], quat [nb,4] (wxyz),
    vel [nb,3], ang [nb,3] (world frame). A vmapped env therefore steps
    [batch, nb, ...] tensors — large fused VPU work, with the MXU load coming
    from the policy/value networks that consume the observations.
  - Joints/contacts are fixed-size index arrays; per-joint forces are
    scattered onto bodies with `.at[].add` (XLA lowers these to efficient
    segment sums). Everything is static-shape; `lax.scan` over substeps.
  - Hinge joints: positional spring on the anchor pair + rotational spring on
    the off-axis swing (swing-twist decomposition) + angle-limit springs +
    actuator torque about the hinge axis.
  - Ground contact: sphere-vs-plane penalty springs with viscous friction.

Numerical regime: spring constants ~1e4 with substep dt ~2e-3 keeps the
semi-implicit integrator comfortably inside its stability region for
unit-scale masses (dt < 2/sqrt(k/m)). The binding constraints are ROTATIONAL:
an anchor spring at lever arm r contributes k*r^2 against the body's inertia
(need dt*sqrt(k*r^2/I) < ~1), and every explicit damper needs c*dt/I < ~1 —
light links therefore carry deliberately padded inertia in system builders,
a standard engine trick that trades a little physical fidelity for a 10x
larger stable-timestep region.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

# --- quaternion helpers (wxyz convention) -----------------------------------


def quat_mul(a: jax.Array, b: jax.Array) -> jax.Array:
    """Hamilton product; a, b [..., 4]."""
    aw, ax, ay, az = a[..., 0], a[..., 1], a[..., 2], a[..., 3]
    bw, bx, by, bz = b[..., 0], b[..., 1], b[..., 2], b[..., 3]
    return jnp.stack(
        [
            aw * bw - ax * bx - ay * by - az * bz,
            aw * bx + ax * bw + ay * bz - az * by,
            aw * by - ax * bz + ay * bw + az * bx,
            aw * bz + ax * by - ay * bx + az * bw,
        ],
        axis=-1,
    )


def quat_conj(q: jax.Array) -> jax.Array:
    return q * jnp.asarray([1.0, -1.0, -1.0, -1.0])


def quat_rotate(q: jax.Array, v: jax.Array) -> jax.Array:
    """Rotate vectors v [..., 3] by quaternions q [..., 4]."""
    qv = q[..., 1:]
    uv = jnp.cross(qv, v)
    uuv = jnp.cross(qv, uv)
    return v + 2.0 * (q[..., :1] * uv + uuv)


def quat_inv_rotate(q: jax.Array, v: jax.Array) -> jax.Array:
    return quat_rotate(quat_conj(q), v)


def quat_integrate(q: jax.Array, omega: jax.Array, dt: float) -> jax.Array:
    """q <- normalize(q + dt/2 * [0, omega] ⊗ q); omega in world frame."""
    zeros = jnp.zeros_like(omega[..., :1])
    dq = quat_mul(jnp.concatenate([zeros, omega], axis=-1), q)
    q = q + 0.5 * dt * dq
    return q / jnp.linalg.norm(q, axis=-1, keepdims=True)


def quat_twist_angle(q_rel: jax.Array, axis: jax.Array) -> jax.Array:
    """Signed rotation of q_rel about `axis` (swing-twist decomposition)."""
    proj = jnp.sum(q_rel[..., 1:] * axis, axis=-1)
    return 2.0 * jnp.arctan2(proj, q_rel[..., 0])


# --- system description ------------------------------------------------------


class RigidBodySystem(NamedTuple):
    """Static description of an articulated rigid-body system.

    All index/parameter arrays are fixed-size; the system is a pytree of
    jnp arrays so it can be closed over by jitted step functions.
    """

    # Bodies.
    mass: jax.Array  # [nb]
    inertia: jax.Array  # [nb, 3] diagonal body-frame inertia
    static: jax.Array  # [nb] 1.0 = immovable (world-pinned base, walls)
    # Hinge joints (parent -> child).
    joint_parent: jax.Array  # [nj] int32
    joint_child: jax.Array  # [nj] int32
    anchor_p: jax.Array  # [nj, 3] anchor in parent frame
    anchor_c: jax.Array  # [nj, 3] anchor in child frame
    axis_p: jax.Array  # [nj, 3] hinge axis in parent frame (unit)
    limit: jax.Array  # [nj, 2] (lo, hi) joint angle limits, radians
    gear: jax.Array  # [nj] actuator torque scale
    # Contact spheres.
    sphere_body: jax.Array  # [ns] int32
    sphere_offset: jax.Array  # [ns, 3] centre in body frame
    sphere_radius: jax.Array  # [ns]
    # Scalars (python floats — static under jit).
    gravity: float = -9.81
    dt: float = 0.002  # substep
    substeps: int = 16  # substeps per control step
    joint_kp: float = 10_000.0  # anchor spring
    joint_kd: float = 50.0  # anchor damper
    swing_kp: float = 500.0  # off-axis rotational spring
    swing_kd: float = 2.0  # off-axis rotational damper
    limit_kp: float = 1_000.0  # angle-limit spring
    # Passive hold PD about the hinge axis itself (tendon/servo stiffness,
    # MuJoCo's per-joint stiffness/damping). 0 = free hinge. Morphologies
    # whose zero-action pose must be statically stable (walker2d standing)
    # set this; the gain separates a biped (2 legs share the load -> stable)
    # from a monoped (1 leg -> still collapses), see envs/locomotion.py.
    hold_kp: float = 0.0
    hold_kd: float = 0.0
    contact_kp: float = 10_000.0  # ground penetration spring
    contact_kd: float = 50.0  # normal damping
    friction: float = 1.0  # Coulomb cap on viscous tangential force
    friction_kv: float = 50.0  # viscous tangential coefficient
    lin_damping: float = 0.02  # global velocity damping (1/s)
    ang_damping: float = 0.05
    # Planar mode: constrain all motion to the x-z plane (hinges about +y).
    # The MuJoCo/brax hopper / walker2d / halfcheetah morphologies are planar
    # robots; a 3D engine integrating them unconstrained lets them fall
    # sideways, so planar systems project velocities onto the plane each
    # substep (y translation and x/z rotation zeroed — a hard constraint,
    # not a spring). Static python bool: jit specializes per system.
    planar: bool = False

    @property
    def num_bodies(self) -> int:
        return self.mass.shape[0]

    @property
    def num_joints(self) -> int:
        return self.joint_parent.shape[0]


class RigidBodyState(NamedTuple):
    pos: jax.Array  # [nb, 3]
    quat: jax.Array  # [nb, 4] wxyz
    vel: jax.Array  # [nb, 3]
    ang: jax.Array  # [nb, 3] world-frame angular velocity


def rest_state(sys: RigidBodySystem, rest_pos: jax.Array) -> RigidBodyState:
    nb = sys.num_bodies
    return RigidBodyState(
        pos=jnp.asarray(rest_pos, jnp.float32),
        quat=jnp.tile(jnp.asarray([1.0, 0.0, 0.0, 0.0], jnp.float32), (nb, 1)),
        vel=jnp.zeros((nb, 3), jnp.float32),
        ang=jnp.zeros((nb, 3), jnp.float32),
    )


# --- dynamics ----------------------------------------------------------------


def joint_angles(sys: RigidBodySystem, state: RigidBodyState) -> jax.Array:
    """Signed hinge angles [nj] via swing-twist about each joint axis."""
    qp = state.quat[sys.joint_parent]
    qc = state.quat[sys.joint_child]
    q_rel = quat_mul(quat_conj(qp), qc)
    # Canonicalize sign (q and -q are the same rotation).
    q_rel = jnp.where(q_rel[..., :1] < 0, -q_rel, q_rel)
    return quat_twist_angle(q_rel, sys.axis_p)


def joint_velocities(sys: RigidBodySystem, state: RigidBodyState) -> jax.Array:
    """Relative angular velocity about each (world-frame) joint axis [nj]."""
    axis_w = quat_rotate(state.quat[sys.joint_parent], sys.axis_p)
    omega_rel = state.ang[sys.joint_child] - state.ang[sys.joint_parent]
    return jnp.sum(omega_rel * axis_w, axis=-1)


def _accumulate_joint_forces(
    sys: RigidBodySystem, state: RigidBodyState, action: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    """Forces/torques [nb,3] from joints: anchor springs, swing springs,
    limits, and actuation. `action` is [nj] in [-1, 1]."""
    p, c = sys.joint_parent, sys.joint_child
    qp, qc = state.quat[p], state.quat[c]

    # World-frame anchor points and their velocities.
    rp = quat_rotate(qp, sys.anchor_p)  # lever arm from parent COM
    rc = quat_rotate(qc, sys.anchor_c)
    ap = state.pos[p] + rp
    ac = state.pos[c] + rc
    vp = state.vel[p] + jnp.cross(state.ang[p], rp)
    vc = state.vel[c] + jnp.cross(state.ang[c], rc)

    # Anchor spring: pull the child anchor onto the parent anchor.
    f_c = sys.joint_kp * (ap - ac) + sys.joint_kd * (vp - vc)  # on child at ac

    # Swing spring: penalize relative rotation off the hinge axis. The
    # rotation vector of q_rel minus its twist component is the swing error.
    q_rel = quat_mul(quat_conj(qp), qc)
    q_rel = jnp.where(q_rel[..., :1] < 0, -q_rel, q_rel)
    rotvec = 2.0 * q_rel[..., 1:]  # small-angle rotation vector, parent frame
    twist = jnp.sum(rotvec * sys.axis_p, axis=-1, keepdims=True) * sys.axis_p
    swing_err_w = quat_rotate(qp, rotvec - twist)
    axis_w = quat_rotate(qp, sys.axis_p)
    omega_rel = state.ang[c] - state.ang[p]
    omega_swing = omega_rel - jnp.sum(omega_rel * axis_w, axis=-1, keepdims=True) * axis_w
    tau_swing = -sys.swing_kp * swing_err_w - sys.swing_kd * omega_swing  # on child

    # Angle limits + actuation + passive hold PD, all about the world hinge
    # axis. The hold term resists rotation of the hinge DOF itself (the
    # swing spring only acts OFF-axis), giving chain robots a statically
    # stable zero-action pose when hold_kp exceeds the gravity stiffness of
    # the corresponding tipping mode.
    angle = quat_twist_angle(q_rel, sys.axis_p)
    omega_axis = jnp.sum(omega_rel * axis_w, axis=-1)
    lo, hi = sys.limit[:, 0], sys.limit[:, 1]
    limit_err = jnp.where(angle < lo, lo - angle, jnp.where(angle > hi, hi - angle, 0.0))
    tau_axis = (
        sys.limit_kp * limit_err
        + sys.gear * action
        - sys.hold_kp * angle
        - sys.hold_kd * omega_axis
    )[:, None] * axis_w

    tau_c = tau_swing + tau_axis
    force = jnp.zeros((sys.num_bodies, 3), jnp.float32)
    torque = jnp.zeros((sys.num_bodies, 3), jnp.float32)
    force = force.at[c].add(f_c).at[p].add(-f_c)
    torque = (
        torque.at[c]
        .add(jnp.cross(rc, f_c) + tau_c)
        .at[p]
        .add(jnp.cross(rp, -f_c) - tau_c)
    )
    return force, torque


def _accumulate_contact_forces(
    sys: RigidBodySystem, state: RigidBodyState
) -> Tuple[jax.Array, jax.Array]:
    """Sphere-vs-ground (z=0 plane) penalty forces/torques [nb,3]."""
    b = sys.sphere_body
    r_off = quat_rotate(state.quat[b], sys.sphere_offset)
    centre = state.pos[b] + r_off
    depth = sys.sphere_radius - centre[:, 2]  # > 0 when penetrating
    contact_vel = state.vel[b] + jnp.cross(state.ang[b], r_off)

    active = depth > 0.0
    normal_mag = jnp.where(
        active,
        sys.contact_kp * depth - sys.contact_kd * contact_vel[:, 2],
        0.0,
    )
    normal_mag = jnp.maximum(normal_mag, 0.0)  # ground only pushes

    # Viscous friction, Coulomb-capped by the normal force.
    tangential = contact_vel.at[:, 2].set(0.0)
    t_speed = jnp.linalg.norm(tangential, axis=-1, keepdims=True) + 1e-8
    friction_mag = jnp.minimum(sys.friction_kv * t_speed, sys.friction * normal_mag[:, None])
    f = jnp.concatenate(
        [-friction_mag * tangential[:, :2] / t_speed, normal_mag[:, None]], axis=-1
    )
    f = jnp.where(active[:, None], f, 0.0)

    force = jnp.zeros((sys.num_bodies, 3), jnp.float32).at[b].add(f)
    torque = jnp.zeros((sys.num_bodies, 3), jnp.float32).at[b].add(jnp.cross(r_off, f))
    return force, torque


def _substep(
    sys: RigidBodySystem, state: RigidBodyState, action: jax.Array
) -> RigidBodyState:
    fj, tj = _accumulate_joint_forces(sys, state, action)
    fc, tc = _accumulate_contact_forces(sys, state)
    force = fj + fc
    torque = tj + tc

    movable = (1.0 - sys.static)[:, None]

    # Linear: gravity + damping, semi-implicit Euler.
    accel = force / sys.mass[:, None] + jnp.asarray([0.0, 0.0, sys.gravity])
    vel = (state.vel + sys.dt * accel * movable) * (1.0 - sys.lin_damping * sys.dt)
    vel = vel * movable
    pos = state.pos + sys.dt * vel

    # Angular: Euler's equations in the body frame (diagonal inertia).
    omega_b = quat_inv_rotate(state.quat, state.ang)
    torque_b = quat_inv_rotate(state.quat, torque)
    domega_b = (torque_b - jnp.cross(omega_b, sys.inertia * omega_b)) / sys.inertia
    ang = (state.ang + sys.dt * quat_rotate(state.quat, domega_b) * movable) * (
        1.0 - sys.ang_damping * sys.dt
    )
    ang = ang * movable
    if sys.planar:
        # Hard x-z plane constraint: no y translation, rotation about +y only.
        vel = vel * jnp.asarray([1.0, 0.0, 1.0])
        pos = pos * jnp.asarray([1.0, 0.0, 1.0])
        ang = ang * jnp.asarray([0.0, 1.0, 0.0])
    quat = quat_integrate(state.quat, ang, sys.dt)
    return RigidBodyState(pos, quat, vel, ang)


def step(sys: RigidBodySystem, state: RigidBodyState, action: jax.Array) -> RigidBodyState:
    """Advance one control step (`sys.substeps` substeps with held action)."""

    def body(carry, _):
        return _substep(sys, carry, action), None

    state, _ = jax.lax.scan(body, state, None, sys.substeps)
    return state
