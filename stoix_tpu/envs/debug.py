"""Pure-JAX debug environments for smoke/correctness testing.

Equivalents of the reference's `IdentityGame` / `SequenceGame`
(reference stoix/utils/debug_env.py:25+, registered via make_env.py:296-304):
fast, fully deterministic dynamics that a correct learner must solve quickly.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from stoix_tpu.envs import spaces
from stoix_tpu.envs.core import Environment
from stoix_tpu.envs.types import Observation, TimeStep, restart, select_step, termination, transition


class IdentityState(NamedTuple):
    key: jax.Array
    target: jax.Array
    step_count: jax.Array
    # Fixed-level episodes (eval-reset hook consumer): >= 0 pins the target to
    # this value for the whole episode; -1 = normal random targets.
    level: jax.Array


class IdentityGame(Environment):
    """Observation is a one-hot target; reward 1 for matching it with the action.

    Optimal return over an episode of length `episode_length` is exactly
    `episode_length` — a learner failing to reach it has a plumbing bug.

    Also the first-party consumer of the evaluator's eval_reset_fn hook
    (reference kinetix levels, wrappers/kinetix.py:15-51): `reset_to_level(k)`
    pins the target to k for the whole episode, so a fixed level list can be
    tiled across eval episodes via make_tiled_eval_reset_fn.
    """

    def __init__(self, num_actions: int = 4, episode_length: int = 10):
        self._num_actions = int(num_actions)
        self._episode_length = int(episode_length)

    def observation_space(self) -> Observation:
        return Observation(
            agent_view=spaces.Array((self._num_actions,), jnp.float32),
            action_mask=spaces.Array((self._num_actions,), jnp.float32),
            step_count=spaces.Array((), jnp.int32),
        )

    def action_space(self) -> spaces.Discrete:
        return spaces.Discrete(self._num_actions)

    def _obs(self, state: IdentityState) -> Observation:
        return Observation(
            agent_view=jax.nn.one_hot(state.target, self._num_actions),
            action_mask=jnp.ones((self._num_actions,), jnp.float32),
            step_count=state.step_count,
        )

    def reset(self, key: jax.Array) -> Tuple[IdentityState, TimeStep]:
        key, sub = jax.random.split(key)
        target = jax.random.randint(sub, (), 0, self._num_actions)
        state = IdentityState(key, target, jnp.zeros((), jnp.int32), jnp.full((), -1, jnp.int32))
        return state, restart(self._obs(state))

    def reset_to_level(self, level: jax.Array, key: jax.Array) -> Tuple[IdentityState, TimeStep]:
        level = jnp.asarray(level, jnp.int32)
        state = IdentityState(key, level, jnp.zeros((), jnp.int32), level)
        return state, restart(self._obs(state))

    def step(self, state: IdentityState, action: jax.Array) -> Tuple[IdentityState, TimeStep]:
        reward = jnp.asarray(action == state.target, jnp.float32)
        key, sub = jax.random.split(state.key)
        random_target = jax.random.randint(sub, (), 0, self._num_actions)
        target = jnp.where(state.level >= 0, state.level, random_target)
        next_state = IdentityState(key, target, state.step_count + 1, state.level)
        obs = self._obs(next_state)
        done = next_state.step_count >= self._episode_length
        return next_state, select_step(done, termination(reward, obs), transition(reward, obs))


class SequenceState(NamedTuple):
    key: jax.Array
    cue: jax.Array
    step_count: jax.Array


class SequenceGame(Environment):
    """Memory task: the cue is visible only at the first observation; the agent
    earns reward 1 at the final step by repeating it. Requires recurrence for
    `delay` > 0 — the oracle env for rec_* systems.
    """

    def __init__(self, num_actions: int = 4, delay: int = 4):
        self._num_actions = int(num_actions)
        self._delay = int(delay)

    def observation_space(self) -> Observation:
        return Observation(
            agent_view=spaces.Array((self._num_actions,), jnp.float32),
            action_mask=spaces.Array((self._num_actions,), jnp.float32),
            step_count=spaces.Array((), jnp.int32),
        )

    def action_space(self) -> spaces.Discrete:
        return spaces.Discrete(self._num_actions)

    def _obs(self, state: SequenceState) -> Observation:
        visible = state.step_count == 0
        view = jnp.where(visible, jax.nn.one_hot(state.cue, self._num_actions), jnp.zeros((self._num_actions,)))
        return Observation(
            agent_view=view.astype(jnp.float32),
            action_mask=jnp.ones((self._num_actions,), jnp.float32),
            step_count=state.step_count,
        )

    def reset(self, key: jax.Array) -> Tuple[SequenceState, TimeStep]:
        key, sub = jax.random.split(key)
        cue = jax.random.randint(sub, (), 0, self._num_actions)
        state = SequenceState(key, cue, jnp.zeros((), jnp.int32))
        return state, restart(self._obs(state))

    def step(self, state: SequenceState, action: jax.Array) -> Tuple[SequenceState, TimeStep]:
        next_count = state.step_count + 1
        at_end = next_count >= self._delay + 1
        reward = jnp.asarray(jnp.logical_and(at_end, action == state.cue), jnp.float32)
        next_state = SequenceState(state.key, state.cue, next_count)
        obs = self._obs(next_state)
        return next_state, select_step(at_end, termination(reward, obs), transition(reward, obs))
