from stoix_tpu.envs import spaces
from stoix_tpu.envs.core import Environment, Wrapper
from stoix_tpu.envs.registry import ENV_REGISTRY, make, make_single, register
from stoix_tpu.envs.types import Observation, StepType, TimeStep, get_final_step_metrics
from stoix_tpu.envs.wrappers import (
    AutoResetWrapper,
    CachedAutoResetWrapper,
    EpisodeStepLimit,
    OptimisticResetVmapWrapper,
    RecordEpisodeMetrics,
    VmapWrapper,
    apply_core_wrappers,
)

__all__ = [
    "spaces",
    "Environment",
    "Wrapper",
    "ENV_REGISTRY",
    "make",
    "make_single",
    "register",
    "Observation",
    "StepType",
    "TimeStep",
    "get_final_step_metrics",
    "AutoResetWrapper",
    "CachedAutoResetWrapper",
    "EpisodeStepLimit",
    "OptimisticResetVmapWrapper",
    "RecordEpisodeMetrics",
    "VmapWrapper",
    "apply_core_wrappers",
]
