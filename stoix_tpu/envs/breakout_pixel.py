"""Breakout-atari, implemented natively in JAX — the full-resolution pixel
workload that runs ENTIRELY on-device.

The reference's full-resolution Atari path ships 84x84x4 frames from an
external C++ EnvPool to the device every step (reference
stoix/wrappers/envpool.py:8-30, configs/env/envpool/*.yaml). This module is
the TPU-native answer for the Anakin architecture: the same game as the
native pool's "Breakout-atari" (envs/native/cvec.cpp BreakoutPixelVec),
RULE FOR RULE, but rendered with vectorized jnp masks so rollout, rendering,
and the Nature-CNN forward all fuse into one on-device XLA program — zero
host<->device observation traffic. Stepping and rendering are bit-identical
with the C++ engine GIVEN a serve index (pinned by the lockstep test in
tests/test_breakout_pixel.py); serve selection is backend-local — the pool
walks a deterministic per-env counter, this twin derives the index from the
reset key so auto-reset episodes stay diverse (the MinAtar-twin precedent).

Game (identical to the C++ twin): 84x84 playfield; 12x2 paddle at row 80
moving +/-3 px/step (3 actions); 2x2 ball at 2 px/step with aim-by-hit-offset
paddle control; 6x14 brick wall (6x3 px bricks, rows 18..35, 1-px right
gutter, row-graded gray), +1 per brick, wall refreshes when cleared; losing
the ball below the paddle terminates. Observations are a 4-frame grayscale
stack in [0, 1], channels oldest->newest — the EnvPool-Atari tensor layout.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from stoix_tpu.envs import spaces
from stoix_tpu.envs.core import Environment
from stoix_tpu.envs.types import (
    Observation,
    TimeStep,
    restart,
    select_step,
    termination,
    transition,
    truncation,
)

_PIX = 84
_STACK = 4
_PAD_W = 12
_PAD_H = 2
_PAD_ROW = 80
_PAD_SPEED = 3
_BALL = 2
_BRICK_W = 6
_BRICK_H = 3
_BRICK_COLS = _PIX // _BRICK_W  # 14
_BRICK_ROWS = 6
_BRICK_TOP = 18
_SERVE_RANGE = _PIX - 16 - _BALL + 1  # 67 (mirrors the C++ serve window)


class BreakoutPixelState(NamedTuple):
    key: jax.Array
    ball_r: jax.Array  # [] int32, top-left of the 2x2 sprite
    ball_c: jax.Array
    dr: jax.Array  # {-2, +2}
    dc: jax.Array  # {-2, -1, +1, +2}
    paddle: jax.Array  # leftmost paddle column
    serves: jax.Array  # episodes served — drives the deterministic serve
    bricks: jax.Array  # [6, 14] int32 in {0, 1}
    frames: jax.Array  # [84, 84, 4] float32 stack, channels oldest->newest
    step_count: jax.Array


def _render(ball_r, ball_c, paddle, bricks) -> jax.Array:
    """Rasterize one 84x84 grayscale frame (vectorized mask composition)."""
    r = jnp.arange(_PIX, dtype=jnp.int32)[:, None]
    c = jnp.arange(_PIX, dtype=jnp.int32)[None, :]
    # Brick wall: row-graded shade, 1-px right gutter per brick.
    band_row = jnp.clip((r - _BRICK_TOP) // _BRICK_H, 0, _BRICK_ROWS - 1)
    in_band = jnp.logical_and(
        r >= _BRICK_TOP, r < _BRICK_TOP + _BRICK_ROWS * _BRICK_H
    )
    alive = bricks[band_row, c // _BRICK_W] == 1
    gutter = (c % _BRICK_W) == (_BRICK_W - 1)
    # Multiply by the reciprocal (not divide) so gray levels are bit-identical
    # with the C++ pool's `uint8 * (1.0f / 255.0f)` conversion.
    inv = jnp.float32(1.0 / 255.0)
    shade = (110.0 + 20.0 * band_row.astype(jnp.float32)) * inv
    frame = jnp.where(in_band & alive & ~gutter, shade, 0.0)
    # Paddle.
    pad = (
        (r >= _PAD_ROW) & (r < _PAD_ROW + _PAD_H) & (c >= paddle) & (c < paddle + _PAD_W)
    )
    frame = jnp.where(pad, jnp.float32(200.0) * inv, frame)
    # Ball, drawn last (on top).
    ball = (r >= ball_r) & (r < ball_r + _BALL) & (c >= ball_c) & (c < ball_c + _BALL)
    return jnp.where(ball, 1.0, frame)


class BreakoutPixel(Environment):
    """JAX twin of the native pool's Breakout-atari (see module docstring)."""

    def __init__(self, max_steps: int = 500):
        self._max_steps = int(max_steps)

    def observation_space(self) -> Observation:
        return Observation(
            agent_view=spaces.Array((_PIX, _PIX, _STACK), jnp.float32),
            action_mask=spaces.Array((3,), jnp.float32),
            step_count=spaces.Array((), jnp.int32),
        )

    def action_space(self) -> spaces.Discrete:
        return spaces.Discrete(3)

    def _observe(self, state: BreakoutPixelState) -> Observation:
        return Observation(
            agent_view=state.frames,
            action_mask=jnp.ones((3,), jnp.float32),
            step_count=state.step_count,
        )

    def _serve(self, key: jax.Array, serves: jax.Array) -> BreakoutPixelState:
        # Deterministic serve (mirrors cvec.cpp BreakoutPixelVec::reset_env):
        # column walks the 67-wide range by a coprime stride, direction
        # alternates with the serve counter.
        k = serves.astype(jnp.int32)
        ball_r = jnp.asarray(_BRICK_TOP + _BRICK_ROWS * _BRICK_H + 4, jnp.int32)
        ball_c = (8 + (k * 37) % _SERVE_RANGE).astype(jnp.int32)
        dc = jnp.where(k % 2 == 0, 1, -1).astype(jnp.int32)
        paddle = jnp.asarray((_PIX - _PAD_W) // 2, jnp.int32)
        bricks = jnp.ones((_BRICK_ROWS, _BRICK_COLS), jnp.int32)
        frame = _render(ball_r, ball_c, paddle, bricks)
        # The stacked reset repeats the serve frame (envpool convention).
        frames = jnp.repeat(frame[:, :, None], _STACK, axis=2)
        return BreakoutPixelState(
            key=key,
            ball_r=ball_r,
            ball_c=ball_c,
            dr=jnp.asarray(2, jnp.int32),
            dc=dc,
            paddle=paddle,
            serves=k + 1,
            bricks=bricks,
            frames=frames,
            step_count=jnp.zeros((), jnp.int32),
        )

    def reset(self, key: jax.Array) -> Tuple[BreakoutPixelState, TimeStep]:
        # The serve index is key-derived so episodes stay diverse under the
        # auto-reset wrappers (which call reset() with a fresh key each
        # episode boundary) and across vmapped envs. Stepping/rendering are
        # bit-identical with the C++ pool GIVEN a serve index (the lockstep
        # test drives both engines through explicit indices); serve SELECTION
        # is backend-local, as with the MinAtar twins.
        serve = jax.random.randint(key, (), 0, 2 * _SERVE_RANGE, jnp.int32)
        state = self._serve(key, serve)
        ts = restart(self._observe(state))
        ts.extras["truncation"] = jnp.zeros((), bool)
        return state, ts

    def step(
        self, state: BreakoutPixelState, action: jax.Array
    ) -> Tuple[BreakoutPixelState, TimeStep]:
        # Mirrors cvec.cpp BreakoutPixelVec::step_env exactly.
        paddle = jnp.clip(
            state.paddle + (jnp.asarray(action, jnp.int32) - 1) * _PAD_SPEED,
            0,
            _PIX - _PAD_W,
        )
        nr = state.ball_r + state.dr
        nc = state.ball_c + state.dc
        dr, dc = state.dr, state.dc

        # Side walls (reflective fold keeps motion exact at any speed).
        dc = jnp.where(nc < 0, -dc, dc)
        nc = jnp.where(nc < 0, -nc, nc)
        over = nc > _PIX - _BALL
        dc = jnp.where(over, -dc, dc)
        nc = jnp.where(over, 2 * (_PIX - _BALL) - nc, nc)
        # Ceiling.
        ceil = nr < 0
        dr = jnp.where(ceil, 2, dr)
        nr = jnp.where(ceil, -nr, nr)

        # Brick band: test the ball-center cell against the brick grid.
        cr = nr + _BALL // 2
        cc = nc + _BALL // 2
        in_band = jnp.logical_and(
            cr >= _BRICK_TOP, cr < _BRICK_TOP + _BRICK_ROWS * _BRICK_H
        )
        br = jnp.clip((cr - _BRICK_TOP) // _BRICK_H, 0, _BRICK_ROWS - 1)
        bc = jnp.minimum(cc // _BRICK_W, _BRICK_COLS - 1)
        hit = jnp.logical_and(in_band, state.bricks[br, bc] == 1)
        bricks = state.bricks.at[br, bc].set(jnp.where(hit, 0, state.bricks[br, bc]))
        reward = jnp.where(hit, 1.0, 0.0).astype(jnp.float32)
        dr = jnp.where(hit, -dr, dr)
        nr = jnp.where(hit, state.ball_r, nr)
        # Wall cleared -> refresh (play continues).
        bricks = jnp.where(jnp.any(bricks == 1), bricks, jnp.ones_like(bricks))

        # Paddle-plane crossing (only tested when not in the brick band).
        crossing = (
            ~in_band
            & (dr > 0)
            & (nr + _BALL > _PAD_ROW)
            & (state.ball_r + _BALL <= _PAD_ROW)
        )
        caught = crossing & (cc >= paddle) & (cc < paddle + _PAD_W)
        dr = jnp.where(caught, -2, dr)
        nr = jnp.where(caught, _PAD_ROW - _BALL, nr)
        # Aim by hit offset: outer thirds send the ball out steeply.
        off = cc - paddle
        aimed_dc = jnp.where(
            off < _PAD_W // 3,
            -2,
            jnp.where(off >= 2 * (_PAD_W // 3), 2, jnp.where(dc >= 0, 1, -1)),
        )
        dc = jnp.where(caught, aimed_dc, dc)
        # Ball lost below the paddle (the final else branch in C++).
        terminated = ~in_band & ~crossing & (nr >= _PIX - _BALL)

        frame = _render(nr, nc, paddle, bricks)
        frames = jnp.concatenate([state.frames[:, :, 1:], frame[:, :, None]], axis=2)
        next_state = BreakoutPixelState(
            key=state.key,
            ball_r=nr,
            ball_c=nc,
            dr=dr,
            dc=dc,
            paddle=paddle,
            serves=state.serves,
            bricks=bricks,
            frames=frames,
            step_count=state.step_count + 1,
        )
        obs = self._observe(next_state)
        truncated = jnp.logical_and(next_state.step_count >= self._max_steps, ~terminated)
        ts = select_step(
            terminated,
            termination(reward, obs),
            select_step(truncated, truncation(reward, obs), transition(reward, obs)),
        )
        ts.extras["truncation"] = truncated
        return next_state, ts
