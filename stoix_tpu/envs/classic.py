"""Classic-control environments, implemented natively in JAX.

The reference gets these from the external `gymnax` suite
(reference stoix/utils/make_env.py:420-433 ENV_MAKERS["gymnax"]); this module is
the first-party TPU-native equivalent. Dynamics follow the standard textbook
formulations (identical to OpenAI Gym / gymnax), with termination conditions and
default step limits matching the `-v1`/`-v0` conventions so published solve
thresholds (e.g. CartPole 500) carry over.

Design notes (TPU-first):
  - All physics is elementwise fp32 math on tiny states — it fuses into the
    surrounding rollout scan; there is no per-env Python.
  - Step limits are emitted as *truncations* (discount stays 1) so GAE
    bootstraps correctly (see stoix_tpu/ops/multistep.py).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from stoix_tpu.envs import spaces
from stoix_tpu.envs.core import Environment
from stoix_tpu.envs.types import Observation, TimeStep, restart, select_step, termination, transition, truncation


def _full_mask(n: int) -> jax.Array:
    return jnp.ones((n,), jnp.float32)


class PhysicsState(NamedTuple):
    key: jax.Array
    physics: jax.Array  # flat fp32 physics vector
    step_count: jax.Array


class _ClassicEnv(Environment):
    """Shared plumbing: PhysicsState, Observation assembly, truncation handling."""

    _obs_dim: int
    _num_actions: int
    _max_steps: int

    def observation_space(self) -> Observation:
        return Observation(
            agent_view=spaces.Array((self._obs_dim,), jnp.float32),
            action_mask=spaces.Array((self._action_mask_dim(),), jnp.float32),
            step_count=spaces.Array((), jnp.int32),
        )

    def _action_mask_dim(self) -> int:
        return self._num_actions

    def _observe(self, state: PhysicsState) -> Observation:
        return Observation(
            agent_view=self._agent_view(state.physics),
            action_mask=_full_mask(self._action_mask_dim()),
            step_count=state.step_count,
        )

    def _agent_view(self, physics: jax.Array) -> jax.Array:
        return physics

    def reset(self, key: jax.Array) -> Tuple[PhysicsState, TimeStep]:
        key, sub = jax.random.split(key)
        physics = self._init_physics(sub)
        state = PhysicsState(key, physics, jnp.zeros((), jnp.int32))
        ts = restart(self._observe(state))
        # Keep reset/step TimeSteps pytree-identical (lax.while_loop carries them).
        ts.extras["truncation"] = jnp.zeros((), bool)
        return state, ts

    def step(self, state: PhysicsState, action: jax.Array) -> Tuple[PhysicsState, TimeStep]:
        physics, reward, terminated = self._dynamics(state.physics, action)
        next_state = PhysicsState(state.key, physics, state.step_count + 1)
        obs = self._observe(next_state)
        truncated = jnp.logical_and(next_state.step_count >= self._max_steps, ~terminated)
        ts = select_step(
            terminated,
            termination(reward, obs),
            select_step(truncated, truncation(reward, obs), transition(reward, obs)),
        )
        ts.extras["truncation"] = truncated
        return next_state, ts

    # Subclass API -----------------------------------------------------------
    def _init_physics(self, key: jax.Array) -> jax.Array:
        raise NotImplementedError

    def _dynamics(self, physics: jax.Array, action: jax.Array) -> Tuple[jax.Array, jax.Array, jax.Array]:
        """Returns (next_physics, reward, terminated)."""
        raise NotImplementedError


class CartPole(_ClassicEnv):
    """CartPole-v1: balance a pole on a cart; +1 per step, 500-step limit."""

    _obs_dim = 4
    _num_actions = 2

    def __init__(self, max_steps: int = 500):
        self._max_steps = int(max_steps)
        self._gravity = 9.8
        self._masscart = 1.0
        self._masspole = 0.1
        self._length = 0.5
        self._force_mag = 10.0
        self._tau = 0.02
        self._theta_threshold = 12 * 2 * jnp.pi / 360
        self._x_threshold = 2.4

    def action_space(self) -> spaces.Discrete:
        return spaces.Discrete(2)

    def _init_physics(self, key: jax.Array) -> jax.Array:
        return jax.random.uniform(key, (4,), minval=-0.05, maxval=0.05)

    def _dynamics(self, physics: jax.Array, action: jax.Array) -> Tuple[jax.Array, jax.Array, jax.Array]:
        x, x_dot, theta, theta_dot = physics
        force = jnp.where(action == 1, self._force_mag, -self._force_mag)
        costheta, sintheta = jnp.cos(theta), jnp.sin(theta)
        total_mass = self._masscart + self._masspole
        polemass_length = self._masspole * self._length
        temp = (force + polemass_length * theta_dot**2 * sintheta) / total_mass
        thetaacc = (self._gravity * sintheta - costheta * temp) / (
            self._length * (4.0 / 3.0 - self._masspole * costheta**2 / total_mass)
        )
        xacc = temp - polemass_length * thetaacc * costheta / total_mass
        x = x + self._tau * x_dot
        x_dot = x_dot + self._tau * xacc
        theta = theta + self._tau * theta_dot
        theta_dot = theta_dot + self._tau * thetaacc
        next_physics = jnp.stack([x, x_dot, theta, theta_dot])
        terminated = jnp.logical_or(jnp.abs(x) > self._x_threshold, jnp.abs(theta) > self._theta_threshold)
        return next_physics, jnp.ones((), jnp.float32), terminated


class Pendulum(_ClassicEnv):
    """Pendulum-v1: continuous torque control; 200-step episodes, no termination."""

    _obs_dim = 3
    _num_actions = 1

    def __init__(self, max_steps: int = 200):
        self._max_steps = int(max_steps)
        self._max_speed = 8.0
        self._max_torque = 2.0
        self._dt = 0.05
        self._g = 10.0
        self._m = 1.0
        self._l = 1.0

    def action_space(self) -> spaces.Box:
        return spaces.Box(low=-self._max_torque, high=self._max_torque, shape=(1,))

    def _action_mask_dim(self) -> int:
        return 1

    def _init_physics(self, key: jax.Array) -> jax.Array:
        k1, k2 = jax.random.split(key)
        theta = jax.random.uniform(k1, (), minval=-jnp.pi, maxval=jnp.pi)
        thdot = jax.random.uniform(k2, (), minval=-1.0, maxval=1.0)
        return jnp.stack([theta, thdot])

    def _agent_view(self, physics: jax.Array) -> jax.Array:
        theta, thdot = physics
        return jnp.stack([jnp.cos(theta), jnp.sin(theta), thdot])

    def _dynamics(self, physics: jax.Array, action: jax.Array) -> Tuple[jax.Array, jax.Array, jax.Array]:
        theta, thdot = physics
        u = jnp.clip(jnp.reshape(action, ()), -self._max_torque, self._max_torque)
        angle_norm = ((theta + jnp.pi) % (2 * jnp.pi)) - jnp.pi
        cost = angle_norm**2 + 0.1 * thdot**2 + 0.001 * u**2
        newthdot = thdot + (3 * self._g / (2 * self._l) * jnp.sin(theta) + 3.0 / (self._m * self._l**2) * u) * self._dt
        newthdot = jnp.clip(newthdot, -self._max_speed, self._max_speed)
        newtheta = theta + newthdot * self._dt
        return jnp.stack([newtheta, newthdot]), -cost, jnp.zeros((), bool)


class Acrobot(_ClassicEnv):
    """Acrobot-v1: swing up a two-link pendulum; -1 per step until the goal."""

    _obs_dim = 6
    _num_actions = 3

    def __init__(self, max_steps: int = 500):
        self._max_steps = int(max_steps)
        self._dt = 0.2
        self._l1 = 1.0
        self._m1 = 1.0
        self._m2 = 1.0
        self._lc1 = 0.5
        self._lc2 = 0.5
        self._i1 = 1.0
        self._i2 = 1.0
        self._g = 9.8
        self._max_vel1 = 4 * jnp.pi
        self._max_vel2 = 9 * jnp.pi

    def action_space(self) -> spaces.Discrete:
        return spaces.Discrete(3)

    def _init_physics(self, key: jax.Array) -> jax.Array:
        return jax.random.uniform(key, (4,), minval=-0.1, maxval=0.1)

    def _agent_view(self, physics: jax.Array) -> jax.Array:
        t1, t2, d1, d2 = physics
        return jnp.stack([jnp.cos(t1), jnp.sin(t1), jnp.cos(t2), jnp.sin(t2), d1, d2])

    def _dsdt(self, s: jax.Array, torque: jax.Array) -> jax.Array:
        t1, t2, d1, d2 = s
        m1, m2, l1, lc1, lc2, i1, i2, g = (
            self._m1, self._m2, self._l1, self._lc1, self._lc2, self._i1, self._i2, self._g,
        )
        d_1 = m1 * lc1**2 + m2 * (l1**2 + lc2**2 + 2 * l1 * lc2 * jnp.cos(t2)) + i1 + i2
        d_2 = m2 * (lc2**2 + l1 * lc2 * jnp.cos(t2)) + i2
        phi2 = m2 * lc2 * g * jnp.cos(t1 + t2 - jnp.pi / 2.0)
        phi1 = (
            -m2 * l1 * lc2 * d2**2 * jnp.sin(t2)
            - 2 * m2 * l1 * lc2 * d2 * d1 * jnp.sin(t2)
            + (m1 * lc1 + m2 * l1) * g * jnp.cos(t1 - jnp.pi / 2)
            + phi2
        )
        ddtheta2 = (torque + d_2 / d_1 * phi1 - m2 * l1 * lc2 * d1**2 * jnp.sin(t2) - phi2) / (
            m2 * lc2**2 + i2 - d_2**2 / d_1
        )
        ddtheta1 = -(d_2 * ddtheta2 + phi1) / d_1
        return jnp.stack([d1, d2, ddtheta1, ddtheta2])

    def _dynamics(self, physics: jax.Array, action: jax.Array) -> Tuple[jax.Array, jax.Array, jax.Array]:
        torque = jnp.asarray(action, jnp.float32) - 1.0
        # RK4 over one control interval (matches the gym implementation).
        s = physics
        dt = self._dt
        k1 = self._dsdt(s, torque)
        k2 = self._dsdt(s + dt / 2 * k1, torque)
        k3 = self._dsdt(s + dt / 2 * k2, torque)
        k4 = self._dsdt(s + dt * k3, torque)
        ns = s + dt / 6.0 * (k1 + 2 * k2 + 2 * k3 + k4)
        t1 = ((ns[0] + jnp.pi) % (2 * jnp.pi)) - jnp.pi
        t2 = ((ns[1] + jnp.pi) % (2 * jnp.pi)) - jnp.pi
        d1 = jnp.clip(ns[2], -self._max_vel1, self._max_vel1)
        d2 = jnp.clip(ns[3], -self._max_vel2, self._max_vel2)
        next_physics = jnp.stack([t1, t2, d1, d2])
        terminated = -jnp.cos(t1) - jnp.cos(t2 + t1) > 1.0
        reward = jnp.where(terminated, 0.0, -1.0)
        return next_physics, reward, terminated


class MountainCar(_ClassicEnv):
    """MountainCar-v0 (discrete): -1 per step until reaching the flag."""

    _obs_dim = 2
    _num_actions = 3

    def __init__(self, max_steps: int = 200):
        self._max_steps = int(max_steps)

    def action_space(self) -> spaces.Discrete:
        return spaces.Discrete(3)

    def _init_physics(self, key: jax.Array) -> jax.Array:
        pos = jax.random.uniform(key, (), minval=-0.6, maxval=-0.4)
        return jnp.stack([pos, jnp.zeros(())])

    def _dynamics(self, physics: jax.Array, action: jax.Array) -> Tuple[jax.Array, jax.Array, jax.Array]:
        pos, vel = physics
        force = (jnp.asarray(action, jnp.float32) - 1.0) * 0.001
        vel = jnp.clip(vel + force + jnp.cos(3 * pos) * (-0.0025), -0.07, 0.07)
        pos = jnp.clip(pos + vel, -1.2, 0.6)
        vel = jnp.where(jnp.logical_and(pos <= -1.2, vel < 0), 0.0, vel)
        terminated = jnp.logical_and(pos >= 0.5, vel >= 0.0)
        return jnp.stack([pos, vel]), jnp.full((), -1.0), terminated


class MountainCarContinuous(_ClassicEnv):
    """MountainCarContinuous-v0: continuous force, +100 at goal, action cost."""

    _obs_dim = 2
    _num_actions = 1

    def __init__(self, max_steps: int = 999):
        self._max_steps = int(max_steps)

    def action_space(self) -> spaces.Box:
        return spaces.Box(low=-1.0, high=1.0, shape=(1,))

    def _action_mask_dim(self) -> int:
        return 1

    def _init_physics(self, key: jax.Array) -> jax.Array:
        pos = jax.random.uniform(key, (), minval=-0.6, maxval=-0.4)
        return jnp.stack([pos, jnp.zeros(())])

    def _dynamics(self, physics: jax.Array, action: jax.Array) -> Tuple[jax.Array, jax.Array, jax.Array]:
        pos, vel = physics
        force = jnp.clip(jnp.reshape(action, ()), -1.0, 1.0)
        vel = jnp.clip(vel + force * 0.0015 + jnp.cos(3 * pos) * (-0.0025), -0.07, 0.07)
        pos = jnp.clip(pos + vel, -1.2, 0.6)
        vel = jnp.where(jnp.logical_and(pos <= -1.2, vel < 0), 0.0, vel)
        terminated = jnp.logical_and(pos >= 0.45, vel >= 0.0)
        reward = jnp.where(terminated, 100.0, 0.0) - 0.1 * force**2
        return jnp.stack([pos, vel]), reward, terminated


class CatchState(NamedTuple):
    key: jax.Array
    ball_xy: jax.Array  # [2] (row, col)
    paddle_x: jax.Array  # []
    step_count: jax.Array


class Catch(Environment):
    """bsuite Catch: a ball falls down a rows×columns board; move the paddle to
    catch it (+1) or miss (-1). A minimal "pixel" env for the DQN family.
    """

    def __init__(self, rows: int = 10, columns: int = 5):
        self._rows = int(rows)
        self._columns = int(columns)

    def observation_space(self) -> Observation:
        return Observation(
            agent_view=spaces.Array((self._rows, self._columns, 1), jnp.float32),
            action_mask=spaces.Array((3,), jnp.float32),
            step_count=spaces.Array((), jnp.int32),
        )

    def action_space(self) -> spaces.Discrete:
        return spaces.Discrete(3)

    def _observe(self, state: CatchState) -> Observation:
        board = jnp.zeros((self._rows, self._columns), jnp.float32)
        board = board.at[state.ball_xy[0], state.ball_xy[1]].set(1.0)
        board = board.at[self._rows - 1, state.paddle_x].set(1.0)
        return Observation(
            agent_view=board[..., None],
            action_mask=_full_mask(3),
            step_count=state.step_count,
        )

    def reset(self, key: jax.Array) -> Tuple[CatchState, TimeStep]:
        key, sub = jax.random.split(key)
        ball_col = jax.random.randint(sub, (), 0, self._columns)
        state = CatchState(
            key,
            jnp.stack([jnp.zeros((), jnp.int32), ball_col]),
            jnp.asarray(self._columns // 2, jnp.int32),
            jnp.zeros((), jnp.int32),
        )
        return state, restart(self._observe(state))

    def step(self, state: CatchState, action: jax.Array) -> Tuple[CatchState, TimeStep]:
        dx = jnp.asarray(action, jnp.int32) - 1
        paddle_x = jnp.clip(state.paddle_x + dx, 0, self._columns - 1)
        ball_xy = state.ball_xy + jnp.asarray([1, 0], jnp.int32)
        next_state = CatchState(state.key, ball_xy, paddle_x, state.step_count + 1)
        obs = self._observe(next_state)
        done = ball_xy[0] >= self._rows - 1
        caught = paddle_x == ball_xy[1]
        reward = jnp.where(done, jnp.where(caught, 1.0, -1.0), 0.0)
        return next_state, select_step(done, termination(reward, obs), transition(reward, obs))
