"""ctypes adapter for the native C++ vectorized env pool (envs/native/cvec.cpp)
— the first-party EnvPool equivalent behind the Sebulba EnvFactory seam
(reference stoix/wrappers/envpool.py adapts EnvPool's API the same way: manual
auto-reset bookkeeping, numpy episode metrics, stoa-style TimeSteps).

Games: "CartPole-v1" (4-float obs), "Pendulum-v1" (continuous torque — the
Sebulba continuous-control workload, float actions through cvec_step_cont),
the 10x10x4-pixel MinAtar-class set "Breakout-minatar",
"Asterix-minatar", "Freeway-minatar", "SpaceInvaders-minatar" — each with a
(bit-)identical pure-JAX twin in envs/minatar.py / envs/classic.py — and
"Breakout-atari", the FULL-RESOLUTION pixel workload: 84x84x4 frame-stacked
grayscale observations, the exact tensor shape the reference's EnvPool Atari
path trains on (reference configs/env/envpool/*.yaml). The shared library is
compiled on first use with g++ and cached next to the source; no
Python-level per-env loops exist anywhere on the hot path.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Any, Optional, Tuple

import numpy as np

from stoix_tpu.envs import spaces
from stoix_tpu.envs.factory import EnvFactory
from stoix_tpu.envs.types import Observation, TimeStep

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libcvec.so")
_BUILD_LOCK = threading.Lock()


def _ensure_built() -> str:
    src = os.path.join(_NATIVE_DIR, "cvec.cpp")
    with _BUILD_LOCK:
        if not os.path.exists(_LIB_PATH) or os.path.getmtime(_LIB_PATH) < os.path.getmtime(src):
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", src, "-o", _LIB_PATH],
                check=True,
                capture_output=True,
            )
    return _LIB_PATH


def _load_lib() -> ctypes.CDLL:
    lib = ctypes.CDLL(_ensure_built())
    lib.cvec_create.restype = ctypes.c_void_p
    lib.cvec_create.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_uint64]
    f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
    i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
    lib.cvec_reset.argtypes = [ctypes.c_void_p, f32p]
    lib.cvec_step.argtypes = [ctypes.c_void_p, i32p, f32p, f32p, f32p, u8p, u8p, f32p, i32p]
    lib.cvec_obs_dim.argtypes = [ctypes.c_void_p]
    lib.cvec_obs_dim.restype = ctypes.c_int
    lib.cvec_obs_shape.argtypes = [ctypes.c_void_p, i32p]
    lib.cvec_num_actions.argtypes = [ctypes.c_void_p]
    lib.cvec_num_actions.restype = ctypes.c_int
    lib.cvec_action_dim.argtypes = [ctypes.c_void_p]
    lib.cvec_action_dim.restype = ctypes.c_int
    lib.cvec_action_bounds.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_float),
    ]
    lib.cvec_step_cont.argtypes = [ctypes.c_void_p, f32p, f32p, f32p, f32p, u8p, u8p, f32p, i32p]
    lib.cvec_destroy.argtypes = [ctypes.c_void_p]
    return lib


class CVecPool:
    """Stateful Sebulba env backed by the native pool: numpy in, TimeStep out."""

    def __init__(self, task: str, num_envs: int, seed: int, max_steps: int = 500):
        self._lib = _load_lib()
        self._handle = self._lib.cvec_create(task.encode(), num_envs, max_steps, seed)
        if not self._handle:
            raise ValueError(f"Unknown native pool game '{task}'")
        self._task = task
        self._n = num_envs
        shape3 = np.zeros((3,), np.int32)
        self._lib.cvec_obs_shape(self._handle, shape3)
        # (d, 1, 1) encodes a flat d-vector; anything else is an image.
        self._obs_shape: Tuple[int, ...] = (
            (int(shape3[0]),) if shape3[1] == 1 and shape3[2] == 1 else tuple(int(s) for s in shape3)
        )
        self._num_actions = int(self._lib.cvec_num_actions(self._handle))
        # action_dim > 0 marks a continuous game (float [n, action_dim]
        # actions through cvec_step_cont; Box action space with the game's
        # native bounds).
        self._action_dim = int(self._lib.cvec_action_dim(self._handle))
        lo, hi = ctypes.c_float(), ctypes.c_float()
        self._lib.cvec_action_bounds(self._handle, ctypes.byref(lo), ctypes.byref(hi))
        self._action_bounds = (float(lo.value), float(hi.value))
        dim = int(self._lib.cvec_obs_dim(self._handle))
        self._obs = np.zeros((num_envs, dim), np.float32)
        self._next_obs = np.zeros((num_envs, dim), np.float32)
        self._reward = np.zeros((num_envs,), np.float32)
        self._done = np.zeros((num_envs,), np.uint8)
        self._trunc = np.zeros((num_envs,), np.uint8)
        self._ep_return = np.zeros((num_envs,), np.float32)
        self._ep_length = np.zeros((num_envs,), np.int32)

    @property
    def num_envs(self) -> int:
        return self._n

    @property
    def num_actions(self) -> int:
        return self._num_actions

    def observation_space(self) -> Observation:
        return Observation(
            agent_view=spaces.Array(self._obs_shape, np.float32),
            action_mask=spaces.Array((self._num_actions,), np.float32),
            step_count=spaces.Array((), np.int32),
        )

    def action_space(self):
        if self._action_dim > 0:
            lo, hi = self._action_bounds
            return spaces.Box(low=lo, high=hi, shape=(self._action_dim,))
        return spaces.Discrete(self._num_actions)

    def _observation(self, view: np.ndarray, counts: np.ndarray) -> Observation:
        return Observation(
            agent_view=view.reshape((self._n,) + self._obs_shape).copy(),
            action_mask=np.ones((self._n, self._num_actions), np.float32),
            step_count=counts.astype(np.int32),
        )

    def _timestep(self, first: bool) -> TimeStep:
        done = self._done.astype(bool)
        trunc = self._trunc.astype(bool)
        last = done | trunc
        counts = np.where(last, 0, self._ep_length)
        return TimeStep(
            step_type=np.where(
                np.zeros((self._n,), bool) if not first else np.ones((self._n,), bool),
                np.int8(0),
                np.where(last, np.int8(2), np.int8(1)),
            ),
            reward=self._reward.copy(),
            discount=np.where(done, 0.0, 1.0).astype(np.float32),
            observation=self._observation(self._obs, counts),
            extras={
                "next_obs": self._observation(self._next_obs, self._ep_length),
                "truncation": trunc.copy(),
                "episode_metrics": {
                    "episode_return": self._ep_return.copy(),
                    "episode_length": self._ep_length.copy(),
                    "is_terminal_step": last.copy(),
                },
            },
        )

    def reset(self, *, seed: Optional[int] = None) -> TimeStep:
        del seed  # seeding fixed at construction (thread-unique via factory)
        self._lib.cvec_reset(self._handle, self._obs)
        self._reward[:] = 0
        self._done[:] = 0
        self._trunc[:] = 0
        self._ep_return[:] = 0
        self._ep_length[:] = 0
        self._next_obs[:] = self._obs
        return self._timestep(first=True)

    def step(self, action: Any) -> TimeStep:
        if self._action_dim > 0:
            actions = np.ascontiguousarray(
                np.asarray(action, np.float32).reshape(self._n, self._action_dim)
            )
            self._lib.cvec_step_cont(
                self._handle, actions, self._obs, self._next_obs, self._reward,
                self._done, self._trunc, self._ep_return, self._ep_length,
            )
        else:
            actions = np.ascontiguousarray(np.asarray(action, np.int32))
            self._lib.cvec_step(
                self._handle, actions, self._obs, self._next_obs, self._reward,
                self._done, self._trunc, self._ep_return, self._ep_length,
            )
        return self._timestep(first=False)

    def __del__(self) -> None:
        if getattr(self, "_handle", None):
            self._lib.cvec_destroy(self._handle)
            self._handle = None


class CVecEnvFactory(EnvFactory):
    """Factory for the native pool; the scenario name selects the game."""

    def __call__(self, num_envs: int) -> CVecPool:
        seed = self._next_seed(num_envs)
        return CVecPool(self._task_id, num_envs, seed, **self._kwargs)


# Backwards-compatible alias (round-1 name, CartPole-only era).
class CVecCartPole(CVecPool):
    def __init__(self, num_envs: int, seed: int, max_steps: int = 500):
        super().__init__("CartPole-v1", num_envs, seed, max_steps)
