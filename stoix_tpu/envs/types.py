"""Core environment types: StepType, TimeStep, Observation.

Mirrors the behavior of the `stoa` types used by the reference (cited throughout
reference stoix/base_types.py:32-60) with a TPU-first representation: everything
is a flat pytree of fixed-shape arrays so that the whole rollout fits inside one
`lax.scan` under `jit`/`shard_map` with static shapes.

Truncation semantics (the subtle part, see reference stoix/utils/multistep.py:119-130):
  - termination: step_type == LAST and discount == 0.0
  - truncation:  step_type == LAST and discount == 1.0  (bootstrapping continues)
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp


class StepType:
    """Integer step-type codes, stored as int8 arrays inside TimeStep.

    Plain Python ints (not jnp arrays) so importing this module does no
    device work; comparisons and jnp.where treat them identically.
    """

    FIRST = 0
    MID = 1
    LAST = 2


class TimeStep(NamedTuple):
    """One transition's worth of env output.

    extras is a flat dict; well-known keys:
      "next_obs"          — true next observation before any auto-reset (bootstrap).
      "episode_metrics"   — dict(episode_return, episode_length, is_terminal_step).
      "truncation"        — bool, LAST due to step limit (discount stays 1).
    """

    step_type: jax.Array  # int8 []
    reward: jax.Array  # float32 []
    discount: jax.Array  # float32 []
    observation: Any  # pytree
    extras: Dict[str, Any]

    def first(self) -> jax.Array:
        return self.step_type == StepType.FIRST

    def mid(self) -> jax.Array:
        return self.step_type == StepType.MID

    def last(self) -> jax.Array:
        return self.step_type == StepType.LAST


def restart(observation: Any, extras: Optional[Dict[str, Any]] = None, shape: tuple = ()) -> TimeStep:
    return TimeStep(
        step_type=jnp.full(shape, 0, dtype=jnp.int8),
        reward=jnp.zeros(shape, dtype=jnp.float32),
        discount=jnp.ones(shape, dtype=jnp.float32),
        observation=observation,
        extras=extras if extras is not None else {},
    )


def transition(
    reward: jax.Array,
    observation: Any,
    discount: Optional[jax.Array] = None,
    extras: Optional[Dict[str, Any]] = None,
    shape: tuple = (),
) -> TimeStep:
    return TimeStep(
        step_type=jnp.full(shape, 1, dtype=jnp.int8),
        reward=jnp.asarray(reward, dtype=jnp.float32),
        discount=jnp.ones(shape, dtype=jnp.float32) if discount is None else jnp.asarray(discount, jnp.float32),
        observation=observation,
        extras=extras if extras is not None else {},
    )


def termination(
    reward: jax.Array, observation: Any, extras: Optional[Dict[str, Any]] = None, shape: tuple = ()
) -> TimeStep:
    return TimeStep(
        step_type=jnp.full(shape, 2, dtype=jnp.int8),
        reward=jnp.asarray(reward, dtype=jnp.float32),
        discount=jnp.zeros(shape, dtype=jnp.float32),
        observation=observation,
        extras=extras if extras is not None else {},
    )


def truncation(
    reward: jax.Array, observation: Any, extras: Optional[Dict[str, Any]] = None, shape: tuple = ()
) -> TimeStep:
    return TimeStep(
        step_type=jnp.full(shape, 2, dtype=jnp.int8),
        reward=jnp.asarray(reward, dtype=jnp.float32),
        discount=jnp.ones(shape, dtype=jnp.float32),
        observation=observation,
        extras=extras if extras is not None else {},
    )


def select_step(done: jax.Array, terminal_ts: TimeStep, mid_ts: TimeStep) -> TimeStep:
    """Elementwise select between terminal and mid timesteps on a traced `done`."""
    return jax.tree.map(lambda a, b: jnp.where(_bcast(done, a), a, b), terminal_ts, mid_ts)


def _bcast(flag: jax.Array, like: jax.Array) -> jax.Array:
    flag = jnp.asarray(flag)
    like = jnp.asarray(like)
    extra = like.ndim - flag.ndim
    return flag.reshape(flag.shape + (1,) * extra) if extra > 0 else flag


class Observation(NamedTuple):
    """Canonical structured observation (reference stoix/base_types.py:32-43).

    agent_view:  the raw observable features (e.g. [obs_dim] or [H, W, C]).
    action_mask: legal-action mask [num_actions] (all-ones when env has no masking).
    step_count:  steps elapsed in the current episode [].
    """

    agent_view: jax.Array
    action_mask: jax.Array
    step_count: jax.Array


def get_final_step_metrics(metrics: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
    """Filter episode metrics to completed episodes only.

    Given a dict with "episode_return", "episode_length", "is_terminal_step"
    (each shaped [...]), returns values gathered where is_terminal_step is True,
    as 1-D host-side arrays. Used by the host logging loop (reference
    ff_ppo.py:624-629 via stoa's helper).
    """
    import numpy as np

    is_final = np.asarray(metrics["is_terminal_step"]).reshape(-1)
    out: Dict[str, jax.Array] = {}
    for k, v in metrics.items():
        if k == "is_terminal_step":
            continue
        out[k] = np.asarray(v).reshape(-1)[is_final]
    return out
