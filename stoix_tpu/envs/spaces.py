"""Space definitions for environment observation/action specs.

TPU-native design notes: spaces are *static* Python objects (never traced); they
exist so that networks can be initialised from spec-generated dummy values and so
that wrappers/systems can interrogate shapes without running the env. Mirrors the
role of the `stoa` spaces used by the reference (see reference
stoix/utils/make_env.py and stoix/base_types.py) without depending on it.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class Space:
    """Base class for all spaces."""

    def generate_value(self) -> Any:
        """Generate a zero-like value conforming to this space (for network init)."""
        raise NotImplementedError

    def sample(self, key: jax.Array) -> Any:
        """Sample a random value from the space."""
        raise NotImplementedError

    def contains(self, value: Any) -> bool:
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class Array(Space):
    """An unbounded array space with fixed shape and dtype."""

    shape: Tuple[int, ...]
    dtype: Any = jnp.float32
    name: str = "array"

    def generate_value(self) -> jax.Array:
        return jnp.zeros(self.shape, dtype=self.dtype)

    def sample(self, key: jax.Array) -> jax.Array:
        if jnp.issubdtype(self.dtype, jnp.integer):
            return jnp.zeros(self.shape, dtype=self.dtype)
        return jax.random.normal(key, self.shape, dtype=self.dtype)

    def contains(self, value: Any) -> bool:
        return tuple(np.shape(value)) == tuple(self.shape)


@dataclasses.dataclass(frozen=True)
class Box(Space):
    """A bounded continuous space. `low`/`high` may be scalars or arrays."""

    low: Any
    high: Any
    shape: Tuple[int, ...] = ()
    dtype: Any = jnp.float32
    name: str = "box"

    def __post_init__(self) -> None:
        if not self.shape:
            inferred = np.broadcast(np.asarray(self.low), np.asarray(self.high)).shape
            object.__setattr__(self, "shape", tuple(inferred))

    def generate_value(self) -> jax.Array:
        mid = (np.asarray(self.low, dtype=np.float64) + np.asarray(self.high, dtype=np.float64)) / 2.0
        mid = np.where(np.isfinite(mid), mid, 0.0)
        return jnp.broadcast_to(jnp.asarray(mid, dtype=self.dtype), self.shape)

    def sample(self, key: jax.Array) -> jax.Array:
        low = jnp.broadcast_to(jnp.asarray(self.low, self.dtype), self.shape)
        high = jnp.broadcast_to(jnp.asarray(self.high, self.dtype), self.shape)
        u = jax.random.uniform(key, self.shape, dtype=self.dtype)
        return low + u * (high - low)

    def contains(self, value: Any) -> bool:
        v = np.asarray(value)
        return bool(np.all(v >= self.low) and np.all(v <= self.high))


@dataclasses.dataclass(frozen=True)
class Discrete(Space):
    """A discrete space {0, ..., num_values - 1}."""

    num_values: int
    dtype: Any = jnp.int32
    name: str = "discrete"

    @property
    def shape(self) -> Tuple[int, ...]:
        return ()

    def generate_value(self) -> jax.Array:
        return jnp.zeros((), dtype=self.dtype)

    def sample(self, key: jax.Array) -> jax.Array:
        return jax.random.randint(key, (), 0, self.num_values, dtype=self.dtype)

    def contains(self, value: Any) -> bool:
        v = int(np.asarray(value))
        return 0 <= v < self.num_values


@dataclasses.dataclass(frozen=True)
class MultiDiscrete(Space):
    """A vector of discrete sub-spaces with per-dimension cardinalities."""

    num_values: Tuple[int, ...]
    dtype: Any = jnp.int32
    name: str = "multi_discrete"

    def __post_init__(self) -> None:
        object.__setattr__(self, "num_values", tuple(int(n) for n in self.num_values))

    @property
    def shape(self) -> Tuple[int, ...]:
        return (len(self.num_values),)

    def generate_value(self) -> jax.Array:
        return jnp.zeros(self.shape, dtype=self.dtype)

    def sample(self, key: jax.Array) -> jax.Array:
        maxes = jnp.asarray(self.num_values)
        u = jax.random.uniform(key, self.shape)
        return jnp.asarray(jnp.floor(u * maxes), dtype=self.dtype)

    def contains(self, value: Any) -> bool:
        v = np.asarray(value)
        return bool(np.all(v >= 0) and np.all(v < np.asarray(self.num_values)))


class DictSpace(Space, dict):
    """A dict of named sub-spaces (pytree-structured observations)."""

    def generate_value(self) -> Any:
        return {k: v.generate_value() for k, v in self.items()}

    def sample(self, key: jax.Array) -> Any:
        keys = jax.random.split(key, max(len(self), 1))
        return {k: v.sample(keys[i]) for i, (k, v) in enumerate(self.items())}

    def contains(self, value: Any) -> bool:
        return all(k in value and s.contains(value[k]) for k, s in self.items())


def tree_generate_value(spec: Any) -> Any:
    """Generate dummy values for an arbitrary pytree of spaces / typed structs."""
    if isinstance(spec, Space):
        return spec.generate_value()
    if hasattr(spec, "_fields"):  # NamedTuple of spaces
        return type(spec)(*(tree_generate_value(s) for s in spec))
    if isinstance(spec, dict):
        return {k: tree_generate_value(v) for k, v in spec.items()}
    if isinstance(spec, (list, tuple)):
        return type(spec)(tree_generate_value(v) for v in spec)
    raise TypeError(f"Cannot generate value for spec of type {type(spec)}")


def num_actions(action_space: Space) -> int:
    """Flat action dimensionality used for network head sizing."""
    if isinstance(action_space, Discrete):
        return int(action_space.num_values)
    if isinstance(action_space, MultiDiscrete):
        return int(sum(action_space.num_values))
    if isinstance(action_space, (Box, Array)):
        return int(np.prod(action_space.shape)) if action_space.shape else 1
    raise TypeError(f"Unsupported action space {type(action_space)}")
