"""EnvPool adapter: full-Atari reset/lives semantics for the Sebulba path.

The reference's Sebulba runs EnvPool Atari through `EnvPoolToStoa`
(reference stoix/wrappers/envpool.py:75-115), whose load-bearing behaviors are:

  1. **done-ids autoreset** (:75-86): envpool's own autoreset returns the
     terminal observation on the done step and the reset observation one step
     LATER; the stoix API wants the reset observation immediately. The adapter
     therefore issues a second `env.step(zeros, done_ids)` restricted to the
     finished envs and splices those reset observations in. The TRUE terminal
     successor is preserved in `extras["next_obs"]` for bootstrapping.
  2. **lives handling** (:99-117): on Atari, losing a life ends an envpool
     episode; episode metrics must only conclude when ALL lives are exhausted
     (`info["lives"] == 0`), otherwise per-life returns pollute the learning
     curves.
  3. **elapsed_step truncation** (:72, :144-148): envpool reports
     `info["elapsed_step"]`; hitting `max_episode_steps` is a truncation
     (discount stays 1) rather than a termination.

Produces the same stateful TimeStep contract as the native CVecPool
(stoix_tpu/envs/cvec.py): Observation(agent_view, action_mask, step_count) and
extras {next_obs, truncation, episode_metrics} — so the whole Sebulba rollout
machinery is backend-agnostic between the first-party C++ pool and EnvPool.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from stoix_tpu.envs import spaces
from stoix_tpu.envs.types import Observation, TimeStep


class EnvPoolAdapter:
    """Wrap a constructed envpool env (gymnasium API, gym_reset_return_info)."""

    def __init__(self, env: Any, has_lives: Optional[bool] = None):
        self._env = env
        obs, _ = env.reset()
        self._n = int(obs.shape[0])
        self._obs_shape = tuple(obs.shape[1:])
        self._num_actions = int(env.action_space.n)
        self._max_episode_steps = int(env.spec.config.max_episode_steps)

        if has_lives is None:
            # Probe: Atari tasks report a positive lives counter (reference
            # envpool.py:24-33 probes with one zero-action step).
            info = env.step(np.zeros(self._n, dtype=np.int32))[-1]
            has_lives = bool("lives" in info and np.sum(info["lives"]) > 0)
            obs, _ = env.reset()
        self._has_lives = bool(has_lives)

        self._obs = obs
        self._elapsed = np.zeros(self._n, dtype=np.int64)
        # Running episode accumulators + the last CONCLUDED episode's metrics
        # (concluded = all lives exhausted when has_lives, else any done).
        self._run_return = np.zeros(self._n, dtype=np.float64)
        self._run_length = np.zeros(self._n, dtype=np.int64)
        self._ep_return = np.zeros(self._n, dtype=np.float64)
        self._ep_length = np.zeros(self._n, dtype=np.int64)

    @property
    def num_envs(self) -> int:
        return self._n

    @property
    def num_actions(self) -> int:
        return self._num_actions

    def observation_space(self):
        return Observation(
            agent_view=spaces.Array(self._obs_shape, np.float32),
            action_mask=spaces.Array((self._num_actions,), np.float32),
            step_count=spaces.Array((), np.int32),
        )

    def action_space(self):
        return spaces.Discrete(self._num_actions)

    def _observation(self, view: np.ndarray, counts: np.ndarray) -> Observation:
        return Observation(
            agent_view=np.asarray(view, np.float32),
            action_mask=np.ones((self._n, self._num_actions), np.float32),
            step_count=counts.astype(np.int32),
        )

    def reset(self, *, seed: Optional[int] = None) -> TimeStep:
        del seed  # envpool seeds at construction
        obs, _ = self._env.reset()
        self._obs = obs
        self._elapsed[:] = 0
        self._run_return[:] = 0
        self._run_length[:] = 0
        self._ep_return[:] = 0
        self._ep_length[:] = 0
        zeros = np.zeros(self._n, np.float32)
        return TimeStep(
            step_type=np.zeros(self._n, np.int8),
            reward=zeros.copy(),
            discount=np.ones(self._n, np.float32),
            observation=self._observation(obs, np.zeros(self._n, np.int64)),
            extras={
                "next_obs": self._observation(obs, np.zeros(self._n, np.int64)),
                "truncation": np.zeros(self._n, bool),
                "episode_metrics": {
                    "episode_return": zeros.astype(np.float64),
                    "episode_length": np.zeros(self._n, np.int64),
                    "is_terminal_step": np.zeros(self._n, bool),
                },
            },
        )

    def step(self, action: Any) -> TimeStep:
        action = np.asarray(action, np.int32).reshape(self._n)
        obs, rewards, terminated, env_truncated, info = self._env.step(action)
        terminated = np.asarray(terminated, bool)
        elapsed = np.asarray(info.get("elapsed_step", self._elapsed + 1))
        # OR the pool's own truncated flag with the elapsed-step check: if the
        # pool truncates on a condition the step counter misses, dropping its
        # flag would desync the done-ids reset splice one step later.
        truncated = np.logical_and(
            np.logical_or(
                np.asarray(env_truncated, bool),
                elapsed >= self._max_episode_steps,
            ),
            ~terminated,
        )
        ep_done = np.logical_or(terminated, truncated)

        # True terminal successors, before any reset splice (bootstrapping).
        next_obs = np.array(obs, copy=True)

        # done-ids autoreset (reference envpool.py:75-86): step ONLY the
        # finished envs with a zero action to obtain their reset observations.
        done_ids = np.where(ep_done)[0]
        if len(done_ids) > 0:
            reset_obs = self._env.step(
                np.zeros(len(done_ids), dtype=np.int32), done_ids
            )[0]
            obs = np.array(obs, copy=True)
            obs[done_ids] = reset_obs

        metric_reward = np.asarray(info.get("reward", rewards), np.float64)
        new_return = self._run_return + metric_reward
        new_length = self._run_length + 1

        if self._has_lives:
            # A game concludes when all lives are gone — OR when the episode
            # is cut by the step limit with lives remaining (the run would
            # otherwise silently merge into the next game's metrics).
            concluded = np.logical_or(
                np.logical_and(ep_done, np.asarray(info["lives"]) == 0),
                truncated,
            )
        else:
            concluded = ep_done
        keep = ~concluded
        self._ep_return = np.where(concluded, new_return, self._ep_return)
        self._ep_length = np.where(concluded, new_length, self._ep_length)
        self._run_return = np.where(concluded, 0.0, new_return)
        self._run_length = np.where(concluded, 0, new_length)

        self._elapsed = np.where(ep_done, 0, elapsed)
        self._obs = obs

        counts = np.where(ep_done, 0, elapsed)
        discount = np.where(terminated, 0.0, 1.0).astype(np.float32)
        return TimeStep(
            step_type=np.where(ep_done, np.int8(2), np.int8(1)),
            reward=np.asarray(rewards, np.float32),
            discount=discount,
            observation=self._observation(obs, counts),
            extras={
                "next_obs": self._observation(next_obs, elapsed),
                "truncation": truncated,
                "episode_metrics": {
                    "episode_return": self._ep_return.copy(),
                    "episode_length": self._ep_length.copy(),
                    "is_terminal_step": concluded.copy(),
                },
            },
        )

    def close(self) -> None:
        self._env.close()
