"""MinAtar-class grid games, implemented natively in JAX.

The reference gets MinAtar-style pixel envs from external suites (gymnax's
`*-MinAtar` family, reference stoix/utils/make_env.py ENV_MAKERS["gymnax"]);
this module is the first-party TPU-native equivalent. Each game mirrors the
native C++ pool's version (envs/native/cvec.cpp) RULE FOR RULE, so Sebulba
(C++ pool actors) and Anakin (this env) train on the same game and a policy's
scores transfer across backends; the equivalence is pinned by
tests/test_minatar.py which steps both engines in lockstep.

Breakout: 10x10 grid, 4 binary channels (paddle, ball, trail, brick),
3 actions (left/stay/right). Serve is from a top corner below the 3-row brick
band, moving down-and-inward; bricks reflect the ball vertically and score +1;
losing the ball past the paddle terminates.

Asterix: 10x10 grid, 4 channels (player, enemy, gold, moving-right), 5 actions
(stay/left/up/right/down). Entities stream across rows 1..8 on a deterministic
spawn schedule; touching gold scores +1, touching an enemy terminates.

All state is fixed-shape int32 arrays; stepping is pure jnp.where logic — no
per-env Python.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from stoix_tpu.envs import spaces
from stoix_tpu.envs.core import Environment
from stoix_tpu.envs.types import (
    Observation,
    TimeStep,
    restart,
    select_step,
    termination,
    transition,
    truncation,
)

_GRID = 10
_BRICK_ROWS = 3
_PADDLE_ROW = _GRID - 1
_ASTERIX_SLOTS = 8
_SPAWN_PERIOD = 5
_MOVE_PERIOD = 2


class BreakoutState(NamedTuple):
    key: jax.Array
    ball_r: jax.Array  # [] int32
    ball_c: jax.Array
    dr: jax.Array  # {-1, +1}
    dc: jax.Array
    last_r: jax.Array
    last_c: jax.Array
    paddle: jax.Array
    bricks: jax.Array  # [3, 10] int32 in {0, 1}
    step_count: jax.Array


class Breakout(Environment):
    """JAX twin of the native pool's Breakout-minatar (see module docstring)."""

    def __init__(self, max_steps: int = 500):
        self._max_steps = int(max_steps)

    def observation_space(self) -> Observation:
        return Observation(
            agent_view=spaces.Array((_GRID, _GRID, 4), jnp.float32),
            action_mask=spaces.Array((3,), jnp.float32),
            step_count=spaces.Array((), jnp.int32),
        )

    def action_space(self) -> spaces.Discrete:
        return spaces.Discrete(3)

    def _observe(self, state: BreakoutState) -> Observation:
        board = jnp.zeros((_GRID, _GRID, 4), jnp.float32)
        board = board.at[_PADDLE_ROW, state.paddle, 0].set(1.0)
        board = board.at[state.ball_r, state.ball_c, 1].set(1.0)
        board = board.at[state.last_r, state.last_c, 2].set(1.0)
        board = board.at[1 : _BRICK_ROWS + 1, :, 3].set(state.bricks.astype(jnp.float32))
        return Observation(
            agent_view=board,
            action_mask=jnp.ones((3,), jnp.float32),
            step_count=state.step_count,
        )

    def _serve(self, key: jax.Array) -> BreakoutState:
        key, sub = jax.random.split(key)
        inward = jax.random.bernoulli(sub)
        dc = jnp.where(inward, 1, -1).astype(jnp.int32)
        ball_c = jnp.where(inward, 0, _GRID - 1).astype(jnp.int32)
        ball_r = jnp.asarray(_BRICK_ROWS + 1, jnp.int32)
        return BreakoutState(
            key=key,
            ball_r=ball_r,
            ball_c=ball_c,
            dr=jnp.asarray(1, jnp.int32),
            dc=dc,
            last_r=ball_r,
            last_c=ball_c,
            paddle=jnp.asarray(_GRID // 2, jnp.int32),
            bricks=jnp.ones((_BRICK_ROWS, _GRID), jnp.int32),
            step_count=jnp.zeros((), jnp.int32),
        )

    def reset(self, key: jax.Array) -> Tuple[BreakoutState, TimeStep]:
        state = self._serve(key)
        ts = restart(self._observe(state))
        ts.extras["truncation"] = jnp.zeros((), bool)
        return state, ts

    def step(self, state: BreakoutState, action: jax.Array) -> Tuple[BreakoutState, TimeStep]:
        # Mirrors cvec.cpp BreakoutVec::step_env exactly.
        paddle = jnp.clip(state.paddle + (jnp.asarray(action, jnp.int32) - 1), 0, _GRID - 1)
        last_r, last_c = state.ball_r, state.ball_c

        # Side-wall bounce.
        nc0 = state.ball_c + state.dc
        wall = jnp.logical_or(nc0 < 0, nc0 >= _GRID)
        dc = jnp.where(wall, -state.dc, state.dc)
        nc = state.ball_c + dc
        # Ceiling bounce.
        nr0 = state.ball_r + state.dr
        ceil = nr0 < 0
        dr = jnp.where(ceil, 1, state.dr)
        nr = state.ball_r + dr

        # Brick hit: break it, reflect vertically, score.
        in_band = jnp.logical_and(nr >= 1, nr <= _BRICK_ROWS)
        brick_row = jnp.clip(nr - 1, 0, _BRICK_ROWS - 1)
        hit = jnp.logical_and(in_band, state.bricks[brick_row, nc] == 1)
        bricks = state.bricks.at[brick_row, nc].set(
            jnp.where(hit, 0, state.bricks[brick_row, nc])
        )
        reward = jnp.where(hit, 1.0, 0.0).astype(jnp.float32)
        dr = jnp.where(hit, -dr, dr)
        nr_after_hit = jnp.where(hit, state.ball_r, nr)
        # All bricks cleared -> fresh wall (play continues).
        bricks = jnp.where(jnp.any(bricks == 1), bricks, jnp.ones_like(bricks))

        # Paddle row: bounce if caught, terminate if lost.
        at_paddle = jnp.logical_and(~hit, nr == _PADDLE_ROW)
        caught = jnp.logical_and(at_paddle, nc == paddle)
        terminated = jnp.logical_and(at_paddle, nc != paddle)
        dr = jnp.where(caught, -1, dr)
        nr_final = jnp.where(caught, state.ball_r, nr_after_hit)

        next_state = BreakoutState(
            key=state.key,
            ball_r=nr_final,
            ball_c=nc,
            dr=dr,
            dc=dc,
            last_r=last_r,
            last_c=last_c,
            paddle=paddle,
            bricks=bricks,
            step_count=state.step_count + 1,
        )
        obs = self._observe(next_state)
        truncated = jnp.logical_and(next_state.step_count >= self._max_steps, ~terminated)
        ts = select_step(
            terminated,
            termination(reward, obs),
            select_step(truncated, truncation(reward, obs), transition(reward, obs)),
        )
        ts.extras["truncation"] = truncated
        return next_state, ts


class AsterixState(NamedTuple):
    key: jax.Array
    player_r: jax.Array  # [] int32
    player_c: jax.Array
    active: jax.Array  # [8] int32 in {0, 1}
    col: jax.Array  # [8] int32
    dirn: jax.Array  # [8] int32 in {-1, +1}
    gold: jax.Array  # [8] int32 in {0, 1}
    spawn_count: jax.Array  # [] int32
    t: jax.Array  # [] int32  (in-episode step index, drives the schedules)
    step_count: jax.Array


class Asterix(Environment):
    """JAX twin of the native pool's Asterix-minatar (see module docstring).

    Mirrors cvec.cpp AsterixVec rule for rule: the spawn schedule is
    deterministic in (spawn_count, slot), so the two engines stay
    bit-identical under lockstep with no shared RNG.
    """

    def __init__(self, max_steps: int = 500):
        self._max_steps = int(max_steps)

    def observation_space(self) -> Observation:
        return Observation(
            agent_view=spaces.Array((_GRID, _GRID, 4), jnp.float32),
            action_mask=spaces.Array((5,), jnp.float32),
            step_count=spaces.Array((), jnp.int32),
        )

    def action_space(self) -> spaces.Discrete:
        return spaces.Discrete(5)

    def _observe(self, state: AsterixState) -> Observation:
        board = jnp.zeros((_GRID, _GRID, 4), jnp.float32)
        board = board.at[state.player_r, state.player_c, 0].set(1.0)
        rows = jnp.arange(_ASTERIX_SLOTS) + 1
        live = state.active.astype(jnp.float32)
        is_gold = state.gold.astype(jnp.float32)
        board = board.at[rows, state.col, 1].max(live * (1.0 - is_gold))
        board = board.at[rows, state.col, 2].max(live * is_gold)
        board = board.at[rows, state.col, 3].max(live * (state.dirn > 0))
        return Observation(
            agent_view=board,
            action_mask=jnp.ones((5,), jnp.float32),
            step_count=state.step_count,
        )

    def reset(self, key: jax.Array) -> Tuple[AsterixState, TimeStep]:
        state = AsterixState(
            key=key,
            player_r=jnp.asarray(_GRID // 2, jnp.int32),
            player_c=jnp.asarray(_GRID // 2, jnp.int32),
            active=jnp.zeros((_ASTERIX_SLOTS,), jnp.int32),
            col=jnp.zeros((_ASTERIX_SLOTS,), jnp.int32),
            dirn=jnp.ones((_ASTERIX_SLOTS,), jnp.int32),
            gold=jnp.zeros((_ASTERIX_SLOTS,), jnp.int32),
            spawn_count=jnp.zeros((), jnp.int32),
            t=jnp.zeros((), jnp.int32),
            step_count=jnp.zeros((), jnp.int32),
        )
        ts = restart(self._observe(state))
        ts.extras["truncation"] = jnp.zeros((), bool)
        return state, ts

    def step(self, state: AsterixState, action: jax.Array) -> Tuple[AsterixState, TimeStep]:
        # Mirrors cvec.cpp AsterixVec::step_env exactly.
        action = jnp.asarray(action, jnp.int32)
        drs = jnp.array([0, 0, -1, 0, 1], jnp.int32)
        dcs = jnp.array([0, -1, 0, 1, 0], jnp.int32)
        player_r = jnp.clip(state.player_r + drs[action], 0, _GRID - 1)
        player_c = jnp.clip(state.player_c + dcs[action], 0, _GRID - 1)

        rows = jnp.arange(_ASTERIX_SLOTS) + 1

        def collide(active, gold, reward, terminated):
            on_player = jnp.logical_and(
                active == 1,
                jnp.logical_and(player_r == rows, player_c == state_col[0]),
            )
            got_gold = jnp.logical_and(on_player, gold == 1)
            hit_enemy = jnp.any(jnp.logical_and(on_player, gold == 0))
            reward = reward + jnp.sum(got_gold.astype(jnp.float32))
            active = jnp.where(got_gold, 0, active)
            terminated = jnp.logical_or(terminated, hit_enemy)
            return active, reward, terminated

        # collide() reads the CURRENT columns; use a one-element list so the
        # closure sees updates as entities move.
        state_col = [state.col]
        active, gold, dirn = state.active, state.gold, state.dirn
        reward = jnp.zeros((), jnp.float32)
        terminated = jnp.zeros((), bool)

        active, reward, terminated = collide(active, gold, reward, terminated)

        # Entity movement every _MOVE_PERIOD steps.
        move_now = state.t % _MOVE_PERIOD == 0
        new_col = state_col[0] + dirn
        off = jnp.logical_or(new_col < 0, new_col >= _GRID)
        moved_col = jnp.where(move_now, new_col, state_col[0])
        active = jnp.where(jnp.logical_and(move_now, off), 0, active)
        state_col[0] = jnp.clip(moved_col, 0, _GRID - 1)
        a2, r2, t2 = collide(active, gold, reward, terminated)
        active = jnp.where(move_now, a2, active)
        reward = jnp.where(move_now, r2, reward)
        terminated = jnp.where(move_now, t2, terminated)

        # Deterministic spawn schedule every _SPAWN_PERIOD steps.
        spawn_now = state.t % _SPAWN_PERIOD == 0
        slot = state.spawn_count % _ASTERIX_SLOTS
        slot_free = active[slot] == 0
        do_spawn = jnp.logical_and(spawn_now, slot_free)
        new_dir = jnp.where((state.spawn_count // _ASTERIX_SLOTS + slot) % 2 == 0, 1, -1)
        spawn_col = jnp.where(new_dir > 0, 0, _GRID - 1)
        new_gold = jnp.where(state.spawn_count % 3 == 0, 1, 0)
        active = active.at[slot].set(jnp.where(do_spawn, 1, active[slot]))
        dirn = dirn.at[slot].set(jnp.where(do_spawn, new_dir, dirn[slot]))
        state_col[0] = state_col[0].at[slot].set(
            jnp.where(do_spawn, spawn_col, state_col[0][slot])
        )
        gold = gold.at[slot].set(jnp.where(do_spawn, new_gold, gold[slot]))
        a3, r3, t3 = collide(active, gold, reward, terminated)
        active = jnp.where(do_spawn, a3, active)
        reward = jnp.where(do_spawn, r3, reward)
        terminated = jnp.where(do_spawn, t3, terminated)

        spawn_count = state.spawn_count + spawn_now.astype(jnp.int32)

        next_state = AsterixState(
            key=state.key,
            player_r=player_r,
            player_c=player_c,
            active=active,
            col=state_col[0],
            dirn=dirn,
            gold=gold,
            spawn_count=spawn_count,
            t=state.t + 1,
            step_count=state.step_count + 1,
        )
        obs = self._observe(next_state)
        truncated = jnp.logical_and(next_state.step_count >= self._max_steps, ~terminated)
        ts = select_step(
            terminated,
            termination(reward, obs),
            select_step(truncated, truncation(reward, obs), transition(reward, obs)),
        )
        ts.extras["truncation"] = truncated
        return next_state, ts


_FREEWAY_START_R = _GRID - 1
_FREEWAY_START_C = _GRID // 2
_SI_ROWS = 4
_SI_COLS = 6
_SI_ALIEN_PERIOD = 4
_SI_SHOOT_PERIOD = 6


class FreewayState(NamedTuple):
    key: jax.Array
    player_r: jax.Array  # [] int32
    player_c: jax.Array
    car_col: jax.Array  # [8] int32
    t: jax.Array  # [] int32 (drives per-row movement periods)
    step_count: jax.Array


class Freeway(Environment):
    """Freeway (MinAtar-class): cross 8 lanes of traffic, +1 per crossing.

    JAX twin of the native pool's Freeway-minatar (envs/native/cvec.cpp),
    rule for rule. Fully deterministic: lane s has fixed direction
    (+1 if s even) and fixed period 1 + (s % 3); a collision sends the
    chicken back to the start (no termination — the episode is purely
    time-limited, as in the published MinAtar freeway).

    Channels: 0 player, 1 car, 2 car-moving-right, 3 fast-car (period 1).
    Actions: 0 stay, 1 up, 2 down.
    """

    def __init__(self, max_steps: int = 500):
        self._max_steps = int(max_steps)

    def observation_space(self) -> Observation:
        return Observation(
            agent_view=spaces.Array((_GRID, _GRID, 4), jnp.float32),
            action_mask=spaces.Array((3,), jnp.float32),
            step_count=spaces.Array((), jnp.int32),
        )

    def action_space(self) -> spaces.Discrete:
        return spaces.Discrete(3)

    @staticmethod
    def _dirs() -> jax.Array:
        s = jnp.arange(8)
        return jnp.where(s % 2 == 0, 1, -1).astype(jnp.int32)

    @staticmethod
    def _periods() -> jax.Array:
        return (1 + jnp.arange(8) % 3).astype(jnp.int32)

    def _observe(self, state: FreewayState) -> Observation:
        board = jnp.zeros((_GRID, _GRID, 4), jnp.float32)
        board = board.at[state.player_r, state.player_c, 0].set(1.0)
        rows = jnp.arange(8) + 1
        board = board.at[rows, state.car_col, 1].set(1.0)
        board = board.at[rows, state.car_col, 2].max(
            (self._dirs() > 0).astype(jnp.float32)
        )
        board = board.at[rows, state.car_col, 3].max(
            (self._periods() == 1).astype(jnp.float32)
        )
        return Observation(
            agent_view=board,
            action_mask=jnp.ones((3,), jnp.float32),
            step_count=state.step_count,
        )

    def reset(self, key: jax.Array) -> Tuple[FreewayState, TimeStep]:
        state = FreewayState(
            key=key,
            player_r=jnp.asarray(_FREEWAY_START_R, jnp.int32),
            player_c=jnp.asarray(_FREEWAY_START_C, jnp.int32),
            car_col=((3 * jnp.arange(8) + 1) % _GRID).astype(jnp.int32),
            t=jnp.zeros((), jnp.int32),
            step_count=jnp.zeros((), jnp.int32),
        )
        ts = restart(self._observe(state))
        ts.extras["truncation"] = jnp.zeros((), bool)
        return state, ts

    def step(self, state: FreewayState, action: jax.Array) -> Tuple[FreewayState, TimeStep]:
        # Mirrors cvec.cpp FreewayVec::step_env exactly: move player, move
        # cars, collide, then score/reset at the top row.
        action = jnp.asarray(action, jnp.int32)
        dr = jnp.where(action == 1, -1, jnp.where(action == 2, 1, 0))
        player_r = jnp.clip(state.player_r + dr, 0, _GRID - 1)
        player_c = state.player_c

        move_now = state.t % self._periods() == 0
        car_col = jnp.where(
            move_now, (state.car_col + self._dirs()) % _GRID, state.car_col
        )

        rows = jnp.arange(8) + 1
        hit = jnp.any(
            jnp.logical_and(player_r == rows, player_c == car_col)
        )
        player_r = jnp.where(hit, _FREEWAY_START_R, player_r)
        player_c = jnp.where(hit, _FREEWAY_START_C, player_c)

        crossed = player_r == 0
        reward = jnp.where(crossed, 1.0, 0.0).astype(jnp.float32)
        player_r = jnp.where(crossed, _FREEWAY_START_R, player_r)
        player_c = jnp.where(crossed, _FREEWAY_START_C, player_c)

        next_state = FreewayState(
            key=state.key,
            player_r=player_r,
            player_c=player_c,
            car_col=car_col,
            t=state.t + 1,
            step_count=state.step_count + 1,
        )
        obs = self._observe(next_state)
        truncated = next_state.step_count >= self._max_steps
        ts = select_step(truncated, truncation(reward, obs), transition(reward, obs))
        ts.extras["truncation"] = truncated
        return next_state, ts


class SpaceInvadersState(NamedTuple):
    key: jax.Array
    player_c: jax.Array  # [] int32 (row fixed at bottom)
    alive: jax.Array  # [4, 6] int32
    alien_r0: jax.Array  # [] int32 block top-left
    alien_c0: jax.Array
    adir: jax.Array  # [] int32 in {-1, +1}
    fb_r: jax.Array  # friendly bullet
    fb_c: jax.Array
    fb_live: jax.Array  # [] int32
    eb_r: jax.Array  # enemy bullet
    eb_c: jax.Array
    eb_live: jax.Array
    shot_count: jax.Array
    t: jax.Array
    step_count: jax.Array


class SpaceInvaders(Environment):
    """Space Invaders (MinAtar-class): shoot the marching alien block.

    JAX twin of the native pool's SpaceInvaders-minatar (cvec.cpp), rule for
    rule, fully deterministic: the 4x6 block marches every 4 steps (drop and
    reverse at the walls); every 6 steps the lowest alien in a cycling column
    fires; one friendly and one enemy bullet may be in flight. +1 per alien;
    being shot or invaded terminates.

    Channels: 0 player, 1 alien, 2 friendly bullet, 3 enemy bullet.
    Actions: 0 stay, 1 left, 2 right, 3 fire.
    """

    def __init__(self, max_steps: int = 500):
        self._max_steps = int(max_steps)

    def observation_space(self) -> Observation:
        return Observation(
            agent_view=spaces.Array((_GRID, _GRID, 4), jnp.float32),
            action_mask=spaces.Array((4,), jnp.float32),
            step_count=spaces.Array((), jnp.int32),
        )

    def action_space(self) -> spaces.Discrete:
        return spaces.Discrete(4)

    def _observe(self, state: SpaceInvadersState) -> Observation:
        board = jnp.zeros((_GRID, _GRID, 4), jnp.float32)
        board = board.at[_GRID - 1, state.player_c, 0].set(1.0)
        rr = state.alien_r0 + jnp.arange(_SI_ROWS)[:, None]
        cc = state.alien_c0 + jnp.arange(_SI_COLS)[None, :]
        rr_c = jnp.clip(rr, 0, _GRID - 1)
        cc_c = jnp.clip(cc, 0, _GRID - 1)
        board = board.at[rr_c, cc_c, 1].max(state.alive.astype(jnp.float32))
        board = board.at[
            jnp.clip(state.fb_r, 0, _GRID - 1), jnp.clip(state.fb_c, 0, _GRID - 1), 2
        ].max(state.fb_live.astype(jnp.float32))
        board = board.at[
            jnp.clip(state.eb_r, 0, _GRID - 1), jnp.clip(state.eb_c, 0, _GRID - 1), 3
        ].max(state.eb_live.astype(jnp.float32))
        return Observation(
            agent_view=board,
            action_mask=jnp.ones((4,), jnp.float32),
            step_count=state.step_count,
        )

    def _fresh_wave(self):
        return (
            jnp.ones((_SI_ROWS, _SI_COLS), jnp.int32),
            jnp.asarray(1, jnp.int32),
            jnp.asarray(2, jnp.int32),
            jnp.asarray(1, jnp.int32),
        )

    def reset(self, key: jax.Array) -> Tuple[SpaceInvadersState, TimeStep]:
        alive, r0, c0, adir = self._fresh_wave()
        zero = jnp.zeros((), jnp.int32)
        state = SpaceInvadersState(
            key=key,
            player_c=jnp.asarray(_GRID // 2, jnp.int32),
            alive=alive, alien_r0=r0, alien_c0=c0, adir=adir,
            fb_r=zero, fb_c=zero, fb_live=zero,
            eb_r=zero, eb_c=zero, eb_live=zero,
            shot_count=zero, t=zero, step_count=zero,
        )
        ts = restart(self._observe(state))
        ts.extras["truncation"] = jnp.zeros((), bool)
        return state, ts

    def step(
        self, state: SpaceInvadersState, action: jax.Array
    ) -> Tuple[SpaceInvadersState, TimeStep]:
        # Mirrors cvec.cpp SpaceInvadersVec::step_env exactly; phase order:
        # player/fire -> friendly bullet -> enemy bullet -> march -> shoot ->
        # wave refresh.
        action = jnp.asarray(action, jnp.int32)
        player_c = jnp.clip(
            state.player_c
            + jnp.where(action == 1, -1, jnp.where(action == 2, 1, 0)),
            0, _GRID - 1,
        )
        fire = jnp.logical_and(action == 3, state.fb_live == 0)
        fb_live = jnp.where(fire, 1, state.fb_live)
        fb_r = jnp.where(fire, _GRID - 2, state.fb_r)
        fb_c = jnp.where(fire, player_c, state.fb_c)

        # Friendly bullet: up one, die off-top, then alien hit check.
        fb_r = jnp.where(fb_live == 1, fb_r - 1, fb_r)
        fb_live = jnp.where(fb_r < 0, 0, fb_live)
        rel_r = fb_r - state.alien_r0
        rel_c = fb_c - state.alien_c0
        in_block = jnp.logical_and(
            jnp.logical_and(rel_r >= 0, rel_r < _SI_ROWS),
            jnp.logical_and(rel_c >= 0, rel_c < _SI_COLS),
        )
        rel_r_c = jnp.clip(rel_r, 0, _SI_ROWS - 1)
        rel_c_c = jnp.clip(rel_c, 0, _SI_COLS - 1)
        hit = jnp.logical_and(
            jnp.logical_and(fb_live == 1, in_block),
            state.alive[rel_r_c, rel_c_c] == 1,
        )
        alive = state.alive.at[rel_r_c, rel_c_c].set(
            jnp.where(hit, 0, state.alive[rel_r_c, rel_c_c])
        )
        reward = jnp.where(hit, 1.0, 0.0).astype(jnp.float32)
        fb_live = jnp.where(hit, 0, fb_live)

        # Enemy bullet: down one, die off-bottom, player hit terminates.
        eb_r = jnp.where(state.eb_live == 1, state.eb_r + 1, state.eb_r)
        eb_live = jnp.where(eb_r >= _GRID, 0, state.eb_live)
        shot_down = jnp.logical_and(
            jnp.logical_and(eb_live == 1, eb_r == _GRID - 1),
            state.eb_c == player_c,
        )

        # Alien march every _SI_ALIEN_PERIOD steps: sideways, or drop+reverse.
        march_now = state.t % _SI_ALIEN_PERIOD == 0
        nc0 = state.alien_c0 + state.adir
        blocked = jnp.logical_or(nc0 < 0, nc0 + _SI_COLS > _GRID)
        alien_c0 = jnp.where(
            march_now, jnp.where(blocked, state.alien_c0, nc0), state.alien_c0
        )
        alien_r0 = jnp.where(
            jnp.logical_and(march_now, blocked), state.alien_r0 + 1, state.alien_r0
        )
        adir = jnp.where(
            jnp.logical_and(march_now, blocked), -state.adir, state.adir
        )
        # Invasion: the lowest LIVING alien row reaching the player row.
        row_alive = jnp.any(alive == 1, axis=1)  # [4]
        lowest = jnp.max(
            jnp.where(row_alive, jnp.arange(_SI_ROWS), -1)
        )
        invaded = jnp.logical_and(
            lowest >= 0, alien_r0 + lowest >= _GRID - 1
        )

        # Enemy shot every _SI_SHOOT_PERIOD steps from the lowest living
        # alien in a cycling column.
        shoot_now = jnp.logical_and(state.t % _SI_SHOOT_PERIOD == 0, eb_live == 0)
        sc = state.shot_count % _SI_COLS
        col_alive = alive[:, sc] == 1  # [4]
        low_in_col = jnp.max(jnp.where(col_alive, jnp.arange(_SI_ROWS), -1))
        can_shoot = jnp.logical_and(shoot_now, low_in_col >= 0)
        eb_live = jnp.where(can_shoot, 1, eb_live)
        eb_r = jnp.where(can_shoot, alien_r0 + low_in_col + 1, eb_r)
        eb_c = jnp.where(can_shoot, alien_c0 + sc, state.eb_c)
        shot_count = state.shot_count + jnp.where(
            state.t % _SI_SHOOT_PERIOD == 0, 1, 0
        )

        # Wave cleared -> fresh block (score keeps accumulating).
        cleared = jnp.all(alive == 0)
        fresh_alive, fresh_r0, fresh_c0, fresh_adir = self._fresh_wave()
        alive = jnp.where(cleared, fresh_alive, alive)
        alien_r0 = jnp.where(cleared, fresh_r0, alien_r0)
        alien_c0 = jnp.where(cleared, fresh_c0, alien_c0)
        adir = jnp.where(cleared, fresh_adir, adir)

        terminated = jnp.logical_or(shot_down, invaded)
        next_state = SpaceInvadersState(
            key=state.key,
            player_c=player_c,
            alive=alive, alien_r0=alien_r0, alien_c0=alien_c0, adir=adir,
            fb_r=fb_r, fb_c=fb_c, fb_live=fb_live,
            eb_r=eb_r, eb_c=eb_c, eb_live=eb_live,
            shot_count=shot_count,
            t=state.t + 1,
            step_count=state.step_count + 1,
        )
        obs = self._observe(next_state)
        truncated = jnp.logical_and(next_state.step_count >= self._max_steps, ~terminated)
        ts = select_step(
            terminated,
            termination(reward, obs),
            select_step(truncated, truncation(reward, obs), transition(reward, obs)),
        )
        ts.extras["truncation"] = truncated
        return next_state, ts
