// Native vectorized environment pool — the first-party EnvPool equivalent.
//
// The reference delegates C++ vectorized simulation to the external EnvPool
// package behind its EnvFactory seam (reference stoix/utils/env_factory.py:48-68);
// this translation unit provides the same capability natively: a batch of
// CartPole environments stepped in one C call with auto-reset and episode
// metrics, exposed through a minimal C ABI consumed via ctypes
// (stoix_tpu/envs/cvec.py). Layout matches the Python classic-control suite so
// learned policies transfer across backends.
//
// Build: g++ -O3 -march=native -shared -fPIC cvec.cpp -o libcvec.so

#include <cmath>
#include <cstdint>
#include <cstring>
#include <random>
#include <vector>

namespace {

constexpr float kGravity = 9.8f;
constexpr float kMassCart = 1.0f;
constexpr float kMassPole = 0.1f;
constexpr float kTotalMass = kMassCart + kMassPole;
constexpr float kLength = 0.5f;
constexpr float kPoleMassLength = kMassPole * kLength;
constexpr float kForceMag = 10.0f;
constexpr float kTau = 0.02f;
constexpr float kThetaThreshold = 12.0f * 2.0f * M_PI / 360.0f;
constexpr float kXThreshold = 2.4f;

struct CartPoleVec {
  int num_envs;
  int max_steps;
  std::vector<float> state;         // [num_envs, 4]
  std::vector<int32_t> step_count;  // [num_envs]
  std::vector<float> ep_return;     // [num_envs]
  std::mt19937 rng;

  CartPoleVec(int n, int max_steps_, uint64_t seed)
      : num_envs(n), max_steps(max_steps_), state(n * 4), step_count(n),
        ep_return(n), rng(seed) {}

  void reset_env(int i) {
    std::uniform_real_distribution<float> dist(-0.05f, 0.05f);
    for (int j = 0; j < 4; ++j) state[i * 4 + j] = dist(rng);
    step_count[i] = 0;
    ep_return[i] = 0.0f;
  }

  void reset_all(float* obs_out) {
    for (int i = 0; i < num_envs; ++i) {
      reset_env(i);
      std::memcpy(obs_out + i * 4, &state[i * 4], 4 * sizeof(float));
    }
  }

  // One synchronous step for every env with auto-reset. Outputs:
  //   obs_out:      post-(auto)reset observation    [num_envs, 4]
  //   next_obs_out: TRUE successor observation      [num_envs, 4]
  //   reward_out / done_out / trunc_out             [num_envs]
  //   ep_return_out / ep_length_out: totals at episode end (else running)
  void step(const int32_t* actions, float* obs_out, float* next_obs_out,
            float* reward_out, uint8_t* done_out, uint8_t* trunc_out,
            float* ep_return_out, int32_t* ep_length_out) {
    for (int i = 0; i < num_envs; ++i) {
      float* s = &state[i * 4];
      float x = s[0], x_dot = s[1], theta = s[2], theta_dot = s[3];
      const float force = actions[i] == 1 ? kForceMag : -kForceMag;
      const float costheta = std::cos(theta), sintheta = std::sin(theta);
      const float temp =
          (force + kPoleMassLength * theta_dot * theta_dot * sintheta) /
          kTotalMass;
      const float thetaacc =
          (kGravity * sintheta - costheta * temp) /
          (kLength * (4.0f / 3.0f - kMassPole * costheta * costheta / kTotalMass));
      const float xacc = temp - kPoleMassLength * thetaacc * costheta / kTotalMass;
      x += kTau * x_dot;
      x_dot += kTau * xacc;
      theta += kTau * theta_dot;
      theta_dot += kTau * thetaacc;
      s[0] = x; s[1] = x_dot; s[2] = theta; s[3] = theta_dot;

      step_count[i] += 1;
      ep_return[i] += 1.0f;
      const bool terminated =
          std::fabs(x) > kXThreshold || std::fabs(theta) > kThetaThreshold;
      const bool truncated = !terminated && step_count[i] >= max_steps;

      reward_out[i] = 1.0f;
      done_out[i] = terminated ? 1 : 0;
      trunc_out[i] = truncated ? 1 : 0;
      std::memcpy(next_obs_out + i * 4, s, 4 * sizeof(float));
      ep_return_out[i] = ep_return[i];
      ep_length_out[i] = step_count[i];

      if (terminated || truncated) {
        reset_env(i);
      }
      std::memcpy(obs_out + i * 4, &state[i * 4], 4 * sizeof(float));
    }
  }
};

}  // namespace

extern "C" {

void* cvec_create(int num_envs, int max_steps, uint64_t seed) {
  return new CartPoleVec(num_envs, max_steps, seed);
}

void cvec_reset(void* handle, float* obs_out) {
  static_cast<CartPoleVec*>(handle)->reset_all(obs_out);
}

void cvec_step(void* handle, const int32_t* actions, float* obs_out,
               float* next_obs_out, float* reward_out, uint8_t* done_out,
               uint8_t* trunc_out, float* ep_return_out, int32_t* ep_length_out) {
  static_cast<CartPoleVec*>(handle)->step(actions, obs_out, next_obs_out,
                                          reward_out, done_out, trunc_out,
                                          ep_return_out, ep_length_out);
}

int cvec_obs_dim(void*) { return 4; }
int cvec_num_actions(void*) { return 2; }

void cvec_destroy(void* handle) { delete static_cast<CartPoleVec*>(handle); }

}  // extern "C"
