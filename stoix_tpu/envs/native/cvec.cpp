// Native vectorized environment pool — the first-party EnvPool equivalent.
//
// The reference delegates C++ vectorized simulation to the external EnvPool
// package behind its EnvFactory seam (reference stoix/utils/env_factory.py:48-68);
// this translation unit provides the same capability natively: a batch of
// environments stepped in one C call with auto-reset and episode metrics,
// exposed through a minimal C ABI consumed via ctypes (stoix_tpu/envs/cvec.py).
//
// Games:
//   "CartPole-v1"       — 4-float observation, 2 actions (classic control;
//                         layout matches the Python classic suite so learned
//                         policies transfer across backends).
//   "Breakout-minatar"  — 10x10x4 binary-channel pixel observation, 3 actions
//                         (first-party reimplementation of the published
//                         MinAtar breakout game description: paddle, ball,
//                         trail and brick channels, row bounce/break rules).
//                         This is the Atari-class Sebulba workload: CNN-scale
//                         observations from a C++ pool.
//   "Asterix-minatar"   — 10x10x4 pixel observation, 5 actions: entities
//                         stream across rows, gold +1 / enemies kill, on a
//                         deterministic spawn schedule (lockstep-equal with
//                         the JAX twin).
//   "Breakout-atari"    — 84x84x4 frame-stacked grayscale pixel Breakout:
//                         the full-resolution EnvPool-Atari-shaped workload
//                         (same observation tensor as the reference's
//                         envpool configs) rendered and stepped natively.
//
// Build: g++ -O3 -march=native -shared -fPIC cvec.cpp -o libcvec.so

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <random>
#include <string>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// Pool base: shared auto-reset stepping loop + episode metrics.
// ---------------------------------------------------------------------------

struct VecEnv {
  int num_envs;
  int max_steps;
  std::vector<int32_t> step_count;  // [num_envs]
  std::vector<float> ep_return;     // [num_envs]
  std::mt19937 rng;

  VecEnv(int n, int max_steps_, uint64_t seed)
      : num_envs(n), max_steps(max_steps_), step_count(n), ep_return(n),
        rng(seed) {}
  virtual ~VecEnv() = default;

  virtual int obs_dim() const = 0;                 // flattened length
  virtual void obs_shape(int32_t* out3) const = 0; // (a, b, c); (d, 1, 1) = vector
  virtual int num_actions() const = 0;
  // Continuous-control surface: action_dim 0 marks a discrete game; a
  // continuous game overrides action_dim/action_bounds/step_env_cont and the
  // pool is stepped through cvec_step_cont with float actions instead.
  virtual int action_dim() const { return 0; }
  virtual void action_bounds(float* lo, float* hi) const { *lo = -1.0f; *hi = 1.0f; }

  virtual void reset_env(int i) = 0;
  virtual void write_obs(int i, float* out) const = 0;
  // Advances env i; returns reward, sets *terminated.
  virtual float step_env(int i, int32_t action, bool* terminated) = 0;
  virtual float step_env_cont(int i, const float* action, bool* terminated) {
    (void)i; (void)action; (void)terminated;
    // Reaching this means a discrete game was stepped through the continuous
    // entry point: fail loudly instead of training on all-zero rewards.
    std::fprintf(stderr,
                 "cvec: step_env_cont called on a discrete game (dispatch "
                 "mismatch)\n");
    std::abort();
  }

  void reset_all(float* obs_out) {
    for (int i = 0; i < num_envs; ++i) {
      reset_env(i);
      step_count[i] = 0;
      ep_return[i] = 0.0f;
      write_obs(i, obs_out + static_cast<size_t>(i) * obs_dim());
    }
  }

  // Shared post-step bookkeeping for env i (auto-reset + episode metrics);
  // the discrete and continuous stepping loops differ only in how the
  // per-env reward is produced.
  void finish_env(int i, float reward, bool terminated, float* obs_out,
                  float* next_obs_out, float* reward_out, uint8_t* done_out,
                  uint8_t* trunc_out, float* ep_return_out,
                  int32_t* ep_length_out) {
    const size_t dim = obs_dim();
    step_count[i] += 1;
    ep_return[i] += reward;
    const bool truncated = !terminated && step_count[i] >= max_steps;

    reward_out[i] = reward;
    done_out[i] = terminated ? 1 : 0;
    trunc_out[i] = truncated ? 1 : 0;
    write_obs(i, next_obs_out + i * dim);
    ep_return_out[i] = ep_return[i];
    ep_length_out[i] = step_count[i];

    if (terminated || truncated) {
      reset_env(i);
      step_count[i] = 0;
      ep_return[i] = 0.0f;
      write_obs(i, obs_out + i * dim);
    } else {
      // No reset -> the post-step observation IS the successor observation;
      // copy it instead of re-rasterizing (for the 84x84x4 pixel game
      // write_obs is a 28k-float strided transpose — the pool's hot path).
      std::memcpy(obs_out + i * dim, next_obs_out + i * dim,
                  dim * sizeof(float));
    }
  }

  // One synchronous step for every env with auto-reset. Outputs:
  //   obs_out:      post-(auto)reset observation    [num_envs, obs_dim]
  //   next_obs_out: TRUE successor observation      [num_envs, obs_dim]
  //   reward_out / done_out / trunc_out             [num_envs]
  //   ep_return_out / ep_length_out: totals at episode end (else running)
  void step(const int32_t* actions, float* obs_out, float* next_obs_out,
            float* reward_out, uint8_t* done_out, uint8_t* trunc_out,
            float* ep_return_out, int32_t* ep_length_out) {
    for (int i = 0; i < num_envs; ++i) {
      bool terminated = false;
      const float reward = step_env(i, actions[i], &terminated);
      finish_env(i, reward, terminated, obs_out, next_obs_out, reward_out,
                 done_out, trunc_out, ep_return_out, ep_length_out);
    }
  }

  // Continuous twin of step(): actions are [num_envs, action_dim] floats.
  void step_cont(const float* actions, float* obs_out, float* next_obs_out,
                 float* reward_out, uint8_t* done_out, uint8_t* trunc_out,
                 float* ep_return_out, int32_t* ep_length_out) {
    const int adim = action_dim();
    for (int i = 0; i < num_envs; ++i) {
      bool terminated = false;
      const float reward =
          step_env_cont(i, actions + static_cast<size_t>(i) * adim, &terminated);
      finish_env(i, reward, terminated, obs_out, next_obs_out, reward_out,
                 done_out, trunc_out, ep_return_out, ep_length_out);
    }
  }
};

// ---------------------------------------------------------------------------
// CartPole-v1
// ---------------------------------------------------------------------------

constexpr float kGravity = 9.8f;
constexpr float kMassCart = 1.0f;
constexpr float kMassPole = 0.1f;
constexpr float kTotalMass = kMassCart + kMassPole;
constexpr float kLength = 0.5f;
constexpr float kPoleMassLength = kMassPole * kLength;
constexpr float kForceMag = 10.0f;
constexpr float kTau = 0.02f;
constexpr float kThetaThreshold = 12.0f * 2.0f * M_PI / 360.0f;
constexpr float kXThreshold = 2.4f;

struct CartPoleVec : VecEnv {
  std::vector<float> state;  // [num_envs, 4]

  CartPoleVec(int n, int max_steps_, uint64_t seed)
      : VecEnv(n, max_steps_, seed), state(static_cast<size_t>(n) * 4) {}

  int obs_dim() const override { return 4; }
  void obs_shape(int32_t* out3) const override { out3[0] = 4; out3[1] = 1; out3[2] = 1; }
  int num_actions() const override { return 2; }

  void reset_env(int i) override {
    std::uniform_real_distribution<float> dist(-0.05f, 0.05f);
    for (int j = 0; j < 4; ++j) state[i * 4 + j] = dist(rng);
  }

  void write_obs(int i, float* out) const override {
    std::memcpy(out, &state[i * 4], 4 * sizeof(float));
  }

  float step_env(int i, int32_t action, bool* terminated) override {
    float* s = &state[i * 4];
    float x = s[0], x_dot = s[1], theta = s[2], theta_dot = s[3];
    const float force = action == 1 ? kForceMag : -kForceMag;
    const float costheta = std::cos(theta), sintheta = std::sin(theta);
    const float temp =
        (force + kPoleMassLength * theta_dot * theta_dot * sintheta) /
        kTotalMass;
    const float thetaacc =
        (kGravity * sintheta - costheta * temp) /
        (kLength * (4.0f / 3.0f - kMassPole * costheta * costheta / kTotalMass));
    const float xacc = temp - kPoleMassLength * thetaacc * costheta / kTotalMass;
    x += kTau * x_dot;
    x_dot += kTau * xacc;
    theta += kTau * theta_dot;
    theta_dot += kTau * thetaacc;
    s[0] = x; s[1] = x_dot; s[2] = theta; s[3] = theta_dot;
    *terminated =
        std::fabs(x) > kXThreshold || std::fabs(theta) > kThetaThreshold;
    return 1.0f;
  }
};

// ---------------------------------------------------------------------------
// Breakout (MinAtar-class): 10x10 grid, 4 binary channels, 3 actions.
// ---------------------------------------------------------------------------

constexpr int kGrid = 10;
constexpr int kBrickRows = 3;     // rows 1..3 carry bricks
constexpr int kPaddleRow = kGrid - 1;
constexpr int kChannels = 4;      // paddle, ball, trail, brick

struct BreakoutVec : VecEnv {
  struct EnvState {
    int ball_r, ball_c;
    int dr, dc;       // ball direction, each in {-1, +1}
    int last_r, last_c;  // trail
    int paddle;
    uint8_t bricks[kBrickRows * kGrid];
  };
  std::vector<EnvState> envs;

  BreakoutVec(int n, int max_steps_, uint64_t seed)
      : VecEnv(n, max_steps_, seed), envs(n) {}

  int obs_dim() const override { return kGrid * kGrid * kChannels; }
  void obs_shape(int32_t* out3) const override {
    out3[0] = kGrid; out3[1] = kGrid; out3[2] = kChannels;
  }
  int num_actions() const override { return 3; }  // left, stay, right

  void reset_env(int i) override {
    EnvState& e = envs[i];
    std::uniform_int_distribution<int> dir(0, 1);
    // Serve from a top corner BELOW the brick band, moving down and inward
    // (MinAtar-style): the landing column is always reachable from the
    // paddle's start, and bricks are only reachable by earning paddle
    // bounces — the score measures control, not luck.
    e.ball_r = kBrickRows + 1;
    e.dr = 1;
    e.dc = dir(rng) ? 1 : -1;
    e.ball_c = e.dc == 1 ? 0 : kGrid - 1;
    e.last_r = e.ball_r;
    e.last_c = e.ball_c;
    e.paddle = kGrid / 2;
    std::fill(e.bricks, e.bricks + kBrickRows * kGrid, uint8_t{1});
  }

  void write_obs(int i, float* out) const override {
    const EnvState& e = envs[i];
    std::memset(out, 0, sizeof(float) * obs_dim());
    auto at = [&](int r, int c, int ch) -> float& {
      return out[(r * kGrid + c) * kChannels + ch];
    };
    at(kPaddleRow, e.paddle, 0) = 1.0f;
    at(e.ball_r, e.ball_c, 1) = 1.0f;
    at(e.last_r, e.last_c, 2) = 1.0f;
    for (int r = 0; r < kBrickRows; ++r)
      for (int c = 0; c < kGrid; ++c)
        if (e.bricks[r * kGrid + c]) at(r + 1, c, 3) = 1.0f;
  }

  float step_env(int i, int32_t action, bool* terminated) override {
    EnvState& e = envs[i];
    // Paddle: 0 = left, 1 = stay, 2 = right.
    e.paddle = std::clamp(e.paddle + (action - 1), 0, kGrid - 1);

    e.last_r = e.ball_r;
    e.last_c = e.ball_c;
    float reward = 0.0f;
    *terminated = false;

    // Side-wall bounce.
    int nc = e.ball_c + e.dc;
    if (nc < 0 || nc >= kGrid) {
      e.dc = -e.dc;
      nc = e.ball_c + e.dc;
    }
    int nr = e.ball_r + e.dr;
    // Ceiling bounce.
    if (nr < 0) {
      e.dr = 1;
      nr = e.ball_r + e.dr;
    }
    // Brick hit: break it, reflect vertically, score.
    if (nr >= 1 && nr <= kBrickRows && e.bricks[(nr - 1) * kGrid + nc]) {
      e.bricks[(nr - 1) * kGrid + nc] = 0;
      reward = 1.0f;
      e.dr = -e.dr;
      nr = e.ball_r;  // bounce back to the incoming row
      // All bricks cleared -> fresh wall (play continues).
      bool any = false;
      for (int b = 0; b < kBrickRows * kGrid; ++b) any |= (envs[i].bricks[b] != 0);
      if (!any) std::fill(e.bricks, e.bricks + kBrickRows * kGrid, uint8_t{1});
    } else if (nr == kPaddleRow) {
      if (nc == e.paddle) {
        e.dr = -1;
        nr = e.ball_r;  // paddle bounce
      } else {
        *terminated = true;  // ball lost
      }
    }
    e.ball_r = nr;
    e.ball_c = nc;
    return reward;
  }
};

// ---------------------------------------------------------------------------
// Asterix (MinAtar-class): 10x10 grid, 4 channels, 5 actions.
//
// Entities stream across rows 1..8 (one slot per row); gold scores +1 on
// contact, enemies kill. The spawn schedule is DETERMINISTIC (slot/direction/
// kind derived from a running counter) so the pure-JAX twin in
// stoix_tpu/envs/minatar.py stays bit-identical under lockstep — game variety
// comes from the entity pattern interacting with the agent's movement, not
// from per-step RNG.
// ---------------------------------------------------------------------------

constexpr int kAsterixSlots = 8;      // rows 1..8
constexpr int kSpawnPeriod = 5;       // spawn attempt every 5 steps
constexpr int kMovePeriod = 2;        // entities advance every 2 steps

struct AsterixVec : VecEnv {
  struct EnvState {
    int player_r, player_c;
    uint8_t active[kAsterixSlots];
    int col[kAsterixSlots];
    int dir[kAsterixSlots];       // -1 or +1
    uint8_t gold[kAsterixSlots];
    int spawn_count;
    int t;
  };
  std::vector<EnvState> envs;

  AsterixVec(int n, int max_steps_, uint64_t seed)
      : VecEnv(n, max_steps_, seed), envs(n) {}

  int obs_dim() const override { return kGrid * kGrid * 4; }
  void obs_shape(int32_t* out3) const override {
    out3[0] = kGrid; out3[1] = kGrid; out3[2] = 4;
  }
  int num_actions() const override { return 5; }  // stay, left, up, right, down

  void reset_env(int i) override {
    EnvState& e = envs[i];
    e.player_r = kGrid / 2;
    e.player_c = kGrid / 2;
    std::fill(e.active, e.active + kAsterixSlots, uint8_t{0});
    std::fill(e.col, e.col + kAsterixSlots, 0);
    std::fill(e.dir, e.dir + kAsterixSlots, 1);
    std::fill(e.gold, e.gold + kAsterixSlots, uint8_t{0});
    e.spawn_count = 0;
    e.t = 0;
  }

  void write_obs(int i, float* out) const override {
    const EnvState& e = envs[i];
    std::memset(out, 0, sizeof(float) * obs_dim());
    auto at = [&](int r, int c, int ch) -> float& {
      return out[(r * kGrid + c) * 4 + ch];
    };
    at(e.player_r, e.player_c, 0) = 1.0f;
    for (int s = 0; s < kAsterixSlots; ++s) {
      if (!e.active[s]) continue;
      const int r = s + 1;
      at(r, e.col[s], e.gold[s] ? 2 : 1) = 1.0f;
      if (e.dir[s] > 0) at(r, e.col[s], 3) = 1.0f;
    }
  }

  float step_env(int i, int32_t action, bool* terminated) override {
    EnvState& e = envs[i];
    float reward = 0.0f;
    *terminated = false;

    // Player move: 0 stay, 1 left, 2 up, 3 right, 4 down (stays on rows 1..8
    // only by bounds, walls clamp).
    const int drs[5] = {0, 0, -1, 0, 1};
    const int dcs[5] = {0, -1, 0, 1, 0};
    e.player_r = std::clamp(e.player_r + drs[action], 0, kGrid - 1);
    e.player_c = std::clamp(e.player_c + dcs[action], 0, kGrid - 1);

    auto collide = [&]() {
      for (int s = 0; s < kAsterixSlots; ++s) {
        if (!e.active[s]) continue;
        if (e.player_r == s + 1 && e.player_c == e.col[s]) {
          if (e.gold[s]) {
            reward += 1.0f;
            e.active[s] = 0;
          } else {
            *terminated = true;
          }
        }
      }
    };
    collide();  // player stepped onto an entity

    // Entity movement every kMovePeriod steps.
    if (e.t % kMovePeriod == 0) {
      for (int s = 0; s < kAsterixSlots; ++s) {
        if (!e.active[s]) continue;
        e.col[s] += e.dir[s];
        if (e.col[s] < 0 || e.col[s] >= kGrid) e.active[s] = 0;
      }
      collide();  // entity moved onto the player
    }

    // Deterministic spawn schedule.
    if (e.t % kSpawnPeriod == 0) {
      const int s = e.spawn_count % kAsterixSlots;
      if (!e.active[s]) {
        e.active[s] = 1;
        e.dir[s] = ((e.spawn_count / kAsterixSlots + s) % 2 == 0) ? 1 : -1;
        e.col[s] = e.dir[s] > 0 ? 0 : kGrid - 1;
        e.gold[s] = (e.spawn_count % 3 == 0) ? 1 : 0;
        collide();  // spawned under the player
      }
      e.spawn_count += 1;
    }
    e.t += 1;
    return reward;
  }
};

// ---------------------------------------------------------------------------
// Freeway (MinAtar-class): cross 8 lanes of traffic, +1 per crossing.
//
// Fully deterministic (lockstep-equal with the JAX twin): lane s has fixed
// direction (+1 if s even) and fixed period 1 + (s % 3); collisions send the
// chicken back to the start; no termination — episodes are time-limited.
// Channels: 0 player, 1 car, 2 car-moving-right, 3 fast-car. Actions:
// 0 stay, 1 up, 2 down.
// ---------------------------------------------------------------------------

struct FreewayVec : VecEnv {
  struct EnvState {
    int player_r, player_c;
    int car_col[8];
    int t;
  };
  std::vector<EnvState> envs;

  FreewayVec(int n, int max_steps_, uint64_t seed)
      : VecEnv(n, max_steps_, seed), envs(n) {}

  int obs_dim() const override { return kGrid * kGrid * kChannels; }
  void obs_shape(int32_t* out3) const override {
    out3[0] = kGrid; out3[1] = kGrid; out3[2] = kChannels;
  }
  int num_actions() const override { return 3; }

  static int lane_dir(int s) { return (s % 2 == 0) ? 1 : -1; }
  static int lane_period(int s) { return 1 + (s % 3); }

  void reset_env(int i) override {
    EnvState& e = envs[i];
    e.player_r = kGrid - 1;
    e.player_c = kGrid / 2;
    for (int s = 0; s < 8; ++s) e.car_col[s] = (3 * s + 1) % kGrid;
    e.t = 0;
  }

  void write_obs(int i, float* out) const override {
    const EnvState& e = envs[i];
    std::memset(out, 0, sizeof(float) * obs_dim());
    auto at = [&](int r, int c, int ch) -> float& {
      return out[(r * kGrid + c) * kChannels + ch];
    };
    at(e.player_r, e.player_c, 0) = 1.0f;
    for (int s = 0; s < 8; ++s) {
      at(s + 1, e.car_col[s], 1) = 1.0f;
      if (lane_dir(s) > 0) at(s + 1, e.car_col[s], 2) = 1.0f;
      if (lane_period(s) == 1) at(s + 1, e.car_col[s], 3) = 1.0f;
    }
  }

  float step_env(int i, int32_t action, bool* terminated) override {
    EnvState& e = envs[i];
    *terminated = false;
    const int dr = action == 1 ? -1 : (action == 2 ? 1 : 0);
    e.player_r = std::clamp(e.player_r + dr, 0, kGrid - 1);

    for (int s = 0; s < 8; ++s)
      if (e.t % lane_period(s) == 0)
        e.car_col[s] = (e.car_col[s] + lane_dir(s) + kGrid) % kGrid;

    bool hit = false;
    for (int s = 0; s < 8; ++s)
      hit |= (e.player_r == s + 1 && e.player_c == e.car_col[s]);
    if (hit) {
      e.player_r = kGrid - 1;
      e.player_c = kGrid / 2;
    }

    float reward = 0.0f;
    if (e.player_r == 0) {
      reward = 1.0f;
      e.player_r = kGrid - 1;
      e.player_c = kGrid / 2;
    }
    e.t += 1;
    return reward;
  }
};

// ---------------------------------------------------------------------------
// Space Invaders (MinAtar-class): shoot the marching 4x6 alien block.
//
// Fully deterministic (lockstep-equal with the JAX twin): the block marches
// every 4 steps (drop + reverse at the walls); every 6 steps the lowest
// alien in a cycling column fires; one friendly and one enemy bullet in
// flight. +1 per alien; being shot or invaded terminates. Channels:
// 0 player, 1 alien, 2 friendly bullet, 3 enemy bullet. Actions: 0 stay,
// 1 left, 2 right, 3 fire.
// ---------------------------------------------------------------------------

constexpr int kSiRows = 4;
constexpr int kSiCols = 6;
constexpr int kSiAlienPeriod = 4;
constexpr int kSiShootPeriod = 6;

struct SpaceInvadersVec : VecEnv {
  struct EnvState {
    int player_c;
    uint8_t alive[kSiRows * kSiCols];
    int alien_r0, alien_c0, adir;
    int fb_r, fb_c, fb_live;
    int eb_r, eb_c, eb_live;
    int shot_count;
    int t;
  };
  std::vector<EnvState> envs;

  SpaceInvadersVec(int n, int max_steps_, uint64_t seed)
      : VecEnv(n, max_steps_, seed), envs(n) {}

  int obs_dim() const override { return kGrid * kGrid * kChannels; }
  void obs_shape(int32_t* out3) const override {
    out3[0] = kGrid; out3[1] = kGrid; out3[2] = kChannels;
  }
  int num_actions() const override { return 4; }

  static void fresh_wave(EnvState& e) {
    std::fill(e.alive, e.alive + kSiRows * kSiCols, uint8_t{1});
    e.alien_r0 = 1;
    e.alien_c0 = 2;
    e.adir = 1;
  }

  void reset_env(int i) override {
    EnvState& e = envs[i];
    e.player_c = kGrid / 2;
    fresh_wave(e);
    e.fb_r = e.fb_c = e.fb_live = 0;
    e.eb_r = e.eb_c = e.eb_live = 0;
    e.shot_count = 0;
    e.t = 0;
  }

  void write_obs(int i, float* out) const override {
    const EnvState& e = envs[i];
    std::memset(out, 0, sizeof(float) * obs_dim());
    auto at = [&](int r, int c, int ch) -> float& {
      return out[(r * kGrid + c) * kChannels + ch];
    };
    at(kGrid - 1, e.player_c, 0) = 1.0f;
    for (int r = 0; r < kSiRows; ++r)
      for (int c = 0; c < kSiCols; ++c)
        if (e.alive[r * kSiCols + c]) {
          const int rr = std::clamp(e.alien_r0 + r, 0, kGrid - 1);
          const int cc = std::clamp(e.alien_c0 + c, 0, kGrid - 1);
          at(rr, cc, 1) = 1.0f;
        }
    if (e.fb_live)
      at(std::clamp(e.fb_r, 0, kGrid - 1), std::clamp(e.fb_c, 0, kGrid - 1), 2) = 1.0f;
    if (e.eb_live)
      at(std::clamp(e.eb_r, 0, kGrid - 1), std::clamp(e.eb_c, 0, kGrid - 1), 3) = 1.0f;
  }

  float step_env(int i, int32_t action, bool* terminated) override {
    EnvState& e = envs[i];
    *terminated = false;
    float reward = 0.0f;

    // Player move / fire.
    e.player_c = std::clamp(
        e.player_c + (action == 1 ? -1 : (action == 2 ? 1 : 0)), 0, kGrid - 1);
    if (action == 3 && !e.fb_live) {
      e.fb_live = 1;
      e.fb_r = kGrid - 2;
      e.fb_c = e.player_c;
    }

    // Friendly bullet: up one, die off-top, alien hit check.
    if (e.fb_live) {
      e.fb_r -= 1;
      if (e.fb_r < 0) e.fb_live = 0;
    }
    if (e.fb_live) {
      const int rel_r = e.fb_r - e.alien_r0;
      const int rel_c = e.fb_c - e.alien_c0;
      if (rel_r >= 0 && rel_r < kSiRows && rel_c >= 0 && rel_c < kSiCols &&
          e.alive[rel_r * kSiCols + rel_c]) {
        e.alive[rel_r * kSiCols + rel_c] = 0;
        reward += 1.0f;
        e.fb_live = 0;
      }
    }

    // Enemy bullet: down one, die off-bottom, player hit terminates.
    if (e.eb_live) {
      e.eb_r += 1;
      if (e.eb_r >= kGrid) e.eb_live = 0;
    }
    if (e.eb_live && e.eb_r == kGrid - 1 && e.eb_c == e.player_c)
      *terminated = true;

    // Alien march: sideways, or drop + reverse at the walls.
    if (e.t % kSiAlienPeriod == 0) {
      const int nc0 = e.alien_c0 + e.adir;
      if (nc0 < 0 || nc0 + kSiCols > kGrid) {
        e.alien_r0 += 1;
        e.adir = -e.adir;
      } else {
        e.alien_c0 = nc0;
      }
    }
    int lowest = -1;
    for (int r = 0; r < kSiRows; ++r)
      for (int c = 0; c < kSiCols; ++c)
        if (e.alive[r * kSiCols + c]) lowest = std::max(lowest, r);
    if (lowest >= 0 && e.alien_r0 + lowest >= kGrid - 1) *terminated = true;

    // Enemy shot from the lowest living alien in a cycling column.
    if (e.t % kSiShootPeriod == 0) {
      if (!e.eb_live) {
        const int sc = e.shot_count % kSiCols;
        int low_in_col = -1;
        for (int r = 0; r < kSiRows; ++r)
          if (e.alive[r * kSiCols + sc]) low_in_col = std::max(low_in_col, r);
        if (low_in_col >= 0) {
          e.eb_live = 1;
          e.eb_r = e.alien_r0 + low_in_col + 1;
          e.eb_c = e.alien_c0 + sc;
        }
      }
      e.shot_count += 1;
    }

    // Wave cleared -> fresh block.
    bool any = false;
    for (int b = 0; b < kSiRows * kSiCols; ++b) any |= (e.alive[b] != 0);
    if (!any) fresh_wave(e);

    e.t += 1;
    return reward;
  }
};

// ---------------------------------------------------------------------------
// Pendulum-v1 — the continuous-control game (gym classic-control dynamics,
// matching the pure-JAX twin envs/classic.py Pendulum exactly: g=10, m=l=1,
// dt=0.05, torque in [-2, 2], never terminates, 200-step truncation).
// ---------------------------------------------------------------------------

struct PendulumVec : VecEnv {
  std::vector<float> state;  // [num_envs, 2]: theta, theta_dot

  static constexpr float kMaxSpeed = 8.0f;
  static constexpr float kMaxTorque = 2.0f;
  static constexpr float kDt = 0.05f;
  static constexpr float kG = 10.0f;

  PendulumVec(int n, int max_steps_, uint64_t seed)
      : VecEnv(n, max_steps_, seed), state(static_cast<size_t>(n) * 2) {}

  int obs_dim() const override { return 3; }
  void obs_shape(int32_t* out3) const override { out3[0] = 3; out3[1] = 1; out3[2] = 1; }
  // For continuous games num_actions mirrors action_dim (mask width).
  int num_actions() const override { return 1; }
  int action_dim() const override { return 1; }
  void action_bounds(float* lo, float* hi) const override {
    *lo = -kMaxTorque;
    *hi = kMaxTorque;
  }

  void reset_env(int i) override {
    std::uniform_real_distribution<float> th(-static_cast<float>(M_PI),
                                             static_cast<float>(M_PI));
    std::uniform_real_distribution<float> thdot(-1.0f, 1.0f);
    state[i * 2] = th(rng);
    state[i * 2 + 1] = thdot(rng);
  }

  void write_obs(int i, float* out) const override {
    const float theta = state[i * 2], thdot = state[i * 2 + 1];
    out[0] = std::cos(theta);
    out[1] = std::sin(theta);
    out[2] = thdot;
  }

  float step_env(int, int32_t, bool*) override {
    // Continuous-only game stepped through the discrete entry point.
    std::fprintf(stderr,
                 "cvec: discrete step_env called on PendulumVec (dispatch "
                 "mismatch)\n");
    std::abort();
  }

  float step_env_cont(int i, const float* action, bool* terminated) override {
    float theta = state[i * 2], thdot = state[i * 2 + 1];
    const float u = std::fmax(-kMaxTorque, std::fmin(kMaxTorque, action[0]));
    // Normalize theta into [-pi, pi) with python-modulo semantics (the JAX
    // twin uses (theta + pi) % (2 pi) - pi; C++ fmod keeps the sign).
    float wrapped = std::fmod(theta + static_cast<float>(M_PI),
                              2.0f * static_cast<float>(M_PI));
    if (wrapped < 0.0f) wrapped += 2.0f * static_cast<float>(M_PI);
    const float angle_norm = wrapped - static_cast<float>(M_PI);
    const float cost =
        angle_norm * angle_norm + 0.1f * thdot * thdot + 0.001f * u * u;
    thdot += (3.0f * kG / 2.0f * std::sin(theta) + 3.0f * u) * kDt;
    thdot = std::fmax(-kMaxSpeed, std::fmin(kMaxSpeed, thdot));
    theta += thdot * kDt;
    state[i * 2] = theta;
    state[i * 2 + 1] = thdot;
    *terminated = false;
    return -cost;
  }
};

// ---------------------------------------------------------------------------
// Breakout-atari — full-resolution pixel Breakout: 84x84x4 frame-stacked
// grayscale observations, the exact tensor shape the reference's EnvPool
// Atari path trains on (reference stoix/wrappers/envpool.py:8-30 consumes
// EnvPool's (84, 84, stack) image obs; configs/env/envpool/*.yaml). Unlike
// the 10x10 MinAtar-class games above, this is a true pixel workload: the
// agent sees rendered frames (paddle/ball/brick sprites at distinct gray
// levels), not feature planes, and the CNN must learn from an 84x84x4
// stack exactly as it would from ALE frames. Game logic is an original
// pixel-physics breakout, not an ALE port:
//   - 84x84 playfield; paddle 12x2 at row 80, moves +/-3 px/step (3 actions).
//   - 2x2 ball at 2 px/step; direction set by paddle-hit offset (outer third
//     of the paddle sends the ball out at the steep +/-2 horizontal speed,
//     the center third at the shallow +/-1) — control depth comes from aiming.
//   - 6x14 brick wall (each brick 6x3 px, rows 18..35); +1 per brick, wall
//     refreshes when cleared; ball lost below the paddle ends the episode.
//   - Frame stack: ring buffer of the last 4 rendered frames, exposed
//     oldest->newest as channels (the envpool stacked-frame layout).
// ---------------------------------------------------------------------------

constexpr int kPix = 84;                  // frame height/width
constexpr int kStack = 4;                 // stacked frames = obs channels
constexpr int kPadW = 12, kPadH = 2;      // paddle sprite
constexpr int kPadRow = 80;               // paddle top row
constexpr int kPadSpeed = 3;              // px per action step
constexpr int kBallSz = 2;                // 2x2 ball sprite
constexpr int kBrickW = 6, kBrickH = 3;   // brick sprite
constexpr int kBrickCols = kPix / kBrickW;    // 14
constexpr int kBrickRowsPx = 6;               // brick rows
constexpr int kBrickTop = 18;                 // first brick row (px)

struct BreakoutPixelVec : VecEnv {
  struct EnvState {
    int ball_r, ball_c;   // top-left of the 2x2 ball sprite
    int dr, dc;           // velocity, px/step (dr in {-2,+2}, dc in {-2,-1,+1,+2})
    int paddle;           // leftmost column of the paddle
    int serves;           // episodes served — drives the DETERMINISTIC serve
    uint8_t bricks[kBrickRowsPx * kBrickCols];
    uint8_t frames[kStack][kPix * kPix];  // grayscale ring buffer
    int head;                             // index of the OLDEST frame
  };
  std::vector<EnvState> envs;

  BreakoutPixelVec(int n, int max_steps_, uint64_t seed)
      : VecEnv(n, max_steps_, seed), envs(n) {
    // Stagger the deterministic serve walk by env index so a fresh pool's
    // envs start decorrelated (adjacent k values land 37 columns apart).
    for (int i = 0; i < n; ++i) envs[i].serves = i;
  }

  int obs_dim() const override { return kPix * kPix * kStack; }
  void obs_shape(int32_t* out3) const override {
    out3[0] = kPix; out3[1] = kPix; out3[2] = kStack;
  }
  int num_actions() const override { return 3; }  // left, stay, right

  // Rasterize the current state into the newest slot of the frame ring.
  void render(EnvState& e) {
    uint8_t* f = e.frames[(e.head + kStack - 1) % kStack];
    std::memset(f, 0, kPix * kPix);
    // Brick wall: gray level graded by row so depth is visible to the CNN.
    for (int br = 0; br < kBrickRowsPx; ++br)
      for (int bc = 0; bc < kBrickCols; ++bc) {
        if (!e.bricks[br * kBrickCols + bc]) continue;
        const uint8_t shade = static_cast<uint8_t>(110 + 20 * br);
        const int r0 = kBrickTop + br * kBrickH, c0 = bc * kBrickW;
        for (int r = r0; r < r0 + kBrickH; ++r)
          // 1-px gutter on the right edge keeps bricks visually distinct.
          for (int c = c0; c < c0 + kBrickW - 1; ++c) f[r * kPix + c] = shade;
      }
    // Paddle.
    for (int r = kPadRow; r < kPadRow + kPadH; ++r)
      for (int c = e.paddle; c < e.paddle + kPadW; ++c) f[r * kPix + c] = 200;
    // Ball (drawn last, on top).
    for (int r = e.ball_r; r < e.ball_r + kBallSz; ++r)
      for (int c = e.ball_c; c < e.ball_c + kBallSz; ++c)
        if (r >= 0 && r < kPix && c >= 0 && c < kPix) f[r * kPix + c] = 255;
  }

  // Advance the ring and render into the freed slot.
  void push_frame(EnvState& e) {
    e.head = (e.head + 1) % kStack;
    render(e);
  }

  void reset_env(int i) override {
    EnvState& e = envs[i];
    // DETERMINISTIC serve schedule (Asterix precedent): column walks the
    // 67-wide serve range via a coprime stride, direction alternates. Keeps
    // the pure-JAX twin (envs/breakout_pixel.py) bit-identical under
    // lockstep with no shared RNG.
    const int k = e.serves;
    e.ball_r = kBrickTop + kBrickRowsPx * kBrickH + 4;  // below the wall
    e.ball_c = 8 + (k * 37) % (kPix - 16 - kBallSz + 1);
    e.dr = 2;                                           // serve downward
    e.dc = (k % 2 == 0) ? 1 : -1;
    e.serves = k + 1;
    e.paddle = (kPix - kPadW) / 2;
    std::fill(e.bricks, e.bricks + kBrickRowsPx * kBrickCols, uint8_t{1});
    e.head = 0;
    // Fill the whole stack with the serve frame (envpool resets the same way:
    // the first stacked observation repeats the initial frame).
    render(e);
    for (int s = 0; s < kStack - 1; ++s) push_frame(e);
  }

  void write_obs(int i, float* out) const override {
    const EnvState& e = envs[i];
    // HWC layout, channel = stack index oldest->newest, scaled to [0, 1].
    for (int s = 0; s < kStack; ++s) {
      const uint8_t* f = e.frames[(e.head + s) % kStack];
      for (int p = 0; p < kPix * kPix; ++p)
        out[p * kStack + s] = f[p] * (1.0f / 255.0f);
    }
  }

  float step_env(int i, int32_t action, bool* terminated) override {
    EnvState& e = envs[i];
    e.paddle = std::clamp(e.paddle + (action - 1) * kPadSpeed, 0, kPix - kPadW);

    float reward = 0.0f;
    *terminated = false;
    int nr = e.ball_r + e.dr;
    int nc = e.ball_c + e.dc;

    // Side walls.
    if (nc < 0) { nc = -nc; e.dc = -e.dc; }
    if (nc > kPix - kBallSz) { nc = 2 * (kPix - kBallSz) - nc; e.dc = -e.dc; }
    // Ceiling.
    if (nr < 0) { nr = -nr; e.dr = 2; }

    // Brick band: test the ball center cell against the brick grid.
    const int cr = nr + kBallSz / 2, cc = nc + kBallSz / 2;
    if (cr >= kBrickTop && cr < kBrickTop + kBrickRowsPx * kBrickH) {
      const int br = (cr - kBrickTop) / kBrickH;
      const int bc = std::min(cc / kBrickW, kBrickCols - 1);
      if (e.bricks[br * kBrickCols + bc]) {
        e.bricks[br * kBrickCols + bc] = 0;
        reward = 1.0f;
        e.dr = -e.dr;
        nr = e.ball_r;  // reflect back toward the incoming side
        bool any = false;
        for (int b = 0; b < kBrickRowsPx * kBrickCols; ++b)
          any |= (e.bricks[b] != 0);
        if (!any)
          std::fill(e.bricks, e.bricks + kBrickRowsPx * kBrickCols, uint8_t{1});
      }
    } else if (e.dr > 0 && nr + kBallSz > kPadRow && e.ball_r + kBallSz <= kPadRow) {
      // Crossing the paddle plane this step.
      if (cc >= e.paddle && cc < e.paddle + kPadW) {
        e.dr = -2;
        nr = kPadRow - kBallSz;
        // Aim by hit offset: outer thirds send the ball out steeply.
        const int off = cc - e.paddle;
        if (off < kPadW / 3) e.dc = -2;
        else if (off >= 2 * kPadW / 3) e.dc = 2;
        else e.dc = (e.dc >= 0) ? 1 : -1;
      }
    } else if (nr >= kPix - kBallSz) {
      *terminated = true;  // ball lost below the paddle
    }

    e.ball_r = nr;
    e.ball_c = nc;
    push_frame(e);
    return reward;
  }
};

VecEnv* make_game(const char* task, int num_envs, int max_steps, uint64_t seed) {
  const std::string name(task ? task : "");
  if (name == "Breakout-minatar")
    return new BreakoutVec(num_envs, max_steps, seed);
  if (name == "Breakout-atari")
    return new BreakoutPixelVec(num_envs, max_steps, seed);
  if (name == "Asterix-minatar")
    return new AsterixVec(num_envs, max_steps, seed);
  if (name == "Freeway-minatar")
    return new FreewayVec(num_envs, max_steps, seed);
  if (name == "SpaceInvaders-minatar")
    return new SpaceInvadersVec(num_envs, max_steps, seed);
  if (name == "Pendulum-v1")
    return new PendulumVec(num_envs, max_steps, seed);
  if (name == "CartPole-v1" || name.empty())
    return new CartPoleVec(num_envs, max_steps, seed);
  return nullptr;
}

}  // namespace

extern "C" {

void* cvec_create(const char* task, int num_envs, int max_steps, uint64_t seed) {
  return make_game(task, num_envs, max_steps, seed);
}

void cvec_reset(void* handle, float* obs_out) {
  static_cast<VecEnv*>(handle)->reset_all(obs_out);
}

void cvec_step(void* handle, const int32_t* actions, float* obs_out,
               float* next_obs_out, float* reward_out, uint8_t* done_out,
               uint8_t* trunc_out, float* ep_return_out, int32_t* ep_length_out) {
  static_cast<VecEnv*>(handle)->step(actions, obs_out, next_obs_out,
                                     reward_out, done_out, trunc_out,
                                     ep_return_out, ep_length_out);
}

int cvec_obs_dim(void* handle) { return static_cast<VecEnv*>(handle)->obs_dim(); }

void cvec_obs_shape(void* handle, int32_t* out3) {
  static_cast<VecEnv*>(handle)->obs_shape(out3);
}

int cvec_num_actions(void* handle) {
  return static_cast<VecEnv*>(handle)->num_actions();
}

int cvec_action_dim(void* handle) {
  return static_cast<VecEnv*>(handle)->action_dim();
}

void cvec_action_bounds(void* handle, float* lo, float* hi) {
  static_cast<VecEnv*>(handle)->action_bounds(lo, hi);
}

void cvec_step_cont(void* handle, const float* actions, float* obs_out,
                    float* next_obs_out, float* reward_out, uint8_t* done_out,
                    uint8_t* trunc_out, float* ep_return_out,
                    int32_t* ep_length_out) {
  static_cast<VecEnv*>(handle)->step_cont(actions, obs_out, next_obs_out,
                                          reward_out, done_out, trunc_out,
                                          ep_return_out, ep_length_out);
}

void cvec_destroy(void* handle) { delete static_cast<VecEnv*>(handle); }

}  // extern "C"
