"""Core wrapper stack.

Reimplements the behavior of the reference's wrapper composition
(reference stoix/utils/make_env.py:29-61 `apply_core_wrappers`):

    env -> EpisodeStepLimit? -> RecordEpisodeMetrics
        -> { OptimisticResetVmapWrapper | AutoReset/CachedAutoReset -> Vmap }

with `next_obs_in_extras=True` semantics: `timestep.extras["next_obs"]` is always
the *true* successor observation (pre-auto-reset) so learners can bootstrap
correctly at truncations (reference ff_ppo.py:110-116).

All wrappers are pure-functional and shape-static: auto-reset uses `jnp.where`
selection over a freshly computed (or cached) reset state rather than host
branching, which keeps the whole rollout a single fused XLA program.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from stoix_tpu.envs.core import Action, Environment, State, Wrapper
from stoix_tpu.envs.types import StepType, TimeStep, _bcast


def _ensure_truncation(ts: TimeStep) -> None:
    """Guarantee the well-known extras["truncation"] key so the extras pytree
    contract is identical for reset/step across every env.

    The default is DERIVED from the timestep (LAST + discount > 0 is the
    truncation convention, types.py) rather than constant zeros: a constant
    is unvarying under shard_map's varying-manual-axes typing and would
    poison every scan carry it enters (check_vma would reject the learner)."""
    ts.extras["truncation"] = ts.extras.get(
        "truncation", jnp.logical_and(ts.last(), ts.discount > 0)
    )


class StepLimitState(NamedTuple):
    inner: Any
    step_count: jax.Array


class EpisodeStepLimit(Wrapper):
    """Truncates episodes at `max_steps`: step_type LAST, discount kept at 1."""

    def __init__(self, env: Environment, max_steps: int):
        super().__init__(env)
        self._max_steps = int(max_steps)

    def reset(self, key: jax.Array) -> Tuple[State, TimeStep]:
        return self._wrap_reset(*self._env.reset(key))

    def reset_to_level(self, level: Any, key: jax.Array) -> Tuple[State, TimeStep]:
        return self._wrap_reset(*self._env.reset_to_level(level, key))

    def _wrap_reset(self, state: State, ts: TimeStep) -> Tuple[State, TimeStep]:
        _ensure_truncation(ts)
        return StepLimitState(state, jnp.zeros((), jnp.int32)), ts

    def step(self, state: StepLimitState, action: Action) -> Tuple[State, TimeStep]:
        inner, ts = self._env.step(state.inner, action)
        count = state.step_count + 1
        truncate = jnp.logical_and(count >= self._max_steps, ~ts.last())
        ts = ts._replace(
            step_type=jnp.where(truncate, StepType.LAST, ts.step_type),
            # discount stays 1 on truncation — this is the whole point.
        )
        inner_trunc = ts.extras.get("truncation", jnp.zeros((), bool))
        ts.extras["truncation"] = jnp.logical_or(truncate, inner_trunc)
        return StepLimitState(inner, count), ts


class EpisodeMetricsState(NamedTuple):
    inner: Any
    episode_return: jax.Array
    episode_length: jax.Array
    # Running totals frozen at episode end, so LAST steps report full episodes.


class RecordEpisodeMetrics(Wrapper):
    """Accumulates per-episode return/length into extras["episode_metrics"]."""

    def reset(self, key: jax.Array) -> Tuple[State, TimeStep]:
        return self._wrap_reset(*self._env.reset(key))

    def reset_to_level(self, level: Any, key: jax.Array) -> Tuple[State, TimeStep]:
        return self._wrap_reset(*self._env.reset_to_level(level, key))

    def _wrap_reset(self, state: State, ts: TimeStep) -> Tuple[State, TimeStep]:
        zero = jnp.zeros((), jnp.float32)
        ts.extras["episode_metrics"] = {
            "episode_return": zero,
            "episode_length": jnp.zeros((), jnp.int32),
            "is_terminal_step": jnp.zeros((), bool),
        }
        _ensure_truncation(ts)
        return EpisodeMetricsState(state, zero, jnp.zeros((), jnp.int32)), ts

    def step(self, state: EpisodeMetricsState, action: Action) -> Tuple[State, TimeStep]:
        inner, ts = self._env.step(state.inner, action)
        ep_return = state.episode_return + ts.reward
        ep_length = state.episode_length + 1
        done = ts.last()
        ts.extras["episode_metrics"] = {
            "episode_return": ep_return,
            "episode_length": ep_length,
            "is_terminal_step": done,
        }
        _ensure_truncation(ts)
        # Reset accumulators after a terminal step (auto-reset follows above us).
        next_state = EpisodeMetricsState(
            inner,
            jnp.where(done, 0.0, ep_return),
            jnp.where(done, 0, ep_length),
        )
        return next_state, ts


class AutoResetState(NamedTuple):
    inner: Any
    key: jax.Array


class AutoResetWrapper(Wrapper):
    """Resets the env within `step` when an episode ends.

    The returned timestep keeps the terminal step_type/reward/discount but its
    `observation` becomes the first observation of the new episode, while
    `extras["next_obs"]` carries the true terminal observation for bootstrapping.
    """

    def __init__(self, env: Environment, next_obs_in_extras: bool = True):
        super().__init__(env)
        self._next_obs_in_extras = next_obs_in_extras

    def reset(self, key: jax.Array) -> Tuple[State, TimeStep]:
        key, inner_key = jax.random.split(key)
        inner, ts = self._env.reset(inner_key)
        if self._next_obs_in_extras:
            ts.extras["next_obs"] = ts.observation
        return AutoResetState(inner, key), ts

    def step(self, state: AutoResetState, action: Action) -> Tuple[State, TimeStep]:
        inner, ts = self._env.step(state.inner, action)
        key, reset_key = jax.random.split(state.key)
        reset_state, reset_ts = self._env.reset(reset_key)
        done = ts.last()
        next_inner = jax.tree.map(lambda a, b: jnp.where(_bcast(done, a), a, b), reset_state, inner)
        new_obs = jax.tree.map(lambda a, b: jnp.where(_bcast(done, a), a, b), reset_ts.observation, ts.observation)
        extras = dict(ts.extras)
        if self._next_obs_in_extras:
            extras["next_obs"] = ts.observation
        ts = ts._replace(observation=new_obs, extras=extras)
        return AutoResetState(next_inner, key), ts


def _reseed(state: Any, key: jax.Array) -> Any:
    """Replace `key` fields in a (nested) NamedTuple env state with fresh keys.

    Env states follow the convention of carrying their PRNG key in a `key` field
    and their wrapped state in an `inner` field; re-seeding on cached-reset
    replay keeps episode randomness fresh even though the initial physics state
    is frozen.
    """
    if hasattr(state, "_fields"):
        updates = {}
        if "key" in state._fields:
            key, sub = jax.random.split(key)
            updates["key"] = sub
        if "inner" in state._fields:
            updates["inner"] = _reseed(state.inner, key)
        if updates:
            return state._replace(**updates)
    return state


class CachedAutoResetState(NamedTuple):
    inner: Any
    cached_state: Any
    cached_obs: Any
    key: jax.Array


class CachedAutoResetWrapper(Wrapper):
    """Auto-reset that replays the episode-initial state instead of re-running
    `reset` every step (reference make_env.py:48-52's CachedAutoResetWrapper).
    Valid for envs whose reset distribution the caller is happy to freeze per
    environment instance; saves the full reset computation in the hot loop.
    PRNG `key` fields in the cached state are re-seeded on replay so episode
    randomness stays fresh.
    """

    def __init__(self, env: Environment, next_obs_in_extras: bool = True):
        super().__init__(env)
        self._next_obs_in_extras = next_obs_in_extras

    def reset(self, key: jax.Array) -> Tuple[State, TimeStep]:
        key, inner_key = jax.random.split(key)
        inner, ts = self._env.reset(inner_key)
        if self._next_obs_in_extras:
            ts.extras["next_obs"] = ts.observation
        return CachedAutoResetState(inner, inner, ts.observation, key), ts

    def step(self, state: CachedAutoResetState, action: Action) -> Tuple[State, TimeStep]:
        inner, ts = self._env.step(state.inner, action)
        done = ts.last()
        key, reseed_key = jax.random.split(state.key)
        replay_state = _reseed(state.cached_state, reseed_key)
        next_inner = jax.tree.map(
            lambda cached, cur: jnp.where(_bcast(done, cached), cached, cur), replay_state, inner
        )
        new_obs = jax.tree.map(
            lambda cached, cur: jnp.where(_bcast(done, cached), cached, cur), state.cached_obs, ts.observation
        )
        extras = dict(ts.extras)
        if self._next_obs_in_extras:
            extras["next_obs"] = ts.observation
        ts = ts._replace(observation=new_obs, extras=extras)
        return CachedAutoResetState(next_inner, state.cached_state, state.cached_obs, key), ts


class FlattenObservationWrapper(Wrapper):
    """Flatten a structured (grid/pixel) agent_view to 1-D so MLP torsos can
    consume it — the reference pairs its MLP networks with grid envs via
    `stoa.FlattenObservationWrapper` (reference configs/env/jumanji/snake.yaml
    `wrapper: _target_: stoa.FlattenObservationWrapper`). Applied to the raw
    env, below the core stack, so `extras["next_obs"]` is flattened too."""

    def __init__(self, env: Environment):
        super().__init__(env)
        spec = env.observation_space().agent_view
        self._feature_rank = len(spec.shape)
        self._flat_dim = 1
        for d in spec.shape:
            self._flat_dim *= int(d)

    def _flatten(self, ts: TimeStep) -> TimeStep:
        view = ts.observation.agent_view
        shape = view.shape[: view.ndim - self._feature_rank] + (self._flat_dim,)
        return ts._replace(
            observation=ts.observation._replace(agent_view=view.reshape(shape))
        )

    def reset(self, key: jax.Array) -> Tuple[State, TimeStep]:
        state, ts = self._env.reset(key)
        return state, self._flatten(ts)

    def step(self, state: State, action: Action) -> Tuple[State, TimeStep]:
        state, ts = self._env.step(state, action)
        return state, self._flatten(ts)

    def observation_space(self) -> Any:
        import dataclasses

        obs = self._env.observation_space()
        return obs._replace(
            agent_view=dataclasses.replace(obs.agent_view, shape=(self._flat_dim,))
        )


class StartFlagPrevActionState(NamedTuple):
    inner: Any
    prev_action: jax.Array


class StartFlagPrevActionWrapper(Wrapper):
    """Append an episode-start flag and the previous action to a flat
    agent_view — the reference applies stoa's AddStartFlagAndPrevAction to
    POPJym POMDP envs (reference make_env.py:369-370) so memory models can
    condition on action history.

    Discrete actions append one-hot(prev_action); Box actions append the raw
    action vector. At reset (and on the first step after it) the start flag is
    1 and the previous action is zeros. Requires a 1-D agent_view — flatten
    structured observations first.
    """

    def __init__(self, env: Environment):
        super().__init__(env)
        space = env.action_space()
        from stoix_tpu.envs import spaces as _spaces

        self._discrete = isinstance(space, _spaces.Discrete)
        self._act_dim = (
            int(space.num_values) if self._discrete else int(space.shape[-1])
        )
        view = env.observation_space().agent_view
        if len(view.shape) != 1:
            raise ValueError(
                "StartFlagPrevActionWrapper needs a flat agent_view; apply "
                f"FlattenObservationWrapper first (got shape {view.shape})"
            )
        self._base_dim = int(view.shape[0])

    def _zero_action(self) -> jax.Array:
        if self._discrete:
            # -1 one-hot-encodes to all-zeros: "no previous action" is
            # distinguishable from "previous action was 0".
            return jnp.full((), -1, jnp.int32)
        return jnp.zeros((self._act_dim,), jnp.float32)

    def _augment(self, ts: TimeStep, start: jax.Array, prev_action: jax.Array) -> TimeStep:
        if self._discrete:
            act_feat = jax.nn.one_hot(prev_action, self._act_dim, dtype=jnp.float32)
        else:
            act_feat = jnp.asarray(prev_action, jnp.float32)
        view = jnp.concatenate(
            [ts.observation.agent_view, start[None].astype(jnp.float32), act_feat]
        )
        return ts._replace(observation=ts.observation._replace(agent_view=view))

    def reset(self, key: jax.Array) -> Tuple[State, TimeStep]:
        state, ts = self._env.reset(key)
        prev = self._zero_action()
        return (
            StartFlagPrevActionState(state, prev),
            self._augment(ts, jnp.ones((), jnp.float32), prev),
        )

    def step(self, state: StartFlagPrevActionState, action: Action) -> Tuple[State, TimeStep]:
        inner, ts = self._env.step(state.inner, action)
        return (
            StartFlagPrevActionState(inner, action),
            self._augment(ts, jnp.zeros((), jnp.float32), action),
        )

    def observation_space(self) -> Any:
        import dataclasses

        obs = self._env.observation_space()
        return obs._replace(
            agent_view=dataclasses.replace(
                obs.agent_view, shape=(self._base_dim + 1 + self._act_dim,)
            )
        )


class VmapWrapper(Wrapper):
    """Vectorizes reset/step over a leading batch of keys/states/actions."""

    def reset(self, keys: jax.Array) -> Tuple[State, TimeStep]:
        return jax.vmap(self._env.reset)(keys)

    def step(self, state: State, action: Action) -> Tuple[State, TimeStep]:
        return jax.vmap(self._env.step)(state, action)


class OptimisticResetState(NamedTuple):
    inner: Any
    key: jax.Array


class OptimisticResetVmapWrapper(Wrapper):
    """Vmapped auto-reset that amortizes reset cost (reference make_env.py:48-61,
    pattern from JaxUED/Craftax): per step only `num_envs / reset_ratio` reset
    states are computed; each done env optimistically grabs one (collisions share
    a reset state, which is statistically fine and much cheaper for expensive
    resets). Behaves like Vmap(AutoReset(env)) with reset_ratio == 1.
    """

    def __init__(self, env: Environment, num_envs: int, reset_ratio: int = 16, next_obs_in_extras: bool = True):
        super().__init__(env)
        if num_envs % reset_ratio != 0:
            raise ValueError(
                f"num_envs ({num_envs}) must be divisible by reset_ratio ({reset_ratio}); "
                "a silent fallback would defeat the amortization this wrapper exists for."
            )
        self._num_envs = int(num_envs)
        self._num_resets = max(1, int(num_envs) // int(reset_ratio))
        self._next_obs_in_extras = next_obs_in_extras

    def reset(self, keys: jax.Array) -> Tuple[State, TimeStep]:
        # keys: [num_envs, 2]; split so wrapper-carried keys never alias the
        # keys handed to the inner env.
        carry_and_env = jax.vmap(jax.random.split)(keys)
        inner, ts = jax.vmap(self._env.reset)(carry_and_env[:, 1])
        if self._next_obs_in_extras:
            ts.extras["next_obs"] = ts.observation
        return OptimisticResetState(inner, carry_and_env[:, 0]), ts

    def step(self, state: OptimisticResetState, action: Action) -> Tuple[State, TimeStep]:
        inner, ts = jax.vmap(self._env.step)(state.inner, action)
        split = jax.vmap(jax.random.split)(state.key)  # [num_envs, 2, key]
        keys, reset_keys = split[:, 0], split[: self._num_resets, 1]
        reset_state, reset_ts = jax.vmap(self._env.reset)(reset_keys)

        # Each env i is assigned reset slot i % num_resets.
        idx = jnp.arange(self._num_envs) % self._num_resets
        gathered_state = jax.tree.map(lambda x: x[idx], reset_state)
        gathered_obs = jax.tree.map(lambda x: x[idx], reset_ts.observation)

        done = ts.last()
        next_inner = jax.tree.map(lambda a, b: jnp.where(_bcast(done, a), a, b), gathered_state, inner)
        new_obs = jax.tree.map(lambda a, b: jnp.where(_bcast(done, a), a, b), gathered_obs, ts.observation)
        extras = dict(ts.extras)
        if self._next_obs_in_extras:
            extras["next_obs"] = ts.observation
        ts = ts._replace(observation=new_obs, extras=extras)
        return OptimisticResetState(next_inner, keys), ts


def apply_core_wrappers(
    env: Environment,
    num_envs: int,
    *,
    max_episode_steps: Optional[int] = None,
    use_optimistic_reset: bool = False,
    reset_ratio: int = 16,
    use_cached_auto_reset: bool = False,
) -> Environment:
    """The canonical wrapper composition (reference make_env.py:29-61)."""
    if max_episode_steps is not None and max_episode_steps > 0:
        env = EpisodeStepLimit(env, max_episode_steps)
    env = RecordEpisodeMetrics(env)
    if use_optimistic_reset:
        env = OptimisticResetVmapWrapper(env, num_envs=num_envs, reset_ratio=reset_ratio)
    else:
        env = CachedAutoResetWrapper(env) if use_cached_auto_reset else AutoResetWrapper(env)
        env = VmapWrapper(env)
    return env


def chained_wrappers(env: Environment, wrappers: list) -> Environment:
    """Compose a list of wrapper constructors (reference stoix/wrappers/base.py:
    6-15): each entry is a callable taking the env (use functools.partial or
    config _partial_ instantiation for extra kwargs)."""
    for ctor in wrappers:
        env = ctor(env)
    return env
