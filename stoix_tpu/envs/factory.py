"""Stateful environment factories — the Sebulba env seam.

Mirrors the reference's factory boundary (reference stoix/utils/env_factory.py
:23-86 and stoix/wrappers/jax_to_factory.py): Sebulba actors consume STATEFUL
envs (`envs.reset() -> TimeStep`, `envs.step(action) -> TimeStep`, numpy-ish
batched outputs), so non-JAX simulators (EnvPool Atari, Gymnasium) and pure
JAX envs sit behind one interface. Thread-safe seed allocation lets every
actor thread draw unique env instances.
"""

from __future__ import annotations

import threading
from typing import Any, Optional

import jax
import jax.numpy as jnp

from stoix_tpu.envs.core import Environment
from stoix_tpu.envs.types import TimeStep
from stoix_tpu.envs.wrappers import AutoResetWrapper, RecordEpisodeMetrics, VmapWrapper


class EnvFactory:
    """Abstract factory with thread-safe unique seeding."""

    def __init__(self, task_id: str, init_seed: int = 42, **kwargs: Any):
        self._task_id = task_id
        self._seed = init_seed
        self._kwargs = kwargs
        self._lock = threading.Lock()

    def __call__(self, num_envs: int) -> Any:
        raise NotImplementedError

    def _next_seed(self, num_envs: int) -> int:
        with self._lock:
            seed = self._seed
            self._seed += num_envs
        return seed


class JaxToStateful:
    """Wraps a batched pure-JAX env as a stateful Sebulba env pinned to a
    device (reference stoix/wrappers/jax_to_factory.py:11-107): reset/step are
    vmapped+jitted once; state lives inside this object."""

    def __init__(self, env: Environment, num_envs: int, seed: int, device: Optional[jax.Device] = None):
        self._env = VmapWrapper(AutoResetWrapper(RecordEpisodeMetrics(env)))
        self._num_envs = num_envs
        self._device = device or jax.devices("cpu")[0]
        self._state = None
        self._keys = jax.device_put(
            jax.random.split(jax.random.PRNGKey(seed), num_envs), self._device
        )
        self._reset_fn = jax.jit(self._env.reset, device=self._device)
        self._step_fn = jax.jit(self._env.step, device=self._device)

    @property
    def num_envs(self) -> int:
        return self._num_envs

    def observation_space(self):
        return self._env.observation_space()

    def action_space(self):
        return self._env.action_space()

    @property
    def num_actions(self) -> int:
        return self._env.num_actions

    def reset(self, *, seed: Optional[int] = None) -> TimeStep:
        if seed is not None:
            self._keys = jax.device_put(
                jax.random.split(jax.random.PRNGKey(seed), self._num_envs), self._device
            )
        self._state, timestep = self._reset_fn(self._keys)
        return timestep

    def step(self, action: Any) -> TimeStep:
        action = jax.device_put(jnp.asarray(action), self._device)
        self._state, timestep = self._step_fn(self._state, action)
        return timestep


class JaxEnvFactory(EnvFactory):
    """Creates JaxToStateful instances of a registered env (CPU-pinned by
    default, reference jax_to_factory.py:110-130)."""

    def __init__(self, task_id: str, init_seed: int = 42, device: Optional[jax.Device] = None, **kwargs: Any):
        super().__init__(task_id, init_seed, **kwargs)
        self._device = device or jax.devices("cpu")[0]

    def __call__(self, num_envs: int) -> JaxToStateful:
        from stoix_tpu.envs.registry import make_single

        seed = self._next_seed(num_envs)
        env = make_single(self._task_id, **self._kwargs)
        return JaxToStateful(env, num_envs, seed, self._device)


class EnvPoolFactory(EnvFactory):
    """EnvPool (C++ vectorized envs) factory — requires the optional `envpool`
    dependency (reference env_factory.py:48-68). Raises a clear error when the
    package is absent from the environment."""

    def __call__(self, num_envs: int) -> Any:
        try:
            import envpool  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "EnvPoolFactory requires the optional 'envpool' package, which "
                "is not installed in this environment. Use JaxEnvFactory, or "
                "the native CVecEnvFactory (stoix_tpu/envs/cvec.py) for the "
                "first-party C++ vectorized envs."
            ) from e
        from stoix_tpu.envs.envpool_adapter import EnvPoolAdapter

        seed = self._next_seed(num_envs)
        # gym_reset_return_info: reset() -> (obs, info), the API the adapter
        # consumes (reference env_factory.py:57-66).
        return EnvPoolAdapter(
            envpool.make(
                self._task_id,
                env_type="gymnasium",
                num_envs=num_envs,
                seed=seed,
                gym_reset_return_info=True,
                **self._kwargs,
            )
        )


def make_factory(config: Any) -> EnvFactory:
    """Build the Sebulba env factory from config (reference make_env.py:469-513)."""
    scenario = (
        config.env.scenario.name
        if hasattr(config.env.scenario, "name")
        else config.env.scenario
    )
    kwargs = dict(config.env.get("kwargs", {}) or {})
    backend = str(config.env.get("backend", "jax"))
    seed = int(config.arch.seed)
    if backend == "envpool":
        return EnvPoolFactory(scenario, seed, **kwargs)
    if backend == "cvec":
        from stoix_tpu.envs.cvec import CVecEnvFactory

        return CVecEnvFactory(scenario, seed, **kwargs)
    if backend == "gymnasium":
        from stoix_tpu.envs.gymnasium_adapter import GymnasiumFactory

        return GymnasiumFactory(scenario, seed, **kwargs)
    return JaxEnvFactory(scenario, seed, **kwargs)
