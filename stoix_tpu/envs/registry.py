"""Environment factory — the `make_env.py` equivalent.

The reference dispatches over 14 external suites (reference
stoix/utils/make_env.py:420-433 `ENV_MAKERS`); this registry dispatches over the
first-party suites plus optional external ones when present, and applies the
canonical wrapper stack (reference make_env.py:29-61).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

from stoix_tpu.envs import (
    breakout_pixel,
    classic,
    debug,
    doorkey,
    game2048,
    locomotion,
    minatar,
    snake,
)
from stoix_tpu.envs.core import Environment
from stoix_tpu.envs.wrappers import (
    EpisodeStepLimit,
    FlattenObservationWrapper,
    RecordEpisodeMetrics,
    apply_core_wrappers,
)

# scenario name -> constructor(**env_kwargs)
ENV_REGISTRY: Dict[str, Callable[..., Environment]] = {
    "CartPole-v1": classic.CartPole,
    "Pendulum-v1": classic.Pendulum,
    "Acrobot-v1": classic.Acrobot,
    "MountainCar-v0": classic.MountainCar,
    "MountainCarContinuous-v0": classic.MountainCarContinuous,
    "Catch-bsuite": classic.Catch,
    "Ant": locomotion.Ant,
    "Hopper": locomotion.Hopper,
    "Walker2d": locomotion.Walker2d,
    "HalfCheetah": locomotion.HalfCheetah,
    "Breakout-minatar": minatar.Breakout,
    "Breakout-atari": breakout_pixel.BreakoutPixel,
    "Asterix-minatar": minatar.Asterix,
    "Freeway-minatar": minatar.Freeway,
    "SpaceInvaders-minatar": minatar.SpaceInvaders,
    "Snake-v1": snake.Snake,
    "Game2048-v1": game2048.Game2048,
    "DoorKey-v0": doorkey.DoorKey,
    "IdentityGame": debug.IdentityGame,
    "SequenceGame": debug.SequenceGame,
}


def register(name: str, ctor: Callable[..., Environment]) -> None:
    ENV_REGISTRY[name] = ctor


def make_single(scenario: str, suite: Optional[str] = None, **env_kwargs: Any) -> Environment:
    """Construct a raw (unwrapped, unbatched) environment.

    `suite` selects an external-suite adapter (gymnax/brax/jumanji, lazy
    imports — see stoix_tpu/envs/suites.py); first-party scenarios resolve
    through ENV_REGISTRY regardless of the suite tag so configs can spell
    `env_name: classic` etc. explicitly.
    """
    from stoix_tpu.envs import suites

    # An explicit external-suite tag wins over the first-party registry —
    # e.g. env_name: gymnax + CartPole-v1 must build the gymnax adapter, not
    # the first-party CartPole that happens to share the scenario name.
    if suite in suites.SUITE_MAKERS:
        return suites.SUITE_MAKERS[suite](scenario, **env_kwargs)
    if scenario in ENV_REGISTRY:
        return ENV_REGISTRY[scenario](**env_kwargs)
    raise ValueError(
        f"Unknown environment '{scenario}' (suite={suite!r}). First-party: "
        f"{sorted(ENV_REGISTRY)}; external suites: {sorted(suites.SUITE_MAKERS)}"
    )


def make(config: Any) -> Tuple[Environment, Environment]:
    """Build (train_env, eval_env) from a config with an `env` section.

    Expected config fields (mirrors reference configs/env/**):
        env.scenario.name        — registry key
        env.kwargs               — ctor kwargs (optional)
        env.wrapper              — dict(max_episode_steps, use_optimistic_reset,
                                   reset_ratio, use_cached_auto_reset,
                                   flatten_observation) (optional)
        arch.total_num_envs      — global env count (split across data shards upstream)
    """
    env_cfg = config.env
    kwargs = dict(getattr(env_cfg, "kwargs", {}) or {})
    scenario = env_cfg.scenario.name if hasattr(env_cfg.scenario, "name") else env_cfg.scenario
    suite = getattr(env_cfg, "env_name", None)
    wrapper_cfg = dict(getattr(env_cfg, "wrapper", {}) or {})

    # Kinetix keeps distinct train/eval level sources (reference
    # make_env.py:240-245 builds separate reset functions); every other suite
    # constructs the two envs identically.
    if suite == "kinetix":
        train_env = make_single(scenario, suite=suite, role="train", **kwargs)
        eval_env = make_single(scenario, suite=suite, role="eval", **kwargs)
    else:
        train_env = make_single(scenario, suite=suite, **kwargs)
        eval_env = make_single(scenario, suite=suite, **kwargs)

    if wrapper_cfg.get("flatten_observation", False):
        train_env = FlattenObservationWrapper(train_env)
        eval_env = FlattenObservationWrapper(eval_env)

    num_envs = int(config.arch.total_num_envs)
    train_env = apply_core_wrappers(
        train_env,
        num_envs=num_envs,
        max_episode_steps=wrapper_cfg.get("max_episode_steps"),
        use_optimistic_reset=bool(wrapper_cfg.get("use_optimistic_reset", False)),
        reset_ratio=int(wrapper_cfg.get("reset_ratio", 16)),
        use_cached_auto_reset=bool(wrapper_cfg.get("use_cached_auto_reset", False)),
    )
    # Eval env: metrics + step limit only; episodes must genuinely end (no
    # auto-reset) because the evaluator's while_loop keys off timestep.last()
    # (reference stoix/evaluator.py:152).
    if wrapper_cfg.get("max_episode_steps"):
        eval_env = EpisodeStepLimit(eval_env, wrapper_cfg["max_episode_steps"])
    eval_env = RecordEpisodeMetrics(eval_env)
    return train_env, eval_env
