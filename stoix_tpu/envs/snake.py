"""Snake — first-party pure-JAX grid game (Jumanji Snake-v1 class).

The driver's BASELINE tracks ff_dqn + ff_c51 on Jumanji Snake
(reference configs/env/jumanji/snake.yaml, observation_attribute "grid");
this module is the no-dependency equivalent so the tracked config runs
first-party. Semantics follow the Jumanji game: a snake moves on a
num_rows x num_cols grid, eating fruit grows it by one and scores +1;
hitting a wall or its own body ends the episode.

TPU-first design: the body is a fixed-size position buffer [max_len, 2]
ordered head-first with an explicit length counter — every step is a static
shift/scatter over that buffer (144 cells; pure VPU work that fuses into the
rollout scan). Fruit respawn samples a categorical over the flattened grid
with occupied cells masked to -inf, so respawn never lands on the snake and
needs no rejection loop.

Observation (jumanji-like "grid" rendering): [rows, cols, 5] float32 channels
    0: body (excluding head)   1: head   2: tail   3: fruit
    4: whole-snake occupancy scaled by body order (head=1 -> tail->0)
Action space: Discrete(4) = up/right/down/left; the action mask excludes the
direct reverse of the current heading (stepping into the neck).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from stoix_tpu.envs import spaces
from stoix_tpu.envs.core import Environment
from stoix_tpu.envs.types import Observation, TimeStep, restart, select_step, termination, transition, truncation

# Row/col deltas for up, right, down, left.
_DELTAS = jnp.array([[-1, 0], [0, 1], [1, 0], [0, -1]], jnp.int32)


class SnakeState(NamedTuple):
    key: jax.Array
    body: jax.Array  # [max_len, 2] positions, head first; rows beyond length unused
    length: jax.Array  # [] int32
    heading: jax.Array  # [] int32, last action direction
    fruit: jax.Array  # [2] int32
    step_count: jax.Array  # [] int32


class Snake(Environment):
    def __init__(self, num_rows: int = 12, num_cols: int = 12, max_steps: int = 500):
        self._rows = int(num_rows)
        self._cols = int(num_cols)
        self._max_len = self._rows * self._cols
        self._max_steps = int(max_steps)

    # ------------------------------------------------------------------ spaces
    def observation_space(self) -> Observation:
        return Observation(
            agent_view=spaces.Array((self._rows, self._cols, 5), jnp.float32),
            action_mask=spaces.Array((4,), jnp.float32),
            step_count=spaces.Array((), jnp.int32),
        )

    def action_space(self) -> spaces.Discrete:
        return spaces.Discrete(4)

    # ------------------------------------------------------------------ helpers
    def _occupancy_mask(self, state: SnakeState) -> jax.Array:
        """[max_len] bool: which body rows hold live segments."""
        return jnp.arange(self._max_len) < state.length

    def _grid_obs(self, state: SnakeState) -> Observation:
        live = self._occupancy_mask(state)
        flat_idx = state.body[:, 0] * self._cols + state.body[:, 1]  # [max_len]
        n_cells = self._rows * self._cols

        def paint(values: jax.Array) -> jax.Array:
            cells = jnp.zeros((n_cells,), jnp.float32).at[flat_idx].max(values)
            return cells.reshape(self._rows, self._cols)

        live_f = live.astype(jnp.float32)
        head_onehot = jnp.zeros((self._max_len,), jnp.float32).at[0].set(1.0)
        tail_idx = jnp.maximum(state.length - 1, 0)
        tail_onehot = jnp.zeros((self._max_len,), jnp.float32).at[tail_idx].set(1.0) * live_f
        # Body order channel: head 1.0 decaying linearly along the CURRENT
        # body (tail -> 1/length), so the ordering gradient spans the full
        # channel range regardless of snake size.
        length_f = jnp.maximum(state.length, 1).astype(jnp.float32)
        order = (1.0 - jnp.arange(self._max_len) / length_f) * live_f

        body_wo_head = paint(live_f * (1.0 - head_onehot))
        head = paint(head_onehot)
        tail = paint(tail_onehot)
        fruit = jnp.zeros((n_cells,), jnp.float32).at[
            state.fruit[0] * self._cols + state.fruit[1]
        ].set(1.0).reshape(self._rows, self._cols)
        order_grid = paint(order)

        view = jnp.stack([body_wo_head, head, tail, fruit, order_grid], axis=-1)
        # Mask out the reverse of the current heading (stepping into the neck).
        reverse = (state.heading + 2) % 4
        mask = jnp.ones((4,), jnp.float32).at[reverse].set(
            jnp.where(state.length > 1, 0.0, 1.0)
        )
        return Observation(agent_view=view, action_mask=mask, step_count=state.step_count)

    def _spawn_fruit(self, key: jax.Array, body: jax.Array, length: jax.Array) -> jax.Array:
        n_cells = self._rows * self._cols
        flat_idx = body[:, 0] * self._cols + body[:, 1]
        live = jnp.arange(self._max_len) < length
        occupied = jnp.zeros((n_cells,), bool).at[flat_idx].max(live)
        logits = jnp.where(occupied, -jnp.inf, 0.0)
        cell = jax.random.categorical(key, logits)
        return jnp.stack([cell // self._cols, cell % self._cols]).astype(jnp.int32)

    # ------------------------------------------------------------------ api
    def reset(self, key: jax.Array) -> Tuple[SnakeState, TimeStep]:
        key, pos_key, fruit_key = jax.random.split(key, 3)
        # Random head cell; snake starts at length 1 heading right (jumanji
        # starts from a random position).
        cell = jax.random.randint(pos_key, (), 0, self._rows * self._cols)
        head = jnp.stack([cell // self._cols, cell % self._cols]).astype(jnp.int32)
        body = jnp.zeros((self._max_len, 2), jnp.int32).at[0].set(head)
        length = jnp.ones((), jnp.int32)
        fruit = self._spawn_fruit(fruit_key, body, length)
        state = SnakeState(
            key=key,
            body=body,
            length=length,
            heading=jnp.ones((), jnp.int32),  # right
            fruit=fruit,
            step_count=jnp.zeros((), jnp.int32),
        )
        ts = restart(self._grid_obs(state))
        ts.extras["truncation"] = jnp.zeros((), bool)
        return state, ts

    def step(self, state: SnakeState, action: jax.Array) -> Tuple[SnakeState, TimeStep]:
        action = jnp.asarray(action, jnp.int32)
        # Reversing: at length >= 3 the neck (body[1]) blocks and the snake
        # dies via the self-collision test below. At length 2 the "neck" IS
        # the vacating tail, so a reversal is a legal head/tail swap — the
        # action mask (reverse excluded when length > 1) is what discourages
        # it for mask-respecting policies.
        new_head = state.body[0] + _DELTAS[action]

        out_of_bounds = jnp.logical_or(
            jnp.logical_or(new_head[0] < 0, new_head[0] >= self._rows),
            jnp.logical_or(new_head[1] < 0, new_head[1] >= self._cols),
        )
        ate = jnp.all(new_head == state.fruit)
        new_length = state.length + ate.astype(jnp.int32)

        # The tail cell vacates unless we grew this step, so moving onto the
        # current tail is legal when not eating (jumanji semantics).
        live = self._occupancy_mask(state)
        is_tail = jnp.arange(self._max_len) == (state.length - 1)
        blocking = jnp.logical_and(live, jnp.logical_or(~is_tail, ate))
        hits_body = jnp.any(
            jnp.logical_and(blocking, jnp.all(state.body == new_head, axis=-1))
        )
        died = jnp.logical_or(out_of_bounds, hits_body)

        # Shift the body: new head at row 0, previous segments slide down.
        shifted = jnp.roll(state.body, 1, axis=0).at[0].set(new_head)

        key, fruit_key = jax.random.split(state.key)
        new_fruit = jnp.where(
            ate, self._spawn_fruit(fruit_key, shifted, new_length), state.fruit
        )

        next_state = SnakeState(
            key=key,
            body=shifted,
            length=new_length,
            heading=action,
            fruit=new_fruit,
            step_count=state.step_count + 1,
        )
        reward = ate.astype(jnp.float32)
        obs = self._grid_obs(next_state)
        full = next_state.length >= self._max_len
        terminated = jnp.logical_or(died, full)
        truncated = jnp.logical_and(next_state.step_count >= self._max_steps, ~terminated)
        # ate and died are mutually exclusive (fruit never spawns on the body),
        # so the terminal reward is correct in both cases.
        ts = select_step(
            terminated,
            termination(reward, obs),
            select_step(truncated, truncation(reward, obs), transition(reward, obs)),
        )
        ts.extras["truncation"] = truncated
        return next_state, ts
