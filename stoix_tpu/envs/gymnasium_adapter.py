"""Gymnasium adapter (reference stoix/wrappers/gymnasium.py VecGymToStoa +
stoix/utils/env_factory.py GymnasiumFactory): wraps vectorized Gymnasium envs
as stateful Sebulba envs emitting the canonical TimeStep/Observation structs,
with episode-metric accounting done host-side in numpy.

Gymnasium's SyncVectorEnv auto-resets internally and reports the true final
observation via `final_observation`/`final_obs` infos, which this adapter
surfaces as extras["next_obs"] for correct bootstrapping.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from stoix_tpu.envs import spaces
from stoix_tpu.envs.factory import EnvFactory
from stoix_tpu.envs.types import Observation, TimeStep


class VecGymToStoix:
    def __init__(self, envs: Any):
        self._envs = envs
        self._n = envs.num_envs
        self._ep_return = np.zeros((self._n,), np.float32)
        self._ep_length = np.zeros((self._n,), np.int32)

    @property
    def num_envs(self) -> int:
        return self._n

    @property
    def num_actions(self) -> int:
        space = self._envs.single_action_space
        import gymnasium as gym

        if isinstance(space, gym.spaces.Discrete):
            return int(space.n)
        return int(np.prod(space.shape))

    def observation_space(self) -> Observation:
        obs_shape = self._envs.single_observation_space.shape
        return Observation(
            agent_view=spaces.Array(tuple(obs_shape), np.float32),
            action_mask=spaces.Array((self.num_actions,), np.float32),
            step_count=spaces.Array((), np.int32),
        )

    def action_space(self) -> spaces.Space:
        import gymnasium as gym

        space = self._envs.single_action_space
        if isinstance(space, gym.spaces.Discrete):
            return spaces.Discrete(int(space.n))
        return spaces.Box(low=space.low, high=space.high, shape=tuple(space.shape))

    def _observation(self, view: np.ndarray) -> Observation:
        return Observation(
            agent_view=np.asarray(view, np.float32),
            action_mask=np.ones((self._n, self.num_actions), np.float32),
            step_count=self._ep_length.copy(),
        )

    def reset(self, *, seed: Optional[int] = None) -> TimeStep:
        obs, _info = self._envs.reset(seed=seed)
        self._ep_return[:] = 0
        self._ep_length[:] = 0
        return TimeStep(
            step_type=np.zeros((self._n,), np.int8),
            reward=np.zeros((self._n,), np.float32),
            discount=np.ones((self._n,), np.float32),
            observation=self._observation(obs),
            extras={
                "next_obs": self._observation(obs),
                "truncation": np.zeros((self._n,), bool),
                "episode_metrics": {
                    "episode_return": self._ep_return.copy(),
                    "episode_length": self._ep_length.copy(),
                    "is_terminal_step": np.zeros((self._n,), bool),
                },
            },
        )

    def step(self, action: Any) -> TimeStep:
        obs, reward, terminated, truncated, infos = self._envs.step(np.asarray(action))
        reward = np.asarray(reward, np.float32)
        terminated = np.asarray(terminated, bool)
        truncated = np.asarray(truncated, bool)
        last = terminated | truncated

        self._ep_return += reward
        self._ep_length += 1
        ep_return = self._ep_return.copy()
        ep_length = self._ep_length.copy()
        self._ep_return[last] = 0
        self._ep_length[last] = 0

        # True successor observations (pre-auto-reset) for bootstrapping.
        next_obs = np.asarray(obs, np.float32).copy()
        final = infos.get("final_observation", infos.get("final_obs"))
        if final is not None:
            for i, fo in enumerate(final):
                if fo is not None:
                    next_obs[i] = np.asarray(fo, np.float32)

        return TimeStep(
            step_type=np.where(last, np.int8(2), np.int8(1)),
            reward=reward,
            discount=np.where(terminated, 0.0, 1.0).astype(np.float32),
            observation=self._observation(obs),
            extras={
                "next_obs": self._observation(next_obs),
                "truncation": truncated,
                "episode_metrics": {
                    "episode_return": ep_return,
                    "episode_length": ep_length,
                    "is_terminal_step": last,
                },
            },
        )


class GymnasiumFactory(EnvFactory):
    """Creates SyncVectorEnv batches of a Gymnasium task behind the Sebulba
    factory seam (thread-safe seeding via EnvFactory)."""

    def __call__(self, num_envs: int) -> VecGymToStoix:
        import gymnasium as gym

        self._next_seed(num_envs)  # keep thread-unique seed accounting
        fns = [lambda: gym.make(self._task_id, **self._kwargs) for _ in range(num_envs)]
        # SAME_STEP autoreset reports the true final observation in infos (the
        # 1.x default NEXT_STEP mode inserts a fabricated reset transition and
        # never exposes final observations).
        try:
            envs = gym.vector.SyncVectorEnv(
                fns, autoreset_mode=gym.vector.AutoresetMode.SAME_STEP
            )
        except TypeError:  # older gymnasium: SAME_STEP was the only behavior
            envs = gym.vector.SyncVectorEnv(fns)
        return VecGymToStoix(envs)
