"""DoorKey — first-party partially-observable gridworld (minigrid/navix
DoorKey class, reference configs/env/navix + xland_minigrid suites; the
external-suite adapters cover the real packages, this is the no-dependency
stand-in).

A wall splits the room; the agent must find the key, open the door, and
reach the goal. Observation is a 5x5 EGOCENTRIC view (agent centered on the
bottom row, facing up) — the layout is randomized per episode, so solving
requires exploration and (for the full task) memory of what was seen.

TPU shape notes: the layout lives as a dense [N, N, C] channel grid; the
egocentric view is a pad + dynamic_slice + rot90 (lax.switch over the four
headings) — all static shapes; actions apply via jnp.where masks, no
data-dependent control flow.

Actions (minigrid convention, subset): 0 turn left, 1 turn right,
2 forward, 3 pickup, 4 toggle.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from stoix_tpu.envs import spaces
from stoix_tpu.envs.core import Environment
from stoix_tpu.envs.types import (
    Observation,
    TimeStep,
    restart,
    select_step,
    termination,
    transition,
    truncation,
)

_VIEW = 5
# Channels: 0 wall, 1 closed door, 2 open door, 3 key, 4 goal.
_C = 5
# Headings: 0 up, 1 right, 2 down, 3 left (row/col deltas).
_DR = jnp.asarray([-1, 0, 1, 0])
_DC = jnp.asarray([0, 1, 0, -1])


class DoorKeyState(NamedTuple):
    key: jax.Array
    agent_rc: jax.Array  # [2] int32
    agent_dir: jax.Array  # () int32
    has_key: jax.Array  # () bool
    door_open: jax.Array  # () bool
    key_rc: jax.Array  # [2] (moves off-grid when picked up)
    door_rc: jax.Array  # [2]
    goal_rc: jax.Array  # [2]
    wall_col: jax.Array  # () int32
    step_count: jax.Array


def _masked_choice(key: jax.Array, mask: jax.Array) -> jax.Array:
    """Uniform index over True cells of a [N, N] mask -> [2] (row, col)."""
    flat = mask.reshape(-1)
    gumbel = jax.random.gumbel(key, flat.shape)
    idx = jnp.argmax(jnp.where(flat, gumbel, -jnp.inf))
    n = mask.shape[1]
    return jnp.stack([idx // n, idx % n]).astype(jnp.int32)


class DoorKey(Environment):
    """Key -> door -> goal gridworld with a 5x5 egocentric view."""

    def __init__(self, size: int = 6, max_steps: int = 0):
        if int(size) < 5:
            raise ValueError(
                f"DoorKey needs size >= 5 (got {size}): the layout requires a "
                "border, an interior wall column, and a free column each side"
            )
        self._n = int(size)
        self._max_steps = int(max_steps) if max_steps else 4 * self._n * self._n

    def observation_space(self) -> Observation:
        return Observation(
            # View channels + has_key broadcast as a 6th plane.
            agent_view=spaces.Array((_VIEW, _VIEW, _C + 1), jnp.float32),
            action_mask=spaces.Array((5,), jnp.float32),
            step_count=spaces.Array((), jnp.int32),
        )

    def action_space(self) -> spaces.Discrete:
        return spaces.Discrete(5)

    # -- layout ----------------------------------------------------------

    def _grid(self, state: DoorKeyState) -> jax.Array:
        """Dense [N, N, C] channel grid from the state."""
        n = self._n
        rows = jnp.arange(n)[:, None]
        cols = jnp.arange(n)[None, :]
        border = (rows == 0) | (rows == n - 1) | (cols == 0) | (cols == n - 1)
        wall = border | (cols == state.wall_col)
        wall = wall & ~(
            (rows == state.door_rc[0]) & (cols == state.door_rc[1])
        )

        def at(rc):
            return (rows == rc[0]) & (cols == rc[1])

        grid = jnp.zeros((n, n, _C), jnp.float32)
        grid = grid.at[:, :, 0].set(wall.astype(jnp.float32))
        grid = grid.at[:, :, 1].set(
            (at(state.door_rc) & ~state.door_open).astype(jnp.float32)
        )
        grid = grid.at[:, :, 2].set(
            (at(state.door_rc) & state.door_open).astype(jnp.float32)
        )
        grid = grid.at[:, :, 3].set(at(state.key_rc).astype(jnp.float32))
        grid = grid.at[:, :, 4].set(at(state.goal_rc).astype(jnp.float32))
        return grid

    def _observe(self, state: DoorKeyState) -> Observation:
        """5x5 egocentric view: agent centered on the bottom row, facing up."""
        grid = self._grid(state)
        pad = _VIEW  # generous halo so the slice never clips
        padded = jnp.pad(grid, ((pad, pad), (pad, pad), (0, 0)))
        # Rotate the WORLD so the agent's heading points up, then slice the
        # window ahead of the agent. rot90(k) needs static k: lax.switch.
        r, c = state.agent_rc[0] + pad, state.agent_rc[1] + pad
        n_pad = padded.shape[0]

        def rot(k):
            def f():
                rotated = jnp.rot90(padded, k=k, axes=(0, 1))
                # Rotating the grid moves the agent's coordinates too.
                if k == 0:
                    rr, cc = r, c
                elif k == 1:
                    rr, cc = n_pad - 1 - c, r
                elif k == 2:
                    rr, cc = n_pad - 1 - r, n_pad - 1 - c
                else:
                    rr, cc = c, n_pad - 1 - r
                return jax.lax.dynamic_slice(
                    rotated,
                    (rr - (_VIEW - 1), cc - (_VIEW // 2), 0),
                    (_VIEW, _VIEW, _C),
                )
            return f

        # Heading 0 (up) needs no rotation; heading 1 (right) rotates the
        # world counter-clockwise once so "right" points up, etc.
        view = jax.lax.switch(state.agent_dir, [rot(0), rot(1), rot(2), rot(3)])
        carried = jnp.full((_VIEW, _VIEW, 1), state.has_key, jnp.float32)
        view = jnp.concatenate([view, carried], axis=-1)
        return Observation(
            agent_view=view,
            action_mask=jnp.ones((5,), jnp.float32),
            step_count=state.step_count,
        )

    # -- episode ---------------------------------------------------------

    def reset(self, key: jax.Array) -> Tuple[DoorKeyState, TimeStep]:
        n = self._n
        key, k_wall, k_door, k_agent, k_key, k_goal, k_dir = jax.random.split(key, 7)
        # Wall column strictly inside, leaving >= 1 free column each side.
        wall_col = jax.random.randint(k_wall, (), 2, n - 2)
        door_row = jax.random.randint(k_door, (), 1, n - 1)
        door_rc = jnp.stack([door_row, wall_col]).astype(jnp.int32)

        rows = jnp.arange(n)[:, None]
        cols = jnp.arange(n)[None, :]
        interior = (rows > 0) & (rows < n - 1) & (cols > 0) & (cols < n - 1)
        left = interior & (cols < wall_col)
        right = interior & (cols > wall_col)

        agent_rc = _masked_choice(k_agent, left)
        key_free = left & ~((rows == agent_rc[0]) & (cols == agent_rc[1]))
        key_rc = _masked_choice(k_key, key_free)
        goal_rc = _masked_choice(k_goal, right)

        state = DoorKeyState(
            key=key,
            agent_rc=agent_rc,
            agent_dir=jax.random.randint(k_dir, (), 0, 4),
            has_key=jnp.zeros((), bool),
            door_open=jnp.zeros((), bool),
            key_rc=key_rc,
            door_rc=door_rc,
            goal_rc=goal_rc,
            wall_col=wall_col,
            step_count=jnp.zeros((), jnp.int32),
        )
        ts = restart(self._observe(state))
        ts.extras["truncation"] = jnp.zeros((), bool)
        return state, ts

    def step(self, state: DoorKeyState, action: jax.Array) -> Tuple[DoorKeyState, TimeStep]:
        action = jnp.reshape(action, ()).astype(jnp.int32)
        d = state.agent_dir
        ahead = state.agent_rc + jnp.stack([_DR[d], _DC[d]])

        # Turn.
        new_dir = jnp.where(
            action == 0, (d - 1) % 4, jnp.where(action == 1, (d + 1) % 4, d)
        )

        # Forward: blocked by walls, closed door, and the (unpicked) key.
        grid = self._grid(state)
        cell = grid[ahead[0], ahead[1]]
        blocked = (cell[0] > 0) | (cell[1] > 0) | (cell[3] > 0)
        new_rc = jnp.where((action == 2) & ~blocked, ahead, state.agent_rc)

        # Pickup: facing the key.
        facing_key = jnp.all(ahead == state.key_rc)
        picked = (action == 3) & facing_key & ~state.has_key
        has_key = state.has_key | picked
        key_rc = jnp.where(picked, jnp.full((2,), -1, jnp.int32), state.key_rc)

        # Toggle: facing the door while carrying the key.
        facing_door = jnp.all(ahead == state.door_rc)
        door_open = state.door_open | ((action == 4) & facing_door & has_key)

        next_state = DoorKeyState(
            key=state.key,
            agent_rc=new_rc,
            agent_dir=new_dir,
            has_key=has_key,
            door_open=door_open,
            key_rc=key_rc,
            door_rc=state.door_rc,
            goal_rc=state.goal_rc,
            wall_col=state.wall_col,
            step_count=state.step_count + 1,
        )

        at_goal = jnp.all(new_rc == state.goal_rc)
        # Minigrid-style shaped terminal reward: earlier is better.
        reward = jnp.where(
            at_goal,
            1.0 - 0.9 * next_state.step_count.astype(jnp.float32) / self._max_steps,
            0.0,
        ).astype(jnp.float32)
        terminated = at_goal
        truncated = jnp.logical_and(
            next_state.step_count >= self._max_steps, ~terminated
        )
        obs = self._observe(next_state)
        ts = select_step(
            terminated,
            termination(reward, obs),
            select_step(truncated, truncation(reward, obs), transition(reward, obs)),
        )
        ts.extras["truncation"] = truncated
        return next_state, ts
