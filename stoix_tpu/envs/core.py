"""Pure-functional Environment API.

The contract (reference uses the external `stoa` package; see SURVEY.md §1 layer 7):

    state, timestep = env.reset(key)
    state, timestep = env.step(state, action)

Both are pure functions of their inputs — safe to `jit`, `vmap`, `lax.scan`, and
`shard_map`. `state` is an arbitrary pytree that the caller threads through; envs
carry their own PRNG key inside `state` so stepping stays functional.

Environments emit the canonical `Observation(agent_view, action_mask, step_count)`
struct so every network/system can rely on one observation vocabulary.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax

from stoix_tpu.envs import spaces
from stoix_tpu.envs.types import TimeStep

State = Any
Action = Any


class Environment:
    """Base class for pure-JAX environments."""

    def reset(self, key: jax.Array) -> Tuple[State, TimeStep]:
        raise NotImplementedError

    def step(self, state: State, action: Action) -> Tuple[State, TimeStep]:
        raise NotImplementedError

    def observation_space(self) -> Any:
        """Pytree of spaces matching the observation pytree."""
        raise NotImplementedError

    def action_space(self) -> spaces.Space:
        raise NotImplementedError

    @property
    def unwrapped(self) -> "Environment":
        return self

    @property
    def name(self) -> str:
        return type(self).__name__

    # --- convenience -------------------------------------------------------
    def observation_value(self) -> Any:
        """A dummy observation for network initialisation."""
        return spaces.tree_generate_value(self.observation_space())

    def action_value(self) -> Any:
        return spaces.tree_generate_value(self.action_space())

    @property
    def num_actions(self) -> int:
        return spaces.num_actions(self.action_space())


class Wrapper(Environment):
    """Delegating base wrapper."""

    def __init__(self, env: Environment):
        self._env = env

    def reset(self, key: jax.Array) -> Tuple[State, TimeStep]:
        return self._env.reset(key)

    def step(self, state: State, action: Action) -> Tuple[State, TimeStep]:
        return self._env.step(state, action)

    def observation_space(self) -> Any:
        return self._env.observation_space()

    def action_space(self) -> spaces.Space:
        return self._env.action_space()

    @property
    def unwrapped(self) -> Environment:
        return self._env.unwrapped

    @property
    def name(self) -> str:
        return self._env.name

    def __getattr__(self, item: str) -> Any:
        # Fall through to the wrapped env for env-specific attributes. Guard
        # private names so object reconstruction (deepcopy/pickle) that probes
        # attributes before __init__ runs cannot recurse on `_env` itself.
        if item.startswith("_"):
            raise AttributeError(item)
        return getattr(self._env, item)
